"""Flagship benchmarks — prints one JSON line per metric.

Secondary metrics first; LAST is always the flagship LSTM text-classification
row (BASELINE.md: 83 ms/batch @ bs=64, hidden=256 — benchmark/README.md:115-119),
the line the driver's tail-parser records. vs_baseline > 1 means we are
faster than the reference by that factor.

Methodology notes live in each benchmarks/*.py docstring (varied lengths,
train-mode BN with stat updates, distinct rotating device-staged batches,
on-device-loop differencing timing).

**Every row runs in its own WATCHDOG SUBPROCESS with a timeout + one retry.**
The remote-tunnel transport can hang a compile RPC indefinitely (round 3's
rc=124 was one such hang, observed again in round 4: a bench process blocked
25+ minutes with ~0 CPU); an in-process retry loop cannot recover from a
blocked C call, but killing the row's subprocess frees the chip for the next
row, so one bad RPC costs a row instead of the round.

Default run = one representative row per family (fits the driver's budget).
``python bench.py --full`` runs every published reference row — use that
when refreshing BASELINE.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))
ROW_TIMEOUT = 420.0        # compile (~40-90 s) + measure, with slack
BIG_TIMEOUT = 900.0        # rows with heavy host-side setup (20 GB table)


def _row(expr: str, timeout: float = ROW_TIMEOUT, tries: int = 2) -> bool:
    """Run one bench row in a watchdog subprocess; print its JSON line(s).

    Returns True if at least one metric line was printed."""
    code = (f"import sys, json\nsys.path.insert(0, {ROOT!r})\n"
            f"_r = {expr}\n"
            "for _d in (_r if isinstance(_r, list) else [_r]):\n"
            "    print(json.dumps(_d), flush=True)\n")
    for attempt in range(tries):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout, cwd=ROOT)
        except subprocess.TimeoutExpired:
            print(f"bench: row {expr!r} timed out after {timeout:.0f}s "
                  f"(attempt {attempt + 1}/{tries}) — killed its process, "
                  "chip freed", file=sys.stderr, flush=True)
            continue
        ok = False
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                print(line, flush=True)
                ok = True
        if ok:
            return True
        tail = "\n".join(r.stderr.splitlines()[-5:])
        print(f"bench: row {expr!r} failed rc={r.returncode} "
              f"(attempt {attempt + 1}/{tries}):\n{tail}",
              file=sys.stderr, flush=True)
        time.sleep(3)
    return False


def bench_mlp_fallback():
    """Emergency fallback if the flagship row fails twice."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.optimizer import Adam

    model = MnistMLP(in_dim=784, hidden=256, classes=10)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, y):
        loss, grads = jax.value_and_grad(model.loss)(params, x, y)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    x = jnp.ones((256, 784), jnp.float32)
    y = jnp.zeros((256,), jnp.int32)
    params, state, _ = step(params, state, x, y)  # compile
    jax.block_until_ready(params)
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        params, state, loss = step(params, state, x, y)
    jax.block_until_ready(loss)
    ms = (time.perf_counter() - t0) / n * 1e3
    return {"metric": "mnist_mlp_ms_per_batch_bs256", "value": round(ms, 3),
            "unit": "ms/batch", "vs_baseline": None}


# Representative rows per family for the default (driver-budget) run; the
# reference numbers live in the benchmarks' own tables (single source of
# truth — the keys here only SELECT rows).
QUICK_IMAGE_KEYS = {("alexnet", 256), ("googlenet", 128)}
QUICK_LSTM_KEYS = {(128, 512)}


def main(full: bool = False):
    from benchmarks.image_suite import ROWS as IMAGE_ROWS
    from benchmarks.lstm_textcls import SUITE_ROWS as LSTM_ROWS

    image = [r for r in IMAGE_ROWS
             if full or (r[0], r[1]) in QUICK_IMAGE_KEYS]
    lstm = [r for r in LSTM_ROWS if full or (r[0], r[1]) in QUICK_LSTM_KEYS]

    for model_key, bs, ref in image:
        _row(f"__import__('benchmarks.image_suite', fromlist=['x'])"
             f".bench_row({model_key!r}, {bs}, {ref})")
    for bs, hidden, ref in lstm:
        _row(f"__import__('benchmarks.lstm_textcls', fromlist=['x'])"
             f".bench_row({bs}, {hidden}, {ref})")

    mods = ["transformer_lm", "resnet50", "seq2seq_nmt", "transformer_nmt",
            "serving_decode"]
    if full:
        mods.append("fused_rnn")
    for name in mods:
        _row(f"__import__('benchmarks.{name}', fromlist=['x']).run()")
    if full:
        _row("__import__('benchmarks.resnet50', fromlist=['x'])"
             ".run_with_infeed()")
    _row("__import__('benchmarks.host_embedding', fromlist=['x']).run()",
         timeout=BIG_TIMEOUT)

    # the flagship — LAST, so the driver's tail-parse records it
    flagship_ok = _row(
        "__import__('benchmarks.lstm_textcls', fromlist=['x']).run()")
    if not flagship_ok:
        # guarantee the LAST line is flagship-or-fallback, never a secondary
        # metric masquerading as the flagship
        print(json.dumps(bench_mlp_fallback()), flush=True)


if __name__ == "__main__":
    main(full="--full" in sys.argv)
