"""Flagship benchmark — prints ONE JSON line.

Benchmarks LSTM text-classification ms/batch against the reference's published K40m
number (BASELINE.md: 83 ms/batch @ bs=64, hidden=256 — benchmark/README.md:115-119).
vs_baseline > 1 means we are faster than the reference by that factor.
"""

from __future__ import annotations

import json
import time


def bench_mlp_fallback():
    """Used until the LSTM bench path exists."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.optimizer import Adam

    model = MnistMLP(in_dim=784, hidden=256, classes=10)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, y):
        loss, grads = jax.value_and_grad(model.loss)(params, x, y)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    x = jnp.ones((256, 784), jnp.float32)
    y = jnp.zeros((256,), jnp.int32)
    params, state, _ = step(params, state, x, y)  # compile
    jax.block_until_ready(params)
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        params, state, loss = step(params, state, x, y)
    jax.block_until_ready(loss)
    ms = (time.perf_counter() - t0) / n * 1e3
    return {"metric": "mnist_mlp_ms_per_batch_bs256", "value": round(ms, 3),
            "unit": "ms/batch", "vs_baseline": None}


def main():
    try:
        from benchmarks.lstm_textcls import run as run_lstm  # noqa
        result = run_lstm()
    except Exception:
        result = bench_mlp_fallback()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
