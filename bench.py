"""Flagship benchmarks — prints one JSON line per metric.

All three BASELINE.md headline configs run on the default jax device (the
real TPU chip under the driver): ResNet-50 images/sec, seq2seq NMT tokens/sec,
and — LAST, as the flagship line with a published reference number — LSTM
text-classification ms/batch vs the K40m baseline (BASELINE.md: 83 ms/batch
@ bs=64, hidden=256 — benchmark/README.md:115-119). vs_baseline > 1 means we
are faster than the reference by that factor.

Methodology notes live in each benchmarks/*.py docstring (varied lengths,
train-mode BN with stat updates, distinct rotating device-staged batches,
on-device-loop differencing timing).

Default run = one representative row per family (fits the driver's timeout;
round 3's full sweep hit rc=124). ``python bench.py --full`` runs every
published reference row — use that when refreshing BASELINE.md.
"""

from __future__ import annotations

import json
import time
import traceback


def bench_mlp_fallback():
    """Emergency fallback if every real bench fails."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.optimizer import Adam

    model = MnistMLP(in_dim=784, hidden=256, classes=10)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, y):
        loss, grads = jax.value_and_grad(model.loss)(params, x, y)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    x = jnp.ones((256, 784), jnp.float32)
    y = jnp.zeros((256,), jnp.int32)
    params, state, _ = step(params, state, x, y)  # compile
    jax.block_until_ready(params)
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        params, state, loss = step(params, state, x, y)
    jax.block_until_ready(loss)
    ms = (time.perf_counter() - t0) / n * 1e3
    return {"metric": "mnist_mlp_ms_per_batch_bs256", "value": round(ms, 3),
            "unit": "ms/batch", "vs_baseline": None}


def _attempt(fn, tries: int = 2):
    """Run a bench with one retry: the remote-tunnel transport occasionally
    drops a compile RPC mid-flight, which must not cost the round a row."""
    for t in range(tries):
        try:
            return fn()
        except Exception:
            traceback.print_exc()
            if t + 1 < tries:
                time.sleep(5)
    return None


# Representative rows per family for the default (driver-budget) run,
# selected FROM the published tables so the reference numbers have one
# source of truth. The full sweep (11 image rows, 9 LSTM rows) lives behind
# --full and is what refreshes BASELINE.md; the default run must finish well
# inside the driver's timeout (round 3 learned the hard way: rc=124).
QUICK_IMAGE_KEYS = {("alexnet", 256), ("googlenet", 128)}
QUICK_LSTM_KEYS = {(128, 512)}


def _quick(rows, keys):
    return [r for r in rows if (r[0], r[1]) in keys]


def main(full: bool = False):
    flagship_ok = False
    # secondary metrics first; the flagship (has a published baseline) last so
    # it is the line the driver's tail-parser records
    try:
        from benchmarks.image_suite import ROWS, bench_row
        for model_key, bs, ref_ms in (
                ROWS if full else _quick(ROWS, QUICK_IMAGE_KEYS)):
            rec = _attempt(lambda: bench_row(model_key, bs, ref_ms))
            if rec is not None:
                print(json.dumps(rec), flush=True)
    except Exception:
        traceback.print_exc()
    try:
        from benchmarks.lstm_textcls import SUITE_ROWS
        from benchmarks.lstm_textcls import bench_row as lstm_row
        for bs, hidden, ref_ms in (
                SUITE_ROWS if full else _quick(SUITE_ROWS, QUICK_LSTM_KEYS)):
            rec = _attempt(lambda: lstm_row(bs, hidden, ref_ms))
            if rec is not None:
                print(json.dumps(rec), flush=True)
    except Exception:
        traceback.print_exc()
    names = ("transformer_lm", "resnet50", "seq2seq_nmt", "fused_rnn",
             "lstm_textcls") if full else (
        "transformer_lm", "resnet50", "seq2seq_nmt", "lstm_textcls")
    for name in names:
        try:
            mod = __import__(f"benchmarks.{name}", fromlist=["run"])
            rec = _attempt(mod.run)
            if rec is not None:
                print(json.dumps(rec), flush=True)
            if name == "resnet50" and full:
                rec2 = _attempt(mod.run_with_infeed)
                if rec2 is not None:
                    print(json.dumps(rec2), flush=True)
            if name == "lstm_textcls" and rec is not None:
                flagship_ok = True
        except Exception:
            traceback.print_exc()
    if not flagship_ok:
        # guarantee the LAST line is flagship-or-fallback, never a secondary
        # metric masquerading as the flagship in the driver's tail-parse
        print(json.dumps(bench_mlp_fallback()), flush=True)


if __name__ == "__main__":
    import sys
    main(full="--full" in sys.argv)
