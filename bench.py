"""Flagship benchmarks — prints one JSON line per metric.

Secondary metrics first; LAST is always the flagship LSTM text-classification
row (BASELINE.md: 83 ms/batch @ bs=64, hidden=256 — benchmark/README.md:115-119),
the line the driver's tail-parser records. vs_baseline > 1 means we are
faster than the reference by that factor.

Methodology notes live in each benchmarks/*.py docstring (varied lengths,
train-mode BN with stat updates, distinct rotating device-staged batches,
on-device-loop differencing timing).

**Every row runs in its own WATCHDOG SUBPROCESS with a timeout + one retry.**
The remote-tunnel transport can hang a compile RPC indefinitely (round 3's
rc=124 was one such hang, observed again in round 4: a bench process blocked
25+ minutes with ~0 CPU); an in-process retry loop cannot recover from a
blocked C call, but killing the row's subprocess frees the chip for the next
row, so one bad RPC costs a row instead of the round.

**The flagship row is measured FIRST but printed LAST** via an atexit +
SIGTERM hook: if the driver's timeout reaps the run mid-suite, the final
printed line is still the flagship (only SIGKILL can break the contract).

Default run = one representative row per family (fits the driver's budget).
``python bench.py --full`` runs every published reference row — use that
when refreshing BASELINE.md.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))
ROW_TIMEOUT = 420.0        # compile (~40-90 s) + measure, with slack
# host_embedding measured 110 s end-to-end once the native zero-fill path
# removed the 20 GB numpy+memcpy init (was ~90 s of the old ~200 s); 300
# declares honest headroom so the default budget run keeps the row
BIG_TIMEOUT = 300.0
# Global wall budget for the SECONDARY rows: the flagship is measured first
# and guaranteed; once the budget is gone the remaining secondaries are
# skipped (loudly) and the run exits 0 — rc=0 + flagship-last hold even
# when the tunnel runs 2-3x slower than usual (observed round 4 evenings).
# Sized so budget + flagship (~2-3 min) stays inside a 30-minute driver
# window with margin (round 3's suite outran the window and was reaped,
# rc=124). The full-suite refresh (--full) can raise it via env.
BUDGET_S = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET_S", "1350"))


# the live watchdog child, visible to the SIGTERM handler: on a driver
# kill the in-flight row's subprocess MUST die too, or it keeps the chip
# open after bench.py reports a clean run and the next round blocks on it
_current_child = None

# span evidence riding along with the numbers: every row's subprocess runs
# under an ObsSession + flight recorder and saves its JSONL dump here, so a
# future perf trajectory can ask "where did the time go" of any past
# BENCH_*.json row (inspect: paddle_tpu obs summary --input <file>).
# Set PADDLE_TPU_BENCH_OBS_DIR="" to disable.
#
# Measurement-conditions note (rows from PR 4 on): the session is live
# DURING the timed loops, so instrumented paths (obs.span/obs.count call
# sites) pay the recording path — a few µs per event against multi-ms
# batches, and zero for the raw-jax device loops most rows time. When
# comparing against pre-PR-4 BENCH_*.json rows, treat sub-percent deltas
# on instrumented paths as noise from this change, not a regression.
OBS_DIR = os.environ.get("PADDLE_TPU_BENCH_OBS_DIR", ROOT)


def _slug(expr: str) -> str:
    """Stable filesystem tag for a row expression. The short expr digest
    keeps parameterized rows (bench_row('alexnet', 256) vs ('googlenet',
    128)) from overwriting each other's span-evidence dumps."""
    import hashlib
    import re
    m = re.findall(r"benchmarks\.(\w+)|\.(\w+)\(", expr)
    parts = [a or b for a, b in m]
    digest = hashlib.md5(expr.encode()).hexdigest()[:6]
    return ("_".join(parts) or "row") + "_" + digest


def _capture_row(expr: str, timeout: float = ROW_TIMEOUT,
                 tries: int = 2) -> list:
    """Run one bench row in a watchdog subprocess; return its JSON lines."""
    global _current_child
    obs_prelude = obs_coda = ""
    if OBS_DIR:
        obs_path = os.path.join(OBS_DIR, f"BENCH_OBS_{_slug(expr)}.jsonl")
        # flight recorder armed first: a row the watchdog SIGKILLs mid-
        # compile still can't dump (nothing survives SIGKILL), but a row
        # that dies on an exception leaves its span ring behind
        obs_prelude = (
            "from paddle_tpu import obs as _obs\n"
            "_s = _obs.ObsSession(registry=_obs.MetricsRegistry())"
            ".install()\n"
            f"_fr = _obs.FlightRecorder(_s, {obs_path!r}).arm()\n")
        # never let a telemetry write discard a completed measurement: the
        # JSON result lines print even if the dump path is unwritable
        obs_coda = ("_fr.disarm()\n_s.uninstall()\n"
                    "try:\n"
                    f"    _s.save({obs_path!r})\n"
                    "except Exception as _e:\n"
                    "    print('bench: obs dump failed:', _e, "
                    "file=sys.stderr)\n")
    code = (f"import sys, json\nsys.path.insert(0, {ROOT!r})\n"
            + obs_prelude
            + f"_r = {expr}\n"
            + obs_coda
            + "for _d in (_r if isinstance(_r, list) else [_r]):\n"
            "    print(json.dumps(_d), flush=True)\n")
    for attempt in range(tries):
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True, cwd=ROOT)
        _current_child = p
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            _current_child = None
            print(f"bench: row {expr!r} timed out after {timeout:.0f}s "
                  f"(attempt {attempt + 1}/{tries}) — killed its process, "
                  "chip freed", file=sys.stderr, flush=True)
            continue
        _current_child = None
        lines = [l for l in out.splitlines() if l.startswith("{")]
        lines = _validate_lines(expr, lines)
        if lines:
            return lines
        tail = "\n".join(err.splitlines()[-5:])
        print(f"bench: row {expr!r} failed rc={p.returncode} "
              f"(attempt {attempt + 1}/{tries}):\n{tail}",
              file=sys.stderr, flush=True)
        time.sleep(3)
    return []


def _validate_lines(expr: str, lines: list) -> list:
    """Bench-row schema gate (benchmarks/schema.py): a malformed row is
    DROPPED loudly — and the row expression then retries/fails like any
    other row failure — instead of printing a dict that silently lacks the
    columns the trend tooling keys on. `paddle_tpu lint --bench-rows`
    runs the same check statically over saved BENCH files."""
    from benchmarks.schema import validate_row
    kept = []
    for line in lines:
        try:
            problems = validate_row(json.loads(line))
        except ValueError as e:
            problems = [f"not valid JSON: {e}"]
        if problems:
            print(f"bench: row {expr!r} emitted a malformed row "
                  f"(dropped): {'; '.join(problems)}\n  {line[:200]}",
                  file=sys.stderr, flush=True)
        else:
            kept.append(line)
    return kept


def _row(expr: str, timeout: float = ROW_TIMEOUT, tries: int = 2) -> bool:
    lines = _capture_row(expr, timeout, tries)
    for line in lines:
        print(line, flush=True)
    return bool(lines)


def bench_mlp_fallback():
    """Emergency fallback if the flagship row fails twice."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.optimizer import Adam

    model = MnistMLP(in_dim=784, hidden=256, classes=10)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, y):
        loss, grads = jax.value_and_grad(model.loss)(params, x, y)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    x = jnp.ones((256, 784), jnp.float32)
    y = jnp.zeros((256,), jnp.int32)
    params, state, _ = step(params, state, x, y)  # compile
    jax.block_until_ready(params)
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        params, state, loss = step(params, state, x, y)
    jax.block_until_ready(loss)
    ms = (time.perf_counter() - t0) / n * 1e3
    return {"metric": "mnist_mlp_ms_per_batch_bs256", "value": round(ms, 3),
            "unit": "ms/batch", "vs_baseline": None}


# Representative rows per family for the default (driver-budget) run; the
# reference numbers live in the benchmarks' own tables (single source of
# truth — the keys here only SELECT rows).
QUICK_IMAGE_KEYS = {("alexnet", 256), ("googlenet", 128)}
QUICK_LSTM_KEYS = {(128, 512)}


def main(full: bool = False):
    t0 = time.monotonic()      # the budget covers the WHOLE run
    from benchmarks.image_suite import ROWS as IMAGE_ROWS
    from benchmarks.lstm_textcls import FLAGSHIP_METRIC
    from benchmarks.lstm_textcls import SUITE_ROWS as LSTM_ROWS

    # ---- the last-line contract is armed BEFORE any chip work: on ANY
    # exit (normal, SIGTERM/SIGINT from the driver's timeout, unhandled
    # exception) the last stdout line is the flagship row. The handler
    # uses raw os.write — a signal landing mid-print of a secondary row
    # would make print() raise CPython's reentrant-buffered-IO guard and
    # lose the line — and marks itself done only AFTER the write, so the
    # atexit copy retries if the handler ever failed. If the kill lands
    # before the flagship measurement finishes, an honest null-value row
    # is emitted (never a fabricated number). Only SIGKILL can break this.
    flagship = []          # JSON lines, filled once measured
    _done = []

    def _emit_flagship():
        if _done:
            return
        lines = flagship or [json.dumps(
            {"metric": FLAGSHIP_METRIC,
             "value": None, "unit": "ms/batch", "vs_baseline": None,
             "note": "killed before the flagship measurement completed"})]
        # leading \n: stdout may hold a partially-printed secondary row
        os.write(1, ("\n" + "\n".join(lines) + "\n").encode())
        _done.append(True)

    atexit.register(_emit_flagship)

    def _on_term(signum, frame):
        child = _current_child
        if child is not None:
            try:
                child.kill()     # free the chip before reporting success
            except OSError:
                pass
        _emit_flagship()
        # 128+signum: a reaped run must not be rc-indistinguishable from a
        # clean one — the tail JSON line stays the honest success signal,
        # the return code says HOW the process ended (driver contract,
        # docs/design/bench_contract.md)
        os._exit(128 + signum)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # ---- flagship FIRST (it is the cheapest row), printed LAST via the
    # hook above — the round-3 rc=124 failure mode (a wrong row in the
    # driver's tail-parse) cannot recur short of SIGKILL.
    flagship += _capture_row(
        "__import__('benchmarks.lstm_textcls', fromlist=['x']).run()")
    if not flagship:
        # the fallback runs under the same subprocess watchdog — an
        # in-process hung compile RPC here would block the whole suite
        flagship += _capture_row(
            "__import__('bench').bench_mlp_fallback()", tries=1)
    if not flagship:
        print("bench: flagship AND fallback failed — the null row will be "
              "the last line", file=sys.stderr, flush=True)

    # ---- secondary metrics, printed as they complete, within the budget
    image = [r for r in IMAGE_ROWS
             if full or (r[0], r[1]) in QUICK_IMAGE_KEYS]
    lstm = [r for r in LSTM_ROWS if full or (r[0], r[1]) in QUICK_LSTM_KEYS]

    rows = []
    for model_key, bs, ref in image:
        rows.append((f"__import__('benchmarks.image_suite', fromlist=['x'])"
                     f".bench_row({model_key!r}, {bs}, {ref})", ROW_TIMEOUT))
    for bs, hidden, ref in lstm:
        rows.append((f"__import__('benchmarks.lstm_textcls', fromlist=['x'])"
                     f".bench_row({bs}, {hidden}, {ref})", ROW_TIMEOUT))
    mods = ["transformer_lm", "resnet50", "seq2seq_nmt", "transformer_nmt",
            "serving_decode", "fluid_executor", "sharded_gpt2"]
    if full:
        mods.append("fused_rnn")
    for name in mods:
        rows.append((f"__import__('benchmarks.{name}', fromlist=['x'])"
                     ".run()", ROW_TIMEOUT))
    # the decode-roofline rows (ROADMAP item 3): int8-KV decode (cache
    # read halved) and speculative decoding (target weights stream once
    # per round) next to the full-precision decode row above
    rows.append(("__import__('benchmarks.serving_decode', fromlist=['x'])"
                 ".run_quantized()", ROW_TIMEOUT))
    rows.append(("__import__('benchmarks.speculative_decode', "
                 "fromlist=['x']).run()", ROW_TIMEOUT))
    rows.append(("__import__('benchmarks.serving_decode', fromlist=['x'])"
                 ".run_continuous()", ROW_TIMEOUT))
    # the serving-plane rows (ROADMAP item 2): paged-vs-pinned residency
    # on the same mixed workload, and the daemon's client-measured SLOs
    rows.append(("__import__('benchmarks.serving_decode', fromlist=['x'])"
                 ".run_paged()", ROW_TIMEOUT))
    rows.append(("__import__('benchmarks.serving_daemon', fromlist=['x'])"
                 ".run()", ROW_TIMEOUT))
    # the disaggregation row (ROADMAP item 2): 1 prefill + 2 decode pools
    # behind the serving router — client-measured SLOs over the real
    # wire, the ship/adopt hop priced into TTFT
    rows.append(("__import__('benchmarks.serving_router', fromlist=['x'])"
                 ".run()", ROW_TIMEOUT))
    # the prefix-cache rows (ROADMAP item 2): zipf shared-prefix workload
    # warm-vs-cold — TTFT p50 and prefill FLOPs/token vs hit rate
    rows.append(("__import__('benchmarks.serving_prefix', fromlist=['x'])"
                 ".run()", ROW_TIMEOUT))
    # the autotune rows (ROADMAP item 3): tuned-vs-heuristic plan deltas
    # for the fused-RNN families + the measured decode-route crossover
    rows.append(("__import__('benchmarks.autotune_delta', fromlist=['x'])"
                 ".run()", ROW_TIMEOUT))
    # the fleet-actor row (ROADMAP item 2): kill half the decode pool,
    # count alert windows until the actor restores membership + SLO
    rows.append(("__import__('benchmarks.fleet_autoscale', fromlist=['x'])"
                 ".run()", ROW_TIMEOUT))
    if full:
        # the remaining BASELINE.md rows, so a --full session covers the
        # whole measured table in one output
        rows.append(("__import__('benchmarks.seq2seq_nmt', fromlist=['x'])"
                     ".run(batch=256)", ROW_TIMEOUT))
        for bs in (8, 32):
            rows.append((f"__import__('benchmarks.serving_decode', "
                         f"fromlist=['x']).run_config({bs})", ROW_TIMEOUT))
        rows.append(("__import__('benchmarks.serving_decode', "
                     "fromlist=['x']).run_config(8, bucket=None)",
                     ROW_TIMEOUT))
        rows.append(("__import__('benchmarks.speculative_decode', "
                     "fromlist=['x']).run_tiny_draft()", ROW_TIMEOUT))
        rows.append(("__import__('benchmarks.resnet50', fromlist=['x'])"
                     ".run_with_infeed()", ROW_TIMEOUT))
        rows.append(("__import__('benchmarks.transformer_lm', "
                     "fromlist=['x']).run_long()", ROW_TIMEOUT))
    rows.append(("__import__('benchmarks.host_embedding', fromlist=['x'])"
                 ".run()", BIG_TIMEOUT))

    budget = float("inf") if full else BUDGET_S
    for expr, timeout in rows:
        left = budget - (time.monotonic() - t0)
        if left < 90:
            print(f"bench: budget exhausted ({BUDGET_S:.0f}s) — skipping "
                  f"remaining secondary rows from {expr!r} on; the flagship "
                  "was measured first and prints last (raise "
                  "PADDLE_TPU_BENCH_BUDGET_S or use --full for the long "
                  "suite)", file=sys.stderr, flush=True)
            break
        if left < 0.5 * timeout:
            # a clamped window well below the row's declared timeout is a
            # guaranteed timeout (compile alone is 40-90 s) — skip rather
            # than burn the budget tail measuring nothing
            print(f"bench: skipping {expr!r} — needs ~{timeout:.0f}s, only "
                  f"{left:.0f}s of budget left", file=sys.stderr, flush=True)
            continue
        # --full is the BASELINE.md refresh: keep the one flaky-RPC retry
        # there; the budgeted default spends its time on coverage instead
        _row(expr, timeout=min(timeout, left), tries=2 if full else 1)
    # atexit prints the flagship as the last line


if __name__ == "__main__":
    main(full="--full" in sys.argv)
