"""Tuned-vs-heuristic bench rows — the autotune plane's evidence.

Runs `paddle_tpu tune`'s measurement driver over the fused-RNN families
(textcls LSTM + NMT-encoder GRU) and the decode-routing space on the
CURRENT backend, then reports one row per shape family: the measured
speedup of the tuned plan over the heuristic plan, with the winning plan
in the note. On TPU the families are the real bench shapes (``bench``
profile); off-TPU the sweep runs the same kernels through the Pallas
interpreter at proxy dims (``cpu`` profile — noted per row; interpreter
ratios do not transfer to the chip, the closed loop does).

The graph-fusion rows close the same loop one level up: for an MLP
training step and a GPT-2-small-shaped MLP-stack step, every certified
fusion group is measured fused-vs-unfused (``tune.fusion.measure_fusion``
— whole executor dispatches), the verdicts persist into the throwaway
cache, and the reported value is the steady-state step-time ratio of the
consulting executor (``fuse=None`` — activates only measured winners)
over the unfused executor (``fuse=False``). A ratio ≤ 1.0 is an honest
result: the measured-only gate refused groups that don't win here.

The sweep writes into a throwaway cache file (a bench row must not mutate
``~/.paddle_tpu``) and points the in-process consult at it, so the rows'
``plan_source: "tuned"`` stamp is literally true: the routing entries
resolved these plans from a measured cache while the row ran.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import List


def _fusion_delta_row(name: str, *, batch: int, width: int, depth: int,
                      note: str, cache_path: str, backend: str,
                      steps: int = 10) -> dict:
    """One `_train_` row: steady-state fused-vs-unfused step time for one
    proxy workload, with the fusion verdicts measured into (and consulted
    from) the throwaway cache first."""
    from benchmarks.mfu import attach_mfu

    from paddle_tpu import tune
    from paddle_tpu.fluid.executor import Executor, Scope
    from paddle_tpu.tune import fusion as F
    from paddle_tpu.tune.cache import AutotuneCache, load_cache

    main, startup, feed, fetch = F.build_proxy_program(
        batch=batch, width=width, depth=depth)
    measured = F.measure_fusion(main, startup, feed, fetch, reps=2,
                                note=note)
    try:
        cache = load_cache(cache_path)
    except (OSError, ValueError):
        cache = AutotuneCache()
    dk = F._device_kind()
    for r in measured:
        meta = {k: r[k] for k in ("certificate", "program_signature",
                                  "shape_family", "fused_ms", "unfused_ms",
                                  "note") if k in r}
        cache.put(r["space"], r["kernel"], dk, r["family"], r["plan"],
                  tune.space_hash("fusion"), methodology="measured",
                  backend=backend, **meta)
    cache.save(cache_path)
    tune.reset()            # the consult now resolves the fresh verdicts

    def steady(fuse) -> float:
        exe = Executor(scope=Scope(), fuse=fuse)
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=fetch)        # warm, untimed
        t0 = time.perf_counter()
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=fetch)
        return (time.perf_counter() - t0) / steps

    unfused_s = steady(False)
    fused_s = steady(None)
    plan = F.plan_for(main, {k: v.shape for k, v in feed.items()},
                      fetch=fetch, feed=list(feed))
    row = {
        "metric": f"fusion_train_{name}_step",
        "value": round(unfused_s / fused_s, 3) if fused_s else None,
        "unit": "x_fused_vs_unfused",
        "vs_baseline": None,
        "plan_source": "tuned",
        "note": {
            "fused_step_ms": round(fused_s * 1e3, 4),
            "unfused_step_ms": round(unfused_s * 1e3, 4),
            "groups_certified": len(measured),
            "groups_activated": len(plan.groups),
            "groups_refused": [reason for _, reason in plan.rejected],
            "workload": note,
            "dims": {"batch": batch, "width": width, "depth": depth},
            "backend": backend,
        },
    }
    # mfu stays an honest null off-TPU; the value is a measured ratio of
    # two whole-step timings (methodology "measured")
    return attach_mfu(row, None, max(fused_s, 1e-9))


def run() -> List[dict]:
    from benchmarks.mfu import attach_hbm_bw, attach_mfu

    from paddle_tpu import tune
    cache_path = os.path.join(tempfile.mkdtemp(prefix="pt_autotune_row_"),
                              "autotune.json")
    prev = os.environ.get(tune.CACHE_ENV)
    os.environ[tune.CACHE_ENV] = cache_path
    tune.reset()
    try:
        report = tune.run_tune(spaces=("fused_rnn", "decode_route"),
                               cache_path=cache_path)
        rows: List[dict] = []
        for r in report["results"]:
            if r["space"] == "fused_rnn":
                if r.get("plan") is None:
                    continue
                tuned_s = (r["tuned_ms"] or 0.0) / 1e3
                row = {
                    "metric": (f"fused_rnn_train_autotune_"
                               f"{r['kernel']}_{r['family']}"),
                    "value": r.get("speedup"),
                    "unit": "x_tuned_vs_heuristic",
                    "vs_baseline": None,
                    "plan_source": "tuned",
                    "note": {
                        "tuned_plan": r["plan"],
                        "heuristic_plan": r.get("heuristic_plan"),
                        "tuned_ms": r.get("tuned_ms"),
                        "heuristic_ms": r.get("heuristic_ms"),
                        "candidates": r.get("candidates"),
                        "family_note": r.get("note"),
                        "backend": report["backend"],
                        "device_kind": report["device_kind"],
                    },
                }
                # mfu is an honest null here: the row's value is a RATIO
                # of two measured times of the same kernel, not a
                # throughput (methodology stays "measured")
                rows.append(attach_mfu(row, None, max(tuned_s, 1e-9)))
            elif r["space"] == "decode_route":
                row = {
                    # not "..._route_...": that substring is the serving
                    # route-row family (bench_schema), whose SLO columns
                    # a crossover sweep doesn't have
                    "metric": "autotune_decode_crossover",
                    "value": r["plan"].get("kernel_min_len"),
                    "unit": "min_kernel_len_tokens",
                    "vs_baseline": None,
                    "plan_source": "tuned",
                    "methodology": "measured",
                    "note": {
                        "sweep": r.get("sweep"),
                        "heuristic_plan": r.get("heuristic_plan"),
                        "family_note": r.get("note"),
                        "backend": report["backend"],
                        "device_kind": report["device_kind"],
                    },
                }
                rows.append(attach_hbm_bw(row, None, 1.0,
                                          methodology="measured"))
        # graph-fusion delta rows: MLP proxy at the profile's sweep dims
        # plus a GPT-2-small-shaped MLP-stack step (d_model-width fc
        # stack — the transformer MLP is where the epilogue chains live)
        fcfg = tune.PROFILES[report["profile"]]["fusion"]
        rows.append(_fusion_delta_row(
            "mlp", batch=fcfg["batch"], width=fcfg["width"],
            depth=fcfg["depth"], note=f"mlp proxy ({fcfg['note']})",
            cache_path=cache_path, backend=report["backend"]))
        gpt_width = 768 if report["backend"] == "device" else 256
        rows.append(_fusion_delta_row(
            "gpt2s", batch=8, width=gpt_width, depth=4,
            note=f"gpt2-small mlp-stack proxy (width={gpt_width})",
            cache_path=cache_path, backend=report["backend"]))
        return rows
    finally:
        if prev is None:
            os.environ.pop(tune.CACHE_ENV, None)
        else:
            os.environ[tune.CACHE_ENV] = prev
        tune.reset()


if __name__ == "__main__":
    import json
    for row in run():
        print(json.dumps(row))
