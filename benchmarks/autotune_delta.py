"""Tuned-vs-heuristic bench rows — the autotune plane's evidence.

Runs `paddle_tpu tune`'s measurement driver over the fused-RNN families
(textcls LSTM + NMT-encoder GRU) and the decode-routing space on the
CURRENT backend, then reports one row per shape family: the measured
speedup of the tuned plan over the heuristic plan, with the winning plan
in the note. On TPU the families are the real bench shapes (``bench``
profile); off-TPU the sweep runs the same kernels through the Pallas
interpreter at proxy dims (``cpu`` profile — noted per row; interpreter
ratios do not transfer to the chip, the closed loop does).

The sweep writes into a throwaway cache file (a bench row must not mutate
``~/.paddle_tpu``) and points the in-process consult at it, so the rows'
``plan_source: "tuned"`` stamp is literally true: the routing entries
resolved these plans from a measured cache while the row ran.
"""

from __future__ import annotations

import os
import tempfile
from typing import List


def run() -> List[dict]:
    from benchmarks.mfu import attach_hbm_bw, attach_mfu

    from paddle_tpu import tune
    cache_path = os.path.join(tempfile.mkdtemp(prefix="pt_autotune_row_"),
                              "autotune.json")
    prev = os.environ.get(tune.CACHE_ENV)
    os.environ[tune.CACHE_ENV] = cache_path
    tune.reset()
    try:
        report = tune.run_tune(spaces=("fused_rnn", "decode_route"),
                               cache_path=cache_path)
        rows: List[dict] = []
        for r in report["results"]:
            if r["space"] == "fused_rnn":
                if r.get("plan") is None:
                    continue
                tuned_s = (r["tuned_ms"] or 0.0) / 1e3
                row = {
                    "metric": (f"fused_rnn_train_autotune_"
                               f"{r['kernel']}_{r['family']}"),
                    "value": r.get("speedup"),
                    "unit": "x_tuned_vs_heuristic",
                    "vs_baseline": None,
                    "plan_source": "tuned",
                    "note": {
                        "tuned_plan": r["plan"],
                        "heuristic_plan": r.get("heuristic_plan"),
                        "tuned_ms": r.get("tuned_ms"),
                        "heuristic_ms": r.get("heuristic_ms"),
                        "candidates": r.get("candidates"),
                        "family_note": r.get("note"),
                        "backend": report["backend"],
                        "device_kind": report["device_kind"],
                    },
                }
                # mfu is an honest null here: the row's value is a RATIO
                # of two measured times of the same kernel, not a
                # throughput (methodology stays "measured")
                rows.append(attach_mfu(row, None, max(tuned_s, 1e-9)))
            elif r["space"] == "decode_route":
                row = {
                    "metric": "autotune_decode_route_crossover",
                    "value": r["plan"].get("kernel_min_len"),
                    "unit": "min_kernel_len_tokens",
                    "vs_baseline": None,
                    "plan_source": "tuned",
                    "methodology": "measured",
                    "note": {
                        "sweep": r.get("sweep"),
                        "heuristic_plan": r.get("heuristic_plan"),
                        "family_note": r.get("note"),
                        "backend": report["backend"],
                        "device_kind": report["device_kind"],
                    },
                }
                rows.append(attach_hbm_bw(row, None, 1.0,
                                          methodology="measured"))
        return rows
    finally:
        if prev is None:
            os.environ.pop(tune.CACHE_ENV, None)
        else:
            os.environ[tune.CACHE_ENV] = prev
        tune.reset()


if __name__ == "__main__":
    import json
    for row in run():
        print(json.dumps(row))
