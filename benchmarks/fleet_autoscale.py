"""Fleet-actor chaos benchmark: kill half the decode pool, measure how
many alert windows the actor needs to restore membership AND the SLO.

This is ISSUE 18's chaos bar as a number. The simulation is fake-clock
end to end (no real sleeps, fully deterministic) but every control-plane
component is the REAL one:

* a real :class:`MembershipService` is the decode pool's lease table —
  the kill is modeled exactly like ``kill -9`` (heartbeats stop, the TTL
  lease evicts the corpses);
* a real :class:`ClusterAggregator` carries the PR 15 burn-rate alert
  engine with ``serving_slo_rules`` parameterized to short windows, fed
  cumulative ``serving.ttft_seconds`` histograms (survivors of the kill
  are overloaded — every observation lands over the SLO bucket until
  the pool is back at target);
* a real :class:`FleetActor` with a :class:`HookSpawnBackend` closes the
  loop: spawned replacements "boot" for ``BOOT_S`` fake seconds, then
  join membership and start answering healthy.

The row's oracle is the alert TRANSITION stream: exactly one fire and
one resolve per degraded series — any extra fire is flapping and fails
the run (``flaps`` column). ``recovery_windows`` counts short alert
windows from the kill to the last resolve; ``slo_recovered`` is the
recovered-or-it-does-not-count bit the ``_fleet_`` schema family makes
mandatory (analysis/bench_schema.py).
"""
from __future__ import annotations

import math

TICK_S = 5.0          # control/telemetry cadence
SHORT_S = 60.0        # burn-rate short window == one "alert window"
LONG_S = 180.0        # burn-rate long window
TTFT_SLO_S = 1.0      # SLO bucket boundary the good/bad split keys on
POOL = 4              # decode pool target size
KILLED = 2            # kill -9 half of it
BOOT_S = 30.0         # spawn -> joined-membership latency of a replacement
OBS_PER_TICK = 20     # requests each live worker answers per tick
T_KILL = 200.0        # warmup before the kill (fills both windows)
T_END = 800.0         # simulation horizon


def _ttft_hist(good: int, total: int):
    """Cumulative TTFT histogram snapshot: ``good`` observations under
    the SLO bucket, the rest only in +Inf (over-SLO)."""
    return {"type": "histogram", "name": "serving.ttft_seconds",
            "labels": {}, "count": total, "sum": 0.25 * total,
            "buckets": [[0.5, good], ["+Inf", total]]}


def run(pool: int = POOL, killed: int = KILLED):
    from paddle_tpu.cluster import FleetActor, HookSpawnBackend, Population
    from paddle_tpu.obs.aggregate import ClusterAggregator
    from paddle_tpu.obs.alerts import serving_slo_rules
    from paddle_tpu.runtime.membership import MembershipService

    clock = [0.0]
    agg = ClusterAggregator(
        clock=lambda: clock[0], window_s=LONG_S + SHORT_S,
        rules=serving_slo_rules(ttft_slo_s=TTFT_SLO_S,
                                short_s=SHORT_S, long_s=LONG_S),
        eval_interval_s=1e9)          # evaluated manually, once per tick
    ms = MembershipService(ttl=12.0, clock=lambda: clock[0])

    alive = {}                        # worker -> membership token
    counts = {}                       # worker -> (good, total) cumulative
    booting = []                      # (worker, ready_ts)

    def spawn_fn(worker, population):
        booting.append((worker, clock[0] + BOOT_S))

    def drain_fn(handle):
        tok = alive.pop(handle.worker, None)
        if tok is not None:
            ms.leave(handle.worker, tok)

    def alive_fn(handle):
        return handle.worker in alive or \
            any(w == handle.worker for w, _ in booting)

    def probe():
        return {"members": ms.view()["members"], "recommendation": None,
                "alerts": [str(a.get("rule"))
                           for a in agg.alerts.active()],
                "busy": True}

    actor = FleetActor(
        [Population("decode",
                    backend=HookSpawnBackend(spawn_fn, drain_fn,
                                             kill_fn=drain_fn,
                                             alive_fn=alive_fn),
                    probe=probe, min_workers=1, max_workers=pool + 2,
                    target=pool)],
        clock=lambda: clock[0], cooldown_s=2 * TICK_S, max_churn=killed,
        spawn_grace_s=3 * BOOT_S, drain_grace_s=60.0)

    for i in range(pool):
        tok, _ = ms.join(f"decode-{i}", caps={"role": "decode"})
        alive[f"decode-{i}"] = tok

    did_kill = False
    while clock[0] < T_END:
        clock[0] += TICK_S
        now = clock[0]
        for w, ready in list(booting):
            if ready <= now:          # replacement finished booting
                booting.remove((w, ready))
                tok, _ = ms.join(w, caps={"role": "decode"})
                alive[w] = tok
        if not did_kill and now >= T_KILL:
            did_kill = True           # kill -9: heartbeats just stop
            for w in sorted(alive)[-killed:]:
                del alive[w]
                del counts[w]
        for w in ms.expire(now):      # the TTL lease reaps the corpses
            agg.forget_worker(w)      # (the attached master does this too)
        degraded = len(alive) < pool  # survivors overloaded while short
        for w, tok in sorted(alive.items()):
            ms.heartbeat(w, tok)
            good, total = counts.get(w, (0, 0))
            total += OBS_PER_TICK
            good += 0 if degraded else OBS_PER_TICK
            counts[w] = (good, total)
            agg.push(w, [_ttft_hist(good, total)])
        agg.evaluate(now)
        actor.step(now)

    fired, resolved = {}, {}          # (rule, worker) -> [ts, ...]
    flaps = 0
    for ev in agg.alerts.events:
        a = ev.get("args", {})
        key = (a.get("rule"), a.get("worker"))
        if a.get("state") == "fired":
            fired.setdefault(key, []).append(ev["ts"])
            if len(fired[key]) > 1:
                flaps += 1            # a series re-firing IS flapping
        elif a.get("state") == "resolved":
            resolved.setdefault(key, []).append(ev["ts"])
    t_resolved = max((ts[-1] for ts in resolved.values()), default=None)
    recovered = bool(fired) and set(fired) == set(resolved) \
        and not agg.alerts.active() and len(alive) >= pool and flaps == 0
    windows = (math.ceil((t_resolved - T_KILL) / SHORT_S)
               if recovered and t_resolved is not None else None)
    journal = list(actor.journal)

    def n(action):
        return sum(1 for e in journal if e["action"] == action)

    return {"metric": "cluster_fleet_autoscale_recovery",
            "value": float(windows) if windows is not None else None,
            "unit": f"alert_windows({SHORT_S:.0f}s)",
            "vs_baseline": None,
            "recovery_windows": windows,
            "slo_recovered": recovered,
            "recovery_s": (round(t_resolved - T_KILL, 1)
                           if t_resolved is not None else None),
            "pool": pool, "killed": killed, "boot_s": BOOT_S,
            "fired": sum(len(v) for v in fired.values()),
            "resolved": sum(len(v) for v in resolved.values()),
            "flaps": flaps,
            "spawns": n("spawn"), "drains": n("drain"),
            "evictions": n("evict"), "spawn_failures": n("spawn_failed"),
            "methodology": "measured",  # real actor/alert/lease planes,
            "note": "fake-clock chaos: kill -9 half the decode pool "
                    "(heartbeats stop, TTL lease evicts), survivors burn "
                    "the TTFT budget, the fleet actor respawns to target "
                    "through the hook backend; windows counted from kill "
                    "to the last burn-rate resolve, zero flapping "
                    "required"}


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    print(json.dumps(run()), flush=True)
