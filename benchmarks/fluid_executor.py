"""Fluid Executor hot-loop throughput — the gen-2 execution plane's row.

Every other bench row drives raw jax or the Trainer's fused step; this row
drives the *fluid Executor* the way the book tests and the v2-on-fluid path
do — ``exe.run()`` in a loop — so the executor fast path (buffer donation,
device-resident scope, ``return_numpy=False``, bounded compiled-fn LRU;
docs/design/executor_perf.md) finally has a perf trajectory like the
trainer rows.

Methodology: fixed-shape MLP classification step (fc 784-256-64-10 + Adam),
bs=256.  Warmup pays the trace+compile, then a timed loop of ``iters``
steps with ``return_numpy=False`` — the host syncs exactly once, on the
final loss read, so the number measures the executor dispatch path rather
than per-step host round-trips.  The JSON note carries the cache hit rate,
the compile count observed *inside* the timed window (must be 0 — a
recompile here is a cache regression), and donated MB, so a regression in
any of the three is visible in the row itself.
"""

from __future__ import annotations

import time

import numpy as np


def _counter_total(reg, name: str) -> float:
    """Sum a counter across its label sets (hit/miss carry `bucketed`)."""
    return sum(v for _, v in reg.counter(name).samples())


def run(iters: int = 200, batch: int = 256):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import obs

    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.executor.Scope()
    img = fluid.layers.data("img", shape=(784,))
    label = fluid.layers.data("label", shape=(), dtype="int32")
    h1 = fluid.layers.fc(img, 256, act="relu")
    h2 = fluid.layers.fc(h1, 64, act="relu")
    logits = fluid.layers.fc(h2, 10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.AdamOptimizer(1e-3).minimize(loss)

    # bench.py's watchdog prelude installs a session per row; standalone
    # invocation (python -c "...fluid_executor.run()") brings its own
    session = obs.session()
    own = None
    if session is None:
        own = obs.ObsSession(registry=obs.MetricsRegistry()).install()
        session = own
    reg = session.registry
    try:
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rs = np.random.RandomState(0)
        feed = {"img": rs.randn(batch, 784).astype(np.float32),
                "label": rs.randint(0, 10, size=batch).astype(np.int32)}
        out = None
        for _ in range(3):            # warmup: trace + XLA compile
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
        np.asarray(out[0])
        c0 = _counter_total(reg, "jax.compiles_total")
        h0 = _counter_total(reg, "fluid.cache_hits_total")
        m0 = _counter_total(reg, "fluid.cache_misses_total")
        t0 = time.perf_counter()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
        final = float(np.asarray(out[0]))   # the ONE host sync ends timing
        dt = time.perf_counter() - t0
        hits = _counter_total(reg, "fluid.cache_hits_total") - h0
        misses = _counter_total(reg, "fluid.cache_misses_total") - m0
        compiles = _counter_total(reg, "jax.compiles_total") - c0
        donated = _counter_total(reg, "fluid.donated_bytes_total")
    finally:
        if own is not None:
            own.uninstall()
    return {"metric": f"fluid_executor_mlp_steps_per_sec_bs{batch}",
            "value": round(iters / dt, 1), "unit": "steps/s",
            "vs_baseline": None,
            "note": {"cache_hit_rate":
                     round(hits / max(hits + misses, 1), 4),
                     "timed_compiles": int(compiles),
                     "donated_mb": round(donated / 1e6, 2),
                     "final_loss": round(final, 4)}}
