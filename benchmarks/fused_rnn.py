"""Fused Pallas LSTM training path vs the lax.scan path — same model, same
data, full train step (fwd + hand-written backward kernel + Adam).

The reference ran its fused hl_lstm kernels in TRAINING
(cuda/src/hl_cuda_lstm.cu, hl_lstm_parallel_backward_data/_weight); this
bench is the evidence for whether the TPU analog (whole-sequence recurrence
in VMEM, ops/pallas_kernels.py lstm_sequence_fused(+_bwd)) beats XLA's scan
on this chip, and by how much. The flagship lstm_textcls shape is used so
the result transfers directly to the headline metric.

Timing: identical methodology to lstm_textcls (chained on-device fori_loop,
short/long differencing, rotating staged batches).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

VOCAB = 30000
EMBED = 128
HIDDEN = 256
SEQ_LEN = 100
MIN_LEN = 30
BATCH = 64
NBUF = 8


def build(fused: bool):
    from paddle_tpu.core import SeqBatch
    from paddle_tpu.models import LSTMTextCls
    from paddle_tpu.optimizer import Adam

    class LastSeqLSTM(LSTMTextCls):
        def __call__(self, params, batch, **kw):
            from paddle_tpu.ops import rnn as R
            from paddle_tpu.ops import sequence as S
            x = self.embed(params["embed"], batch.data)
            h = x
            for i in range(self.num_layers):
                h, _ = R.lstm(h, batch.lengths, params[f"w{i}"],
                              params[f"u{i}"], params[f"b{i}"],
                              forget_bias=1.0, fused=fused)
            return self.fc(params["fc"],
                           S.sequence_last_step(h, batch.lengths))

    model = LastSeqLSTM(VOCAB, embed_dim=EMBED, hidden=HIDDEN, classes=2)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(2e-3)
    state = opt.init(params)

    def step_fn(params, state, data, lengths, labels):
        sb = SeqBatch(data, lengths)
        loss, grads = jax.value_and_grad(model.loss)(params, sb, labels)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, data, lengths, labels, n):
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            d = jax.lax.dynamic_index_in_dim(data, j, 0, keepdims=False)
            ln = jax.lax.dynamic_index_in_dim(lengths, j, 0, keepdims=False)
            lb = jax.lax.dynamic_index_in_dim(labels, j, 0, keepdims=False)
            return step_fn(params, state, d, ln, lb)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randint(0, VOCAB, (NBUF, BATCH, SEQ_LEN)), jnp.int32)
    lengths = jnp.asarray(rs.randint(MIN_LEN, SEQ_LEN + 1, (NBUF, BATCH)),
                          jnp.int32)
    labels = jnp.asarray(rs.randint(0, 2, (NBUF, BATCH)), jnp.int32)
    return run_n, params, state, (data, lengths, labels)


def _time_path(fused: bool, iters: int, repeats: int) -> float:
    from benchmarks.timing import chained_ms_per_step

    run_n, params, state, batch = build(fused)
    return chained_ms_per_step(run_n, (params, state) + batch, iters,
                               repeats, short=2)


def run(iters: int = 100, repeats: int = 3):
    from paddle_tpu.ops.rnn import _fused_bwd_plan, _fused_plan

    scan_ms = _time_path(False, iters, repeats)
    fused_ms = _time_path(True, iters, repeats)
    return {"metric": "lstm_fused_vs_scan_train_speedup_bs64_h256_len30-100",
            "value": round(scan_ms / fused_ms, 3), "unit": "x (scan_ms/fused_ms)",
            "vs_baseline": None,
            "scan_ms": round(scan_ms, 3), "fused_ms": round(fused_ms, 3),
            "fwd_plan": _fused_plan(SEQ_LEN, HIDDEN, seq_h_units=6,
                                    batch=BATCH),
            "bwd_plan": _fused_bwd_plan(SEQ_LEN, HIDDEN, 4, 11, BATCH),
            "note": "full train step; fused = Pallas fwd + hand bwd "
                    "kernels under the ISSUE 7 wide-tile (block_b, "
                    "chunk_t) plans — this row is the on-chip re-measure "
                    "of the old blk=8 crossover (docs/design/kernels.md)"}


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
