"""Host-offloaded embedding streaming throughput — the >HBM sparse path
(trainer/RemoteParameterUpdater.h:265 SparseRemoteParameterUpdater role).

The table (default 20M x 256 f32 = 20.5 GB) is DELIBERATELY larger than a
v5e chip's 16 GB HBM: it lives in host RAM inside the native HostOptimizer;
each step streams only the batch's unique touched rows to the device (bf16,
halving wire bytes), computes grads, and applies a sparse row update on
host. The prefetcher overlaps the next batch's gather/H2D with device
compute, with post-update intersection fix-up (exactness proven in
tests/test_host_embedding.py).

On this rig the host->device link is a ~24 MB/s remote tunnel, so the
streamed MB/s is printed next to the rate: the row shows the framework
saturating whatever link it is given (a local PCIe/ICI host moves the same
protocol at GB/s).
"""

from __future__ import annotations

import time

import numpy as np

VOCAB = 20_000_000
DIM = 256
BATCH_IDS = 8192
STEPS = 6


def run(vocab: int = VOCAB, dim: int = DIM, batch_ids: int = BATCH_IDS,
        steps: int = STEPS) -> dict:
    import jax
    import jax.numpy as jnp

    from paddle_tpu.runtime import HostEmbeddingTable, HostEmbedPrefetcher

    table_gb = vocab * dim * 4 / 1e9
    # zeros init: the bench measures streaming, not init; the native
    # zero-fill path makes the 20 GB table one allocation (no numpy
    # source buffer + memcpy, which used to cost ~90 s alone)
    table = HostEmbeddingTable(
        vocab, dim, optimizer="sgd", lr=0.01, capacity=batch_ids,
        compute_dtype=jnp.bfloat16, init="zeros")

    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.standard_normal((dim,)).astype(np.float32))

    def loss(rows, inverse, w):
        e = HostEmbeddingTable.lookup(rows, inverse)
        return jnp.sum(jnp.tanh(e @ w.astype(rows.dtype)).astype(jnp.float32))

    grad_fn = jax.jit(jax.grad(loss))

    def ids_stream(n):
        for i in range(n):
            yield np.random.RandomState(i).randint(0, vocab, (batch_ids,))

    # warmup: compile + first gather
    pf = HostEmbedPrefetcher(table, ids_stream(2))
    b = pf.next()
    pf.commit(b, grad_fn(b.rows, b.inverse, w))
    b = pf.next()
    pf.commit(b, grad_fn(b.rows, b.inverse, w))

    pf = HostEmbedPrefetcher(table, ids_stream(steps))
    t0 = time.perf_counter()
    n = 0
    while True:
        b = pf.next()
        if b is None:
            break
        pf.commit(b, grad_fn(b.rows, b.inverse, w))
        n += 1
    dt = (time.perf_counter() - t0) / n
    # wire bytes: rows down (bf16) + grads up (bf16 on device -> fetched)
    stream_mb = (batch_ids * dim * 2 * 2) / 1e6
    return {"metric": f"host_offload_embedding_ids_per_sec_"
                      f"{vocab // 1_000_000}Mx{dim}_bs{batch_ids}",
            "value": round(batch_ids / dt, 1), "unit": "ids/sec",
            "vs_baseline": None,
            "ms_per_step": round(dt * 1e3, 1),
            "table_gb": round(table_gb, 1), "hbm_gb": 16,
            "streamed_mb_per_sec": round(stream_mb / dt, 1),
            "note": "20.5 GB table in host RAM (> one chip's 16 GB HBM), "
                    "touched rows streamed bf16 with overlapped prefetch; "
                    "host link here is a ~24 MB/s remote tunnel — the "
                    "MB/s column shows the link, not the protocol, binding"}


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
