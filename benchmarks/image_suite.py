"""AlexNet / GoogleNet / SmallNet ms/batch — every published single-GPU row
of the reference's benchmark table (benchmark/README.md:36-60):

| model | batch sizes | K40m ms/batch |
|---|---|---|
| AlexNet | 64/128/256/512 | 195 / 334 / 602 / 1629 |
| GoogleNet | 64/128/256 | 613 / 1149 / 2348 |
| SmallNet (cifar-quick) | 64/128/256/512 | 10.463 / 18.184 / 33.113 / 63.039 |

Config parity: benchmark/paddle/image/{alexnet,googlenet,smallnet_mnist_cifar}.py
— SGD momentum 0.9, softmax loss, training mode with dropout/LRN/aux-towers
live (GoogleNet trains with both auxiliary losses at 0.3, AlexNet with both
0.5 dropouts; per-step PRNG folded from the loop counter so every step drops
differently). Same honest-bench methodology as the other benches: rotating
device-staged distinct batches, N chained steps in one on-device fori_loop,
short/long differencing. bf16 matmul compute with f32 params, the
TPU-idiomatic mixed precision (the K40m numbers are f32 — noted in the
record).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# (model_key, batch, reference_ms)  — benchmark/README.md:36-60
ROWS = [
    ("smallnet", 64, 10.463), ("smallnet", 128, 18.184),
    ("smallnet", 256, 33.113), ("smallnet", 512, 63.039),
    ("alexnet", 64, 195.0), ("alexnet", 128, 334.0),
    ("alexnet", 256, 602.0), ("alexnet", 512, 1629.0),
    ("googlenet", 64, 613.0), ("googlenet", 128, 1149.0),
    ("googlenet", 256, 2348.0),
]

NBUF = 4


def _make(model_key: str):
    from paddle_tpu.models import AlexNet, GoogleNet, SmallNet
    if model_key == "smallnet":
        return SmallNet(classes=10), 32, 10
    if model_key == "alexnet":
        return AlexNet(classes=1000), 224, 1000
    if model_key == "googlenet":
        return GoogleNet(classes=1000), 224, 1000
    raise KeyError(model_key)


def build(model_key: str, batch: int, bf16: bool = True):
    from paddle_tpu.optimizer import Momentum

    model, image, classes = _make(model_key)
    params = model.init(jax.random.PRNGKey(0))
    opt = Momentum(0.01, momentum=0.9)
    state = opt.init(params)
    takes_rng = model_key in ("alexnet", "googlenet")

    def loss_fn(params, x, y, rng):
        kw = {"train": True, "rng": rng} if takes_rng else {}
        if bf16:
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a, params)
            return model.loss(p16, x.astype(jnp.bfloat16), y,
                              **kw).astype(jnp.float32)
        return model.loss(params, x, y, **kw)

    def step_fn(params, state, x, y, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y, rng)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    key = jax.random.PRNGKey(7)

    @jax.jit
    def run_n(params, state, xs, ys, n):
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            x = jax.lax.dynamic_index_in_dim(xs, j, 0, keepdims=False)
            y = jax.lax.dynamic_index_in_dim(ys, j, 0, keepdims=False)
            return step_fn(params, state, x, y, jax.random.fold_in(key, i))
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    xs = jnp.asarray(rs.rand(NBUF, batch, image, image, 3), jnp.float32)
    ys = jnp.asarray(rs.randint(0, classes, (NBUF, batch)), jnp.int32)
    return run_n, step_fn, params, state, (xs, ys), key


def bench_row(model_key: str, batch: int, ref_ms: float,
              iters: int = 20, repeats: int = 2) -> dict:
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, b, key = build(model_key, batch)
    ms = chained_ms_per_step(run_n, (params, state) + b, iters, repeats)
    flops = step_flops(step_fn, params, state, b[0][0], b[1][0], key)
    return attach_mfu(
        {"metric": f"{model_key}_train_ms_per_batch_bs{batch}",
         "value": round(ms, 3), "unit": "ms/batch",
         "vs_baseline": round(ref_ms / ms, 2),
         "note": f"K40m {ref_ms} ms (benchmark/README.md:36-60); "
                 "bf16 compute, train mode (dropout/LRN/aux live)"},
        flops, ms / 1e3)


def run_all(rows=None):
    out = []
    for model_key, batch, ref_ms in (rows or ROWS):
        out.append(bench_row(model_key, batch, ref_ms))
    return out


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for rec in run_all():
        print(json.dumps(rec), flush=True)
