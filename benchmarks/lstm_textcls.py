"""LSTM text-classification benchmark — the reference's published RNN baseline.

Exact config of ``benchmark/paddle/rnn/rnn.py``: vocab 30000, embedding 128,
1x LSTM hidden 256, last-seq pool, fc softmax-2, Adam, padded length 100,
batch 64. Published number: 83 ms/batch on 1x K40m
(benchmark/README.md:115-119).

Methodology (honest-bench notes):
* Lengths VARY per sample (uniform 30..100, IMDB-like), so the masked
  variable-length path — the whole point of the LoD story — does real work
  every step. The reference's IMDB runs were variable-length too (padding-free
  LoD batching), so this is the comparable configuration.
* Eight distinct batches are staged on device and rotated through the loop so
  no step reuses the previous step's data.
* Timing: N chained training steps in ONE on-device ``fori_loop`` dispatch,
  short/long-loop differencing to cancel the remote-tunnel dispatch latency.

Measures the full training step (fwd+bwd+Adam update) steady-state ms/batch on
the default jax device; ``vs_baseline`` = reference_ms / our_ms (>1 == faster).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

VOCAB = 30000
EMBED = 128
HIDDEN = 256
SEQ_LEN = 100
MIN_LEN = 30
BATCH = 64
NBUF = 8          # distinct staged batches rotated through the loop
BASELINE_MS = 83.0


def build(batch_size: int = BATCH, hidden: int = HIDDEN):
    from paddle_tpu.core import SeqBatch
    from paddle_tpu.models import LSTMTextCls
    from paddle_tpu.optimizer import Adam

    class LastSeqLSTM(LSTMTextCls):
        """rnn.py uses last_seq, not max pool."""

        def __call__(self, params, batch, **kw):
            from paddle_tpu.ops import rnn as R
            from paddle_tpu.ops import sequence as S
            x = self.embed(params["embed"], batch.data)
            h = x
            for i in range(self.num_layers):
                h, _ = R.lstm(h, batch.lengths, params[f"w{i}"],
                              params[f"u{i}"], params[f"b{i}"], forget_bias=1.0)
            return self.fc(params["fc"], S.sequence_last_step(h, batch.lengths))

    model = LastSeqLSTM(VOCAB, embed_dim=EMBED, hidden=hidden, classes=2)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(2e-3)
    state = opt.init(params)

    def loss_fn(params, sb, labels):
        # bf16 compute, f32 master params/Adam — same mixed precision as the
        # image/NMT benches (MXU-native; the K40m row is f32, noted in the
        # record)
        p16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        return model.loss(p16, sb, labels).astype(jnp.float32)

    def step_fn(params, state, data, lengths, labels):
        sb = SeqBatch(data, lengths)
        loss, grads = jax.value_and_grad(loss_fn)(params, sb, labels)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, data, lengths, labels, n):
        # n chained steps in ONE dispatch, rotating over NBUF distinct staged
        # batches: timing is device compute, immune to the remote-tunnel
        # per-call dispatch latency, and no step sees repeated data
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            d = jax.lax.dynamic_index_in_dim(data, j, 0, keepdims=False)
            ln = jax.lax.dynamic_index_in_dim(lengths, j, 0, keepdims=False)
            lb = jax.lax.dynamic_index_in_dim(labels, j, 0, keepdims=False)
            return step_fn(params, state, d, ln, lb)
        loss0 = jnp.float32(0)
        return jax.lax.fori_loop(0, n, body, (params, state, loss0))

    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randint(0, VOCAB, (NBUF, batch_size, SEQ_LEN)),
                       jnp.int32)
    lengths = jnp.asarray(rs.randint(MIN_LEN, SEQ_LEN + 1, (NBUF, batch_size)),
                          jnp.int32)
    labels = jnp.asarray(rs.randint(0, 2, (NBUF, batch_size)), jnp.int32)
    return run_n, step_fn, params, state, (data, lengths, labels)


# metric key carries the methodology (len30-100 varied) — renamed from the
# round-1 all-len-100 key so trend tracking can't silently mix semantics.
# bench.py imports this for its killed-before-measurement null row, so the
# key lives in ONE place.
FLAGSHIP_METRIC = "lstm_textcls_train_ms_per_batch_bs64_h256_len30-100"


def run(iters: int = 100, repeats: int = 3):
    """Difference a short and a long on-device loop so the fixed dispatch +
    host-fetch latency (large under the remote tunnel, where block_until_ready
    is unreliable) cancels; float(loss) forces completion."""
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, batch = build()
    ms = chained_ms_per_step(run_n, (params, state) + batch, iters, repeats,
                             short=2)
    flops = step_flops(step_fn, params, state, batch[0][0], batch[1][0],
                       batch[2][0])
    return attach_mfu(
        {"metric": FLAGSHIP_METRIC,
         "value": round(ms, 3), "unit": "ms/batch",
         "vs_baseline": round(BASELINE_MS / ms, 3),
         "note": "varied lengths 30..100, 8 distinct rotating batches; "
                 "bf16 compute vs the K40m's f32"},
        flops, ms / 1e3)


# every published LSTM row of benchmark/README.md:115-134 beyond the
# flagship (bs, hidden) -> K40m ms/batch
SUITE_ROWS = [
    (64, 512, 184.0), (64, 1280, 641.0),
    (128, 256, 110.0), (128, 512, 261.0), (128, 1280, 1007.0),
    (256, 256, 170.0), (256, 512, 414.0), (256, 1280, 1655.0),
]


def bench_row(batch_size: int, hidden: int, ref_ms: float,
              iters: int = 60, repeats: int = 2) -> dict:
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, b = build(batch_size, hidden)
    ms = chained_ms_per_step(run_n, (params, state) + b, iters, repeats,
                             short=2)
    flops = step_flops(step_fn, params, state, b[0][0], b[1][0], b[2][0])
    return attach_mfu(
        {"metric": f"lstm_textcls_train_ms_per_batch_bs{batch_size}"
                   f"_h{hidden}_len30-100",
         "value": round(ms, 3), "unit": "ms/batch",
         "vs_baseline": round(ref_ms / ms, 3),
         "note": f"K40m {ref_ms} ms (benchmark/README.md:115-134); varied "
                 "lengths 30..100, bf16 compute vs the K40m's f32"},
        flops, ms / 1e3)


def run_suite(rows=None):
    for batch_size, hidden, ref_ms in (rows or SUITE_ROWS):
        yield bench_row(batch_size, hidden, ref_ms)


if __name__ == "__main__":
    import json
    for rec in run_suite():
        print(json.dumps(rec), flush=True)
    print(json.dumps(run()))
