"""Model-FLOP accounting for the benchmark harness.

Every bench metric reports ``mfu`` (model FLOPs utilization): the training
step's FLOPs — XLA's own cost analysis of the compiled step HLO — divided by
measured step time and the chip's peak. The reference never measured this
(its README reports raw ms/batch, benchmark/README.md); on TPU it is the
number that says whether a throughput is actually good, so the harness
carries it next to every throughput figure.

Notes on methodology:
* FLOPs come from ``compiled.cost_analysis()['flops']`` of ONE training
  step (fwd + bwd + optimizer). Pallas custom calls report zero flops to
  XLA, so benches that route through hand kernels must cost-analyze the
  numerically identical non-Pallas step (same model math) and reuse that
  count for both paths.
* Peak is the chip's dense peak for the matmul precision actually used,
  from a device_kind table (v5e: 197 bf16 TFLOP/s; bf16 and f32 share the
  MXU peak via XLA's f32-as-3-bf16-passes, so f32 workloads are reported
  against the same ceiling with the convention noted in the JSON).
  Override with PADDLE_TPU_PEAK_TFLOPS for new chips.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

# dense bf16 peak TFLOP/s by jax device_kind
_PEAK_TFLOPS = {
    "TPU v5 lite": 197.0,       # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,            # v5p
    "TPU v4": 275.0,
    "TPU v6 lite": 918.0,       # v6e / Trillium
    "cpu": None,
}


def peak_flops_per_sec() -> Optional[float]:
    """Chip peak in FLOP/s, or None when unknown (mfu omitted then)."""
    env = os.environ.get("PADDLE_TPU_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = jax.devices()[0].device_kind
    tf = _PEAK_TFLOPS.get(kind)
    return None if tf is None else tf * 1e12


def step_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one call of ``fn(*args)`` per XLA cost analysis."""
    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca["flops"])
        return flops if flops > 0 else None
    except Exception:
        return None


def attach_mfu(result: dict, flops_per_step: Optional[float],
               sec_per_step: float) -> dict:
    """Add mfu + gflops_per_step fields to a bench JSON record.

    ``mfu`` is ALWAYS present — null when the chip peak or the step FLOPs
    are unknown (off-TPU hosts) — per the bench-row schema
    (benchmarks/schema.py): a missing roofline column reads as a tooling
    bug, an explicit null as an honest unknown."""
    result.setdefault("mfu", None)
    if flops_per_step:
        result["gflops_per_step"] = round(flops_per_step / 1e9, 2)
        peak = peak_flops_per_sec()
        if peak:
            mfu = flops_per_step / sec_per_step / peak
            if mfu > 1.0:
                # physically impossible: the timing collapsed (window below
                # the noise floor) — flag it rather than publish nonsense
                result["mfu"] = None
                result["timing_suspect"] = round(mfu, 2)
            else:
                result["mfu"] = round(mfu, 4)
            result["peak_tflops"] = round(peak / 1e12, 1)
    return result
