"""Roofline accounting for the benchmark harness — a thin veneer over
``paddle_tpu.obs.roofline``, the ONE resolution path for FLOPs / HBM
bytes / chip peaks.

Every bench metric reports ``mfu`` (model FLOPs utilization): the training
step's FLOPs — XLA's own cost analysis of the compiled step HLO — divided by
measured step time and the chip's peak. Decode/serving rows report
``hbm_bw_util`` the same way against the chip's HBM ceiling. The reference
never measured either (its README reports raw ms/batch); on TPU they are
the numbers that say whether a throughput is actually good, so the harness
carries them next to every throughput figure.

Notes on methodology:
* FLOPs/bytes come from ``compiled.cost_analysis()`` of ONE step
  (fwd + bwd + optimizer). Pallas custom calls report zero to XLA, so
  benches that route through hand kernels resolve the kernel's modeled
  bytes through ``roofline.kernel_cost`` — the same registry the live
  ``fluid.device_bytes_total`` accounting uses, so bench rows and live
  gauges can never disagree on methodology.
* Peaks come from ``roofline.PEAK_TFLOPS`` / ``roofline.PEAK_HBM_GBPS``
  by jax device_kind (bf16 and f32 share the MXU peak via XLA's
  f32-as-3-bf16-passes; the convention is noted in the JSON). Override
  with PADDLE_TPU_PEAK_TFLOPS / PADDLE_TPU_PEAK_HBM_GBPS for new chips.
* A broken cost analysis warns once per process and counts
  ``roofline.cost_analysis_failures_total`` (an installed obs session
  sees it); the derived column is an explicit null, never a silent one.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.obs import roofline

# the peak tables live in ONE place now; these aliases keep the bench
# modules' historical import surface working
peak_flops_per_sec = roofline.peak_flops_per_sec
peak_hbm_bytes_per_sec = roofline.peak_hbm_bytes_per_sec
_PEAK_TFLOPS = roofline.PEAK_TFLOPS


def _plan_source() -> str:
    """The row's ``plan_source`` stamp (bench-row schema): "tuned" when
    the process's kernel-plan consults can resolve against a loaded
    autotune cache, else "heuristic" (paddle_tpu.tune owns the check)."""
    from paddle_tpu import tune
    return tune.plan_source()


def step_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one call of ``fn(*args)`` per XLA cost analysis — None is
    an honest unknown (the failure warned once and was counted, see
    roofline.cost_failure; the old version swallowed every exception into
    a silent None)."""
    cost = roofline.analyze_fn(fn, *args, where="benchmarks.mfu.step_flops",
                               **kwargs)
    return cost.flops if cost is not None else None


def step_bytes(fn, *args, **kwargs) -> Optional[float]:
    """HBM bytes accessed by one call of ``fn(*args)`` per XLA cost
    analysis — the numerator of a measured ``hbm_bw_util``. Kernel-routed
    steps add ``roofline.kernel_cost(...)`` on top (XLA sees zero bytes
    for Pallas custom calls)."""
    cost = roofline.analyze_fn(fn, *args, where="benchmarks.mfu.step_bytes",
                               **kwargs)
    return cost.bytes if cost is not None else None


def attach_mfu(result: dict, flops_per_step: Optional[float],
               sec_per_step: float) -> dict:
    """Add mfu + gflops_per_step fields to a bench JSON record.

    ``mfu`` is ALWAYS present — null when the chip peak or the step FLOPs
    are unknown (off-TPU hosts) — per the bench-row schema
    (benchmarks/schema.py): a missing roofline column reads as a tooling
    bug, an explicit null as an honest unknown.

    ``methodology`` defaults to "measured" — attach_mfu's FLOPs come from
    XLA's cost analysis of the real compiled step over a real timing;
    pre-set the key to "modeled" before calling when the FLOPs are a hand
    projection. ``plan_source`` defaults to
    ``paddle_tpu.tune.plan_source()`` — "tuned" when an autotune cache
    with current-hash entries for this device_kind was consultable during
    the row, "heuristic" otherwise; pre-set the key to pin it."""
    result.setdefault("mfu", None)
    result.setdefault("methodology", "measured")
    result.setdefault("plan_source", _plan_source())
    if flops_per_step:
        result["gflops_per_step"] = round(flops_per_step / 1e9, 2)
        peak = peak_flops_per_sec()
        if peak:
            mfu = flops_per_step / sec_per_step / peak
            if mfu > 1.0:
                # physically impossible: the timing collapsed (window below
                # the noise floor) — flag it rather than publish nonsense
                result["mfu"] = None
                result["timing_suspect"] = round(mfu, 2)
            else:
                result["mfu"] = round(mfu, 4)
            result["peak_tflops"] = round(peak / 1e12, 1)
    return result


def attach_hbm_bw(result: dict, bytes_per_step: Optional[float],
                  sec_per_step: float, *,
                  methodology: Optional[str] = None) -> dict:
    """The ``hbm_bw_util`` twin of :func:`attach_mfu` — same null
    semantics, same one-owner derivation (bytes / time / chip HBM peak,
    ``roofline.peak_hbm_bytes_per_sec``), so a decode row's bandwidth
    figure and the live ``roofline.hbm_bw_util`` gauge can never diverge
    on formula. ``methodology`` stamps the row "measured" (on-chip
    timing) or "modeled" (projected bytes over an analytic model) — the
    bench-row schema requires the field on rows carrying roofline
    columns."""
    result.setdefault("hbm_bw_util", None)
    result.setdefault("plan_source", _plan_source())
    if methodology is not None:
        result["methodology"] = methodology
    if bytes_per_step:
        result["gbytes_per_step"] = round(bytes_per_step / 1e9, 3)
        peak = peak_hbm_bytes_per_sec()
        if peak:
            util = bytes_per_step / sec_per_step / peak
            if util > 1.0:
                result["hbm_bw_util"] = None
                result["timing_suspect"] = round(util, 2)
            else:
                result["hbm_bw_util"] = round(util, 4)
            result["peak_hbm_gbps"] = round(peak / 1e9, 1)
    return result
