"""ResNet-50 training throughput — the driver's image north-star metric
(BASELINE.json: ResNet-50 ImageNet images/sec/chip; config parity:
benchmark/paddle/image/resnet.py layer_num=50, batch 64, 224x224x3).

bf16 compute (MXU native) with f32 params/optimizer — the TPU-idiomatic mixed
precision; same on-device-loop timing discipline as lstm_textcls.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 64
IMAGE = 224
CLASSES = 1000


def build(batch: int = BATCH, bf16: bool = True):
    from paddle_tpu.models import ResNet
    from paddle_tpu.optimizer import Momentum

    model = ResNet(depth=50, classes=CLASSES)
    params = model.init(jax.random.PRNGKey(0))
    opt = Momentum(0.1, momentum=0.9)
    state = opt.init(params)

    def loss_fn(params, x, y):
        if bf16:
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a, params)
            logits = model(p16, x.astype(jnp.bfloat16)).astype(jnp.float32)
        else:
            logits = model(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()

    def step_fn(params, state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, x, y, n):
        def body(_, carry):
            params, state, _ = carry
            return step_fn(params, state, x, y)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, IMAGE, IMAGE, 3), jnp.float32)
    y = jnp.asarray(rs.randint(0, CLASSES, batch), jnp.int32)
    return run_n, params, state, (x, y)


def run(iters: int = 20, repeats: int = 2, batch: int = BATCH):
    run_n, params, state, b = build(batch)
    run_n(params, state, *b, 1)

    def timed(n):
        t0 = time.perf_counter()
        _, _, loss = run_n(params, state, *b, n)
        float(loss)
        return time.perf_counter() - t0

    t_short = min(timed(1) for _ in range(repeats))
    t_long = min(timed(iters + 1) for _ in range(repeats))
    sec = max(t_long - t_short, 1e-9) / iters
    ips = batch / sec
    return {"metric": "resnet50_train_images_per_sec_bs64_224",
            "value": round(ips, 2), "unit": "images/sec",
            "vs_baseline": None}  # no published reference ResNet number (BASELINE.md)


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
