"""ResNet-50 training throughput — the driver's image north-star metric
(BASELINE.json: ResNet-50 ImageNet images/sec/chip; config parity:
benchmark/paddle/image/resnet.py layer_num=50, batch 64, 224x224x3).

bf16 compute (MXU native) with f32 params/optimizer — the TPU-idiomatic mixed
precision.

Methodology (honest-bench notes):
* TRAIN-mode batch norm: per-batch statistics are computed and the running
  stats are updated and merged back every step (`nn.apply_stat_updates`), so
  the measured step includes all BN-stat work.
* Four distinct input batches are staged on device and rotated through the
  loop, so BN statistics do real, different work each step. (In deployment the
  host->HBM infeed overlaps compute via data/prefetch.py DoubleBuffer; staging
  keeps the remote-tunnel transfer out of the timed region while preserving
  per-step data variation.)
* Timing: N chained steps in one on-device ``fori_loop`` dispatch with
  short/long differencing, as in lstm_textcls.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 64
IMAGE = 224
CLASSES = 1000
NBUF = 4          # distinct staged batches rotated through the loop


def build(batch: int = BATCH, bf16: bool = True):
    from paddle_tpu import nn
    from paddle_tpu.models import ResNet
    from paddle_tpu.optimizer import Momentum

    model = ResNet(depth=50, classes=CLASSES)
    params = model.init(jax.random.PRNGKey(0))
    opt = Momentum(0.1, momentum=0.9)
    state = opt.init(params)

    def loss_fn(params, x, y):
        mut = {}
        if bf16:
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a, params)
            logits = model(p16, x.astype(jnp.bfloat16), train=True,
                           mutable=mut).astype(jnp.float32)
        else:
            logits = model(params, x, train=True, mutable=mut)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return loss, mut

    def step_fn(params, state, x, y):
        (loss, mut), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, x, y)
        params, state = opt.update(grads, state, params)
        # merge the train-mode BN running-stat updates back (f32 master copy)
        mut = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), mut)
        params = nn.apply_stat_updates(params, mut)
        return params, state, loss

    @jax.jit
    def run_n(params, state, xs, ys, n):
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            x = jax.lax.dynamic_index_in_dim(xs, j, 0, keepdims=False)
            y = jax.lax.dynamic_index_in_dim(ys, j, 0, keepdims=False)
            return step_fn(params, state, x, y)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    xs = jnp.asarray(rs.rand(NBUF, batch, IMAGE, IMAGE, 3), jnp.float32)
    ys = jnp.asarray(rs.randint(0, CLASSES, (NBUF, batch)), jnp.int32)
    return run_n, step_fn, params, state, (xs, ys)


def run(iters: int = 20, repeats: int = 2, batch: int = BATCH):
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, b = build(batch)
    sec = chained_ms_per_step(run_n, (params, state) + b, iters,
                              repeats) / 1e3
    ips = batch / sec
    flops = step_flops(step_fn, params, state, b[0][0], b[1][0])
    # key carries train-mode-BN semantics (r1 measured inference-mode BN)
    return attach_mfu(
        {"metric": f"resnet50_train_images_per_sec_bs{batch}_224_trainbn",
         "value": round(ips, 2), "unit": "images/sec",
         "vs_baseline": None,  # no published reference ResNet number
         "note": "train-mode BN with stat updates, 4 distinct rotating batches"},
        flops, sec)


def run_with_infeed(steps: int = 24, batch: int = BATCH):
    """images/sec INCLUDING host->HBM infeed, via the data/prefetch.py
    DoubleBuffer (the DataProvider.h:249 capability): a worker thread
    device_puts batches while the previous step computes; dispatch is async
    so transfer and compute overlap.

    The feed is uint8 pixels normalized ON DEVICE (x/255 in bf16) — the
    production image pipeline's wire format (JPEG decode yields uint8), and
    4x fewer transfer bytes than f32. Reports the end-to-end rate, the
    overlap ratio vs the compute-only number (1.0 == infeed fully hidden),
    and the achieved host->device MB/s. On this rig the host->device link
    is a remote tunnel (tens of MB/s), so the e2e number is a lower bound
    on what a local host achieves — the MB/s line makes the link, not the
    framework, visibly the binding constraint.
    """
    from paddle_tpu.data.prefetch import DoubleBuffer

    run_n, step_fn, params, state, b = build(batch)

    def step_u8(params, state, x_u8, y):
        # on-device normalize: uint8 -> bf16 in [0, 1]
        x = x_u8.astype(jnp.bfloat16) * jnp.bfloat16(1.0 / 255.0)
        return step_fn(params, state, x, y)

    step = jax.jit(step_u8, donate_argnums=(0, 1))

    rs = np.random.RandomState(1)
    host_batches = [(rs.randint(0, 256, (batch, IMAGE, IMAGE, 3),
                                np.uint8),
                     rs.randint(0, CLASSES, (batch,)).astype(np.int32))
                    for _ in range(NBUF)]
    batch_bytes = host_batches[0][0].nbytes + host_batches[0][1].nbytes

    total = steps + 4                       # warmup + pipeline depth; the
                                            # worker exits when exhausted
                                            # (no leaked thread / pinned HBM)
    def gen():
        for i in range(total):
            yield host_batches[i % NBUF]

    def to_device(hb):
        x, y = hb
        return jax.device_put(x), jax.device_put(y)

    db = iter(DoubleBuffer(gen, depth=2, transform=to_device))
    for _ in range(2):                      # warm: compile + fill pipeline
        x, y = next(db)
        params, state, loss = step(params, state, x, y)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        x, y = next(db)
        params, state, loss = step(params, state, x, y)
    float(loss)                             # drain the async queue
    e2e = (time.perf_counter() - t0) / steps

    # compute-only rate for the overlap ratio (same method as run())
    from benchmarks.timing import chained_ms_per_step
    staged = (jnp.asarray(np.stack([hb[0] for hb in host_batches])),
              jnp.asarray(np.stack([hb[1] for hb in host_batches])))
    compute = chained_ms_per_step(run_n, (params, state) + staged, 12,
                                  2) / 1e3
    from benchmarks.mfu import attach_mfu, step_flops
    flops = step_flops(step_fn, params, state,
                       staged[0][0].astype(jnp.bfloat16) / 255.0,
                       staged[1][0])
    # e2e time: mfu here reads "fraction of peak sustained INCLUDING the
    # infeed stall", pairing with overlap_ratio (bench-row schema:
    # every *_train_* row carries its mfu column)
    return attach_mfu(
        {"metric": f"resnet50_train_images_per_sec_bs{batch}_incl_infeed",
         "value": round(batch / e2e, 2), "unit": "images/sec",
         "vs_baseline": None,
         "compute_only_images_per_sec": round(batch / compute, 2),
         "overlap_ratio": round(compute / e2e, 3),
         "infeed_mb_per_sec": round(batch_bytes / e2e / 1e6, 1),
         "note": "DoubleBuffer uint8 host->HBM feed (on-device "
                 "normalize) overlapped with compute; host link is a "
                 "remote tunnel (deployment lower bound)"},
        flops, e2e)


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
    print(json.dumps(run_with_infeed()))
