"""ResNet-50 training throughput — the driver's image north-star metric
(BASELINE.json: ResNet-50 ImageNet images/sec/chip; config parity:
benchmark/paddle/image/resnet.py layer_num=50, batch 64, 224x224x3).

bf16 compute (MXU native) with f32 params/optimizer — the TPU-idiomatic mixed
precision.

Methodology (honest-bench notes):
* TRAIN-mode batch norm: per-batch statistics are computed and the running
  stats are updated and merged back every step (`nn.apply_stat_updates`), so
  the measured step includes all BN-stat work.
* Four distinct input batches are staged on device and rotated through the
  loop, so BN statistics do real, different work each step. (In deployment the
  host->HBM infeed overlaps compute via data/prefetch.py DoubleBuffer; staging
  keeps the remote-tunnel transfer out of the timed region while preserving
  per-step data variation.)
* Timing: N chained steps in one on-device ``fori_loop`` dispatch with
  short/long differencing, as in lstm_textcls.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 64
IMAGE = 224
CLASSES = 1000
NBUF = 4          # distinct staged batches rotated through the loop


def build(batch: int = BATCH, bf16: bool = True):
    from paddle_tpu import nn
    from paddle_tpu.models import ResNet
    from paddle_tpu.optimizer import Momentum

    model = ResNet(depth=50, classes=CLASSES)
    params = model.init(jax.random.PRNGKey(0))
    opt = Momentum(0.1, momentum=0.9)
    state = opt.init(params)

    def loss_fn(params, x, y):
        mut = {}
        if bf16:
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a, params)
            logits = model(p16, x.astype(jnp.bfloat16), train=True,
                           mutable=mut).astype(jnp.float32)
        else:
            logits = model(params, x, train=True, mutable=mut)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return loss, mut

    def step_fn(params, state, x, y):
        (loss, mut), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, x, y)
        params, state = opt.update(grads, state, params)
        # merge the train-mode BN running-stat updates back (f32 master copy)
        mut = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), mut)
        params = nn.apply_stat_updates(params, mut)
        return params, state, loss

    @jax.jit
    def run_n(params, state, xs, ys, n):
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            x = jax.lax.dynamic_index_in_dim(xs, j, 0, keepdims=False)
            y = jax.lax.dynamic_index_in_dim(ys, j, 0, keepdims=False)
            return step_fn(params, state, x, y)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    xs = jnp.asarray(rs.rand(NBUF, batch, IMAGE, IMAGE, 3), jnp.float32)
    ys = jnp.asarray(rs.randint(0, CLASSES, (NBUF, batch)), jnp.int32)
    return run_n, params, state, (xs, ys)


def run(iters: int = 20, repeats: int = 2, batch: int = BATCH):
    run_n, params, state, b = build(batch)
    run_n(params, state, *b, 1)

    def timed(n):
        t0 = time.perf_counter()
        _, _, loss = run_n(params, state, *b, n)
        float(loss)
        return time.perf_counter() - t0

    t_short = min(timed(1) for _ in range(repeats))
    t_long = min(timed(iters + 1) for _ in range(repeats))
    sec = max(t_long - t_short, 1e-9) / iters
    ips = batch / sec
    # key carries train-mode-BN semantics (r1 measured inference-mode BN)
    return {"metric": "resnet50_train_images_per_sec_bs64_224_trainbn",
            "value": round(ips, 2), "unit": "images/sec",
            "vs_baseline": None,  # no published reference ResNet number
            "note": "train-mode BN with stat updates, 4 distinct rotating batches"}


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
