"""Bench-row schema — thin re-export; the single source of truth lives in
paddle_tpu.analysis.bench_schema so the installed `paddle_tpu lint
--bench-rows` CLI shares exactly the rules bench.py enforces at print
time."""

from paddle_tpu.analysis.bench_schema import (FAMILY_EXEMPT,  # noqa: F401
                                              FAMILY_REQUIRED,
                                              METHODOLOGIES, PLAN_SOURCES,
                                              REQUIRED_KEYS, validate_row,
                                              validate_rows)
