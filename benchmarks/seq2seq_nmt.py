"""Attention seq2seq NMT training throughput — the driver's seq2seq
north-star (BASELINE.json tokens/sec/chip; the reference's benchmark README
deferred its seq2seq numbers, benchmark/README.md:141,168).

Config: vocab 30k/30k, embed 512, hidden 512, src/trg length 32, batch 64 —
a standard GNMT-small-ish shape. Counts target tokens/sec through the full
training step.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

SRC_VOCAB = TRG_VOCAB = 30000
EMBED = 512
HIDDEN = 512
SEQ = 32
BATCH = 64


def build():
    from paddle_tpu.core import SeqBatch
    from paddle_tpu.models import AttentionSeq2Seq
    from paddle_tpu.optimizer import Adam

    model = AttentionSeq2Seq(SRC_VOCAB, TRG_VOCAB, embed_dim=EMBED,
                             hidden=HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    def loss_fn(params, src, slen, tin, tout, tlen):
        return model.loss(params, SeqBatch(src, slen), SeqBatch(tin, tlen),
                          SeqBatch(tout, tlen))

    def step_fn(params, state, *b):
        loss, grads = jax.value_and_grad(loss_fn)(params, *b)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, src, slen, tin, tout, tlen, n):
        def body(_, carry):
            params, state, _ = carry
            return step_fn(params, state, src, slen, tin, tout, tlen)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    src = jnp.asarray(rs.randint(3, SRC_VOCAB, (BATCH, SEQ)), jnp.int32)
    tin = jnp.asarray(rs.randint(3, TRG_VOCAB, (BATCH, SEQ)), jnp.int32)
    tout = jnp.asarray(rs.randint(3, TRG_VOCAB, (BATCH, SEQ)), jnp.int32)
    lens = jnp.full((BATCH,), SEQ, jnp.int32)
    return run_n, params, state, (src, lens, tin, tout, lens)


def run(iters: int = 30, repeats: int = 2):
    run_n, params, state, b = build()
    run_n(params, state, *b, 1)

    def timed(n):
        t0 = time.perf_counter()
        _, _, loss = run_n(params, state, *b, n)
        float(loss)
        return time.perf_counter() - t0

    t_short = min(timed(1) for _ in range(repeats))
    t_long = min(timed(iters + 1) for _ in range(repeats))
    sec = max(t_long - t_short, 1e-9) / iters
    tokens = BATCH * SEQ
    return {"metric": "seq2seq_nmt_train_tokens_per_sec_h512_len32_bs64",
            "value": round(tokens / sec, 1), "unit": "tokens/sec",
            "vs_baseline": None}  # reference published no seq2seq number


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
