"""Attention seq2seq NMT training throughput — the driver's seq2seq
north-star (BASELINE.json tokens/sec/chip; the reference's benchmark README
deferred its seq2seq numbers, benchmark/README.md:141,168).

Config: vocab 30k/30k, embed 512, hidden 512, src/trg padded length 32,
batch 64 — a standard GNMT-small-ish shape.

Methodology (honest-bench notes):
* Source/target lengths VARY per sample (uniform 16..32), so the masked
  variable-length path does real work; tokens/sec counts the TRUE number of
  target tokens processed (sum of target lengths), not padded positions.
* Four distinct batches staged on device, rotated through the loop.
* Timing: on-device fori_loop with short/long differencing (see lstm_textcls).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

SRC_VOCAB = TRG_VOCAB = 30000
EMBED = 512
HIDDEN = 512
SEQ = 32
MIN_LEN = 16
BATCH = 64
NBUF = 4


def build(batch: int = BATCH):
    from paddle_tpu.core import SeqBatch
    from paddle_tpu.models import AttentionSeq2Seq
    from paddle_tpu.optimizer import Adam

    model = AttentionSeq2Seq(SRC_VOCAB, TRG_VOCAB, embed_dim=EMBED,
                             hidden=HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    def loss_fn(params, src, slen, tin, tout, tlen):
        # bf16 compute with f32 master params/optimizer — same mixed
        # precision as the image benches (MXU-native)
        p16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        return model.loss(p16, SeqBatch(src, slen), SeqBatch(tin, tlen),
                          SeqBatch(tout, tlen)).astype(jnp.float32)

    def step_fn(params, state, *b):
        loss, grads = jax.value_and_grad(loss_fn)(params, *b)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, srcs, slens, tins, touts, tlens, n):
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            pick = lambda a: jax.lax.dynamic_index_in_dim(a, j, 0,
                                                          keepdims=False)
            return step_fn(params, state, pick(srcs), pick(slens),
                           pick(tins), pick(touts), pick(tlens))
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    srcs = jnp.asarray(rs.randint(3, SRC_VOCAB, (NBUF, batch, SEQ)), jnp.int32)
    tins = jnp.asarray(rs.randint(3, TRG_VOCAB, (NBUF, batch, SEQ)), jnp.int32)
    touts = jnp.asarray(rs.randint(3, TRG_VOCAB, (NBUF, batch, SEQ)), jnp.int32)
    slens = jnp.asarray(rs.randint(MIN_LEN, SEQ + 1, (NBUF, batch)), jnp.int32)
    tlens = jnp.asarray(rs.randint(MIN_LEN, SEQ + 1, (NBUF, batch)), jnp.int32)
    # true target tokens per step, averaged over the rotation
    tokens_per_step = float(np.asarray(tlens).sum()) / NBUF
    return (run_n, step_fn, params, state, (srcs, slens, tins, touts, tlens),
            tokens_per_step)


def run(iters: int = 30, repeats: int = 2, batch: int = BATCH):
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, b, tokens_per_step = build(batch)
    sec = chained_ms_per_step(run_n, (params, state) + b, iters,
                              repeats) / 1e3
    flops = step_flops(step_fn, params, state, *(a[0] for a in b))
    # true-token semantics + varied lengths are in the key (vs r1's padded-len32)
    return attach_mfu(
        {"metric": "seq2seq_nmt_train_true_tokens_per_sec_h512_"
                   f"len16-32_bs{batch}",
         "value": round(tokens_per_step / sec, 1), "unit": "tokens/sec",
         "vs_baseline": None,  # reference published no seq2seq number
         "note": "varied lengths 16..32, true-token count, 4 rotating "
                 "batches; bf16 compute, hoisted enc/embed projections"},
        flops, sec)


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
