"""Serving-daemon SLO row — TTFT/TPOT through the WHOLE serve path.

The decode rows measure the chip; this row measures the service: requests
submitted over the native RPC plane into `paddle_tpu serve`'s engine
(paged KV-cache, continuous batching, admission queue), tokens streamed
back via srv_poll. TTFT (submit -> first token, queueing + prefill
included) and TPOT (per-token cadence after the first) are measured
CLIENT-side — what a caller actually experiences — and reported as p50/p95
next to delivered tokens/sec. The `_serve_` bench-row family rule
(analysis/bench_schema.py) makes the SLO pair mandatory for rows like
this one.
"""

from __future__ import annotations

import time

import numpy as np

from .serving_decode import VOCAB, build


def _pct(xs, q) -> float:
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else 0.0


def run(n_requests: int = 48, slots: int = 16, segment: int = 32) -> dict:
    from paddle_tpu.serving import ServingClient, ServingDaemon, ServingEngine

    model, p16, _ = build(slots)
    rs = np.random.RandomState(0)
    workload = [(rs.randint(0, VOCAB, int(rs.randint(32, 257))),
                 int(rs.randint(32, 257))) for _ in range(n_requests)]

    engine = ServingEngine(model, p16, slots=slots, segment=segment,
                           page_block=64, cache_bucket=512,
                           prompt_buckets=(256,),
                           queue_cap=max(2 * n_requests, 64))
    daemon = ServingDaemon(engine).start()
    try:
        client = ServingClient(*daemon.address, call_timeout=120.0)
        # warm every compiled program (admission tpad-256 + both cache-read
        # buckets) before timing — a long-lived daemon serves warm
        warm = [client.submit(rs.randint(0, VOCAB, 256), 256)
                for _ in range(slots)]
        for rid in warm:
            while not client.poll(rid)[1]:
                time.sleep(0.05)

        t0 = time.perf_counter()
        t_submit, t_first, t_done, counts = {}, {}, {}, {}
        pending = []
        for i, (prompt, gen) in enumerate(workload):
            t_submit[i] = time.perf_counter()
            pending.append((i, client.submit_with_backoff(prompt, gen)))
        cursors = {i: 0 for i, _ in pending}
        while pending:
            for i, rid in list(pending):
                toks, done, _ = client.poll(rid, cursors[i])
                now = time.perf_counter()
                if toks and i not in t_first:
                    t_first[i] = now
                cursors[i] += len(toks)
                if done:
                    t_done[i], counts[i] = now, cursors[i]
                    pending.remove((i, rid))
            time.sleep(0.01)
        dt = time.perf_counter() - t0
    finally:
        daemon.stop()

    delivered = sum(counts.values())
    ttft = [(t_first[i] - t_submit[i]) * 1e3 for i in t_first]
    tpot = [(t_done[i] - t_first[i]) / (counts[i] - 1) * 1e3
            for i in t_done if counts[i] > 1 and i in t_first]
    return {"metric": f"transformer_lm_serve_daemon_tokens_per_sec_"
                      f"slots{slots}_seg{segment}_mixed32-256",
            "value": round(delivered / dt, 1), "unit": "tokens/sec",
            "vs_baseline": None,
            "requests": n_requests, "delivered_tokens": delivered,
            "ttft_p50_ms": round(_pct(ttft, 50), 1),
            "ttft_p95_ms": round(_pct(ttft, 95), 1),
            "tpot_p50_ms": round(_pct(tpot, 50), 2),
            "tpot_p95_ms": round(_pct(tpot, 95), 2),
            "methodology": "measured",    # client-clock SLOs, real wire
            "note": "end-to-end over the native RPC plane (srv_submit/"
                    "srv_poll): paged KV-cache engine, FIFO admission, "
                    "client-measured SLOs incl. queue wait; TTFT counts "
                    "queueing + ragged prefill, TPOT the segment-paced "
                    "token cadence after the first"}


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    print(json.dumps(run()), flush=True)
