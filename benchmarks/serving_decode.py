"""KV-cache incremental decode throughput WITH roofline accounting — the
serving path (feeds the C inference ABI, capi/gradient_machine.h:73).

Decode is memory-bound: every token streams the bf16 weights plus the live
KV-cache rows from HBM. So next to ms/token this prints what MFU is to
training rows: bytes moved per step and the achieved fraction of the v5e's
~819 GB/s HBM bandwidth. Bucketed cache reads (generate_cached's ``bucket``)
keep the cache term proportional to the CURRENT position instead of the
max_len padding.

Timing: whole decode is one (or few, bucketed) jitted scans — a single
dispatch per segment, so the remote tunnel's per-call latency amortizes; the
reported rate divides by the total generated tokens.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

VOCAB = 50257
D_MODEL, N_HEADS, N_LAYERS, MAX_LEN = 768, 12, 12, 1024
PROMPT, STEPS = 128, 256


def _param_bytes(params) -> int:
    return sum(a.size * 2 for a in jax.tree_util.tree_leaves(params)
               if hasattr(a, "size"))            # bf16 on the wire


def build(batch: int):
    from paddle_tpu.models import TransformerLM

    model = TransformerLM(VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
                          n_layers=N_LAYERS, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0))
    p16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if a.dtype == jnp.float32 else a, params)
    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, VOCAB, (batch, PROMPT)), jnp.int32)
    return model, p16, prompt


def _avg_step_bytes(model, params, batch: int, bucket,
                    kv_dtype=None) -> float:
    """Average HBM bytes per decode step: weights + live cache rows.

    The cache term resolves through the ONE registered kernel byte model
    (obs/roofline.py, registered by ops/pallas_kernels.py) — the same
    resolution the live ``fluid.device_bytes_total`` accounting and the
    ``kernels.bytes_total`` dispatch counters use, so this row and the
    live ``roofline.hbm_bw_util`` gauge can never disagree on the bytes
    side of the formula."""
    from paddle_tpu.obs import roofline

    w = _param_bytes(params)
    d_head = D_MODEL // N_HEADS
    total_cache = 0.0
    for i in range(STEPS):
        pos = PROMPT + i
        read = (MAX_LEN if bucket is None
                else min(-(-(pos + 1) // bucket) * bucket, MAX_LEN))
        total_cache += roofline.kernel_cost(
            "decode_attention", batch=batch, read=read, n_heads=N_HEADS,
            d_head=d_head, layers=N_LAYERS, kv_dtype=kv_dtype, itemsize=2)
    return w + total_cache / STEPS


def run_config(batch: int, bucket=256, kv_dtype=None) -> dict:
    model, p16, prompt = build(batch)

    # ONE jitted program for prefill + every bucketed segment scan: an
    # unjitted generate_cached runs the prefill eagerly, and through the
    # remote tunnel each eager op pays the full dispatch RTT (measured
    # 35x slower end-to-end)
    decode = jax.jit(lambda p, ids: model.generate_cached(
        p, ids, steps=STEPS, bucket=bucket, kv_dtype=kv_dtype))

    out = decode(p16, prompt)          # compile + warm
    int(out[0, -1])                    # fetch: block_until_ready lies
    t0 = time.perf_counter()           # through the tunnel, a D2H doesn't
    out = decode(p16, prompt)
    int(out[0, -1])
    dt = time.perf_counter() - t0
    ms_tok = dt / STEPS * 1e3
    toks_sec = batch * STEPS / dt
    from benchmarks.mfu import attach_hbm_bw

    step_bytes = _avg_step_bytes(model, p16, batch, bucket, kv_dtype)
    bw = step_bytes / (ms_tok / 1e3) / 1e9
    note = ("GPT-2-small KV-cache greedy decode; bytes/step = bf16 "
            "weights + live cache rows (bucketed reads, shared kernel "
            "byte model); util vs the chip HBM peak "
            "(obs/roofline.PEAK_HBM_GBPS — null off-TPU)")
    row = {"metric": f"transformer_lm_decode_tokens_per_sec_bs{batch}"
                     f"_prompt{PROMPT}_gen{STEPS}"
                     + ("" if bucket is None else f"_bucket{bucket}")
                     + ("" if kv_dtype is None else f"_kv{kv_dtype}"),
           "value": round(toks_sec, 1), "unit": "tokens/sec",
           "vs_baseline": None,
           "ms_per_token": round(ms_tok, 3),
           "step_bytes_mb": round(step_bytes / 1e6, 1),
           "hbm_bw_gbps": round(bw, 1),
           "note": note}
    # bytes are an analytic model (Pallas cache reads are invisible to
    # XLA), so the row is honest about it: methodology="modeled"
    attach_hbm_bw(row, step_bytes, ms_tok / 1e3, methodology="modeled")
    if kv_dtype is not None:
        full = _avg_step_bytes(model, p16, batch, bucket, None)
        row["projected_bytes_reduction"] = round(full / step_bytes, 3)
        row["note"] = (note + f"; {kv_dtype} KV cache — bytes/step "
                       f"{step_bytes / 1e6:.1f} MB vs {full / 1e6:.1f} MB "
                       "full-precision (the projected reduction; tokens "
                       "follow the quantized-KV numerics contract, "
                       "docs/design/kernels.md)")
    return row


def run() -> dict:
    """Driver row: the strongest static config, bs64 bucketed (bs8/bs32 in
    __main__)."""
    return run_config(64)


def run_quantized() -> dict:
    """The int8-KV decode row: same workload as run(), cache read halved —
    the decode-roofline lever of ROADMAP item 3 (target >= 0.30 HBM-bw
    util; on bytes-bound decode the tokens/sec gain tracks the bytes
    reduction)."""
    return run_config(64, kv_dtype="int8")


def run_continuous(n_requests: int = 128, slots: int = 64,
                   segment: int = 64) -> dict:
    """Continuous (in-flight) batching over a MIXED workload: prompts and
    generation budgets each uniform in [32, 256], requests admitted into
    freed slots at segment boundaries (paddle_tpu/serving/batcher.py). Shapes are
    bucketed so the whole run compiles a handful of programs (prompt pad
    256; cache reads 512/1024). Exactness vs solo decode is proven in
    tests/test_serving.py; this row measures delivered tokens/sec."""
    from paddle_tpu.serving import ContinuousBatcher, Request

    model, p16, _ = build(slots)
    rs = np.random.RandomState(0)
    reqs = [Request(i, rs.randint(0, VOCAB, int(rs.randint(32, 257))),
                    int(rs.randint(32, 257)))
            for i in range(n_requests)]
    total_new = sum(r.max_new for r in reqs)

    b = ContinuousBatcher(model, p16, slots=slots, segment=segment,
                          cache_bucket=512, prompt_buckets=(256,))
    # warm EVERY program the measured pass will hit (compile is ~20-40 s
    # each through this tunnel and amortizes away in a long-running
    # server): prompt 256 + gen 256 pushes positions past 512, compiling
    # both the cache_len=512 and =1024 segment scans plus the tpad-256
    # prefill and the merge
    warm = [Request(-1 - i, rs.randint(0, VOCAB, 256), 256)
            for i in range(slots)]
    b.serve(warm)

    t0 = time.perf_counter()
    got = b.serve(reqs)
    dt = time.perf_counter() - t0
    delivered = sum(len(v) for v in got.values())
    return {"metric": f"transformer_lm_continuous_batching_tokens_per_sec_"
                      f"slots{slots}_seg{segment}_mixed32-256",
            "value": round(delivered / dt, 1), "unit": "tokens/sec",
            "vs_baseline": None,
            "requests": n_requests, "delivered_tokens": delivered,
            "budget_tokens": total_new,
            "note": "in-flight batching, mixed prompt/gen lengths "
                    "U[32,256], longest-first admission, slot refill at "
                    "segment boundaries via ragged prefill + masked merge; "
                    "greedy tokens exactly equal solo decode "
                    "(tests/test_serving.py)"}


def run_paged(n_requests: int = 128, slots: int = 64,
              segment: int = 64) -> dict:
    """Paged-vs-pinned continuous batching: the SAME mixed U[32,256]
    workload as :func:`run_continuous`, served through the paged KV-cache
    (block pool + per-request block tables, serving/paged.py) instead of
    per-slot max_len rows. Reports delivered tokens/sec, the modeled
    HBM-bandwidth utilization of the decode segments, and the residency
    story: peak pool pages + mean page occupancy vs the pinned pool's
    slots*max_len rows — the 'HBM holds live tokens, not padding' claim,
    measured."""
    from paddle_tpu.serving import PagedBatcher, Request

    model, p16, _ = build(slots)
    block = 64
    rs = np.random.RandomState(0)
    reqs = [Request(i, rs.randint(0, VOCAB, int(rs.randint(32, 257))),
                    int(rs.randint(32, 257)))
            for i in range(n_requests)]

    b = PagedBatcher(model, p16, slots=slots, segment=segment,
                     page_block=block, cache_bucket=512,
                     prompt_buckets=(256,))
    # warm every program the measured pass hits: tpad-256 admission and
    # both cache-read buckets (nb=8 and nb=16)
    warm = [Request(-1 - i, rs.randint(0, VOCAB, 256), 256)
            for i in range(slots)]
    b.serve(warm)
    pool = b.pool
    pool.reset_tallies()

    t0 = time.perf_counter()
    got = b.serve(reqs)
    dt = time.perf_counter() - t0
    delivered = sum(len(v) for v in got.values())
    from benchmarks.mfu import attach_hbm_bw

    w = _param_bytes(p16)
    total_bytes = (pool.segments_total * segment * w
                   + pool.read_bytes_total)
    bw = total_bytes / dt / 1e9
    occupancy = (pool.occupancy_num / pool.occupancy_den
                 if pool.occupancy_den else 0.0)
    pinned_rows = slots * MAX_LEN
    peak_rows = max(pool.peak_pages_used, 1) * block
    row = {"metric": f"transformer_lm_continuous_batching_paged_tokens_"
                     f"per_sec_slots{slots}_seg{segment}_mixed32-256",
            "value": round(delivered / dt, 1), "unit": "tokens/sec",
            "vs_baseline": None,
            "requests": n_requests, "delivered_tokens": delivered,
            "hbm_bw_gbps": round(bw, 1),
            "page_occupancy": round(occupancy, 3),
            "peak_pages": pool.peak_pages_used,
            "cache_rows_pinned": pinned_rows,
            "cache_rows_paged_peak": peak_rows,
            "residency_ratio": round(pinned_rows / peak_rows, 2),
            "note": "paged KV-cache (block 64, shared pool, per-request "
                    "block tables) vs the pinned slots*max_len pool of "
                    "transformer_lm_continuous_batching_*: greedy tokens "
                    "exactly equal solo decode "
                    "(tests/test_serving_paged.py); residency_ratio = "
                    "pinned cache rows / paged peak rows — cache bytes "
                    "per resident token shrink by that factor, the "
                    "headroom for bigger live batches"}
    # per-delivered-token bytes/time (ratio-invariant vs the run totals) so
    # gbytes_per_step is comparable with run_config's per-token figure
    return attach_hbm_bw(row, total_bytes / max(delivered, 1),
                         dt / max(delivered, 1), methodology="modeled")


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for bs in (8, 32, 64):
        print(json.dumps(run_config(bs)), flush=True)
    print(json.dumps(run_config(8, bucket=None)), flush=True)
    print(json.dumps(run_quantized()), flush=True)
    print(json.dumps(run_continuous()), flush=True)
    print(json.dumps(run_paged()), flush=True)
