"""Shared-prefix serving row — the prefix cache under production-shaped
traffic, vs a cold-cache control.

Production traffic is a few thousand system prompts × millions of
continuations, and popularity is heavy-tailed: a handful of prompts carry
most of the load. This bench reproduces that shape — ``n_prefixes``
distinct system prompts, zipf-distributed popularity, each request a
(prefix, short unique continuation, decode budget) — and serves it twice
through the SAME engine configuration:

* **warm row**: ``prefix_cache=True`` — requests sharing a system prompt
  admit with only their continuation prefilled (copy-on-write radix
  index, serving/paged.py);
* **cold control**: ``prefix_cache=False`` — every request re-prefills
  from token 0 (the PR 8 behavior).

Reported per row: ``hit_rate`` (shared prompt tokens / total prompt
tokens — the fraction of prefill work the cache elided), engine-clock
``ttft_p50_ms``/``tpot_p50_ms``, and ``prefill_flops_per_token`` — the
admission executables' FLOPs from the PR 9 cost ledger
(obs/roofline.py, methodology="measured") divided by admitted prompt
tokens, which is the column that must FALL as hit rate rises. The
``_serve_`` + ``_prefix_`` bench-row family rules make the SLO pair and
``hit_rate`` mandatory (analysis/bench_schema.py).
"""

from __future__ import annotations

import time

import numpy as np

from .serving_daemon import _pct
from .serving_decode import VOCAB, build

PREFIX_LEN = 384        # 6 pages at block 64 — the shared system prompt
CONT_LEN = 16           # the per-request unique continuation
GEN = 16                # decode budget per request: one segment — the
#                         system-prompt + short-answer shape, where
#                         admission (prefill) dominates the queue and the
#                         prefix cache's elision shows up in TTFT


def _workload(n_requests: int, n_prefixes: int, zipf_a: float):
    rs = np.random.RandomState(0)
    prefixes = [rs.randint(0, VOCAB, PREFIX_LEN) for _ in range(n_prefixes)]
    # zipf popularity over the prefix catalogue (rank r ~ 1/r^a), clipped
    # into range — the few-prompts-carry-most-load shape
    ranks = np.minimum(rs.zipf(zipf_a, n_requests) - 1, n_prefixes - 1)
    reqs = []
    for i in range(n_requests):
        prompt = np.concatenate([prefixes[int(ranks[i])],
                                 rs.randint(0, VOCAB, CONT_LEN)])
        reqs.append(prompt)
    return reqs


def _serve_once(prompts, *, prefix_cache: bool, slots: int,
                segment: int) -> dict:
    from paddle_tpu import obs
    from paddle_tpu.serving import ServingEngine

    model, p16, _ = build(slots)
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        eng = ServingEngine(model, p16, slots=slots, segment=segment,
                            page_block=64, cache_bucket=512,
                            prompt_buckets=(32, 64, 512),
                            queue_cap=2 * len(prompts),
                            prefix_cache=prefix_cache)
        # warm EVERY compiled program the measured pass will hit — the
        # miss-admission bucket, the segment scans, AND (second wave:
        # replayed prompts) the CoW + suffix-prefill hit program — then
        # drop the warm-up's cache entries and tallies so the measured
        # pass starts cold-but-compiled, like a long-lived daemon
        rs = np.random.RandomState(7)
        warm_prompts = [rs.randint(0, VOCAB, PREFIX_LEN + CONT_LEN)
                        for _ in range(min(slots, 4))]
        for wave in (warm_prompts, warm_prompts):
            rids = [eng.submit(np.concatenate([p[:PREFIX_LEN],
                                               rs.randint(0, VOCAB,
                                                          CONT_LEN)]),
                               GEN, prefix_len=PREFIX_LEN)
                    for p in wave]
            while not all(eng.poll(r)[1] for r in rids):
                eng.step()
        eng.pool.clear_prefix_cache()
        eng.pool.reset_tallies()

        t0 = time.perf_counter()
        rids = [eng.submit(p, GEN, prefix_len=PREFIX_LEN) for p in prompts]
        while not all(eng.poll(r)[1] for r in rids):
            eng.step()
        dt = time.perf_counter() - t0
        pool = eng.pool
        delivered = sum(len(eng.poll(r)[0]) for r in rids)
        ttft, tpot = [], []
        for r in rids:
            t = eng.timings(r)
            if t["t_first"] is not None:
                ttft.append((t["t_first"] - t["t_submit"]) * 1e3)
                n = len(eng.poll(r)[0])
                if t["t_done"] is not None and n > 1:
                    tpot.append((t["t_done"] - t["t_first"]) / (n - 1)
                                * 1e3)
        hit_rate = (1.0 - pool.prefill_tokens_total
                    / max(pool.prompt_tokens_total, 1))
        flops = pool.admit_flops_total
        return {"dt": dt, "delivered": delivered,
                "ttft_p50_ms": _pct(ttft, 50), "ttft_p95_ms": _pct(ttft, 95),
                "tpot_p50_ms": _pct(tpot, 50),
                "hit_rate": round(hit_rate, 4),
                "prefill_flops_per_token":
                    round(flops / max(pool.prompt_tokens_total, 1), 1),
                "flops_measured": flops > 0,
                "stats": pool.prefix_stats()}


def run(n_requests: int = 128, n_prefixes: int = 4, zipf_a: float = 1.2,
        slots: int = 8, segment: int = 32) -> list:
    # 128 requests / 4 system prompts = 32 continuations per prompt — a
    # SMALL-sample proxy for the production few-prompts × millions shape
    # (more prefixes per request would overweight the cache-warming
    # transient a microbench can't amortize the way a daemon does);
    # measured on the d256 CPU proxy: warm ttft_p50 2.25x lower than the
    # cold control at hit_rate 0.89, prefill FLOPs/token 4.9x lower
    """Two rows: the warm zipf shared-prefix row and its cold-cache
    control (same workload, same engine shape, prefix cache off)."""
    prompts = _workload(n_requests, n_prefixes, zipf_a)
    cold = _serve_once(prompts, prefix_cache=False, slots=slots,
                       segment=segment)
    warm = _serve_once(prompts, prefix_cache=True, slots=slots,
                       segment=segment)

    def row(name, r, note, vs=None):
        meth = "measured" if r["flops_measured"] else "modeled"
        return {"metric": f"transformer_lm_serve_prefix_{name}_tokens_per_"
                          f"sec_slots{slots}_seg{segment}_p{n_prefixes}"
                          f"x{PREFIX_LEN}",
                "value": round(r["delivered"] / r["dt"], 1),
                "unit": "tokens/sec", "vs_baseline": vs,
                "requests": n_requests,
                "hit_rate": r["hit_rate"],
                "ttft_p50_ms": round(r["ttft_p50_ms"], 1),
                "ttft_p95_ms": round(r["ttft_p95_ms"], 1),
                "tpot_p50_ms": round(r["tpot_p50_ms"], 2),
                "prefill_flops_per_token": r["prefill_flops_per_token"],
                "methodology": meth,
                "note": note}

    cold_note = ("cold-cache CONTROL: same zipf(%.1f) workload (%d system "
                 "prompts x %d-token prefix + %d-token continuations, "
                 "gen %d), prefix_cache=False — every request re-prefills "
                 "from token 0; prefill_flops_per_token from the PR 9 "
                 "cost ledger over the admission executables"
                 % (zipf_a, n_prefixes, PREFIX_LEN, CONT_LEN, GEN))
    ttft_ratio = (cold["ttft_p50_ms"] / warm["ttft_p50_ms"]
                  if warm["ttft_p50_ms"] else None)
    warm_note = ("prefix_cache=True on the same workload: hits admit with "
                 "only the continuation prefilled (CoW radix index); "
                 "ttft_p50 is %.1fx LOWER than the cold control's and "
                 "prefill FLOPs/token fall with hit rate (greedy tokens "
                 "stay exactly equal to solo decode — "
                 "tests/test_serving_prefix.py); index state: %s"
                 % (ttft_ratio or float("nan"),
                    {k: v for k, v in warm["stats"].items()
                     if k.startswith("prefix_")}))
    warm_row = row("zipf", warm, warm_note,
                   vs=None)
    warm_row["ttft_p50_vs_cold"] = (round(ttft_ratio, 2)
                                    if ttft_ratio else None)
    return [row("cold", cold, cold_note), warm_row]


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for r in run():
        print(json.dumps(r), flush=True)
