"""Routed disaggregated-serving row — 1 prefill + 2 decode pools.

The serving_daemon row measures one engine behind one daemon; this row
measures the DISAGGREGATED fleet: a ServingRouter fronting one prefill
worker (admits + ships KV pages, serving/ship.py) and two decode
workers, all joined in the router's membership table. Requests go
through route_submit (health-trend placement + the prefill->ship->adopt
hop), tokens stream back through route_poll. TTFT/TPOT are measured
CLIENT-side over the real wire — the ship hop's cost is IN the TTFT,
which is the honest number for disaggregation. The ``_route_`` bench-row
family rule (analysis/bench_schema.py) makes the SLO pair plus
``n_decode_workers`` mandatory for rows like this one.
"""

from __future__ import annotations

import time

import numpy as np

from .serving_daemon import _pct
from .serving_decode import VOCAB, build


def run(n_requests: int = 32, slots: int = 8, segment: int = 32) -> dict:
    from paddle_tpu import obs as _obs
    from paddle_tpu.obs.requests import group_legs, stitch
    from paddle_tpu.serving import (PagePool, PrefillDaemon, RouterClient,
                                    ServingDaemon, ServingEngine,
                                    ServingRouter)

    model, p16, _ = build(slots)
    rs = np.random.RandomState(0)
    workload = [(rs.randint(0, VOCAB, int(rs.randint(32, 257))),
                 int(rs.randint(32, 257))) for _ in range(n_requests)]

    # an installed obs plane arms the fleet's always-on request-timeline
    # ledger (obs/requests.py) — the phase breakdown below comes from the
    # SAME production instrumentation the daemons run in deployment
    session = _obs.ObsSession(registry=_obs.MetricsRegistry()).install()
    timelines = []
    router = ServingRouter(scrape_interval_s=0.1).start()
    daemons = []
    try:
        for i in range(2):
            eng = ServingEngine(model, p16, slots=slots, segment=segment,
                                page_block=64, cache_bucket=512,
                                prompt_buckets=(256,),
                                queue_cap=max(2 * n_requests, 64))
            d = ServingDaemon(eng).start()
            d.join_router(router.address, f"decode-{i}", role="decode")
            daemons.append(d)
        pool = PagePool(model, p16, slots=4, segment=segment,
                        page_block=64, cache_bucket=512,
                        prompt_buckets=(256,))
        pd = PrefillDaemon(pool).start()
        pd.join_router(router.address, "prefill-0", role="prefill")
        daemons.append(pd)

        client = RouterClient(*router.address, call_timeout=120.0)
        # warm every compiled program on BOTH decode pools and the
        # prefill pool before timing — a long-lived fleet serves warm
        warm = [client.submit(rs.randint(0, VOCAB, 256), 256)
                for _ in range(2 * slots)]
        for rid in warm:
            while not client.poll(rid)[1]:
                time.sleep(0.05)

        t0 = time.perf_counter()
        t_submit, t_first, t_done, counts = {}, {}, {}, {}
        pending = []
        for i, (prompt, gen) in enumerate(workload):
            t_submit[i] = time.perf_counter()
            pending.append((i, client.submit_with_backoff(prompt, gen)))
        cursors = {i: 0 for i, _ in pending}
        while pending:
            for i, rid in list(pending):
                toks, done, _ = client.poll(rid, cursors[i])
                now = time.perf_counter()
                if toks and i not in t_first:
                    t_first[i] = now
                cursors[i] += len(toks)
                if done:
                    t_done[i], counts[i] = now, cursors[i]
                    pending.remove((i, rid))
            time.sleep(0.01)
        dt = time.perf_counter() - t0
        stats = client.serving_stats()
        led = _obs.request_ledger()
        if led is not None:
            timelines = led.export(n=1024)
    finally:
        for d in daemons:
            d.stop()
        router.stop()
        session.uninstall()

    delivered = sum(counts.values())
    ttft = [(t_first[i] - t_submit[i]) * 1e3 for i in t_first]
    tpot = [(t_done[i] - t_first[i]) / (counts[i] - 1) * 1e3
            for i in t_done if counts[i] > 1 and i in t_first]
    # phase-decomposed TTFT p50s (ms) from the stitched timelines — the
    # _route_ family rule makes this mandatory so a routed-TTFT
    # regression names WHICH hop (queue/prefill/ship/adopt) moved
    phase_ms = {ph: [] for ph in ("queued", "prefill", "ship", "adopt")}
    for legs in group_legs(timelines).values():
        st = stitch(legs)
        for ph, arr in phase_ms.items():
            v = st["breakdown"].get(ph)
            if v:
                arr.append(v * 1e3)
    ttft_breakdown = {ph: (round(_pct(arr, 50), 2) if arr else 0.0)
                      for ph, arr in phase_ms.items()}
    return {"metric": f"transformer_lm_route_disagg_tokens_per_sec_"
                      f"1p2d_slots{slots}_seg{segment}_mixed32-256",
            "value": round(delivered / dt, 1), "unit": "tokens/sec",
            "vs_baseline": None,
            "requests": n_requests, "delivered_tokens": delivered,
            "n_decode_workers": int(stats.get("n_decode_workers", 2)),
            "ttft_p50_ms": round(_pct(ttft, 50), 1),
            "ttft_p95_ms": round(_pct(ttft, 95), 1),
            "tpot_p50_ms": round(_pct(tpot, 50), 2),
            "tpot_p95_ms": round(_pct(tpot, 95), 2),
            "ttft_breakdown": ttft_breakdown,
            "methodology": "measured",    # client-clock SLOs, real wire
            "note": "disaggregated fleet over the native RPC plane: "
                    "route_submit -> health-trend placement -> prefill "
                    "worker admits + ships KV pages -> decode worker "
                    "adopts and streams; TTFT counts the ship/adopt hop, "
                    "TPOT the segment-paced cadence after the first "
                    "token; client-measured over the wire"}


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    print(json.dumps(run()), flush=True)
