"""Sharded GPT-2 training throughput — the GSPMD sharding plane's row.

GPT-2-small (same shape as benchmarks/transformer_lm.py) trained over a
named ``data x fsdp x tp`` mesh: parameters and Adam moments are placed
per :class:`paddle_tpu.parallel.SpecLayout` (embeddings vocab-sharded over
fsdp x tp, 2-D weights over (fsdp, tp)), the batch shards over ``data``,
and the step compiles through ``jax.jit(..., in_shardings=...,
donate_argnums=...)`` — the same jit+in_shardings path the mesh-aware
fluid Executor lowers annotations through (docs/design/spmd.md), measured
with the shared chained-loop methodology.

The JSON note carries the mesh shape, the resolved per-axis layout
utilization (the fraction of parameter bytes each axis actually divides —
the ``mesh.axis_utilization`` gauge's definition), per-device parameter MB
vs replicated, and MFU against the FULL mesh peak (chip peak x device
count), decomposed per axis as ``mfu_vs_axis`` = achieved FLOP/s over the
peak of that axis's device count alone.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.transformer_lm import (BATCH, D_MODEL, N_HEADS, N_LAYERS,
                                       NBUF, SEQ, VOCAB)


def build_mesh():
    from paddle_tpu import parallel as pp
    n = len(jax.devices())
    tp = 2 if n % 2 == 0 else 1
    fsdp = 2 if (n // tp) % 2 == 0 else 1
    data = n // (tp * fsdp)
    return pp.make_mesh(data=data, fsdp=fsdp, tp=tp)


def build(batch: int = BATCH, seq: int = SEQ):
    from paddle_tpu import parallel as pp
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.optimizer import Adam

    mesh = build_mesh()
    from jax.sharding import PartitionSpec as _P
    # the positional table is tiny and added to tp-sharded activations
    # every block — sharding it buys nothing and costs an SPMD
    # rematerialization per add, so pin it replicated ahead of the roles
    layout = pp.SpecLayout(rules=[(r"pos_embed$", _P())])
    model = TransformerLM(VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
                          n_layers=N_LAYERS, max_len=seq)
    params = layout.apply(mesh, model.init(jax.random.PRNGKey(0)))
    opt = Adam(3e-4)
    state = layout.apply(mesh, opt.init(params))

    def loss_fn(params, ids):
        p16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        return model.loss(p16, ids)

    def step_fn(params, state, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    p_sh = layout.shardings(mesh, params)
    s_sh = layout.shardings(mesh, state)
    from jax.sharding import NamedSharding, PartitionSpec as P
    ids_sh = NamedSharding(mesh, pp.SpecLayout.fit(
        mesh, P("data", None, None), (NBUF, batch, seq)))

    @jax.jit
    def run_n(params, state, idss, n):
        def body(i, carry):
            params, state, _ = carry
            ids = jax.lax.dynamic_index_in_dim(idss, i % NBUF, 0,
                                               keepdims=False)
            return step_fn(params, state, ids)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    idss = jax.device_put(
        jnp.asarray(rs.randint(0, VOCAB, (NBUF, batch, seq)), jnp.int32),
        ids_sh)
    return mesh, layout, run_n, step_fn, params, state, idss


def _layout_note(mesh, params):
    """Per-axis utilization + per-device footprint of the placed tree."""
    leaves = jax.tree_util.tree_leaves(params)
    total = sum(l.nbytes for l in leaves)
    by_axis = {a: 0 for a in mesh.shape}
    per_device = 0
    for l in leaves:
        ways = 1
        for entry in l.sharding.spec:
            axes = (entry,) if isinstance(entry, str) else tuple(entry or ())
            for a in axes:
                by_axis[a] += l.nbytes
                ways *= mesh.shape[a]
        per_device += l.nbytes // ways
    return {"mesh": dict(mesh.shape),
            "axis_utilization": {a: round(b / total, 3)
                                 for a, b in by_axis.items()},
            "param_mb_per_device": round(per_device / 2**20, 1),
            "param_mb_replicated": round(total / 2**20, 1)}


def run(iters: int = 12, repeats: int = 2, batch: int = BATCH,
        seq: int = SEQ):
    from benchmarks.mfu import (_plan_source, peak_flops_per_sec,
                                step_flops)
    from benchmarks.timing import chained_ms_per_step

    mesh, layout, run_n, step_fn, params, state, idss = build(batch, seq)
    note = _layout_note(mesh, params)
    with mesh:
        ms = chained_ms_per_step(run_n, (params, state, idss), iters,
                                 repeats)
        flops = step_flops(step_fn, params, state, idss[0])
    tokens = batch * (seq - 1)
    n_dev = int(np.prod(list(mesh.shape.values())))
    row = {"metric": f"sharded_gpt2s_train_tokens_per_sec_bs{batch}"
                     f"_seq{seq}_mesh{n_dev}",
           "value": round(tokens / (ms / 1e3), 1), "unit": "tokens/sec",
           "vs_baseline": None,
           "mfu": None,           # overwritten below when peak is known
           "methodology": "measured",   # XLA-analyzed FLOPs, real timing
           "plan_source": _plan_source(),
           "note": note}
    peak = peak_flops_per_sec()
    if flops and peak:
        row["gflops_per_step"] = round(flops / 1e9, 2)
        achieved = flops / (ms / 1e3)
        mfu = achieved / (peak * n_dev)
        row["mfu"] = None if mfu > 1.0 else round(mfu, 4)
        note["mfu_vs_axis"] = {
            a: round(min(achieved / (peak * size), 99.0), 4)
            for a, size in mesh.shape.items()}
        row["peak_tflops"] = round(peak * n_dev / 1e12, 1)
    return row


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    print(json.dumps(run()))
