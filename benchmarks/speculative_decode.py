"""Speculative decoding throughput + acceptance rate — the bench-visible
scenario for paddle_tpu.serving.SpeculativeDecoder (ROADMAP item 3).

Scenario: GPT-2-small target, SELF-speculation draft — the target's own
weights reading an int8-quantized KV cache. The draft's per-token cache
read halves while its argmax agrees with the full-precision target on
most steps (quantization noise rarely flips a greedy choice), so the
target's weights stream once per ROUND instead of once per token and the
emitted stream stays EXACTLY the full-precision greedy one (the verify
pass guarantees it for any acceptance pattern — tests/test_serving.py).

Headline columns: delivered tokens/sec, acceptance_rate, and
``hbm_bw_util`` for the modeled bytes actually streamed per emitted token
(draft cache reads + one target verify per round, amortized over
1 + accepted tokens). A separate tiny-draft row (2-layer d256) shows the
classic small-draft trade: cheaper proposals, lower acceptance.

Timing note: each draft proposal is its own dispatch here (k-1 per
round), so on a remote tunnel the HOST-side rate underestimates the chip;
the acceptance rate and bytes model are transport-independent.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.mfu import attach_hbm_bw
from benchmarks.serving_decode import (MAX_LEN, N_HEADS, N_LAYERS,
                                       D_MODEL, PROMPT, VOCAB, build,
                                       _param_bytes)

STEPS = 192      # leaves 2k rollback margin under max_len (k <= 16)
K = 4


def _spec_row(tag, model, p16, draft_model, draft_params, draft_kv, prompt,
              note_extra=""):
    from paddle_tpu.serving import SpeculativeDecoder

    batch = prompt.shape[0]
    sd = SpeculativeDecoder(model, p16, draft_model, draft_params, k=K,
                            draft_kv_dtype=draft_kv)
    out, _ = sd.generate(np.asarray(prompt), 8)          # compile + warm
    t0 = time.perf_counter()
    out, stats = sd.generate(np.asarray(prompt), STEPS)
    dt = time.perf_counter() - t0
    delivered = out.size
    toks_sec = delivered / dt

    # modeled HBM bytes per EMITTED token (batch-wide tokens, consistent
    # with toks_sec): every round streams the draft's weights + cache k
    # times (k-1 proposals + the cache-fill step) and the target's weights
    # + cache once (the verify), then yields batch*(1 + accepted) tokens.
    # Cache terms resolve through the ONE registered kernel byte model
    # (obs/roofline.py) — same resolution as the live gauges
    from paddle_tpu.obs import roofline

    d_head = D_MODEL // N_HEADS
    read = MAX_LEN                                        # unbucketed reads
    t_bytes = _param_bytes(p16) + roofline.kernel_cost(
        "decode_attention", batch=batch, read=read, n_heads=N_HEADS,
        d_head=d_head, layers=N_LAYERS, kv_dtype=None, itemsize=2)
    dm = draft_model.blocks[0]
    d_bytes = _param_bytes(draft_params) + roofline.kernel_cost(
        "decode_attention", batch=batch, read=read, n_heads=dm.n_heads,
        d_head=dm.d_head, layers=len(draft_model.blocks),
        kv_dtype=draft_kv, itemsize=2)
    per_round = (K if K > 1 else 0) * d_bytes + t_bytes
    toks_per_round = delivered / max(stats["rounds"], 1)  # batch-wide
    bytes_per_tok = per_round / toks_per_round
    # plain greedy: one target stream per dispatch, which emits `batch`
    # tokens — so per emitted token it costs t_bytes / batch
    plain_per_tok = t_bytes / batch
    bw = bytes_per_tok * toks_sec / 1e9                   # total bytes/sec
    row = {"metric": f"transformer_lm_decode_speculative_tokens_per_sec_"
                     f"{tag}_k{K}_bs{batch}_prompt{PROMPT}_gen{STEPS}",
           "value": round(toks_sec, 1), "unit": "tokens/sec",
           "vs_baseline": None,
           "acceptance_rate": round(stats["acceptance_rate"], 3),
           "rounds": stats["rounds"],
           "tokens_per_round": round(toks_per_round / batch, 2),
           "bytes_per_token_mb": round(bytes_per_tok / 1e6, 2),
           "projected_bytes_reduction": round(plain_per_tok
                                              / bytes_per_tok, 3),
           "hbm_bw_gbps": round(bw, 1),
           "note": "greedy speculative decode, output exactly equals "
                   "plain greedy (verify pass, tests/test_serving.py); "
                   "bytes model: k draft streams (k-1 proposals + cache "
                   "fill) + 1 target verify per round, amortized over "
                   "emitted tokens" + note_extra}
    # per-token bytes over per-token time: same utilization ratio as the
    # whole-run totals, but gbytes_per_step stays an honest per-token figure
    return attach_hbm_bw(row, bytes_per_tok, dt / max(delivered, 1),
                         methodology="modeled")


def run(batch: int = 8) -> dict:
    """Driver row: int8-KV self-speculation (same weights, quantized cache
    draft)."""
    model, p16, prompt = build(batch)
    return _spec_row("int8self", model, p16, model, p16, "int8", prompt,
                     "; draft = target reading int8 KV (self-speculation)")


def run_tiny_draft(batch: int = 8) -> dict:
    """2-layer d256 random-init draft: the cheap-draft/low-acceptance end
    of the trade (a TRAINED small draft would sit between the two rows)."""
    from paddle_tpu.models import TransformerLM

    model, p16, prompt = build(batch)
    draft = TransformerLM(VOCAB, d_model=256, n_heads=4, n_layers=2,
                          max_len=MAX_LEN)
    dparams = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        draft.init(jax.random.PRNGKey(1)))
    return _spec_row("draft2x256", model, p16, draft, dparams, None, prompt,
                     "; draft = untrained 2-layer d256 (acceptance floor)")


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    print(json.dumps(run()), flush=True)
    print(json.dumps(run_tiny_draft()), flush=True)
