"""Shared chained-loop timing used by every bench.

One methodology, one implementation: ``run_n(*args, n)`` executes n chained
training steps in a single on-device ``lax.fori_loop`` dispatch and returns
a carry whose last element is a scalar loss; we time a short and a long loop
(best of ``repeats``) and difference them, cancelling the fixed dispatch +
host-fetch latency that dominates under the remote TPU tunnel (where
``block_until_ready`` timing is unreliable). Chained state (the carry
threads params) prevents XLA from hoisting loop-invariant work out of the
loop — the failure mode that invalidates naive forward-only timing loops.
"""

from __future__ import annotations

import time


def chained_ms_per_step(run_n, args, iters: int, repeats: int,
                        short: int = 1) -> float:
    """ms per step via short/long on-device-loop differencing."""

    def timed(n):
        t0 = time.perf_counter()
        out = run_n(*args, n)
        loss = out[-1]
        float(loss)                     # force completion
        return time.perf_counter() - t0

    timed(short)                        # compile both trip counts
    timed(short + iters)
    t_short = min(timed(short) for _ in range(repeats))
    t_long = min(timed(short + iters) for _ in range(repeats))
    return max(t_long - t_short, 1e-9) / iters * 1e3
