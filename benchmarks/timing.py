"""Shared chained-loop timing used by every bench.

One methodology, one implementation: ``run_n(*args, n)`` executes n chained
training steps in a single on-device ``lax.fori_loop`` dispatch and returns
a carry whose last element is a scalar loss; we time a short and a long loop
(best of ``repeats``) and difference them, cancelling the fixed dispatch +
host-fetch latency that dominates under the remote TPU tunnel (where
``block_until_ready`` timing is unreliable). Chained state (the carry
threads params) prevents XLA from hoisting loop-invariant work out of the
loop — the failure mode that invalidates naive forward-only timing loops.
"""

from __future__ import annotations

import time


def chained_ms_per_step(run_n, args, iters: int, repeats: int,
                        short: int = 1, min_window_s: float = 0.025,
                        max_iters: int = 25000) -> float:
    """ms per step via short/long on-device-loop differencing.

    The long-short window must clear the dispatch/fetch noise floor (several
    ms of RTT jitter under the remote-tunnel transport) or the difference can
    collapse to ~0 for sub-ms steps and report nonsense; when the measured
    window is below ``min_window_s`` the trip count grows (x4) and the row
    re-measures, so fast models are timed over enough chained steps for the
    per-step quotient to be trustworthy."""

    def timed(n):
        t0 = time.perf_counter()
        out = run_n(*args, n)
        loss = out[-1]
        float(loss)                     # force completion
        return time.perf_counter() - t0

    # warm compile once: n is a traced scalar, so every trip count reuses
    # the same executable
    timed(short)
    while True:
        # short and long runs interleave within a round so slow drift in
        # the dispatch/RTT floor cancels out of the difference; the floor's
        # own jitter (measured as the short-run spread) sets how big the
        # window must be before the quotient is trustworthy
        shorts = [timed(short) for _ in range(max(repeats, 4))]
        t_short = min(shorts)
        noise = max(shorts) - t_short
        t_long = min(timed(short + iters) for _ in range(repeats))
        window = t_long - t_short
        if window >= max(min_window_s, 6 * noise) or iters >= max_iters:
            return max(window, 1e-9) / iters * 1e3
        iters *= 4
