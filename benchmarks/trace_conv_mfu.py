"""Capture + analyze an in-graph XLA trace of a benchmark train step —
the evidence backing docs/design/conv_mfu.md's and nmt_roofline.md's
ceiling claims with REAL in-graph per-HLO timings instead of isolated-op
upper bounds. Models: resnet50 (default), any image_suite key
(googlenet/alexnet/smallnet), seq2seq_nmt, or transformer_lm
(pass its bench batch, e.g. `transformer_lm 8` — the bare default of 64
is the conv benches' batch).

Usage (on the TPU host):
    python benchmarks/trace_conv_mfu.py [model [batch]]     # capture+analyze
    python benchmarks/trace_conv_mfu.py <xplane.pb> [steps] # analyze
    (``steps`` = profiled step count of that trace; default 20, which is
    what capture() records — pass it for traces captured elsewhere or the
    per-step numbers are silently scaled wrong)

Pipeline: utils/profiler.py (jax.profiler trace) -> .xplane.pb ->
xprof's hlo_stats tool -> per-HLO total_self_time / model_flop_rate /
measured_memory_bw / bound_by -> the category and roofline summaries
printed below (and pasted into docs/design/conv_mfu.md).
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict

# must precede the first google.protobuf import anywhere in the process
# (jax pulls it in): xprof's generated protos need the pure-python impl
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

STEPS = 20


def _peak_tflops() -> float:
    from benchmarks.mfu import peak_flops_per_sec

    peak = peak_flops_per_sec()
    return peak / 1e12 if peak else 197.0   # v5e fallback off-device


def _peak_hbm_gbps() -> float:
    from benchmarks.mfu import peak_hbm_bytes_per_sec

    peak = peak_hbm_bytes_per_sec()   # the obs/roofline device_kind table
    return peak / 1e9 if peak else 819.0    # v5e fallback off-device


def capture(logdir: str = "/tmp/rn50_trace", model: str = "resnet50",
            batch: int = 64) -> str:
    import jax

    from paddle_tpu.utils import profiler

    if model == "resnet50":
        import benchmarks.resnet50 as rb

        run_n, _, params, state, bufs = rb.build(batch)
    elif model == "seq2seq_nmt":
        import benchmarks.seq2seq_nmt as nmt

        run_n, _, params, state, bufs, _ = nmt.build(batch)
    elif model == "transformer_lm":
        import benchmarks.transformer_lm as tlm

        run_n, _, params, state, idss = tlm.build(batch)
        bufs = (idss,)
    else:
        import benchmarks.image_suite as ims

        run_n, _, params, state, bufs, _ = ims.build(model, batch)
    args = (params, state) + tuple(bufs)
    out = run_n(*args, 3)                                   # compile+warm
    jax.block_until_ready(out[-1])
    with profiler.profile(logdir):
        out = run_n(*args, STEPS)
        jax.block_until_ready(out[-1])
        float(out[-1])
    return profiler.trace_files(logdir)[-1]


def hlo_rows(xplane_path: str):
    # the parsing lives in the obs plane now (obs/xplane.py): this rich
    # per-HLO path needs xprof; the raw wire parser + `paddle_tpu
    # profile` carry the toolchain-free path
    from paddle_tpu.obs.xplane import hlo_stats_rows, read_xspace, \
        top_ops_report

    rows = hlo_stats_rows(xplane_path)
    if rows is None:
        print("xprof unavailable — falling back to the raw-parse per-op "
              "report (no flop-rate/bw columns):\n")
        print(top_ops_report(read_xspace(xplane_path), steps=STEPS))
        sys.exit(0)
    return rows


def analyze(rows, steps: int = STEPS):
    peak_tflops = _peak_tflops()
    peak_hbm_gbps = _peak_hbm_gbps()
    total_us = sum(r["total_self_time"] for r in rows)
    step_ms = total_us / 1e3 / steps
    # model_flop_rate is GFLOP/s and self time is us: GFLOP = rate * t * 1e-6
    gflops_step = sum((r["model_flop_rate"] or 0) * r["total_self_time"]
                      for r in rows) / 1e6 / steps
    # step_ms is ms: GFLOP / ms = TFLOP/s
    mfu = gflops_step / step_ms / peak_tflops
    print(f"device step: {step_ms:.2f} ms, model {gflops_step:.0f} GFLOP "
          f"-> in-graph MFU {100 * mfu:.1f}%")

    agg = defaultdict(lambda: [0.0, 0.0, 0.0])
    for r in rows:
        a = agg[r["category"]]
        a[0] += r["total_self_time"]
        a[1] += (r["model_flop_rate"] or 0.0) * r["total_self_time"]
        a[2] += (r["measured_memory_bw"] or 0.0) * r["total_self_time"]
    print(f"\n{'category':26s} {'ms/step':>8s} {'%time':>6s} "
          f"{'TFLOP/s':>8s} {'GB/s':>6s}")
    for cat, (t, ft, bt) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        if t / total_us < 0.005:
            continue
        print(f"{cat:26s} {t / 1e3 / steps:8.2f} {100 * t / total_us:6.1f} "
              f"{ft / t / 1e3:8.1f} {bt / t:6.0f}")

    conv = [r for r in rows if r["category"] == "convolution fusion"]
    conv_t = sum(r["total_self_time"] for r in conv)
    for bound in ("HBM", "Compute"):
        sub = [r for r in conv if r["bound_by"] == bound]
        t = sum(r["total_self_time"] for r in sub)
        if not t:
            continue
        fr = sum((r["model_flop_rate"] or 0) * r["total_self_time"]
                 for r in sub) / t
        bw = sum((r["measured_memory_bw"] or 0) * r["total_self_time"]
                 for r in sub) / t
        print(f"conv fusions {bound:8s}: {100 * t / conv_t:5.1f}% of conv "
              f"time at {fr / 1e3:5.1f} TFLOP/s "
              f"({100 * fr / 1e3 / peak_tflops:.0f}% MXU) / {bw:.0f} GB/s "
              f"({100 * bw / peak_hbm_gbps:.0f}% HBM)")

    # roofline-perfect bound: every op at min(its achieved time scaled to
    # 100% of whichever roof binds it) — what the step would cost if XLA
    # hit BOTH roofs perfectly everywhere
    ideal_us = 0.0
    for r in rows:
        t = r["total_self_time"]
        fr = (r["model_flop_rate"] or 0.0) / 1e3 / peak_tflops
        bw = min((r["measured_memory_bw"] or 0.0), peak_hbm_gbps) \
            / peak_hbm_gbps
        util = max(fr, bw)
        ideal_us += t * min(util, 1.0)
    ideal_ms = ideal_us / 1e3 / steps
    print(f"\nroofline-perfect step (both roofs at 100%): {ideal_ms:.2f} ms "
          f"-> MFU ceiling {100 * gflops_step / ideal_ms / peak_tflops:.1f}%")
    return step_ms, mfu


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if len(sys.argv) > 1 and sys.argv[1].endswith(".pb"):
        path = sys.argv[1]
        steps = int(sys.argv[2]) if len(sys.argv) > 2 else STEPS
    else:
        # `trace_conv_mfu.py [model [batch]]` — an image_suite key
        # ("googlenet"/"alexnet"/"smallnet"), "seq2seq_nmt",
        # "transformer_lm" (pass batch 8), or the default "resnet50"
        model = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
        batch = int(sys.argv[2]) if len(sys.argv) > 2 else 64
        path, steps = capture(f"/tmp/{model}_trace", model, batch), STEPS
    print(f"trace: {path} ({steps} steps)")
    analyze(hlo_rows(path), steps)
