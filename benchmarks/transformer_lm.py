"""Transformer LM training throughput — the modern-model headline.

GPT-2-small shape (d=768, 12 heads, 12 layers, T=1024; vocab 32768 for
MXU-aligned head matmuls), causal Pallas flash attention, bf16 compute with
f32 master params + Adam. The reference has no transformer (2017); this
metric exists to show the framework's ceiling on a compute-dense modern
model rather than 2017-scale RNN/CNNs — MFU is the number that matters.
Same honest-bench methodology as every other metric: distinct rotating
device-staged batches, chained on-device fori_loop, noise-adaptive
short/long differencing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

VOCAB = 32768
D_MODEL = 768
N_HEADS = 12
N_LAYERS = 12
SEQ = 1024
BATCH = 8
NBUF = 2


def build(batch: int = BATCH, seq: int = SEQ):
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.optimizer import Adam

    model = TransformerLM(VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
                          n_layers=N_LAYERS, max_len=seq)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(3e-4)
    state = opt.init(params)

    def loss_fn(params, ids):
        p16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        return model.loss(p16, ids)

    def step_fn(params, state, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, idss, n):
        def body(i, carry):
            params, state, _ = carry
            ids = jax.lax.dynamic_index_in_dim(idss, i % NBUF, 0,
                                               keepdims=False)
            return step_fn(params, state, ids)
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    idss = jnp.asarray(rs.randint(0, VOCAB, (NBUF, batch, seq)), jnp.int32)
    return run_n, step_fn, params, state, idss


def run(iters: int = 12, repeats: int = 2, batch: int = BATCH,
        seq: int = SEQ):
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, idss = build(batch, seq)
    ms = chained_ms_per_step(run_n, (params, state, idss), iters, repeats)
    flops = step_flops(step_fn, params, state, idss[0])
    tokens = batch * (seq - 1)
    return attach_mfu(
        {"metric": f"transformer_lm_gpt2s_train_tokens_per_sec_bs{batch}"
                   f"_seq{seq}",
         "value": round(tokens / (ms / 1e3), 1), "unit": "tokens/sec",
         "vs_baseline": None,   # no 2017 transformer to compare against
         "note": "GPT-2-small shape, causal Pallas flash attention, bf16 "
                 "compute + f32 master Adam"},
        flops, ms / 1e3)


def run_long(batch: int = 2, seq: int = 4096):
    """Long-context single-chip row: same GPT-2-small blocks with the
    positional table stretched to ``seq`` — exercises the flash kernels'
    causal block skipping (docs/design/attention_kernels.md). Sequences
    past ~8k on ONE chip exceed the kernels' whole-K/V-in-VMEM budget;
    that is the ring-attention regime (parallel/ring_attention.py)."""
    return run(iters=8, batch=batch, seq=seq)


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
