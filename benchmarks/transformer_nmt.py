"""Transformer encoder-decoder NMT training throughput — the flash-attention
seq2seq configuration (models/transformer_nmt.py).

The GRU seq2seq keeps the reference-parity architecture
(benchmarks/seq2seq_nmt.py); its additive attention is trapped inside the
recurrence, which caps its MFU (docs/design/nmt_roofline.md). This bench is
the TPU-first NMT shape: transformer-base-ish (d512, 8 heads, 6+6 layers),
src/trg len 64, every attention through the Pallas flash kernel with
per-sample source-length masking in-kernel.

Same honest-bench methodology as the rest: varied lengths (32..64), true
target tokens counted, rotating staged batches, on-device fori_loop with
short/long differencing, bf16 compute + f32 master Adam.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

SRC_VOCAB = TRG_VOCAB = 30000
D_MODEL = 512
SEQ = 64
MIN_LEN = 32
BATCH = 64
NBUF = 4


def build(batch: int = BATCH):
    from paddle_tpu.core import SeqBatch
    from paddle_tpu.models import TransformerSeq2Seq
    from paddle_tpu.optimizer import Adam

    model = TransformerSeq2Seq(SRC_VOCAB, TRG_VOCAB, d_model=D_MODEL,
                               n_heads=8, n_enc=6, n_dec=6, max_len=SEQ)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-3)
    state = opt.init(params)

    def loss_fn(params, src, slen, tin, tout, tlen):
        p16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        return model.loss(p16, SeqBatch(src, slen), SeqBatch(tin, tlen),
                          SeqBatch(tout, tlen)).astype(jnp.float32)

    def step_fn(params, state, *b):
        loss, grads = jax.value_and_grad(loss_fn)(params, *b)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    @jax.jit
    def run_n(params, state, srcs, slens, tins, touts, tlens, n):
        def body(i, carry):
            params, state, _ = carry
            j = i % NBUF
            pick = lambda a: jax.lax.dynamic_index_in_dim(a, j, 0,
                                                          keepdims=False)
            return step_fn(params, state, pick(srcs), pick(slens),
                           pick(tins), pick(touts), pick(tlens))
        return jax.lax.fori_loop(0, n, body, (params, state, jnp.float32(0)))

    rs = np.random.RandomState(0)
    srcs = jnp.asarray(rs.randint(3, SRC_VOCAB, (NBUF, batch, SEQ)), jnp.int32)
    tins = jnp.asarray(rs.randint(3, TRG_VOCAB, (NBUF, batch, SEQ)), jnp.int32)
    touts = jnp.asarray(rs.randint(3, TRG_VOCAB, (NBUF, batch, SEQ)), jnp.int32)
    slens = jnp.asarray(rs.randint(MIN_LEN, SEQ + 1, (NBUF, batch)), jnp.int32)
    tlens = jnp.asarray(rs.randint(MIN_LEN, SEQ + 1, (NBUF, batch)), jnp.int32)
    tokens_per_step = float(np.asarray(tlens).sum()) / NBUF
    return (run_n, step_fn, params, state,
            (srcs, slens, tins, touts, tlens), tokens_per_step)


def run(iters: int = 30, repeats: int = 2, batch: int = BATCH):
    from benchmarks.mfu import attach_mfu, step_flops
    from benchmarks.timing import chained_ms_per_step

    run_n, step_fn, params, state, b, tokens_per_step = build(batch)
    sec = chained_ms_per_step(run_n, (params, state) + b, iters,
                              repeats) / 1e3
    flops = step_flops(step_fn, params, state, *(a[0] for a in b))
    return attach_mfu(
        {"metric": f"transformer_nmt_train_true_tokens_per_sec_d512_6x6"
                   f"_len32-64_bs{batch}",
         "value": round(tokens_per_step / sec, 1), "unit": "tokens/sec",
         "vs_baseline": None,
         "note": "encoder-decoder; attention auto-routes (len<256 -> fused "
                 "dense with kv_lens masks, longer -> Pallas flash kernel); "
                 "varied lengths 32..64, true-token count, bf16 compute + "
                 "f32 master Adam"},
        flops, sec)


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(run()))
