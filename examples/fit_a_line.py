"""fit_a_line demo config (fluid/tests/book/test_fit_a_line analog).

Run: python -m paddle_tpu train --config examples/fit_a_line.py --num_passes 5
"""

import paddle_tpu.v2 as paddle
from paddle_tpu.data.dataset import uci_housing

x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
pred = paddle.layer.fc(x, 1)
cost = paddle.layer.square_error_cost(pred, y)

optimizer = paddle.optimizer.SGD(0.01)
feeding = [x, y]
outputs = [pred]


def train_reader():
    return paddle.batch(uci_housing.train(256), 64)()


def test_reader():
    return paddle.batch(uci_housing.test(64), 64)()
