"""GAN + VAE on MNIST — the v1_api_demo/{gan,vae} walk-through as one
standalone script (the reference trained both demos on MNIST digits;
gan_conf.py / vae_conf.py shapes live in models/generative.py).

Run: python examples/gan_vae_mnist.py
Trains a few hundred alternating GAN steps (D step, G step — the reference's
two-pass scheme) and a VAE, then reports: D's real/fake accuracy near
chance on fresh fakes (G fools D), and VAE ELBO improvement. Exit 0 on
success.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from paddle_tpu.models import GAN, VAE
from paddle_tpu.optimizer import Adam

BATCH = 64


def batches(n):
    """Offline stand-in for MNIST digits: samples from a fixed low-rank
    manifold x = tanh(A z + b) — a distribution an MLP generator can
    actually match (the synthetic-noise mnist generator has no structure
    for a GAN to learn; with real idx files the reference's exact task
    applies — see examples/mnist_lenet.py for the real-data path)."""
    rs = np.random.RandomState(0)
    A = rs.randn(8, 784).astype(np.float32) * 0.6
    b = rs.randn(784).astype(np.float32) * 0.1
    z = rs.randn(n, 8).astype(np.float32)
    xs = np.tanh(z @ A + b)
    for i in range(0, n - BATCH + 1, BATCH):
        yield jnp.asarray(xs[i:i + BATCH])


def train_gan(steps=300):
    model = GAN(data_dim=784, noise_dim=32, hidden=128)
    params = model.init(jax.random.PRNGKey(0))
    opt_g, opt_d = Adam(2e-4), Adam(2e-4)
    sg, sd = opt_g.init(params), opt_d.init(params)

    @jax.jit
    def d_step(params, sd, real, key):
        z = jax.random.normal(key, (real.shape[0], model.noise_dim))
        loss, grads = jax.value_and_grad(model.d_loss)(params, real, z)
        _, d_g = model.split_grads(grads)
        zero = jax.tree_util.tree_map(jnp.zeros_like,
                                      {k: v for k, v in params.items()
                                       if k.startswith("g")})
        params, sd = opt_d.update({**zero, **d_g}, sd, params)
        return params, sd, loss

    @jax.jit
    def g_step(params, sg, key):
        z = jax.random.normal(key, (BATCH, model.noise_dim))
        loss, grads = jax.value_and_grad(model.g_loss)(params, z)
        g_g, _ = model.split_grads(grads)
        zero = jax.tree_util.tree_map(jnp.zeros_like,
                                      {k: v for k, v in params.items()
                                       if k.startswith("d")})
        params, sg = opt_g.update({**zero, **g_g}, sg, params)
        return params, sg, loss

    key = jax.random.PRNGKey(1)
    data = list(batches(2048))
    g0 = jax.device_get(params["g3"]["w"])
    d0 = jax.device_get(params["d3"]["w"])
    for step in range(steps):
        key, k1, k2 = jax.random.split(key, 3)
        real = data[step % len(data)]
        params, sd, dl = d_step(params, sd, real, k1)
        params, sg, gl = g_step(params, sg, k2)
        if step % 100 == 0:
            print(f"gan step {step:4d} d_loss {float(dl):.3f} "
                  f"g_loss {float(gl):.3f}", flush=True)

    # the reference demo asserts mechanics, not equilibrium (GAN endpoints
    # oscillate): both adversarial steps trained their OWN halves, losses
    # stayed finite, and fresh samples are well-formed tanh outputs
    assert np.isfinite(float(dl)) and np.isfinite(float(gl))
    assert not np.allclose(g0, jax.device_get(params["g3"]["w"]))
    assert not np.allclose(d0, jax.device_get(params["d3"]["w"]))
    z = jax.random.normal(jax.random.PRNGKey(7), (64, model.noise_dim))
    fakes = np.asarray(model.generate(params, z))
    assert fakes.shape == (64, 784) and np.abs(fakes).max() <= 1.0
    print(f"gan done: d_loss {float(dl):.3f} g_loss {float(gl):.3f}, "
          f"64 samples in [-1, 1]")
    return params


def train_vae(steps=300):
    model = VAE(data_dim=784, latent=16, hidden=128)
    params = model.init(jax.random.PRNGKey(2))
    opt = Adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, key):
        loss, grads = jax.value_and_grad(model.loss)(params, x, key)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    key = jax.random.PRNGKey(3)
    data = list(batches(2048))
    first = last = None
    for i in range(steps):
        key, k = jax.random.split(key)
        params, state, loss = step(params, state, data[i % len(data)], k)
        if i == 0:
            first = float(loss)
        last = float(loss)
        if i % 100 == 0:
            print(f"vae step {i:4d} elbo-loss {float(loss):.2f}", flush=True)
    print(f"vae loss {first:.1f} -> {last:.1f}")
    assert last < first * 0.8
    samples = model.sample(params, jax.random.PRNGKey(8), 4)
    assert np.asarray(samples).shape == (4, 784)
    return params


if __name__ == "__main__":
    train_gan()
    train_vae()
    print("OK")
