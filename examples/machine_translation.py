"""seqToseq demo — train an attention encoder-decoder through the v2 DSL,
then GENERATE with beam search sharing the trained weights by ParamAttr name
(the reference's demo/seqToseq train.conf/gen.conf workflow,
v1_api_demo + trainer_config_helpers beam_search:964; weight sharing via
ParameterAttribute names, attrs.py:52).

The task is a synthetic but genuinely learnable translation: target token t
is (first source token + t) mod V_TRG. After a few hundred steps the beam
decode emits the correct "translation" for unseen sources — checked at the
end (exit 0 on success).

Run: python examples/machine_translation.py
"""

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle
from paddle_tpu.fluid import layers as FL
from paddle_tpu.nn import initializer as I
from paddle_tpu.v2 import networks as NW
from paddle_tpu.v2.attr import ParamAttr
from paddle_tpu.v2.layer import (GeneratedInput, LayerOutput, StaticInput,
                                 beam_search, memory, recurrent_group)

L = paddle.layer
V_SRC, V_TRG, E, H = 16, 12, 16, 32
B, TS, TT = 16, 6, 5
BOS, EOS = 0, 1          # EOS never appears in the mapping: decode runs full length


def encoder(src):
    emb = L.embedding(src, E, param_attr=ParamAttr(name="src_embed"))
    enc = L.grumemory(emb, H)
    w = FL._create_parameter("enc_proj_w", (H, H), "float32",
                             I.uniform(-0.1, 0.1), attr={"name": "enc_proj_w"})
    proj = LayerOutput(FL.matmul(enc.var, w), enc.lengths)
    return enc, proj, L.last_seq(enc)


def decoder_step(enc_last):
    """One step net, shared verbatim between training rg and beam gen —
    every parameter carries an explicit name, so the second build reuses
    the first's weights."""
    def step(y_t, enc_s, proj_s):
        dec_mem = memory("dec_state", H, boot_layer=enc_last)
        context = NW.simple_attention(enc_s, proj_s, dec_mem, name="att")
        h = L.fc([y_t, context, dec_mem], H, act="tanh", name="dec_state",
                 param_attr=ParamAttr(name="dec_h_w"),
                 bias_attr=ParamAttr(name="dec_h_b"))
        return L.fc(h, V_TRG, act="softmax",
                    param_attr=ParamAttr(name="dec_out_w"),
                    bias_attr=ParamAttr(name="dec_out_b"))
    return step


def build():
    src = L.data("src", paddle.data_type.integer_value_sequence(V_SRC))
    trg = L.data("trg", paddle.data_type.integer_value_sequence(V_TRG))
    nxt = FL.data("nxt", shape=(TT,), dtype="int64")

    enc, proj, enc_last = encoder(src)
    step = decoder_step(enc_last)

    # training branch: teacher forcing through recurrent_group
    trg_emb = L.embedding(trg, E, param_attr=ParamAttr(name="trg_embed"))
    dec = recurrent_group(step, [trg_emb, StaticInput(enc), StaticInput(proj)])
    probs2d = FL.reshape(dec.var, (-1, V_TRG))
    loss = FL.mean(FL.cross_entropy(probs2d, FL.reshape(nxt, (-1,))))

    # generation branch: beam search, every weight shared by name
    tokens, scores = beam_search(
        step,
        [GeneratedInput(V_TRG, E, embedding_param=ParamAttr(name="trg_embed")),
         StaticInput(enc), StaticInput(proj)],
        bos_id=BOS, eos_id=EOS, beam_size=4, max_length=TT)
    return loss, tokens, scores


def sample_batch(rng, n=B):
    srcs = rng.randint(2, V_SRC, (n, TS)).astype(np.int32)
    trgs = np.zeros((n, TT), np.int32)
    nxts = np.zeros((n, TT), np.int64)
    for b in range(n):
        for t in range(TT):
            # targets live in [2, V_TRG): BOS/EOS never appear mid-sequence,
            # so a correct decode is never cut short by the EOS-sticky beam
            nxts[b, t] = 2 + (srcs[b, 0] + t) % (V_TRG - 2)
            trgs[b, t] = nxts[b, t - 1] if t else BOS
    return srcs, trgs, nxts


def main():
    loss, tokens, scores = build()
    fluid.AdamOptimizer(0.02).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(7)
    lens_s = np.full((B,), TS, np.int32)
    lens_t = np.full((B,), TT, np.int32)
    for it in range(800):
        srcs, trgs, nxts = sample_batch(rng)
        feed = {"src": srcs, "src__len__": lens_s,
                "trg": trgs, "trg__len__": lens_t, "nxt": nxts}
        lv = float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]))
        if it % 100 == 0:
            print(f"iter {it:4d} loss {lv:.4f}", flush=True)

    # decode UNSEEN sources with the shared-weight generation branch
    test_rng = np.random.RandomState(99)
    srcs, trgs, nxts = sample_batch(test_rng, n=8)
    feed = {"src": srcs, "src__len__": np.full((8,), TS, np.int32),
            "trg": trgs, "trg__len__": np.full((8,), TT, np.int32),
            "nxt": nxts}
    t, s = exe.run(feed=feed, fetch_list=[tokens, scores])
    best = np.asarray(t)[:, 0, :]                   # [8, TT] best beam
    acc = float((best == nxts).mean())
    for b in range(3):
        print(f"src {srcs[b].tolist()} -> decoded {best[b].tolist()} "
              f"(want {nxts[b].tolist()})")
    print(f"beam-decode token accuracy on unseen sources: {acc:.2%}")
    assert acc > 0.9, acc
    print("OK")


if __name__ == "__main__":
    main()
