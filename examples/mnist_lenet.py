"""LeNet on MNIST — the v1_api_demo/mnist (light_mnist.py) analog.

Run:  python -m paddle_tpu train --config examples/mnist_lenet.py \
          --num_passes 3 --save_dir /tmp/mnist_out [--local_master]

Data: points at REAL idx files when ``PADDLE_TPU_MNIST_DIR`` holds
train-images-idx3-ubyte.gz / train-labels-idx1-ubyte.gz (the parser path,
data/parsers.py — the reference downloads these via dataset/common.py); in
this offline sandbox it falls back to the synthetic mnist generator, and the
checked-in 10-sample fixture demonstrates the real-bytes path in
tests/test_data_parsers.py.
"""

import os

import paddle_tpu.v2 as paddle
from paddle_tpu.fluid import layers as FL
from paddle_tpu.v2.layer import LayerOutput

img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
label = paddle.layer.data("label", paddle.data_type.integer_value(10))

x = LayerOutput(FL.reshape(img.var, (-1, 28, 28, 1)))
h = paddle.networks.simple_img_conv_pool(x, filter_size=5, num_filters=8,
                                         pool_size=2)
h = paddle.networks.simple_img_conv_pool(h, filter_size=5, num_filters=16,
                                         pool_size=2)
h = paddle.layer.fc(h, 64, act="relu")
logits = paddle.layer.fc(h, 10)
cost = paddle.layer.classification_cost(logits, label)

optimizer = paddle.optimizer.Adam(1e-3)
feeding = [img, label]
outputs = [logits]


def _readers():
    d = os.environ.get("PADDLE_TPU_MNIST_DIR")
    if d and os.path.exists(os.path.join(d, "train-images-idx3-ubyte.gz")):
        from paddle_tpu.data.parsers import mnist_reader
        return (mnist_reader(os.path.join(d, "train-images-idx3-ubyte.gz"),
                             os.path.join(d, "train-labels-idx1-ubyte.gz")),
                mnist_reader(os.path.join(d, "t10k-images-idx3-ubyte.gz"),
                             os.path.join(d, "t10k-labels-idx1-ubyte.gz")))
    from paddle_tpu.data.dataset import mnist
    return mnist.train(2048), mnist.test(512)


_train, _test = _readers()
train_reader = paddle.batch(_train, 64)
test_reader = paddle.batch(_test, 64)
