"""Pretrained-model feature extraction — the v1_api_demo/model_zoo
workflow (resnet feature extraction / embedding dump): train a small
classifier, save its parameters tar (the "model zoo" artifact), reload the
tar into a FRESH topology, and extract an intermediate layer's activations
with ``infer(field=...)`` multi-layer fetch.

Run: python examples/model_zoo_features.py
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle


def build():
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(10))
    feat = paddle.layer.fc(img, 64, act="tanh", name="feature")
    logits = paddle.layer.fc(feat, 10)
    cost = paddle.layer.classification_cost(logits, label)
    return img, label, feat, logits, cost


def main():
    # --- phase 1: train and publish the "zoo" artifact (params tar) -------
    fluid.reset_default_programs()     # standalone-script hygiene: build
    #                                    into a fresh Program regardless of
    #                                    what the importing process did
    img, label, feat, logits, cost = build()
    trainer = paddle.SGD(cost, paddle.optimizer.Adam(1e-3))
    trainer.train(paddle.batch(paddle.dataset.mnist.train(1024), 64),
                  num_passes=2, feeding=[img, label])
    tar = io.BytesIO()
    trainer.parameters.to_tar(tar)
    print(f"published artifact: {len(tar.getvalue())} bytes")

    # --- phase 2: fresh topology, load the artifact, extract features -----
    fluid.reset_default_programs()
    img, label, feat, logits, cost = build()
    consumer = paddle.SGD(cost, paddle.optimizer.Adam(1e-3))
    tar.seek(0)
    consumer.parameters.from_tar(tar)

    rows = [s for s in paddle.dataset.mnist.test(16)()]
    feats, logit_vals = paddle.infer([feat, logits], consumer, rows,
                                     feeding=[img, label], field="value")
    pred_ids = paddle.infer(logits, consumer, rows, feeding=[img, label],
                            field="id")
    assert np.asarray(feats).shape == (16, 64)
    assert np.asarray(logit_vals).shape == (16, 10)
    assert np.asarray(pred_ids).shape == (16,)

    # the consumer's predictions must match the trainer's own (the artifact
    # round-trip is faithful)
    want = paddle.infer(logits, trainer, rows, feeding=[img, label],
                        field="id")
    np.testing.assert_array_equal(np.asarray(pred_ids), np.asarray(want))
    print(f"extracted {np.asarray(feats).shape} features; "
          f"predictions match the publisher exactly")
    print("OK")


if __name__ == "__main__":
    main()
