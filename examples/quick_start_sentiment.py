"""quick_start text-classification demo (v1_api_demo/quick_start LSTM config
analog: embedding -> LSTM -> max pool -> softmax).

Run: python -m paddle_tpu train --config examples/quick_start_sentiment.py
"""

import paddle_tpu.v2 as paddle
from paddle_tpu.data.dataset import imdb

words = paddle.layer.data(
    "words", paddle.data_type.integer_value_sequence(imdb.VOCAB))
label = paddle.layer.data("label", paddle.data_type.integer_value(2))
emb = paddle.layer.embedding(words, 32)
lstm = paddle.networks.simple_lstm(emb, 32)
pooled = paddle.layer.pooling(lstm, "max")
logits = paddle.layer.fc(pooled, 2)
cost = paddle.layer.classification_cost(logits, label)

optimizer = paddle.optimizer.Adam(1e-2)
feeding = [words, label]
outputs = [logits]


def train_reader():
    return paddle.batch(imdb.train(256), 32)()


def test_reader():
    return paddle.batch(imdb.test(64), 32)()
