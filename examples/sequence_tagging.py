"""sequence_tagging demo (v1_api_demo/sequence_tagging linear_crf analog:
embedding -> BiLSTM-ish projection -> linear-chain CRF cost).

Run: python -m paddle_tpu train --config examples/sequence_tagging.py
"""

import paddle_tpu.v2 as paddle
from paddle_tpu.data.dataset import conll05

L = paddle.layer

words = L.data("words",
               paddle.data_type.integer_value_sequence(conll05.VOCAB))
tags = L.data("tags",
              paddle.data_type.integer_value_sequence(conll05.TAGS))
emb = L.embedding(words, 24)
hidden = L.lstmemory(emb, 24)
emission = L.mixed_layer(
    size=conll05.TAGS,
    input=[L.full_matrix_projection(hidden, conll05.TAGS)])
cost = L.crf_layer(emission, tags)

optimizer = paddle.optimizer.Adam(5e-3)
feeding = [words, tags]
outputs = [emission]


def train_reader():
    return paddle.batch(conll05.train(128), 16)()
