"""Serving demo: continuous (in-flight) batching over a transformer LM.

The modern serving loop on top of the incremental-decode path: a fixed pool
of KV-cache slots, requests with MIXED prompt and generation lengths
admitted into freed slots at segment boundaries, longest-first scheduling
(paddle_tpu/serving/batcher.py). The 2017 reference's serving story stops at the C
inference ABI (capi/gradient_machine.h:73 forward); this is the capability
a 2024 deployment expects on top of it — every emitted token is exactly
what solo greedy decode would produce (tests/test_serving.py).

Run: python examples/serving_llm.py  (set SERVING_DEMO_SMALL=1 for the CI
shape: tiny model, runs in seconds on CPU).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

from paddle_tpu.models import TransformerLM  # noqa: E402
from paddle_tpu.serving import ContinuousBatcher, Request  # noqa: E402


def main():
    small = bool(os.environ.get("SERVING_DEMO_SMALL"))
    if small:
        vocab, d_model, n_heads, n_layers, max_len = 211, 32, 4, 2, 128
        slots, segment, n_requests, lo, hi = 4, 8, 10, 4, 24
    else:
        vocab, d_model, n_heads, n_layers, max_len = 50257, 768, 12, 12, 1024
        slots, segment, n_requests, lo, hi = 64, 64, 128, 32, 256

    model = TransformerLM(vocab, d_model=d_model, n_heads=n_heads,
                          n_layers=n_layers, max_len=max_len)
    params = model.init(jax.random.PRNGKey(0))

    rs = np.random.RandomState(0)
    # hi is inclusive (randint's upper bound is exclusive) — same U[lo, hi]
    # distribution as benchmarks/serving_decode.py run_continuous
    requests = [Request(
        rid=i,
        prompt=rs.randint(0, vocab, int(rs.randint(lo, hi + 1))),
        max_new=int(rs.randint(lo, hi + 1)))
        for i in range(n_requests)]

    batcher = ContinuousBatcher(model, params, slots=slots, segment=segment)
    t0 = time.perf_counter()
    results = batcher.serve(requests)
    dt = time.perf_counter() - t0

    delivered = 0
    for r in requests:
        out = results[r.rid]
        delivered += len(out)
        print(f"request {r.rid:3d}: prompt {len(r.prompt):3d} tokens -> "
              f"generated {len(out):3d}  head={out[:6].tolist()}")
    print(f"\nserved {len(requests)} requests, {delivered} tokens in "
          f"{dt:.2f}s ({delivered / dt:.0f} tok/s delivered)")


if __name__ == "__main__":
    main()
