"""Traffic-flow time-series regression — the v1_api_demo/traffic_prediction
analog (LSTM over a sliding window of lane-sensor readings, regressing the
next reading).

Run: python -m paddle_tpu train --config examples/traffic_prediction.py \
         --num_passes 5 --log_period 8

The demo's corpus is synthesized here (offline sandbox): daily-periodic
sensor curves plus noise, windowed into (history sequence, next value)
pairs — the same shape the reference fed from its CSV.
"""

import numpy as np

import paddle_tpu.v2 as paddle

WINDOW = 24      # hours of history per sample
SENSORS = 4      # readings per timestep

seq = paddle.layer.data(
    "seq", paddle.data_type.dense_vector_sequence(SENSORS))
nxt = paddle.layer.data("next", paddle.data_type.dense_vector(SENSORS))

lstm = paddle.networks.simple_lstm(seq, 32)
last = paddle.layer.last_seq(lstm)
pred = paddle.layer.fc(last, SENSORS)
cost = paddle.layer.mse_cost(pred, nxt)

optimizer = paddle.optimizer.Adam(5e-3)
feeding = [seq, nxt]
outputs = [pred]


def _series(n_days=20, seed=0):
    """Synthetic lane sensors: daily sinusoid + rush-hour bumps + noise."""
    rs = np.random.RandomState(seed)
    t = np.arange(n_days * 24)
    base = np.stack([
        0.5 + 0.4 * np.sin(2 * np.pi * (t - 6 - 2 * s) / 24.0)
        + 0.2 * np.exp(-((t % 24 - 8) ** 2) / 4.0)       # morning rush
        + 0.15 * np.exp(-((t % 24 - 18) ** 2) / 6.0)     # evening rush
        for s in range(SENSORS)], axis=-1)
    return (base + rs.randn(*base.shape) * 0.03).astype(np.float32)


def _windows(series):
    def reader():
        for i in range(len(series) - WINDOW):
            yield series[i:i + WINDOW], series[i + WINDOW]
    return reader


train_reader = paddle.batch(_windows(_series(20)), 32)
test_reader = paddle.batch(_windows(_series(4, seed=9)), 32)
