// Host buddy allocator — re-provision of paddle/memory's BuddyAllocator
// (reference: memory/detail/buddy_allocator.cc over system_allocator.cc,
// wired by memory/memory.cc:30-66). On TPU the device HBM is managed by
// PJRT/XLA; this arena manages *host* staging memory for the feeder path
// (pinned-buffer analog) so batch assembly doesn't churn malloc.
//
// Classic power-of-two buddy over one contiguous arena; offsets returned, the
// Python side views them into a shared bytearray/mmap.

#include <cstdint>
#include <map>
#include <mutex>
#include <set>
#include <vector>

namespace {

struct Buddy {
  std::mutex mu;
  uint64_t total = 0;
  uint64_t min_block = 0;
  int levels = 0;  // level 0 = whole arena; level L blocks of total>>L
  // free lists per level: set of offsets
  std::vector<std::set<uint64_t>> free_lists;
  // allocated offset -> level
  std::map<uint64_t, int> allocated;
  uint64_t in_use = 0;
};

int level_for(Buddy* b, uint64_t size) {
  uint64_t block = b->total;
  int lvl = 0;
  while (lvl < b->levels - 1 && block / 2 >= size && block / 2 >= b->min_block) {
    block /= 2;
    lvl++;
  }
  return lvl;
}

}  // namespace

extern "C" {

void* pta_create(uint64_t total, uint64_t min_block) {
  if (total == 0 || (total & (total - 1)) != 0) return nullptr;   // pow2 only
  if (min_block == 0 || (min_block & (min_block - 1)) != 0) return nullptr;
  auto* b = new Buddy();
  b->total = total;
  b->min_block = min_block;
  b->levels = 1;
  uint64_t s = total;
  while (s > min_block) {
    s /= 2;
    b->levels++;
  }
  b->free_lists.resize(b->levels);
  b->free_lists[0].insert(0);
  return b;
}

void pta_destroy(void* h) { delete static_cast<Buddy*>(h); }

// Returns offset, or UINT64_MAX on OOM.
uint64_t pta_alloc(void* h, uint64_t size) {
  auto* b = static_cast<Buddy*>(h);
  std::lock_guard<std::mutex> g(b->mu);
  if (size == 0 || size > b->total) return UINT64_MAX;
  int want = level_for(b, size);
  int lvl = want;
  while (lvl >= 0 && b->free_lists[lvl].empty()) lvl--;
  if (lvl < 0) return UINT64_MAX;
  // split down to the wanted level
  while (lvl < want) {
    uint64_t off = *b->free_lists[lvl].begin();
    b->free_lists[lvl].erase(b->free_lists[lvl].begin());
    uint64_t half = b->total >> (lvl + 1);
    b->free_lists[lvl + 1].insert(off);
    b->free_lists[lvl + 1].insert(off + half);
    lvl++;
  }
  uint64_t off = *b->free_lists[want].begin();
  b->free_lists[want].erase(b->free_lists[want].begin());
  b->allocated[off] = want;
  b->in_use += b->total >> want;
  return off;
}

// Free + coalesce with buddy (buddy_allocator.cc merge path).
int pta_free(void* h, uint64_t off) {
  auto* b = static_cast<Buddy*>(h);
  std::lock_guard<std::mutex> g(b->mu);
  auto it = b->allocated.find(off);
  if (it == b->allocated.end()) return -1;
  int lvl = it->second;
  b->allocated.erase(it);
  b->in_use -= b->total >> lvl;
  while (lvl > 0) {
    uint64_t block = b->total >> lvl;
    uint64_t buddy = off ^ block;
    auto& fl = b->free_lists[lvl];
    auto bit = fl.find(buddy);
    if (bit == fl.end()) break;
    fl.erase(bit);
    off = off < buddy ? off : buddy;
    lvl--;
  }
  b->free_lists[lvl].insert(off);
  return 0;
}

void pta_stats(void* h, uint64_t* total, uint64_t* in_use, uint64_t* largest_free) {
  auto* b = static_cast<Buddy*>(h);
  std::lock_guard<std::mutex> g(b->mu);
  *total = b->total;
  *in_use = b->in_use;
  *largest_free = 0;
  for (int lvl = 0; lvl < b->levels; lvl++) {
    if (!b->free_lists[lvl].empty()) {
      *largest_free = b->total >> lvl;
      break;
    }
  }
}

}  // extern "C"
