// C inference API — the paddle/capi analog for deployment embedding.
//
// Reference surface re-provided (capi/gradient_machine.h:36-88):
//   paddle_gradient_machine_create_for_inference  -> pti_create(model_dir)
//   paddle_gradient_machine_forward               -> pti_forward(...)
//   paddle_gradient_machine_destroy               -> pti_destroy
// plus pti_last_error() for diagnostics.
//
// Design: the reference's capi wraps its real C++ engine; ours wraps the real
// XLA-backed executor by EMBEDDING CPython (the reference itself embeds
// Python for data providers — PyDataProvider2.cpp precedent) and driving
// paddle_tpu.runtime.capi_host.InferenceHost, which loads the exported
// inference bundle (fluid/io.py export_inference_model: pruned program JSON +
// params tar — the merged-model artifact of trainer/MergeModel.cpp:29).
// Forward-only, thread-safe: every call takes the GIL (concurrent callers
// serialize; XLA releases the GIL during device execution).
//
// ABI (all through ctypes/dlopen; no C++ name mangling):
//   void* pti_create(const char* model_dir);
//   int   pti_forward(void* h,
//                     const void** inputs,      // n_inputs buffers
//                     const long long* shapes,  // concatenated dims
//                     const int* ndims,         // dims per input
//                     const int* dtypes,        // 0=f32 1=i32 per input
//                     int n_inputs,
//                     int fetch_index,          // which fetch target
//                     float* out_buf, long long out_capacity,
//                     long long* out_shape,     // >= PTI_MAX_NDIM entries
//                     int* out_ndim);           // <- results
//         returns number of f32 elements written, or -1 (error: see
//         pti_last_error) / -2 (out_buf too small; out_shape/out_ndim are
//         still filled so the caller can retry with a bigger buffer).
//   void  pti_destroy(void* h);
//   const char* pti_last_error(void);

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string g_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  g_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) g_error = msg;
      else PyErr_Clear();  // un-encodable message: keep the generic text
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Host {
  PyObject* obj;  // paddle_tpu.runtime.capi_host.InferenceHost
};

std::once_flag g_py_init;

void ensure_python() {
  // once_flag: concurrent first-time pti_create calls must not
  // double-initialize CPython (double PyEval_SaveThread is fatal)
  std::call_once(g_py_init, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL acquired by initialization so pti_forward's
      // PyGILState_Ensure works from any thread
      PyEval_SaveThread();
    }
  });
}

}  // namespace

extern "C" {

// maximum output rank written to out_shape; callers size their buffer to this
#define PTI_MAX_NDIM 8

const char* pti_last_error(void) { return g_error.c_str(); }

void* pti_create(const char* model_dir) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.runtime.capi_host");
  if (!mod) {
    set_error_from_python();
    PyGILState_Release(gil);
    return nullptr;
  }
  PyObject* cls = PyObject_GetAttrString(mod, "InferenceHost");
  Py_DECREF(mod);
  if (cls) {
    PyObject* obj = PyObject_CallFunction(cls, "s", model_dir);
    Py_DECREF(cls);
    if (obj) {
      Host* h = new Host{obj};
      result = h;
    } else {
      set_error_from_python();
    }
  } else {
    set_error_from_python();
  }
  PyGILState_Release(gil);
  return result;
}

int pti_forward(void* handle, const void** inputs, const long long* shapes,
                const int* ndims, const int* dtypes, int n_inputs,
                int fetch_index, float* out_buf, long long out_capacity,
                long long* out_shape, int* out_ndim) {
  if (!handle) {
    g_error = "null handle";
    return -1;
  }
  Host* h = static_cast<Host*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;

  // build [(bytes, (dims...), dtype_code), ...]
  PyObject* args_list = PyList_New(n_inputs);
  if (!args_list) {
    set_error_from_python();
    PyGILState_Release(gil);
    return -1;
  }
  long long shape_off = 0;
  for (int i = 0; i < n_inputs; i++) {
    long long numel = 1;
    PyObject* dims = PyTuple_New(ndims[i]);
    if (!dims) {
      set_error_from_python();
      Py_DECREF(args_list);
      PyGILState_Release(gil);
      return -1;
    }
    for (int d = 0; d < ndims[i]; d++) {
      long long dim = shapes[shape_off + d];
      numel *= dim;
      PyObject* dim_obj = PyLong_FromLongLong(dim);
      if (!dim_obj) {
        set_error_from_python();
        Py_DECREF(dims);
        Py_DECREF(args_list);
        PyGILState_Release(gil);
        return -1;
      }
      PyTuple_SET_ITEM(dims, d, dim_obj);
    }
    shape_off += ndims[i];
    size_t nbytes = (size_t)numel * 4;  // f32 and i32 are both 4 bytes
    PyObject* payload = PyBytes_FromStringAndSize(
        static_cast<const char*>(inputs[i]), (Py_ssize_t)nbytes);
    PyObject* dtype_obj = payload ? PyLong_FromLong(dtypes[i]) : nullptr;
    PyObject* entry =
        dtype_obj ? PyTuple_Pack(3, payload, dims, dtype_obj) : nullptr;
    Py_XDECREF(dtype_obj);
    Py_XDECREF(payload);
    Py_DECREF(dims);
    if (!entry) {
      set_error_from_python();
      Py_DECREF(args_list);
      PyGILState_Release(gil);
      return -1;
    }
    PyList_SET_ITEM(args_list, i, entry);  // steals entry
  }

  PyObject* res = PyObject_CallMethod(h->obj, "run_raw", "Oi", args_list,
                                      fetch_index);
  Py_DECREF(args_list);
  if (!res) {
    set_error_from_python();
    PyGILState_Release(gil);
    return -1;
  }
  // res = (bytes, (dims...))
  PyObject* payload = PyTuple_GetItem(res, 0);
  PyObject* dims = PyTuple_GetItem(res, 1);
  Py_ssize_t n_dims = PyTuple_Size(dims);
  long long numel = 1;
  for (Py_ssize_t d = 0; d < n_dims; d++) {
    long long v = PyLong_AsLongLong(PyTuple_GetItem(dims, d));
    if (out_shape && d < PTI_MAX_NDIM) out_shape[d] = v;
    numel *= v;
  }
  if (n_dims > PTI_MAX_NDIM) {
    g_error = "output rank exceeds PTI_MAX_NDIM";
    Py_DECREF(res);
    PyGILState_Release(gil);
    return -1;
  }
  if (out_ndim) *out_ndim = (int)n_dims;
  if (numel > out_capacity) {
    g_error = "output buffer too small";
    rc = -2;
  } else {
    memcpy(out_buf, PyBytes_AsString(payload), (size_t)numel * 4);
    rc = (int)numel;
  }
  Py_DECREF(res);
  PyGILState_Release(gil);
  return rc;
}

void pti_destroy(void* handle) {
  if (!handle) return;
  Host* h = static_cast<Host*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_DECREF(h->obj);
  PyGILState_Release(gil);
  delete h;
}

}  // extern "C"
