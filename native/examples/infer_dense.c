/* Dense-input inference from plain C — the capi/examples/model_inference/
 * dense analog. Links against libpaddle_tpu_capi.so; the library embeds
 * CPython and runs the real XLA executor on the exported bundle.
 *
 * Build: gcc infer_dense.c -o infer_dense -L../.. -lpaddle_tpu_capi
 * Run:   ./infer_dense <model_dir> <n_rows> <in_dim>
 * Prints one line per output row; exit 0 on success.
 */
#include <stdio.h>
#include <stdlib.h>

extern void* pti_create(const char* model_dir);
extern int pti_forward(void* h, const void** inputs, const long long* shapes,
                       const int* ndims, const int* dtypes, int n_inputs,
                       int fetch_index, float* out_buf, long long out_capacity,
                       long long* out_shape, int* out_ndim);
extern void pti_destroy(void* h);
extern const char* pti_last_error(void);

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <model_dir> <n_rows> <in_dim>\n", argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  int n = atoi(argv[2]);
  int d = atoi(argv[3]);

  void* h = pti_create(model_dir);
  if (!h) {
    fprintf(stderr, "create failed: %s\n", pti_last_error());
    return 1;
  }

  float* in = malloc(sizeof(float) * n * d);
  for (int i = 0; i < n * d; i++) in[i] = (float)(i % 7) * 0.1f - 0.3f;

  const void* inputs[1] = {in};
  long long shapes[2] = {n, d};
  int ndims[1] = {2};
  int dtypes[1] = {0}; /* f32 */
  long long cap = 1 << 20;
  float* out = malloc(sizeof(float) * cap);
  long long out_shape[8];
  int out_ndim = 0;

  int rc = pti_forward(h, inputs, shapes, ndims, dtypes, 1, 0, out, cap,
                       out_shape, &out_ndim);
  if (rc < 0) {
    fprintf(stderr, "forward failed (%d): %s\n", rc, pti_last_error());
    return 1;
  }
  long long rows_n = out_ndim >= 1 ? out_shape[0] : 1; /* 0-dim -> 1 value */
  long long cols = out_ndim >= 2 ? out_shape[1] : 1;
  for (long long r = 0; r < rows_n; r++) {
    for (long long c = 0; c < cols; c++)
      printf("%s%.6f", c ? " " : "", out[r * cols + c]);
    printf("\n");
  }
  free(in);
  free(out);
  pti_destroy(h);
  return 0;
}
