/* Concurrent inference from plain C — the
 * capi/examples/model_inference/multi_thread analog. N pthreads share ONE
 * model handle and forward simultaneously; the library serializes through
 * the embedded interpreter's GIL (XLA releases it during device execution)
 * so every call must return the same bit-exact result for the same input.
 *
 * Build: gcc infer_multi_thread.c -o infer_multi_thread -pthread \
 *            -L../.. -lpaddle_tpu_capi
 * Run:   ./infer_multi_thread <model_dir> <n_threads> <iters> <n_rows> <dim>
 * Prints the reference row values then "OK <n_threads>x<iters>"; exit 0 on
 * success, 1 on any thread error or cross-thread mismatch.
 */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

extern void* pti_create(const char* model_dir);
extern int pti_forward(void* h, const void** inputs, const long long* shapes,
                       const int* ndims, const int* dtypes, int n_inputs,
                       int fetch_index, float* out_buf, long long out_capacity,
                       long long* out_shape, int* out_ndim);
extern void pti_destroy(void* h);
extern const char* pti_last_error(void);

#define MAX_OUT (1 << 16)

static void* g_handle;
static float* g_input;
static long long g_shapes[2];
static float g_ref[MAX_OUT];
static int g_ref_elems;

static int do_forward(float* out, long long* out_shape, int* out_ndim) {
  const void* inputs[1] = {g_input};
  int ndims[1] = {2};
  int dtypes[1] = {0};
  return pti_forward(g_handle, inputs, g_shapes, ndims, dtypes, 1, 0, out,
                     MAX_OUT, out_shape, out_ndim);
}

struct worker_arg {
  int iters;
  int failed;
};

static void* worker(void* p) {
  struct worker_arg* a = (struct worker_arg*)p;
  float out[MAX_OUT];
  long long out_shape[8];
  int out_ndim;
  for (int i = 0; i < a->iters; i++) {
    int rc = do_forward(out, out_shape, &out_ndim);
    if (rc != g_ref_elems ||
        memcmp(out, g_ref, sizeof(float) * (size_t)g_ref_elems) != 0) {
      fprintf(stderr, "thread mismatch at iter %d (rc=%d): %s\n", i, rc,
              rc < 0 ? pti_last_error() : "values differ");
      a->failed = 1;
      return NULL;
    }
  }
  return NULL;
}

int main(int argc, char** argv) {
  if (argc != 6) {
    fprintf(stderr, "usage: %s <model_dir> <n_threads> <iters> <n_rows> <dim>\n",
            argv[0]);
    return 2;
  }
  int n_threads = atoi(argv[2]), iters = atoi(argv[3]);
  int n = atoi(argv[4]), d = atoi(argv[5]);

  g_handle = pti_create(argv[1]);
  if (!g_handle) {
    fprintf(stderr, "create failed: %s\n", pti_last_error());
    return 1;
  }
  g_input = malloc(sizeof(float) * n * d);
  for (int i = 0; i < n * d; i++) g_input[i] = (float)(i % 5) * 0.2f - 0.4f;
  g_shapes[0] = n;
  g_shapes[1] = d;

  long long out_shape[8];
  int out_ndim;
  g_ref_elems = do_forward(g_ref, out_shape, &out_ndim);
  if (g_ref_elems < 0) {
    fprintf(stderr, "reference forward failed: %s\n", pti_last_error());
    return 1;
  }
  long long cols = out_ndim >= 2 ? out_shape[1] : 1;
  for (int r = 0; r < (out_ndim >= 1 ? out_shape[0] : 1); r++) {
    for (long long c = 0; c < cols; c++)
      printf("%s%.6f", c ? " " : "", g_ref[r * cols + c]);
    printf("\n");
  }

  pthread_t* tids = malloc(sizeof(pthread_t) * n_threads);
  struct worker_arg* args = calloc(n_threads, sizeof(struct worker_arg));
  for (int t = 0; t < n_threads; t++) {
    args[t].iters = iters;
    pthread_create(&tids[t], NULL, worker, &args[t]);
  }
  int failed = 0;
  for (int t = 0; t < n_threads; t++) {
    pthread_join(tids[t], NULL);
    failed |= args[t].failed;
  }
  free(tids);
  free(args);
  free(g_input);
  pti_destroy(g_handle);
  if (failed) return 1;
  printf("OK %dx%d\n", n_threads, iters);
  return 0;
}
