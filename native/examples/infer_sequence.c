/* Sequence (lengths-carrying) inference from plain C — the
 * capi/examples/model_inference/sequence analog. The exported model takes
 * int32 token ids padded to [batch, max_len] plus an int32 [batch] lengths
 * slot (the TPU-native LoD encoding: SURVEY sequence design — padded dense
 * tensor + true lengths instead of the reference's row offsets).
 *
 * Build: gcc infer_sequence.c -o infer_sequence -L../.. -lpaddle_tpu_capi
 * Run:   ./infer_sequence <model_dir> <batch> <max_len> <vocab>
 * Prints one line per sequence; exit 0 on success.
 */
#include <stdio.h>
#include <stdlib.h>

extern void* pti_create(const char* model_dir);
extern int pti_forward(void* h, const void** inputs, const long long* shapes,
                       const int* ndims, const int* dtypes, int n_inputs,
                       int fetch_index, float* out_buf, long long out_capacity,
                       long long* out_shape, int* out_ndim);
extern void pti_destroy(void* h);
extern const char* pti_last_error(void);

int main(int argc, char** argv) {
  if (argc != 5) {
    fprintf(stderr, "usage: %s <model_dir> <batch> <max_len> <vocab>\n",
            argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  int batch = atoi(argv[2]);
  int max_len = atoi(argv[3]);
  int vocab = atoi(argv[4]);

  void* h = pti_create(model_dir);
  if (!h) {
    fprintf(stderr, "create failed: %s\n", pti_last_error());
    return 1;
  }

  /* deterministic ragged batch: sequence b has length max_len - b (>=1),
   * ids cycle through the vocabulary; padding positions hold 0 and must be
   * ignored by the model because the lengths slot masks them. */
  int* ids = calloc((size_t)batch * max_len, sizeof(int));
  int* lens = malloc(sizeof(int) * batch);
  for (int b = 0; b < batch; b++) {
    int len = max_len - b;
    if (len < 1) len = 1;
    lens[b] = len;
    for (int t = 0; t < len; t++)
      ids[b * max_len + t] = (b * 31 + t * 7) % vocab;
  }

  const void* inputs[2] = {ids, lens};
  long long shapes[3] = {batch, max_len, batch}; /* [B,T] then [B] */
  int ndims[2] = {2, 1};
  int dtypes[2] = {1, 1}; /* both i32 */
  long long cap = 1 << 20;
  float* out = malloc(sizeof(float) * cap);
  long long out_shape[8];
  int out_ndim = 0;

  int rc = pti_forward(h, inputs, shapes, ndims, dtypes, 2, 0, out, cap,
                       out_shape, &out_ndim);
  if (rc < 0) {
    fprintf(stderr, "forward failed (%d): %s\n", rc, pti_last_error());
    return 1;
  }
  long long rows_n = out_ndim >= 1 ? out_shape[0] : 1;
  long long cols = out_ndim >= 2 ? out_shape[1] : 1;
  for (long long r = 0; r < rows_n; r++) {
    for (long long c = 0; c < cols; c++)
      printf("%s%.6f", c ? " " : "", out[r * cols + c]);
    printf("\n");
  }
  free(ids);
  free(lens);
  free(out);
  pti_destroy(h);
  return 0;
}
