/* Sparse-binary-input inference from plain C — the
 * capi/examples/model_inference/sparse_binary analog. Each row is a
 * multi-hot feature set passed as a padded int32 index list plus an int32
 * nnz-count slot; the exported model embeds the active features and
 * row-sums them (the weighted-row-sum sparse-fc path, quick_start LR
 * config) — the TPU-native encoding of the reference's sparse_binary_vector
 * argument.
 *
 * Build: gcc infer_sparse_binary.c -o infer_sparse_binary -L../.. \
 *            -lpaddle_tpu_capi
 * Run:   ./infer_sparse_binary <model_dir> <batch> <max_nnz> <dim>
 * Prints one line per row; exit 0 on success.
 */
#include <stdio.h>
#include <stdlib.h>

extern void* pti_create(const char* model_dir);
extern int pti_forward(void* h, const void** inputs, const long long* shapes,
                       const int* ndims, const int* dtypes, int n_inputs,
                       int fetch_index, float* out_buf, long long out_capacity,
                       long long* out_shape, int* out_ndim);
extern void pti_destroy(void* h);
extern const char* pti_last_error(void);

int main(int argc, char** argv) {
  if (argc != 5) {
    fprintf(stderr, "usage: %s <model_dir> <batch> <max_nnz> <dim>\n",
            argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  int batch = atoi(argv[2]);
  int max_nnz = atoi(argv[3]);
  int dim = atoi(argv[4]);

  void* h = pti_create(model_dir);
  if (!h) {
    fprintf(stderr, "create failed: %s\n", pti_last_error());
    return 1;
  }

  /* deterministic multi-hot rows: row b activates features
   * (b*13 + j*5) % dim for j < nnz, nnz = max_nnz - (b % max_nnz). */
  int* ids = calloc((size_t)batch * max_nnz, sizeof(int));
  int* counts = malloc(sizeof(int) * batch);
  for (int b = 0; b < batch; b++) {
    int nnz = max_nnz - (b % max_nnz);
    counts[b] = nnz;
    for (int j = 0; j < nnz; j++)
      ids[b * max_nnz + j] = (b * 13 + j * 5) % dim;
  }

  const void* inputs[2] = {ids, counts};
  long long shapes[3] = {batch, max_nnz, batch};
  int ndims[2] = {2, 1};
  int dtypes[2] = {1, 1}; /* both i32 */
  long long cap = 1 << 20;
  float* out = malloc(sizeof(float) * cap);
  long long out_shape[8];
  int out_ndim = 0;

  int rc = pti_forward(h, inputs, shapes, ndims, dtypes, 2, 0, out, cap,
                       out_shape, &out_ndim);
  if (rc < 0) {
    fprintf(stderr, "forward failed (%d): %s\n", rc, pti_last_error());
    return 1;
  }
  long long rows_n = out_ndim >= 1 ? out_shape[0] : 1;
  long long cols = out_ndim >= 2 ? out_shape[1] : 1;
  for (long long r = 0; r < rows_n; r++) {
    for (long long c = 0; c < cols; c++)
      printf("%s%.6f", c ? " " : "", out[r * cols + c]);
    printf("\n");
  }
  free(ids);
  free(counts);
  free(out);
  pti_destroy(h);
  return 0;
}
