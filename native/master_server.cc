// Length-framed RPC server for the task master — the C++ host-RPC plane
// (reference: pserver/ProtoServer.h:36 length-framed messages over raw
// sockets + go/master/service.go's RPC surface). The accept/dispatch loop
// runs natively over the ptm_* C ABI (task_master.cc); Python keeps the
// control plane (lease election, fencing decisions, snapshot policy) and
// pushes the resulting fenced flag down via ptms_set_fenced.
//
// Wire format (runtime/master_service.py): uint32 LE body length + JSON
// body. Requests: {"op": str, "task_id"?: int, "payloads"?: [str]}.
// Responses mirror MasterServer._dispatch exactly, including the
// "fenced: ..." error string the client's failover logic matches on.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

// task_master.cc C ABI
extern "C" {
void ptm_set_dataset(void* h, const char** payloads, int n);
int ptm_get_task(void* h, double now, char* buf, int buflen, int* needed);
int ptm_task_finished(void* h, int task_id);
int ptm_task_failed(void* h, int task_id);
int ptm_new_pass(void* h);
void ptm_stats(void* h, int* todo, int* pending, int* done, int* discarded,
               int* epoch);
}

namespace {

constexpr uint32_t kMaxFrame = 64u << 20;  // 64 MB request guard

double mono_now() {
  // CLOCK_MONOTONIC — the same clock Python's time.monotonic() uses, so
  // deadlines set here agree with the Python housekeeping tick's clock
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// ---------------------------------------------------------------- JSON ----
// Minimal parser for the request shapes above (full string escapes incl.
// \uXXXX with surrogate pairs) and an escaping emitter for responses.

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++; }

  bool lit(const char* s) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  void utf8(uint32_t cp, std::string* out) {
    if (cp < 0x80) { out->push_back((char)cp); }
    else if (cp < 0x800) {
      out->push_back((char)(0xC0 | (cp >> 6)));
      out->push_back((char)(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out->push_back((char)(0xE0 | (cp >> 12)));
      out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out->push_back((char)(0x80 | (cp & 0x3F)));
    } else {
      out->push_back((char)(0xF0 | (cp >> 18)));
      out->push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
      out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out->push_back((char)(0x80 | (cp & 0x3F)));
    }
  }

  bool hex4(uint32_t* v) {
    if (end - p < 4) return false;
    *v = 0;
    for (int i = 0; i < 4; i++) {
      char c = *p++;
      *v <<= 4;
      if (c >= '0' && c <= '9') *v |= c - '0';
      else if (c >= 'a' && c <= 'f') *v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') *v |= c - 'A' + 10;
      else return false;
    }
    return true;
  }

  bool str(std::string* out) {
    ws();
    if (p >= end || *p != '"') return false;
    p++;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        p++;
        if (p >= end) return false;
        char c = *p++;
        switch (c) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!hex4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (!lit("\\u")) return false;
              uint32_t lo;
              if (!hex4(&lo) || lo < 0xDC00 || lo > 0xDFFF) return false;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            utf8(cp, out);
            break;
          }
          default: return false;
        }
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return false;
    p++;  // closing quote
    return true;
  }

  bool integer(long long* out) {
    ws();
    char* q = nullptr;
    long long v = strtoll(p, &q, 10);
    if (q == p) return false;
    *out = v;
    p = q;
    return true;
  }

  // scan (and discard) one JSON number, fraction/exponent included —
  // hand-rolled rather than strtod() because strtod honours LC_NUMERIC
  // (a comma-decimal locale would stop at the '.') while JSON does not
  bool skip_number() {
    const char* q = p;
    if (q < end && *q == '-') q++;
    bool digits = false;
    while (q < end && *q >= '0' && *q <= '9') { q++; digits = true; }
    if (!digits) return false;
    if (q < end && *q == '.') {
      q++;
      bool frac = false;
      while (q < end && *q >= '0' && *q <= '9') { q++; frac = true; }
      if (!frac) return false;
    }
    if (q < end && (*q == 'e' || *q == 'E')) {
      q++;
      if (q < end && (*q == '+' || *q == '-')) q++;
      bool exp = false;
      while (q < end && *q >= '0' && *q <= '9') { q++; exp = true; }
      if (!exp) return false;
    }
    p = q;
    return true;
  }

  // skip any JSON value (for unknown keys). Numbers may be doubles here:
  // skipped values include metric samples (obs_push) whose floats the
  // integer() path would choke on mid-frame.
  bool skip() {
    ws();
    if (p >= end) return false;
    if (*p == '"') { std::string s; return str(&s); }
    if (*p == '{' || *p == '[') {
      char open = *p, close = (open == '{') ? '}' : ']';
      p++;
      ws();
      if (p < end && *p == close) { p++; return true; }
      for (;;) {
        if (open == '{') {
          std::string k;
          if (!str(&k)) return false;
          ws();
          if (p >= end || *p != ':') return false;
          p++;
        }
        if (!skip()) return false;
        ws();
        if (p < end && *p == ',') { p++; continue; }
        if (p < end && *p == close) { p++; return true; }
        return false;
      }
    }
    if (lit("true") || lit("false") || lit("null")) return true;
    return skip_number();
  }
};

struct Request {
  std::string op;
  long long task_id = -1;
  std::vector<std::string> payloads;
  bool ok = false;
};

Request parse_request(const std::string& body) {
  Request r;
  Parser ps(body);
  ps.ws();
  if (ps.p >= ps.end || *ps.p != '{') return r;
  ps.p++;
  ps.ws();
  if (ps.p < ps.end && *ps.p == '}') { ps.p++; r.ok = true; return r; }
  for (;;) {
    std::string key;
    if (!ps.str(&key)) return r;
    ps.ws();
    if (ps.p >= ps.end || *ps.p != ':') return r;
    ps.p++;
    if (key == "op") {
      if (!ps.str(&r.op)) return r;
    } else if (key == "task_id") {
      if (!ps.integer(&r.task_id)) return r;
    } else if (key == "payloads") {
      ps.ws();
      if (ps.p >= ps.end || *ps.p != '[') return r;
      ps.p++;
      ps.ws();
      if (ps.p < ps.end && *ps.p == ']') {
        ps.p++;
      } else {
        for (;;) {
          std::string s;
          if (!ps.str(&s)) return r;
          r.payloads.push_back(std::move(s));
          ps.ws();
          if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
          if (ps.p < ps.end && *ps.p == ']') { ps.p++; break; }
          return r;
        }
      }
    } else {
      if (!ps.skip()) return r;
    }
    ps.ws();
    if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
    if (ps.p < ps.end && *ps.p == '}') { ps.p++; r.ok = true; return r; }
    return r;
  }
}

void json_escape(const std::string& s, std::string* out) {
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back((char)c);
        }
    }
  }
}

// ---------------------------------------------------------------- server --

// Python fallback for ops this dispatch does not know (obs_push/obs_stats
// and anything future): receives the RAW request frame (the native Request
// struct drops unknown keys) and must answer via ptms_reply before
// returning. ctypes acquires the GIL for the call, so handler threads may
// invoke it concurrently with the Python control plane.
typedef void (*ptms_fallback_fn)(const char* req, int len, void* reply);

struct Reply {
  std::string body;
};

struct Server {
  void* master = nullptr;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::atomic<bool> fenced{false};
  std::atomic<ptms_fallback_fn> fallback{nullptr};
  std::thread accept_thread;
  std::mutex mu;                 // guards conns + active
  std::condition_variable cv;    // signals active reaching 0
  std::set<int> conns;
  int active = 0;                // live (detached) handler threads

  std::string dispatch(const Request& req, const std::string& body) {
    static const char* kMutating[] = {"set_dataset", "get_task",
                                      "task_finished", "task_failed",
                                      "new_pass"};
    bool mutating = false;
    for (const char* m : kMutating) mutating |= (req.op == m);
    if (mutating && fenced.load()) {
      return "{\"ok\": false, \"error\": \"fenced: stale master token\"}";
    }
    if (req.op == "set_dataset") {
      std::vector<const char*> ptrs;
      ptrs.reserve(req.payloads.size());
      for (const auto& s : req.payloads) ptrs.push_back(s.c_str());
      ptm_set_dataset(master, ptrs.data(), (int)ptrs.size());
      return "{\"ok\": true}";
    }
    if (req.op == "get_task") {
      std::vector<char> buf(4096);
      int id, needed = 0;
      for (;;) {
        id = ptm_get_task(master, mono_now(), buf.data(), (int)buf.size(),
                          &needed);
        if (id == -3) { buf.resize(needed); continue; }
        break;
      }
      if (id < 0) {
        return std::string("{\"ok\": true, \"task\": null, "
                           "\"pass_finished\": ") +
               (id == -2 ? "true}" : "false}");
      }
      std::string out = "{\"ok\": true, \"task\": {\"id\": ";
      out += std::to_string(id);
      out += ", \"payload\": \"";
      json_escape(buf.data(), &out);
      out += "\"}}";
      return out;
    }
    if (req.op == "task_finished") {
      ptm_task_finished(master, (int)req.task_id);
      return "{\"ok\": true}";
    }
    if (req.op == "task_failed") {
      int discarded = ptm_task_failed(master, (int)req.task_id);
      return std::string("{\"ok\": true, \"discarded\": ") +
             (discarded == 1 ? "true}" : "false}");
    }
    if (req.op == "new_pass") {
      return std::string("{\"ok\": ") +
             (ptm_new_pass(master) == 0 ? "true}" : "false}");
    }
    if (req.op == "stats") {
      int todo, pending, done, disc, epoch;
      ptm_stats(master, &todo, &pending, &done, &disc, &epoch);
      std::string out = "{\"ok\": true, \"todo\": " + std::to_string(todo);
      out += ", \"pending\": " + std::to_string(pending);
      out += ", \"done\": " + std::to_string(done);
      out += ", \"discarded\": " + std::to_string(disc);
      out += ", \"epoch\": " + std::to_string(epoch) + "}";
      return out;
    }
    // unknown op: give the Python control plane a chance before erroring —
    // this is how obs_push/obs_stats (and future control ops) are served
    // without teaching the C++ data plane their payloads
    ptms_fallback_fn fb = fallback.load();
    if (fb != nullptr) {
      Reply r;
      fb(body.data(), (int)body.size(), &r);
      if (!r.body.empty()) return r.body;
    }
    std::string out = "{\"ok\": false, \"error\": \"unknown op '";
    json_escape(req.op, &out);
    out += "'\"}";
    return out;
  }

  static bool recv_exact(int fd, char* buf, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = recv(fd, buf + got, n - got, 0);
      if (r <= 0) return false;
      got += (size_t)r;
    }
    return true;
  }

  static bool send_all(int fd, const char* buf, size_t n) {
    size_t sent = 0;
    while (sent < n) {
      ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
      if (r <= 0) return false;
      sent += (size_t)r;
    }
    return true;
  }

  void handle(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    while (!stop.load()) {
      uint32_t len_le;
      if (!recv_exact(fd, (char*)&len_le, 4)) break;
      uint32_t n = le32toh(len_le);
      if (n > kMaxFrame) break;
      std::string body(n, '\0');
      if (n && !recv_exact(fd, &body[0], n)) break;
      Request req = parse_request(body);
      std::string resp =
          req.ok ? dispatch(req, body)
                 : std::string("{\"ok\": false, \"error\": \"bad request\"}");
      // the Python client drops any frame over kMaxFrame as a dead
      // connection, so an oversized response (a near-64 MB set_dataset
      // payload whose JSON escaping expanded past the limit in a
      // get_task reply) must degrade to a STRUCTURED error the client
      // can surface, not a silent hangup (ADVICE r5).
      // $PTMS_MAX_RESPONSE_FRAME shrinks the bound for tests (read per
      // request so an in-process test can arm it after startup); the
      // REQUEST bound stays kMaxFrame (the client enforces the same).
      const char* rm_env = getenv("PTMS_MAX_RESPONSE_FRAME");
      unsigned long rm_v = rm_env ? strtoul(rm_env, nullptr, 10) : 0;
      const uint32_t resp_max =
          (rm_v > 0 && rm_v <= kMaxFrame) ? (uint32_t)rm_v : kMaxFrame;
      if (resp.size() > resp_max) {
        resp = "{\"ok\": false, \"error\": \"payload too large: response "
               "of " + std::to_string(resp.size()) +
               " bytes exceeds the frame limit of " +
               std::to_string((unsigned long)resp_max) + " bytes\"}";
      }
      uint32_t out_le = htole32((uint32_t)resp.size());
      char hdr[4];
      memcpy(hdr, &out_le, 4);
      if (!send_all(fd, hdr, 4) ||
          !send_all(fd, resp.data(), resp.size()))
        break;
    }
    // erase BEFORE close: once closed, the kernel may hand the same fd
    // number to a concurrent accept — erasing after would remove the NEW
    // connection from the set and ptms_stop could never sever it
    {
      std::lock_guard<std::mutex> g(mu);
      conns.erase(fd);
    }
    close(fd);
    {
      std::lock_guard<std::mutex> g(mu);
      if (--active == 0) cv.notify_all();
    }
  }

  void accept_loop() {
    for (;;) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) return;
        continue;
      }
      std::lock_guard<std::mutex> g(mu);
      if (stop.load()) { close(fd); return; }
      conns.insert(fd);
      active++;
      // detached: liveness is tracked by `active` (bounded by open
      // connections), not by an ever-growing vector of joinable threads
      std::thread([this, fd] { handle(fd); }).detach();
    }
  }
};

}  // namespace

extern "C" {

// Start serving `master` (a ptm_create handle) on host:port (port 0 = any;
// the bound port is written to *out_port). Returns a server handle or NULL.
void* ptms_start(void* master, const char* host, int port, int* out_port) {
  auto* s = new Server();
  s->master = master;
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) { delete s; return nullptr; }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (!host || !*host) host = "127.0.0.1";
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof addr) != 0 ||
      listen(s->listen_fd, 64) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  if (out_port) *out_port = s->port;
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int ptms_port(void* h) { return static_cast<Server*>(h)->port; }

// Live client connections — the serving daemon's drain/telemetry signal
// (a long-lived `paddle_tpu serve` wants to know who is still attached
// before stopping, and exports the count as a gauge).
int ptms_active_conns(void* h) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return (int)s->conns.size();
}

// Fencing flag, pushed from the Python control plane (lease/fence checks):
// while set, mutating ops answer the "fenced: ..." error the client's
// failover logic matches on; reads (stats) still serve.
void ptms_set_fenced(void* h, int fenced) {
  static_cast<Server*>(h)->fenced.store(fenced != 0);
}

// Unknown-op fallback into the Python control plane. The callback must
// stay callable until after ptms_stop returns (ptms_stop drains every
// handler thread before returning, so releasing it afterwards is safe).
void ptms_set_fallback(void* h, ptms_fallback_fn fn) {
  static_cast<Server*>(h)->fallback.store(fn);
}

// Called by the fallback (from inside its invocation) to publish the
// response frame for the request it was handed.
void ptms_reply(void* reply, const char* data, int n) {
  if (reply == nullptr || data == nullptr || n < 0) return;
  static_cast<Reply*>(reply)->body.assign(data, (size_t)n);
}

void ptms_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // sever every live connection, then wait for the detached handlers to
  // drain (they erase themselves and decrement `active` on exit)
  std::unique_lock<std::mutex> g(s->mu);
  for (int fd : s->conns) ::shutdown(fd, SHUT_RDWR);
  s->cv.wait(g, [s] { return s->active == 0; });
  g.unlock();
  delete s;
}

}  // extern "C"
