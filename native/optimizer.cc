// Standalone optimizer library with a C ABI and serializable state — the
// re-provision of paddle/optimizer (reference: optimizer.h C API
// paddle_create_optimizer/paddle_update_parameter, sgd_optimizer.cc,
// adagrad/adadelta/adam_optimizer.cc, lr_policy.h const/linear,
// serialization.h), which the Go pserver called through cgo
// (go/pserver/optimizer.go). Here it backs host-side embedding/optimizer
// offload paths (huge sparse tables kept out of HBM) and gives checkpointable
// optimizer state independent of the device runtime.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

enum OptType { SGD = 0, MOMENTUM = 1, ADAGRAD = 2, ADADELTA = 3, ADAM = 4 };
enum LrPolicy { LR_CONST = 0, LR_LINEAR = 1 };

struct Opt {
  int type = SGD;
  int lr_policy = LR_CONST;
  double lr = 0.01;
  double lr_decay_a = 0, lr_decay_b = 0;  // linear: max(lr - a*step, b)
  double mu = 0.9, rho = 0.95, eps = 1e-6;
  double beta1 = 0.9, beta2 = 0.999;
  int64_t num_steps = 0;
  size_t n = 0;
  std::vector<float> param;
  std::vector<float> s1;  // velocity / accum / m / accum_g
  std::vector<float> s2;  // v / accum_d
};

double cur_lr(Opt* o) {
  if (o->lr_policy == LR_LINEAR)
    return std::fmax(o->lr - o->lr_decay_a * (double)o->num_steps, o->lr_decay_b);
  return o->lr;
}

}  // namespace

extern "C" {

// type: 0 sgd, 1 momentum, 2 adagrad, 3 adadelta, 4 adam.
// lr_policy: 0 const, 1 linear(lr - a*step, floor b).
void* pto_create(int type, const float* param_init, uint64_t n, double lr,
                 int lr_policy, double decay_a, double decay_b, double mu,
                 double rho, double eps, double beta1, double beta2) {
  auto* o = new Opt();
  o->type = type;
  o->lr = lr;
  o->lr_policy = lr_policy;
  o->lr_decay_a = decay_a;
  o->lr_decay_b = decay_b;
  o->mu = mu;
  o->rho = rho;
  o->eps = eps;
  o->beta1 = beta1;
  o->beta2 = beta2;
  o->n = n;
  // NULL init = zero-fill without a host-side source buffer: a 20 GB
  // embedding table starts as one allocation instead of numpy-zeros +
  // copy (half the peak RSS, and no 20 GB memcpy at bench/JOB start)
  if (param_init == nullptr) {
    o->param.assign(n, 0.f);
  } else {
    o->param.assign(param_init, param_init + n);
  }
  if (type != SGD) o->s1.assign(n, 0.f);
  if (type == ADADELTA || type == ADAM) o->s2.assign(n, 0.f);
  return o;
}

void pto_destroy(void* h) { delete static_cast<Opt*>(h); }

// One SGD step with gradient `grad` (paddle_update_parameter analog).
int pto_update(void* h, const float* grad, uint64_t n) {
  auto* o = static_cast<Opt*>(h);
  if (n != o->n) return -1;
  o->num_steps++;
  const double lr = cur_lr(o);
  float* p = o->param.data();
  switch (o->type) {
    case SGD:
      for (size_t i = 0; i < n; i++) p[i] -= (float)(lr * grad[i]);
      break;
    case MOMENTUM: {
      float* v = o->s1.data();
      for (size_t i = 0; i < n; i++) {
        v[i] = (float)(o->mu * v[i] + grad[i]);
        p[i] -= (float)(lr * v[i]);
      }
      break;
    }
    case ADAGRAD: {
      float* a = o->s1.data();
      for (size_t i = 0; i < n; i++) {
        a[i] += grad[i] * grad[i];
        p[i] -= (float)(lr * grad[i] / (std::sqrt((double)a[i]) + o->eps));
      }
      break;
    }
    case ADADELTA: {
      float* ag = o->s1.data();
      float* ad = o->s2.data();
      for (size_t i = 0; i < n; i++) {
        ag[i] = (float)(o->rho * ag[i] + (1 - o->rho) * grad[i] * grad[i]);
        double dx = std::sqrt(((double)ad[i] + o->eps) / ((double)ag[i] + o->eps)) * grad[i];
        ad[i] = (float)(o->rho * ad[i] + (1 - o->rho) * dx * dx);
        p[i] -= (float)(lr * dx);
      }
      break;
    }
    case ADAM: {
      float* m = o->s1.data();
      float* v = o->s2.data();
      double b1p = 1 - std::pow(o->beta1, (double)o->num_steps);
      double b2p = 1 - std::pow(o->beta2, (double)o->num_steps);
      for (size_t i = 0; i < n; i++) {
        m[i] = (float)(o->beta1 * m[i] + (1 - o->beta1) * grad[i]);
        v[i] = (float)(o->beta2 * v[i] + (1 - o->beta2) * grad[i] * grad[i]);
        double mh = m[i] / b1p, vh = v[i] / b2p;
        p[i] -= (float)(lr * mh / (std::sqrt(vh) + o->eps));
      }
      break;
    }
    default:
      return -2;
  }
  return 0;
}

// Sparse row update: rows[i] indexes a [num_rows, width] view of param.
int pto_update_rows(void* h, const int* rows, const float* grad,
                    uint64_t n_rows, uint64_t width) {
  auto* o = static_cast<Opt*>(h);
  if (o->type != SGD && o->type != ADAGRAD) return -2;  // row-local types only
  o->num_steps++;
  const double lr = cur_lr(o);
  float* p = o->param.data();
  for (size_t r = 0; r < n_rows; r++) {
    if (rows[r] < 0) return -1;  // unsigned wrap would bypass the range test
    size_t base = (size_t)rows[r] * width;
    if (base + width > o->n) return -1;
    const float* g = grad + r * width;
    if (o->type == SGD) {
      for (size_t i = 0; i < width; i++) p[base + i] -= (float)(lr * g[i]);
    } else {
      float* a = o->s1.data();
      for (size_t i = 0; i < width; i++) {
        a[base + i] += g[i] * g[i];
        p[base + i] -= (float)(lr * g[i] / (std::sqrt((double)a[base + i]) + o->eps));
      }
    }
  }
  return 0;
}

const float* pto_get_param(void* h, uint64_t* n) {
  auto* o = static_cast<Opt*>(h);
  *n = o->n;
  return o->param.data();
}

// Row gather from the [num_rows, width] param view — the touched-row
// prefetch read of the host-offloaded embedding path (the pserver's
// getParameterSparse role, ParameterServer2.h:510).
int pto_get_rows(void* h, const int* rows, float* out, uint64_t n_rows,
                 uint64_t width) {
  auto* o = static_cast<Opt*>(h);
  const float* p = o->param.data();
  for (size_t r = 0; r < n_rows; r++) {
    // negative check first: (size_t)(-1) * width wraps so that base + width
    // == 0 passes the range test and reads before the buffer
    if (rows[r] < 0) return -1;
    size_t base = (size_t)rows[r] * width;
    if (base + width > o->n) return -1;
    std::memcpy(out + r * width, p + base, width * sizeof(float));
  }
  return 0;
}

// State serialization (serialization.h / OptimizerConfig.proto analog):
// [type i32][num_steps i64][n u64][param f32*n][len1 u64][s1][len2 u64][s2]
uint64_t pto_state_size(void* h) {
  auto* o = static_cast<Opt*>(h);
  return 4 + 8 + 8 + 4 * o->n + 8 + 4 * o->s1.size() + 8 + 4 * o->s2.size();
}

int pto_serialize(void* h, char* buf, uint64_t buflen) {
  auto* o = static_cast<Opt*>(h);
  if (buflen < pto_state_size(h)) return -1;
  char* q = buf;
  auto put = [&](const void* src, size_t len) { memcpy(q, src, len); q += len; };
  int32_t ty = o->type;
  uint64_t n = o->n, l1 = o->s1.size(), l2 = o->s2.size();
  put(&ty, 4);
  put(&o->num_steps, 8);
  put(&n, 8);
  put(o->param.data(), 4 * n);
  put(&l1, 8);
  put(o->s1.data(), 4 * l1);
  put(&l2, 8);
  put(o->s2.data(), 4 * l2);
  return 0;
}

int pto_deserialize(void* h, const char* buf, uint64_t buflen) {
  auto* o = static_cast<Opt*>(h);
  const char* q = buf;
  const char* end = buf + buflen;
  auto get = [&](void* dst, size_t len) -> bool {
    if (q + len > end) return false;
    memcpy(dst, q, len);
    q += len;
    return true;
  };
  int32_t ty;
  uint64_t n, l1, l2;
  if (!get(&ty, 4) || !get(&o->num_steps, 8) || !get(&n, 8)) return -1;
  if (ty != o->type || n != o->n) return -2;
  if (!get(o->param.data(), 4 * n)) return -1;
  if (!get(&l1, 8) || l1 != o->s1.size() || !get(o->s1.data(), 4 * l1)) return -1;
  if (!get(&l2, 8) || l2 != o->s2.size() || !get(o->s2.data(), 4 * l2)) return -1;
  return 0;
}

}  // extern "C"
