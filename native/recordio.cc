// Chunked record file format — re-provision of the recordio chunks the Go
// master shards (reference: go/master/service.go partitions RecordIO chunks;
// proto DataFormat stream, SURVEY.md §8.2) and the binary data path of
// ProtoDataProvider. Format:
//   file  := magic(u32) { record }*
//   record:= len(u32) crc32(u32) payload[len]
// CRC verified on read (the Go pserver checkpoint discipline,
// go/pserver/service.go:119-126, applied to data files).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

constexpr uint32_t kMagic = 0x50545231;  // "PTR1"

uint32_t crc32(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f = nullptr;
  int64_t count = 0;
};

struct Reader {
  FILE* f = nullptr;
};

}  // namespace

extern "C" {

void* ptr_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  uint32_t m = kMagic;
  fwrite(&m, 4, 1, f);
  auto* w = new Writer();
  w->f = f;
  return w;
}

int ptr_writer_write(void* h, const void* data, int len) {
  auto* w = static_cast<Writer*>(h);
  uint32_t l = (uint32_t)len;
  uint32_t c = crc32(static_cast<const uint8_t*>(data), len);
  if (fwrite(&l, 4, 1, w->f) != 1) return -1;
  if (fwrite(&c, 4, 1, w->f) != 1) return -1;
  if (len > 0 && fwrite(data, 1, len, w->f) != (size_t)len) return -1;
  w->count++;
  return 0;
}

int64_t ptr_writer_close(void* h) {
  auto* w = static_cast<Writer*>(h);
  int64_t n = w->count;
  fclose(w->f);
  delete w;
  return n;
}

void* ptr_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  uint32_t m = 0;
  if (fread(&m, 4, 1, f) != 1 || m != kMagic) {
    fclose(f);
    return nullptr;
  }
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Returns payload length (>=0), -1 on clean EOF, -2 on corruption (bad CRC or
// truncated record). buf==nullptr => peek length only (seek back).
int ptr_reader_next(void* h, void* buf, int buflen) {
  auto* r = static_cast<Reader*>(h);
  long pos = ftell(r->f);
  uint32_t len = 0, crc = 0;
  if (fread(&len, 4, 1, r->f) != 1) return -1;
  if (fread(&crc, 4, 1, r->f) != 1) return -2;
  if (buf == nullptr || (int)len > buflen) {
    fseek(r->f, pos, SEEK_SET);
    return (int)len;
  }
  if (len > 0 && fread(buf, 1, len, r->f) != len) return -2;
  if (crc32(static_cast<uint8_t*>(buf), len) != crc) return -2;
  return (int)len;
}

void ptr_reader_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  fclose(r->f);
  delete r;
}

}  // extern "C"
