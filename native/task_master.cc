// Task-queue data master — C++ re-provision of the Go master's semantics
// (reference: go/master/service.go — three-queue todo/pending/done dispatch
// :63-89, timeout requeue :198-200, failureMax discard :311-321, state
// snapshot/recovery :166-227). Drives fault-tolerant data sharding for
// multi-host TPU training: trainers are stateless task consumers; a dead
// trainer's pending task times out and is re-dispatched.
//
// C ABI for ctypes (paddle_tpu/runtime/master.py).

#include <fcntl.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Task {
  int id = 0;
  std::string payload;   // typically a chunk path [+ byte range]
  int failures = 0;
  double deadline = 0;   // valid while pending
};

struct Master {
  std::mutex mu;
  std::deque<Task> todo;
  std::map<int, Task> pending;  // id -> task
  std::vector<Task> done;
  std::vector<Task> discarded;
  double timeout_s = 60.0;
  int failure_max = 3;
  int next_id = 0;
  int epoch = 0;  // bumped when todo refills from done (pass boundary)
};

}  // namespace

extern "C" {

void* ptm_create(double timeout_s, int failure_max) {
  auto* m = new Master();
  m->timeout_s = timeout_s;
  m->failure_max = failure_max;
  return m;
}

void ptm_destroy(void* h) { delete static_cast<Master*>(h); }

// SetDataset (service.go:280): one task per chunk payload.
void ptm_set_dataset(void* h, const char** payloads, int n) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  m->todo.clear();
  m->pending.clear();
  m->done.clear();
  m->discarded.clear();
  for (int i = 0; i < n; i++) {
    Task t;
    t.id = m->next_id++;
    t.payload = payloads[i];
    m->todo.push_back(t);
  }
}

// GetTask (service.go:366 GetTask): todo -> pending with deadline.
// Returns task id >= 0, -1 if nothing available, -2 if pass finished
// (todo+pending empty), -3 if buf is too small — then *needed holds the
// required byte count (incl. NUL) and the task is NOT dequeued, so the
// caller can reallocate and retry (recordio peek/seek-back pattern; a
// truncate-and-consume here would silently corrupt large chunk payloads).
// `now` is caller-supplied monotonic seconds.
int ptm_get_task(void* h, double now, char* buf, int buflen, int* needed) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  if (m->todo.empty()) return m->pending.empty() ? -2 : -1;
  Task& front = m->todo.front();
  int want = (int)front.payload.size() + 1;
  if (needed) *needed = want;
  if (want > buflen) return -3;
  Task t = std::move(front);
  m->todo.pop_front();
  t.deadline = now + m->timeout_s;
  memcpy(buf, t.payload.c_str(), want);
  int id = t.id;
  m->pending[id] = std::move(t);
  return id;
}

// TaskFinished (service.go:450): pending -> done. The pass boundary is
// surfaced to clients (get_task returns -2, Go's ErrPassAfter analog);
// ptm_new_pass() then refills todo for the next pass.
int ptm_task_finished(void* h, int task_id) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  auto it = m->pending.find(task_id);
  if (it == m->pending.end()) return -1;
  it->second.failures = 0;
  m->done.push_back(it->second);
  m->pending.erase(it);
  return 0;
}

// Start the next pass: refill todo from done (service.go pass cycling).
int ptm_new_pass(void* h) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  if (!m->todo.empty() || !m->pending.empty()) return -1;  // pass not finished
  for (auto& t : m->done) m->todo.push_back(t);
  m->done.clear();
  m->epoch++;
  return 0;
}

// TaskFailed (service.go:475) + failureMax discard (:311-321).
int ptm_task_failed(void* h, int task_id) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  auto it = m->pending.find(task_id);
  if (it == m->pending.end()) return -1;
  Task t = it->second;
  m->pending.erase(it);
  t.failures++;
  if (t.failures >= m->failure_max) {
    m->discarded.push_back(t);
    return 1;  // discarded
  }
  m->todo.push_back(t);
  return 0;
}

// Timeout check (service.go:198-200 checkTimeoutFunc): requeue overdue
// pending tasks (counts as a failure). Returns number requeued/discarded.
int ptm_tick(void* h, double now) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  int n = 0;
  for (auto it = m->pending.begin(); it != m->pending.end();) {
    if (it->second.deadline <= now) {
      Task t = it->second;
      it = m->pending.erase(it);
      t.failures++;
      if (t.failures >= m->failure_max)
        m->discarded.push_back(t);
      else
        m->todo.push_back(t);
      n++;
    } else {
      ++it;
    }
  }
  return n;
}

void ptm_stats(void* h, int* todo, int* pending, int* done, int* discarded,
               int* epoch) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  *todo = (int)m->todo.size();
  *pending = (int)m->pending.size();
  *done = (int)m->done.size();
  *discarded = (int)m->discarded.size();
  *epoch = m->epoch;
}

// Snapshot/restore (service.go:166-227: etcd snapshot -> here a local file;
// the multi-host deployment points it at shared storage).
// Format v3: header "ptm_snapshot_v3 next_id epoch bodylen crc32\n" followed
// by the body — per task a "tag id failures len\n" line plus exactly len raw
// payload bytes + '\n' (length-prefixed so arbitrary payload bytes survive).
// The CRC32 over the body (same integrity discipline as the Go pserver's
// checkpoints, go/pserver/service.go:119-126) is verified on restore, and the
// file is written to a temp path then renamed so readers never see a torn
// snapshot.

static uint32_t crc32_of(const std::string& data) {
  // magic-static init: thread-safe under C++11 (snapshots may run
  // concurrently from several servers' housekeeping threads)
  static const std::vector<uint32_t> table = [] {
    std::vector<uint32_t> t(256);
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  uint32_t c = 0xFFFFFFFFu;
  for (unsigned char ch : data) c = table[(c ^ ch) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

int ptm_snapshot(void* h, const char* path) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  std::string body;
  char line[128];
  auto dump = [&](const char* tag, const Task& t) {
    snprintf(line, sizeof(line), "%s %d %d %zu\n", tag, t.id, t.failures,
             t.payload.size());
    body += line;
    body += t.payload;
    body += '\n';
  };
  for (auto& t : m->todo) dump("todo", t);
  // pending tasks snapshot as todo: after recovery they must be re-dispatched
  for (auto& kv : m->pending) dump("todo", kv.second);
  for (auto& t : m->done) dump("done", t);
  for (auto& t : m->discarded) dump("disc", t);

  std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  fprintf(f, "ptm_snapshot_v3 %d %d %zu %u\n", m->next_id, m->epoch,
          body.size(), crc32_of(body));
  bool ok = fwrite(body.data(), 1, body.size(), f) == body.size();
  // fsync before the rename: otherwise a crash can journal the rename while
  // the data blocks never hit disk, atomically replacing a good snapshot
  // with garbage
  ok = (fflush(f) == 0) && ok;
  ok = (fsync(fileno(f)) == 0) && ok;
  ok = (fclose(f) == 0) && ok;
  if (!ok || rename(tmp.c_str(), path) != 0) {
    remove(tmp.c_str());
    return -1;
  }
  // persist the rename itself
  std::string dir(path);
  size_t slash = dir.find_last_of('/');
  dir = (slash == std::string::npos) ? "." : dir.substr(0, slash);
  int dfd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd >= 0) {
    fsync(dfd);
    close(dfd);
  }
  return 0;
}

int ptm_restore(void* h, const char* path) {
  auto* m = static_cast<Master*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  char header[64];
  int next_id = 0, epoch = 0;
  size_t body_len = 0;
  unsigned int crc_want = 0;
  if (fscanf(f, "%63s", header) != 1) {
    fclose(f);
    return -2;
  }
  if (strcmp(header, "ptm_snapshot_v2") == 0) {
    // migration path for pre-CRC snapshots (same per-task body format, no
    // length/CRC in the header). Parsed into temporaries and committed only
    // on FULL success — a truncated file must not leave half-restored state.
    if (fscanf(f, "%d %d", &next_id, &epoch) != 2 || fgetc(f) != '\n') {
      fclose(f);
      return -2;
    }
    std::deque<Task> todo;
    std::vector<Task> done, discarded;
    char tag[8];
    int id, failures;
    size_t len;
    while (fscanf(f, "%7s %d %d %zu", tag, &id, &failures, &len) == 4) {
      if (fgetc(f) != '\n') { fclose(f); return -3; }
      Task t;
      t.id = id;
      t.failures = failures;
      t.payload.resize(len);
      if (len > 0 && fread(&t.payload[0], 1, len, f) != len) {
        fclose(f);
        return -3;
      }
      if (fgetc(f) != '\n') { fclose(f); return -3; }
      if (strcmp(tag, "todo") == 0) todo.push_back(t);
      else if (strcmp(tag, "done") == 0) done.push_back(t);
      else discarded.push_back(t);
    }
    fclose(f);
    m->todo = std::move(todo);
    m->pending.clear();
    m->done = std::move(done);
    m->discarded = std::move(discarded);
    m->next_id = next_id;
    m->epoch = epoch;
    return 0;
  }
  if (fscanf(f, "%d %d %zu %u", &next_id, &epoch, &body_len,
             &crc_want) != 4 ||
      strcmp(header, "ptm_snapshot_v3") != 0 || fgetc(f) != '\n') {
    fclose(f);
    return -2;  // bad header
  }
  // the header is outside the CRC: sanity-bound body_len by the file size so
  // a corrupted length digit can't drive a huge allocation
  long data_start = ftell(f);
  fseek(f, 0, SEEK_END);
  long file_end = ftell(f);
  fseek(f, data_start, SEEK_SET);
  if (data_start < 0 || file_end < data_start ||
      body_len > (size_t)(file_end - data_start)) {
    fclose(f);
    return -4;  // truncated / corrupt length
  }
  std::string body(body_len, '\0');
  if (body_len > 0 && fread(&body[0], 1, body_len, f) != body_len) {
    fclose(f);
    return -4;  // truncated
  }
  fclose(f);
  if (crc32_of(body) != crc_want) return -5;  // corruption detected

  // parse into temporaries and commit only on full success, so a corrupt
  // body can't leave the master half-restored (mirrors the v2 path)
  std::deque<Task> todo;
  std::vector<Task> done, discarded;
  size_t pos = 0;
  while (pos < body.size()) {
    size_t eol = body.find('\n', pos);
    if (eol == std::string::npos) return -3;
    char tag[8];
    int id, failures;
    size_t len;
    if (sscanf(body.substr(pos, eol - pos).c_str(), "%7s %d %d %zu", tag, &id,
               &failures, &len) != 4)
      return -3;
    pos = eol + 1;
    if (pos + len >= body.size() || body[pos + len] != '\n') return -3;
    Task t;
    t.id = id;
    t.failures = failures;
    t.payload = body.substr(pos, len);
    pos += len + 1;
    if (strcmp(tag, "todo") == 0) todo.push_back(t);
    else if (strcmp(tag, "done") == 0) done.push_back(t);
    else discarded.push_back(t);
  }
  m->todo = std::move(todo);
  m->pending.clear();
  m->done = std::move(done);
  m->discarded = std::move(discarded);
  m->next_id = next_id;
  m->epoch = epoch;
  return 0;
}

}  // extern "C"
