"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability set of early PaddlePaddle (reference at
/root/reference, see SURVEY.md): op/layer zoo, LoD variable-length sequences,
optimizers, readers/datasets, trainer with events/evaluators/checkpoints, and
distributed training — designed TPU-first on JAX/XLA/Pallas/pjit: compute lowers to
HLO onto the MXU, parallelism is SPMD over a jax.sharding.Mesh with XLA collectives
over ICI/DCN (replacing the reference's pserver/RDMA/NCCL paths), and the host runtime
(stats, queues, data master) is native C++.
"""

__version__ = "0.1.0"

from . import (analysis, core, data, faults, fluid, models, nn, obs, ops,
               optimizer, parallel, trainer, utils, v2)
from .core import CPUPlace, Place, SeqBatch, TPUPlace, sequence_mask
from .trainer import Trainer

__all__ = ["analysis", "core", "data", "faults", "fluid", "nn", "obs", "ops",
           "optimizer",
           "parallel", "trainer", "utils", "models", "v2", "Trainer",
           "Place", "TPUPlace", "CPUPlace", "SeqBatch", "sequence_mask",
           "__version__"]
