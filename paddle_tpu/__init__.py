"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability set of early PaddlePaddle (reference at
/root/reference, see SURVEY.md): op/layer zoo, LoD variable-length sequences,
optimizers, readers/datasets, trainer with events/evaluators/checkpoints, and
distributed training — designed TPU-first on JAX/XLA/Pallas/pjit: compute lowers to
HLO onto the MXU, parallelism is SPMD over a jax.sharding.Mesh with XLA collectives
over ICI/DCN (replacing the reference's pserver/RDMA/NCCL paths), and the host runtime
(stats, queues, data master) is native C++.
"""

__version__ = "0.1.0"

import os as _os

from . import (analysis, core, data, faults, fluid, models, nn, obs, ops,
               optimizer, parallel, trainer, utils, v2)
from .core import CPUPlace, Place, SeqBatch, TPUPlace, sequence_mask
from .trainer import Trainer

#: env var naming a persistent XLA compilation-cache directory; applied at
#: import (and by :func:`init`) so a preemption-resume under the same env
#: restarts without re-paying its compiles
COMPILE_CACHE_ENV = "PADDLE_TPU_COMPILE_CACHE_DIR"


def enable_compile_cache(path: str) -> str:
    """Point jax's persistent XLA compilation cache at ``path``.

    Compiled executables are keyed on the serialized computation + jaxlib
    version, so a restarted process (preemption-resume, a re-run bench, a
    new trainer on the same pod) loads them from disk instead of
    recompiling.  The min-compile-time/entry-size floors are dropped to 0
    so small fluid programs cache too (the knobs are best-effort across
    jax versions).  Returns the path.
    """
    import jax
    _os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass   # older jax: knob absent; the cache still works
    return path


def init(compile_cache_dir: str = None, **flags):
    """Process-level runtime init (the ``paddle.init`` analog).

    ``compile_cache_dir`` (or ``$PADDLE_TPU_COMPILE_CACHE_DIR``) enables
    the persistent XLA compilation cache via
    :func:`enable_compile_cache`; remaining keyword flags are recorded
    through :func:`v2.init`. Returns the recorded flag dict.
    """
    path = compile_cache_dir or _os.environ.get(COMPILE_CACHE_ENV)
    if path:
        flags["compile_cache_dir"] = enable_compile_cache(path)
    return v2.init(**flags)


if _os.environ.get(COMPILE_CACHE_ENV):
    try:
        enable_compile_cache(_os.environ[COMPILE_CACHE_ENV])
    except Exception:   # an unwritable dir must not break `import paddle_tpu`
        pass

__all__ = ["analysis", "core", "data", "faults", "fluid", "nn", "obs", "ops",
           "optimizer",
           "parallel", "trainer", "utils", "models", "v2", "Trainer",
           "Place", "TPUPlace", "CPUPlace", "SeqBatch", "sequence_mask",
           "init", "enable_compile_cache", "COMPILE_CACHE_ENV",
           "__version__"]
