"""paddle_tpu.analysis — static verification + lint passes over Program IR.

The TVM lesson (PAPERS.md): a compiler stack is debuggable when its IR can be
checked *before* lowering.  This subpackage rejects malformed programs with
precise :class:`Diagnostic`\\ s before any JAX trace or XLA compile starts:

- :func:`verify_program`   — structural checks (V0xx): def-before-use with
  parent-scope lookup, registered op types, duplicate writes, sub-block
  index sanity/acyclicity, while-condition liveness, fetch existence.
- :func:`infer_program_shapes` — abstract shape/dtype interpretation (S0xx)
  with per-op rules via :func:`register_shape_infer` and a ``jax.eval_shape``
  fallback over the registered compute.
- :func:`lint_program`     — advisory catalogue (L0xx): dead ops, unused
  vars, trace-safety, sharding-annotation consistency.

Entry points: ``analyze_program`` (everything, returns diagnostics),
``check_or_raise`` (the ``Executor.run(verify=True)`` pre-flight), and the
``paddle_tpu lint`` CLI subcommand.  See docs/design/analysis.md.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .dataflow import (Dataflow, DonationHazard, Effect, FusionGroup,
                       analyze_dataflow, certificate_matches,
                       classify_effect, donation_hazards, explain_var,
                       fusable_groups, region_schedulable)
from .diagnostics import (Diagnostic, ProgramVerificationError, Severity,
                          block_paths, errors, format_diagnostics,
                          max_severity, op_site)
from .lints import (LINT_CATALOGUE, lint_alert_rules, lint_autotune_cache,
                    lint_catalogue_drift, lint_metric_names, lint_program)
from .shape_infer import (UNKNOWN, ShapeInferRegistry, infer_program_shapes,
                          register_shape_infer)
from .verify import verify_program

__all__ = [
    "Diagnostic", "Severity", "ProgramVerificationError",
    "errors", "format_diagnostics", "max_severity", "op_site", "block_paths",
    "verify_program", "infer_program_shapes", "register_shape_infer",
    "ShapeInferRegistry", "UNKNOWN", "lint_program", "lint_metric_names",
    "lint_catalogue_drift", "lint_autotune_cache", "lint_alert_rules",
    "LINT_CATALOGUE",
    "Dataflow", "DonationHazard", "Effect", "FusionGroup",
    "analyze_dataflow", "classify_effect", "donation_hazards",
    "explain_var", "fusable_groups", "region_schedulable",
    "certificate_matches",
    "analyze_program", "check_or_raise",
]


def _feed_shapes(feed: Optional[Dict[str, Any]]) -> Dict[str, Tuple]:
    out: Dict[str, Tuple] = {}
    for name, val in (feed or {}).items():
        arr = np.asarray(val) if not hasattr(val, "shape") else val
        out[name] = (tuple(arr.shape), np.dtype(arr.dtype).name)
    return out


def analyze_program(program, feed: Optional[Dict[str, Any]] = None,
                    fetch: Iterable[str] = (),
                    run_verify: bool = True, run_shapes: bool = True,
                    run_lints: bool = True,
                    mesh_axes: Optional[Sequence[str]] = None,
                    severity_overrides: Optional[Dict[str, Severity]] = None,
                    donate: Optional[bool] = None,
                    ) -> List[Diagnostic]:
    """Run every enabled pass over ``program`` and return all diagnostics.

    ``feed`` may hold real arrays (their shapes seed the interpreter) or be
    omitted, in which case data vars use declared shapes with placeholder
    dynamic dims.  ``fetch`` is a list of var names (strings).  ``donate``
    mirrors the Executor's donation switch for L011 (True: hazards are
    errors; None: advisory; False: skipped)."""
    fetch_names = [v if isinstance(v, str) else v.name for v in fetch]
    diags: List[Diagnostic] = []
    if run_verify:
        verify_program(program, feed=list(feed or ()), fetch=fetch_names,
                       diags=diags)
    if run_shapes and not errors(diags):
        # structural errors make abstract interpretation meaningless noise
        infer_program_shapes(program, feed_shapes=_feed_shapes(feed),
                             diags=diags)
    if run_lints:
        # the dataflow walker recurses through the same sub-block indices
        # the verifier validates; structural errors there would make the
        # chains (and L010-L012) nonsense, so those lints gate on V0xx
        enable = (set(LINT_CATALOGUE) - {"L010", "L011", "L012"}
                  if errors(diags) else None)
        lint_program(program, fetch=fetch_names, mesh_axes=mesh_axes,
                     severity_overrides=severity_overrides,
                     feed=list(feed or ()), donate=donate,
                     enable=enable, diags=diags)
    # nested sub-block sites cite the full parent chain (block 0.2, op #5)
    paths = block_paths(program)
    for d in diags:
        if d.block_idx is not None and d.block_path is None:
            d.block_path = paths.get(d.block_idx)
    return diags


def check_or_raise(program, feed: Optional[Dict[str, Any]] = None,
                   fetch: Iterable[str] = (),
                   mesh_axes: Optional[Sequence[str]] = None,
                   donate: Optional[bool] = None
                   ) -> List[Diagnostic]:
    """Pre-flight for ``Executor.run(verify=True)``: raise
    :class:`ProgramVerificationError` on any error-severity diagnostic,
    return the full list (warnings included) otherwise.  ``mesh_axes``
    pins the valid sharding axis names (L004) for custom meshes.
    ``donate`` is the run's donation switch — with it True a provable
    donation hazard (L011) is an error this pre-flight refuses."""
    diags = analyze_program(program, feed=feed, fetch=fetch,
                            mesh_axes=mesh_axes, donate=donate)
    if errors(diags):
        raise ProgramVerificationError(diags)
    return diags
