"""Bench-row schema — the contract between benchmarks/*.py rows and every
consumer downstream (bench.py's stdout JSONL, the driver's tail parser,
BENCH_r0x.json trend tracking, `paddle_tpu lint --bench-rows`).

A malformed row used to fail SILENTLY: a benchmark that dropped `mfu` or
`hbm_bw_util` from its dict still printed, the trend tooling skipped the
missing column, and the regression surfaced rounds later as a "why is this
column empty" archaeology session. Rows are validated here instead — at
print time in bench.py (loud stderr + nonzero-signal) and statically in
the lint CLI.

Family rules key on the metric NAME, which is itself part of the contract
(metric keys carry methodology; see benchmarks/lstm_textcls.py):

* every row: ``metric`` (str), ``value`` (number or null), ``unit`` (str),
  ``vs_baseline`` (number or null);
* ``*_train_*`` rows: ``mfu`` — the roofline campaign's target column
  (no training row below 15% MFU, ROADMAP item 3) — plus ``plan_source``
  ("tuned" | "heuristic": did this row's kernel-plan consults resolve
  against measured autotune winners, ``paddle_tpu.tune.plan_source()``);
* ``*_decode_*`` rows: ``hbm_bw_util`` — decode is bytes-bound, so its
  roofline column is bandwidth, not FLOPs (target >= 0.30) — plus
  ``plan_source`` as above;
* ``*_serve_*`` rows: ``ttft_p50_ms`` + ``tpot_p50_ms`` — a serving row
  without its SLO pair is throughput theater (time-to-first-token and
  time-per-output-token are what callers experience; PR 8's daemon rows);
* ``*_prefix_*`` rows additionally: ``hit_rate`` — a prefix-cache row
  whose speedup is not conditioned on its measured hit rate is
  unreproducible (a serve+prefix metric name matches BOTH families, so
  the SLO pair stays mandatory too; benchmarks/serving_prefix.py);
* ``*_route_*`` rows: the SLO pair PLUS ``n_decode_workers`` — a routed
  serving number is meaningless without the fleet size it was spread
  over (1 prefill + 2 decode pools is not comparable to a solo daemon;
  benchmarks/serving_router.py) — PLUS ``ttft_breakdown``: the
  phase-decomposed TTFT p50s (queued/prefill/ship/adopt, ms) from the
  request-timeline ledger, so a routed TTFT regression names WHICH hop
  moved instead of reopening the whole fabric;
* ``*_fleet_*`` rows: ``recovery_windows`` + ``slo_recovered`` — a
  fleet-actor recovery number is the chaos bar itself: how many alert
  windows from kill to restored SLO, and whether the SLO actually
  recovered (a recovery-time row that never re-met the SLO is a
  failure wearing a latency; benchmarks/fleet_autoscale.py).
"""

from __future__ import annotations

from typing import Dict, List

#: keys every row must carry
REQUIRED_KEYS = ("metric", "value", "unit", "vs_baseline")

#: metric-name substring -> additionally required keys. ``methodology``
#: ("measured" | "modeled") says whether the roofline/SLO columns come
#: from on-chip measurement of the real executable or from an analytic
#: projection — so on-chip vs projected numbers are distinguishable in
#: the trajectory (attach_mfu defaults it to "measured"; the decode
#: rows' hand byte models stamp "modeled")
FAMILY_REQUIRED = {
    "_train_": ("mfu", "methodology", "plan_source"),
    "_decode_": ("hbm_bw_util", "methodology", "plan_source"),
    "_serve_": ("ttft_p50_ms", "tpot_p50_ms", "methodology"),
    "_prefix_": ("hit_rate",),
    "_route_": ("ttft_p50_ms", "tpot_p50_ms", "n_decode_workers",
                "ttft_breakdown"),
    "_fleet_": ("recovery_windows", "slo_recovered"),
}

#: the only legal methodology stamps
METHODOLOGIES = ("measured", "modeled")

#: the only legal plan_source stamps: whether the row's kernel-plan
#: consults could resolve against MEASURED autotune winners
#: (paddle_tpu.tune.plan_source()) or the built-in heuristics owned every
#: plan — required on the _train_/_decode_ families so tuned-vs-heuristic
#: deltas are machine-checkable across BENCH files
#: (benchmarks/autotune_delta.py emits the paired rows)
PLAN_SOURCES = ("tuned", "heuristic")

#: substrings exempting a row from family rules (comparative/meta rows
#: that are not themselves roofline measurements)
FAMILY_EXEMPT = ("_speedup_",)


def validate_row(row) -> List[str]:
    """Problems with one row dict; empty list == valid."""
    if not isinstance(row, dict):
        return [f"row is {type(row).__name__}, not a dict"]
    problems = []
    for key in REQUIRED_KEYS:
        if key not in row:
            problems.append(f"missing required key '{key}'")
    metric = row.get("metric")
    if metric is not None and not isinstance(metric, str):
        problems.append("'metric' must be a string")
    for key in ("value", "vs_baseline"):
        if key in row and row[key] is not None \
                and not isinstance(row[key], (int, float)):
            problems.append(f"'{key}' must be a number or null")
    if "methodology" in row and row["methodology"] not in METHODOLOGIES:
        problems.append(f"'methodology' must be one of {METHODOLOGIES}, "
                        f"got {row['methodology']!r}")
    if "plan_source" in row and row["plan_source"] not in PLAN_SOURCES:
        problems.append(f"'plan_source' must be one of {PLAN_SOURCES}, "
                        f"got {row['plan_source']!r}")
    if isinstance(metric, str) and not any(t in metric
                                           for t in FAMILY_EXEMPT):
        for tag, extra in FAMILY_REQUIRED.items():
            if tag in metric:
                for key in extra:
                    if key not in row:
                        problems.append(
                            f"'{metric}' is a {tag.strip('_')} row but "
                            f"lacks '{key}' (family rule: roofline rows "
                            "carry their utilization column)")
    return problems


def validate_rows(rows) -> Dict[int, List[str]]:
    """{row index: problems} over an iterable of row dicts (valid rows are
    omitted)."""
    out: Dict[int, List[str]] = {}
    for i, row in enumerate(rows):
        problems = validate_row(row)
        if problems:
            out[i] = problems
    return out
