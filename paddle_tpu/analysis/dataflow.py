"""Dataflow analysis over Program IR — def-use chains, liveness, aliasing,
effects.

The structural verifier (verify.py) answers "is this desc well-formed"; this
module answers "who defines what, who reads it, and what may alias what" —
the dependency facts a fusion/layout pass (ROADMAP item 3(c)) and the
executor's donation fast path need to be *provably* safe rather than
dynamically lucky.  It is pure desc-level analysis: no jax import, no trace.

Model
-----
- :class:`Def` — one binding of a name: an op output, an attr-defined extra
  output, a control-flow bind (scan step slice / carried memory), or the
  block-entry value of a feed/data/persistable var.  SSA-flavored: every
  write site is its own Def; an "SSA variable" is a (name, site) pair.
- :class:`Use` — one read site; ``use.defs`` is the set of Defs that *may
  reach* it (reaching definitions, may-analysis).  Reads come from
  ``op.inputs`` plus the attr side channels the executor lowers from env
  (``verify._ATTR_READ_KEYS`` and the lowering-read keys).
- **alias roots** — each Def carries the set of root Defs whose *storage*
  its value shares.  View/share ops (``assign``, ``reshape``, ``squeeze``,
  ``unsqueeze``, ``seq_reshape``, ``lod_reset``) propagate their input's
  roots; every other Def is its own root.  A read of a Def rooted at a
  donated entry value is a read of the donated buffer.
- **effects** — per-op classification: ``pure`` (value function of inputs),
  ``in-place`` (writes one of its own input names — optimizer updates),
  ``side-effecting`` (RNG, host callables), ``control`` (lowers sub-blocks
  or replays the trace: while/cond/scan/beam/autodiff).

Control flow
------------
``conditional_block`` branches fork the reaching env and re-merge by union
(may-reach).  Loop bodies (``while``/``static_rnn``/``beam_search_gen``) are
walked **twice**: the second pass runs over the first pass's merged end
state so back-edge reads (a loop counter's ``increment`` feeding next
iteration's ``less_than``) land on the body's Defs — without it every loop
carry would look like a dead write.  Def/Use objects are interned per site,
so the replay adds edges but never duplicates nodes.  Zero-trip semantics
are preserved: the pre-loop env stays reaching after the loop.

Consumers
---------
- :func:`donation_hazards` — the donation-safety proof obligation: for each
  donated persistable ``p``, no Use may read a Def rooted at ``p``'s entry
  value after ``p``'s first overwrite (or share a loop with one — loops
  re-execute).  Backs lint **L011** and the executor's donate downgrade.
- :func:`fusable_groups` — the fusion-legality oracle: elementwise chains
  and single-consumer producer→consumer pairs in the global block, each
  with a dependence certificate (every internal edge's def/use site and
  consumer count).  Backs the ROADMAP 3(c) pass.
- :func:`explain_var` — the ``lint --explain`` chain text
  ("defined at block B, op #I; last read at block B', op #J").
- lints **L010** (dead write across blocks) and **L012** (alias escape from
  a sub-block) consume :class:`Dataflow` in ``lints.py``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .diagnostics import block_paths, op_site
from .verify import (BLOCK_ATTR_KEYS, _ATTR_BIND_KEYS, _ATTR_DEFINE_KEYS,
                     _ATTR_READ_KEYS, _attr_names, _names, _transitive_writes)


class Effect(str, enum.Enum):
    """Per-op effect taxonomy (docs/design/analysis.md)."""

    PURE = "pure"
    INPLACE = "in-place"
    SIDE_EFFECT = "side-effecting"
    CONTROL = "control"

    def __str__(self):
        return self.value


#: ops lowered through sub-blocks or trace replay, not their compute
CONTROL_OPS = frozenset(("while", "conditional_block", "static_rnn",
                         "beam_search_gen", "autodiff_grad"))

#: RNG / host-state ops: same inputs, different values (never fusable by
#: value equality, never safe to re-execute speculatively)
SIDE_EFFECT_OPS = frozenset(("gaussian_random", "uniform_random", "dropout",
                             "sampling_id", "fill_init"))

#: ops whose output VALUE is (a view of) an input's storage — alias roots
#: propagate through them.  In the reference these share the LoDTensor
#: buffer; in the traced semantics they share the jax value.
VIEW_OPS = frozenset(("assign", "reshape", "squeeze", "unsqueeze",
                      "seq_reshape", "lod_reset"))

#: elementwise value functions: one output element per input element, no
#: cross-element reads — the always-fusable set (TVM's injective class)
ELEMENTWISE_OPS = frozenset((
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "scale", "cast", "clip", "sign", "minus", "pow",
    "power", "logical_not", "slope_intercept", "fill_zeros_like",
    "sigmoid", "tanh", "relu", "gelu", "leaky_relu", "elu", "softsign",
    "square", "sqrt", "abs_act", "exponential", "brelu", "soft_shrink",
    "hard_shrink", "thresholded_relu", "stanh", "softrelu", "hard_sigmoid",
    "swish", "reciprocal", "log",
))

#: attr keys naming sub-block results the executor reads when lowering a
#: control op (lints._EXTRA_READ_KEYS minus the keys verify already owns)
_LOWERING_READ_KEYS = ("mem_update_names", "step_out_names", "prob_name")


def classify_effect(op) -> Effect:
    """Desc-level effect of one op (no registry lookup, no trace)."""
    if op.type in CONTROL_OPS or any(k in op.attrs for k in BLOCK_ATTR_KEYS):
        return Effect.CONTROL
    if op.type in SIDE_EFFECT_OPS:
        return Effect.SIDE_EFFECT
    if any(callable(v) for v in op.attrs.values()):
        return Effect.SIDE_EFFECT
    if set(op.output_vars()) & set(op.input_vars()):
        return Effect.INPLACE
    return Effect.PURE


@dataclass(eq=False)
class Def:
    """One binding of ``name``.  ``kind``: ``"op"`` (an op output /
    attr-defined extra output), ``"bind"`` (control-flow entry binding),
    ``"entry"`` (block-entry value of a feed/data/persistable)."""

    name: str
    block_idx: Optional[int]
    op_idx: Optional[int]
    op_type: Optional[str]
    pos: int
    kind: str
    loops: Tuple = ()
    uses: List["Use"] = field(default_factory=list)
    roots: Set["Def"] = field(default_factory=set)

    def site(self, paths: Optional[Dict[int, str]] = None) -> str:
        if self.kind == "entry":
            return "entry"
        bp = (paths or {}).get(self.block_idx)
        return op_site(self.block_idx, self.op_idx, self.op_type,
                       block_path=bp)


@dataclass(eq=False)
class Use:
    """One read site; ``defs`` = the Defs that may reach it."""

    name: str
    block_idx: int
    op_idx: int
    op_type: str
    pos: int
    loops: Tuple = ()
    defs: Set[Def] = field(default_factory=set)

    def site(self, paths: Optional[Dict[int, str]] = None) -> str:
        bp = (paths or {}).get(self.block_idx)
        return op_site(self.block_idx, self.op_idx, self.op_type,
                       block_path=bp)


@dataclass
class Dataflow:
    """The analysis result: chains + liveness + aliasing + effects."""

    program: Any
    defs: List[Def]
    uses: List[Use]
    entry_defs: Dict[str, Def]
    final_env: Dict[str, Set[Def]]
    effects: Dict[Tuple[int, int], Effect]
    block_paths: Dict[int, str]
    alias_escapes: List[dict]
    fetch: Set[str]
    feed: Set[str]

    def defs_of(self, name: str) -> List[Def]:
        return sorted((d for d in self.defs if d.name == name),
                      key=lambda d: d.pos)

    def uses_of(self, name: str) -> List[Use]:
        return sorted((u for u in self.uses if u.name == name),
                      key=lambda u: u.pos)

    def site(self, node) -> str:
        return node.site(self.block_paths)


@dataclass
class DonationHazard:
    """Proof failure for one donated persistable: its entry value may be
    read after its first overwrite."""

    name: str
    entry: Def
    overwrites: List[Def]
    stale_reads: List[Use]

    def describe(self, paths: Optional[Dict[int, str]] = None) -> str:
        ow = ", ".join(d.site(paths) for d in self.overwrites[:3])
        reads = ", ".join(
            u.site(paths) + (f" via alias '{u.name}'"
                             if u.name != self.name else "")
            for u in self.stale_reads[:3])
        return (f"donated persistable '{self.name}' (defined on entry) is "
                f"overwritten at {ow} but its pre-update value may still be "
                f"read at {reads}")


@dataclass
class FusionGroup:
    """One legality-certified fusion candidate in the global block.

    ``edges`` is the dependence certificate the 3(c) pass consumes: every
    intra-group producer→consumer edge with its def site, use site, and
    consumer count (always 1 — the single-consumer proof)."""

    kind: str                   # "elementwise_chain" | "producer_consumer"
    block_idx: int
    op_idxs: List[int]
    inputs: List[str]
    outputs: List[str]
    edges: List[dict]

    def to_dict(self) -> dict:
        return {"kind": self.kind, "block_idx": self.block_idx,
                "op_idxs": list(self.op_idxs), "inputs": list(self.inputs),
                "outputs": list(self.outputs), "edges": list(self.edges)}


# --------------------------------------------------------------------------
# the walker
# --------------------------------------------------------------------------

class _Walker:
    def __init__(self, program, feed: Iterable[str], fetch: Iterable[str]):
        self.program = program
        self.feed = {n if isinstance(n, str) else getattr(n, "name", str(n))
                     for n in (feed or ())}
        self.fetch = {n if isinstance(n, str) else getattr(n, "name", str(n))
                      for n in (fetch or ())}
        self._pos = 0
        self._def_index: Dict[tuple, Def] = {}
        self._use_index: Dict[tuple, Use] = {}
        self.defs: List[Def] = []
        self.uses: List[Use] = []
        self.entry_defs: Dict[str, Def] = {}
        self.effects: Dict[Tuple[int, int], Effect] = {}
        self.alias_escapes: List[dict] = []
        self._escape_seen: Set[tuple] = set()
        self._loop_stack: List[Tuple[int, int]] = []
        # transitive write set of the OUTERMOST active control region —
        # "is the aliased base var updated anywhere in this loop/branch?"
        self._region_writes: List[Set[str]] = []

    # -- node interning ----------------------------------------------------
    def _entry(self, name: str) -> Def:
        d = self.entry_defs.get(name)
        if d is None:
            d = Def(name, None, None, None, 0, "entry")
            d.roots = {d}
            self.entry_defs[name] = d
            self.defs.append(d)
        return d

    def _def(self, name: str, block_idx: int, op_idx: Optional[int],
             op_type: Optional[str], kind: str) -> Def:
        key = (kind, block_idx, op_idx, name)
        d = self._def_index.get(key)
        if d is None:
            d = Def(name, block_idx, op_idx, op_type, self._pos, kind,
                    loops=tuple(self._loop_stack))
            d.roots = {d}
            self._def_index[key] = d
            self.defs.append(d)
        return d

    def _use(self, name: str, block_idx: int, op_idx: int, op_type: str,
             reaching: Set[Def]) -> Use:
        key = (block_idx, op_idx, name)
        u = self._use_index.get(key)
        if u is None:
            u = Use(name, block_idx, op_idx, op_type, self._pos,
                    loops=tuple(self._loop_stack))
            self._use_index[key] = u
            self.uses.append(u)
        for d in reaching:
            if u not in d.uses:
                d.uses.append(u)
            u.defs.add(d)
        return u

    # -- env helpers -------------------------------------------------------
    @staticmethod
    def _copy_env(env: Dict[str, Set[Def]]) -> Dict[str, Set[Def]]:
        return {k: set(v) for k, v in env.items()}

    @staticmethod
    def _merge_into(env: Dict[str, Set[Def]], other: Dict[str, Set[Def]]):
        for k, s in other.items():
            env.setdefault(k, set()).update(s)

    def _seed_block(self, block, env: Dict[str, Set[Def]]):
        for name, v in block.vars.items():
            if (v.is_data or v.persistable) and name not in env:
                env[name] = {self._entry(name)}

    def _reach(self, name: str, env: Dict[str, Set[Def]]) -> Set[Def]:
        got = env.get(name)
        if not got:
            # undefined read (V001's finding) or a feed-only name: give it
            # an entry Def so chains stay total and nothing here crashes
            got = {self._entry(name)}
            env[name] = set(got)
        return got

    # -- the walk ----------------------------------------------------------
    def run(self) -> Dataflow:
        program = self.program
        root = program.blocks[0]
        env: Dict[str, Set[Def]] = {}
        for n in self.feed:
            env[n] = {self._entry(n)}
        self._seed_block(root, env)
        self._walk_block(root, env, visiting=(0,))
        paths = block_paths(program)
        return Dataflow(program, self.defs, self.uses, self.entry_defs,
                        env, self.effects, paths, self.alias_escapes,
                        self.fetch, self.feed)

    def _walk_block(self, block, env: Dict[str, Set[Def]],
                    visiting: Tuple[int, ...]):
        program = self.program
        for idx, op in enumerate(block.ops):
            self._pos += 1
            self.effects.setdefault((block.idx, idx), classify_effect(op))

            # ---- reads (inputs + env-read attr names) -------------------
            for n in op.input_vars() + _attr_names(op, _ATTR_READ_KEYS):
                self._use(n, block.idx, idx, op.type, self._reach(n, env))
            if op.type == "autodiff_grad":
                # the grad replay re-runs forward ops from the trace-entry
                # env: every entry-defined feed/data value is read again
                for n, e in list(self.entry_defs.items()):
                    v = block.vars.get(n)
                    if v is not None and v.is_data or n in self.feed:
                        self._use(n, block.idx, idx, op.type, {e})

            # ---- sub-blocks ---------------------------------------------
            subs = []
            for key in BLOCK_ATTR_KEYS:
                si = op.attrs.get(key)
                if (isinstance(si, int) and 0 < si < len(program.blocks)
                        and si not in visiting):
                    subs.append(si)
            if subs and op.type == "conditional_block":
                branch_envs = []
                for si in subs:
                    benv = self._copy_env(env)
                    self._enter_region(op, block, idx)
                    self._seed_block(program.blocks[si], benv)
                    self._walk_block(program.blocks[si], benv,
                                     visiting + (si,))
                    self._exit_region()
                    branch_envs.append(benv)
                # may-reach merge; an else-less cond keeps env as the
                # implicit empty branch, and both-branch kills stay
                # conservatively reaching (union, never intersection)
                for benv in branch_envs:
                    self._merge_into(env, benv)
            elif subs:
                # loop-shaped: walk twice so back-edge reads land on the
                # body's Defs (see module docstring)
                for si in subs:
                    sub = program.blocks[si]
                    self._loop_stack.append((block.idx, idx))
                    self._enter_region(op, block, idx)
                    benv = self._copy_env(env)
                    for n in _attr_names(op, _ATTR_BIND_KEYS):
                        d = self._def(n, si, None, op.type, "bind")
                        benv[n] = {d}
                    self._seed_block(sub, benv)
                    self._walk_block(sub, benv, visiting + (si,))
                    merged = self._copy_env(env)
                    self._merge_into(merged, benv)
                    for n in _attr_names(op, _ATTR_BIND_KEYS):
                        merged[n] = {self._def(n, si, None, op.type, "bind")}
                    self._walk_block(sub, merged, visiting + (si,))
                    # per-iteration re-reads of the loop-carried inputs
                    # (the while condition, scan memories) hit body writes
                    for n in (op.input_vars()
                              + _attr_names(op, _ATTR_READ_KEYS)):
                        if n in merged:
                            self._use(n, block.idx, idx, op.type, merged[n])
                    self._exit_region()
                    self._loop_stack.pop()
                    self._merge_into(env, merged)
            # lowering-time reads of sub-block results (scan step outputs,
            # memory updates) — reads even though not in op.inputs
            for key in _LOWERING_READ_KEYS:
                if key in op.attrs:
                    for n in _names(op.attrs.get(key)):
                        self._use(n, block.idx, idx, op.type,
                                  self._reach(n, env))

            # ---- writes -------------------------------------------------
            view_roots: Optional[Set[Def]] = None
            if op.type in VIEW_OPS:
                ins = op.input_vars()
                if ins:
                    view_roots = set()
                    for d in env.get(ins[0], ()):
                        view_roots |= d.roots
            out_names = list(dict.fromkeys(op.output_vars()))
            for n in out_names:
                if block.idx != 0:
                    self._check_alias_escape(n, env, block, idx, op)
                d = self._def(n, block.idx, idx, op.type, "op")
                if view_roots:
                    d.roots |= view_roots
                env[n] = {d}
            for n in _attr_names(op, _ATTR_DEFINE_KEYS):
                d = self._def(n, block.idx, idx, op.type, "op")
                env[n] = {d}

    # -- alias escape (L012) ----------------------------------------------
    def _enter_region(self, op, block, idx):
        if not self._region_writes:
            writes: Set[str] = set()
            for key in BLOCK_ATTR_KEYS:
                si = op.attrs.get(key)
                if isinstance(si, int) and 0 < si < len(self.program.blocks):
                    writes |= _transitive_writes(self.program,
                                                 self.program.blocks[si])
            writes |= set(_attr_names(op, _ATTR_DEFINE_KEYS))
            self._region_writes.append(writes)
        else:
            self._region_writes.append(self._region_writes[0])

    def _exit_region(self):
        self._region_writes.pop()

    def _check_alias_escape(self, name, env, block, idx, op):
        region = self._region_writes[0] if self._region_writes else set()
        for d_prev in env.get(name, ()):
            for r in d_prev.roots:
                if r.name == name or r is d_prev:
                    continue
                outer = (r.kind == "entry"
                         or (r.block_idx is not None
                             and r.block_idx != block.idx
                             and self._is_ancestor(r.block_idx, block)))
                if not outer or r.name in region:
                    continue
                key = (block.idx, idx, name, r.name)
                if key in self._escape_seen:
                    continue
                self._escape_seen.add(key)
                self.alias_escapes.append({
                    "name": name, "base": r.name,
                    "block_idx": block.idx, "op_idx": idx,
                    "op_type": op.type,
                    "view_def": d_prev, "base_def": r})

    def _is_ancestor(self, anc_idx: int, block) -> bool:
        b = block
        guard = len(self.program.blocks) + 1
        while b is not None and guard:
            guard -= 1
            if b.idx == anc_idx:
                return True
            p = b.parent_idx
            b = (self.program.blocks[p]
                 if isinstance(p, int) and 0 <= p < len(self.program.blocks)
                 else None)
        return anc_idx == 0


def analyze_dataflow(program, feed: Iterable[str] = (),
                     fetch: Iterable[str] = ()) -> Dataflow:
    """Build def-use chains, reaching defs, alias roots, and effects for
    ``program``.  ``feed``/``fetch`` are var-name iterables (liveness roots
    and entry seeds); both optional."""
    return _Walker(program, feed, fetch).run()


# --------------------------------------------------------------------------
# consumer 1: donation-safety proof
# --------------------------------------------------------------------------

def donation_hazards(program, feed: Iterable[str] = (),
                     fetch: Iterable[str] = (),
                     df: Optional[Dataflow] = None) -> List[DonationHazard]:
    """Statically prove donation safety for every donate candidate.

    Candidates mirror the executor's split: global-block persistables the
    program overwrites, minus fed/fetched names.  For candidate ``p`` with
    entry Def ``e``: a :class:`DonationHazard` is reported iff some Use
    reads, *through a view alias*, a Def rooted at ``e`` after ``p``'s
    first overwrite in walk order, or from inside a loop that also
    contains an overwrite (loops re-execute, so intra-iteration order
    does not protect the read).  Direct reads of ``p``'s own name are
    never hazardous — a name read always observes the current scope
    value, and a post-overwrite read that still reaches ``e`` does so
    only on a path where the overwrite did not execute (zero-trip loop
    or untaken branch).  Only an alias captured *before* the overwrite
    can pin the donated buffer's pre-update bytes.  An empty return is
    the proof: every donated buffer's entry value is dead at its
    overwrite."""
    if df is None:
        df = analyze_dataflow(program, feed=feed, fetch=fetch)
    block = program.blocks[0]
    skip = df.feed | df.fetch
    hazards: List[DonationHazard] = []
    for name, v in sorted(block.vars.items()):
        if not v.persistable or name in skip:
            continue
        entry = df.entry_defs.get(name)
        if entry is None:
            continue
        overwrites = [d for d in df.defs_of(name) if d.kind == "op"]
        if not overwrites:
            continue
        first = min(d.pos for d in overwrites)
        ow_loops = {l for d in overwrites for l in d.loops}
        stale: List[Use] = []
        for u in df.uses:
            if u.name == name:
                continue   # a direct name read observes the current value
            if not any(entry in d.roots for d in u.defs):
                continue
            if u.pos > first or (ow_loops and set(u.loops) & ow_loops):
                stale.append(u)
        if stale:
            hazards.append(DonationHazard(
                name, entry, overwrites,
                sorted(stale, key=lambda u: u.pos)))
    return hazards


# --------------------------------------------------------------------------
# consumer 2: fusion-legality oracle
# --------------------------------------------------------------------------

def _single_consumer_edges(df: Dataflow, block) -> Dict[tuple, dict]:
    """(producer op idx, consumer op idx, name) -> certificate dict for
    every global-block edge that is provably single-consumer: the value is
    produced by exactly one reaching Def, read at exactly one op site, and
    escapes nowhere (not fetched, not persistable, not read from another
    block, not live-out as a data var)."""
    edges: Dict[tuple, dict] = {}
    for d in df.defs:
        if d.kind != "op" or d.block_idx != block.idx:
            continue
        v = block.vars.get(d.name)
        if v is not None and (v.persistable or v.is_data):
            continue
        if d.name in df.fetch:
            continue
        sites = {(u.block_idx, u.op_idx) for u in d.uses}
        if len(sites) != 1:
            continue
        (ub, uo), = sites
        if ub != block.idx:
            continue
        use = next(u for u in d.uses if u.op_idx == uo)
        if use.defs != {d}:
            continue          # the consumer may read a different Def too
        edges[(d.op_idx, uo, d.name)] = {
            "var": d.name, "def": df.site(d), "use": df.site(use),
            "n_consumers": 1}
    return edges


def fusable_groups(program, fetch: Iterable[str] = (),
                   feed: Iterable[str] = (),
                   df: Optional[Dataflow] = None) -> List[FusionGroup]:
    """The fusion-legality oracle over the global block.

    Emits two group kinds, each carrying a dependence certificate:

    - ``elementwise_chain`` — maximal components of pure elementwise ops
      linked by single-consumer intermediates.  Always legal to fuse: the
      composition is a pure per-element function of the group inputs.
    - ``producer_consumer`` — a pure non-elementwise producer (matmul,
      conv, reduce) whose single consumer is a pure elementwise op: the
      epilogue-fusion shape (TVM's complex-out-fusable class).

    A value read by two ops is *never* inside a group (the shared-consumer
    rejection): fusing one consumer would either recompute the producer or
    force a materialization — exactly the cases the 3(c) pass must prove
    about, so the oracle refuses to certify them.  Groups only ever
    contain ``pure`` ops: in-place, side-effecting, and control ops have
    ordering obligations a fused region cannot honor."""
    if df is None:
        df = analyze_dataflow(program, feed=feed, fetch=fetch)
    block = program.blocks[0]
    eff = df.effects
    ops = block.ops

    def pure(i):
        return eff.get((block.idx, i)) == Effect.PURE

    def ew(i):
        return pure(i) and ops[i].type in ELEMENTWISE_OPS

    edges = _single_consumer_edges(df, block)

    # union-find over elementwise ops linked by single-consumer edges
    parent = list(range(len(ops)))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(i, j):
        ri, rj = find(i), find(j)
        if ri != rj:
            parent[rj] = ri

    for (i, j, _name) in edges:
        if ew(i) and ew(j):
            union(i, j)
    comps: Dict[int, List[int]] = {}
    for i in range(len(ops)):
        if ew(i):
            comps.setdefault(find(i), []).append(i)

    groups: List[FusionGroup] = []
    chained: Set[int] = set()
    for comp in comps.values():
        if len(comp) < 2:
            continue
        comp = sorted(comp)
        chained.update(comp)
        groups.append(_certify(df, block, comp, "elementwise_chain", edges))

    # producer -> consumer epilogues: pure non-elementwise producer whose
    # sole consumer is an elementwise op not already inside a chain
    for (i, j, name) in sorted(edges):
        if pure(i) and not ew(i) and ew(j) and j not in chained:
            groups.append(_certify(df, block, [i, j], "producer_consumer",
                                   edges))
    groups.sort(key=lambda g: g.op_idxs[0])
    return groups


def _certify(df: Dataflow, block, comp: List[int], kind: str,
             edges: Dict[tuple, dict]) -> FusionGroup:
    inside = set(comp)
    cert = [c for (i, j, _n), c in sorted(edges.items())
            if i in inside and j in inside]
    internal = {c["var"] for c in cert}
    inputs: List[str] = []
    for i in comp:
        for n in block.ops[i].input_vars():
            if n not in internal and n not in inputs:
                inputs.append(n)
    outputs: List[str] = []
    for i in comp:
        for n in block.ops[i].output_vars():
            if n not in internal and n not in outputs:
                outputs.append(n)
    return FusionGroup(kind, block.idx, sorted(comp), inputs, outputs, cert)


def region_schedulable(block, group: FusionGroup) -> bool:
    """Can ``group`` legally execute as ONE region at its first member's
    position?  The dependence certificate proves the intra-group edges;
    this proves the *rewrite*: hoisting every member up to the first
    member's slot must not cross a non-member op that (re)defines a group
    input or touches a group output name.  Conservative — a False here
    forgoes a fusion, never risks one (the executor counts it as
    ``reason="not_schedulable"``)."""
    s, e = group.op_idxs[0], group.op_idxs[-1]
    members = set(group.op_idxs)
    ins, outs = set(group.inputs), set(group.outputs)
    for k in range(s + 1, e):
        if k in members:
            continue
        op = block.ops[k]
        if set(op.output_vars()) & (ins | outs):
            return False
        if set(op.input_vars()) & outs:
            return False
    return True


def certificate_matches(cert: dict, group: FusionGroup,
                        op_types: Sequence[str]) -> bool:
    """Does a *persisted* certificate (an autotune-cache ``fusion`` entry)
    still describe ``group`` as the oracle certifies it TODAY?  Exact
    match on kind, member indices, member op types, boundary vars, and
    edge vars — any drift means the entry was measured on a different
    graph and is refused at consult time (and flagged by L008)."""
    if not isinstance(cert, dict):
        return False
    try:
        return (cert.get("kind") == group.kind
                and list(cert.get("op_idxs") or []) == list(group.op_idxs)
                and list(cert.get("op_types") or []) == list(op_types)
                and list(cert.get("inputs") or []) == list(group.inputs)
                and list(cert.get("outputs") or []) == list(group.outputs)
                and [e.get("var") for e in (cert.get("edges") or [])]
                == [e["var"] for e in group.edges])
    except (TypeError, AttributeError):
        return False


# --------------------------------------------------------------------------
# consumer 4: --explain chains
# --------------------------------------------------------------------------

def explain_var(df: Dataflow, name: str) -> Optional[str]:
    """One-line def-use chain for ``name``: where it is defined (and
    redefined), and where it is last read — the ``lint --explain`` text."""
    defs = df.defs_of(name)
    if not defs:
        return None
    paths = df.block_paths
    first = defs[0]
    if first.kind == "entry":
        s = f"'{name}': defined on entry"
    else:
        s = f"'{name}': defined at {first.site(paths)}"
    redefs = [d for d in defs[1:] if d.kind == "op"]
    if redefs:
        s += (f", redefined at {redefs[0].site(paths)}"
              + (f" (+{len(redefs) - 1} more)" if len(redefs) > 1 else ""))
    all_uses = sorted({u for d in defs for u in d.uses}, key=lambda u: u.pos)
    if all_uses:
        s += f", last read at {all_uses[-1].site(paths)}"
    elif name in df.fetch:
        s += ", read by fetch"
    else:
        s += ", never read"
    return s
