"""Diagnostic model for the static verifier / linter (analysis subpackage).

Every finding — structural error, shape mismatch, lint — is one
:class:`Diagnostic` carrying a stable code, a severity, the op's location
(block idx + op idx + op type) and a fix hint.  The location string format
``block B, op #I (type)`` is shared verbatim with the executor's trace-time
error notes (fluid/executor.py:_trace_ops) so a static diagnostic and the
runtime failure for the same op cite the same site.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Sequence


class Severity(enum.IntEnum):
    """Ordered so ``severity >= Severity.WARNING`` style filters work."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


def op_site(block_idx: Optional[int], op_idx: Optional[int],
            op_type: Optional[str]) -> str:
    """Canonical location string — keep in sync with executor._trace_ops."""
    if block_idx is None:
        return "program"
    if op_idx is None:
        return f"block {block_idx}"
    t = f" ({op_type})" if op_type else ""
    return f"block {block_idx}, op #{op_idx}{t}"


@dataclass
class Diagnostic:
    """One verifier/linter finding.

    ``code`` is stable across releases (``V0xx`` structural, ``S0xx`` shape,
    ``L0xx`` lint) so tooling can filter/suppress by id.
    """

    code: str
    severity: Severity
    message: str
    block_idx: Optional[int] = None
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None
    # which program the finding is in ("main"/"startup") when several are
    # analyzed together, e.g. by the lint CLI; block/op indices alone are
    # ambiguous across programs
    program: Optional[str] = None

    def location(self) -> str:
        site = op_site(self.block_idx, self.op_idx, self.op_type)
        return f"[{self.program}] {site}" if self.program else site

    def __str__(self):
        parts = [f"{self.severity}", f"[{self.code}]", self.location() + ":",
                 self.message]
        s = " ".join(parts)
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": str(self.severity),
                "message": self.message, "block_idx": self.block_idx,
                "op_idx": self.op_idx, "op_type": self.op_type,
                "var": self.var, "hint": self.hint, "program": self.program}


def errors(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity >= Severity.ERROR]


def max_severity(diags: Sequence[Diagnostic]) -> Optional[Severity]:
    return max((d.severity for d in diags), default=None)


def format_diagnostics(diags: Sequence[Diagnostic]) -> str:
    if not diags:
        return "no diagnostics"
    return "\n".join(str(d) for d in diags)


class ProgramVerificationError(ValueError):
    """Raised by ``Executor.run(verify=True)`` / ``check_or_raise`` when a
    program has error-severity diagnostics.  ``.diagnostics`` holds the full
    list (warnings included) for tooling."""

    def __init__(self, diags: Sequence[Diagnostic]):
        self.diagnostics = list(diags)
        errs = errors(diags)
        super().__init__(
            f"program verification failed with {len(errs)} error(s):\n"
            + format_diagnostics(errs))
