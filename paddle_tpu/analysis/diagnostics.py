"""Diagnostic model for the static verifier / linter (analysis subpackage).

Every finding — structural error, shape mismatch, lint — is one
:class:`Diagnostic` carrying a stable code, a severity, the op's location
(block idx + op idx + op type) and a fix hint.  The location string format
``block B, op #I (type)`` is shared verbatim with the executor's trace-time
error notes (fluid/executor.py:_trace_ops) so a static diagnostic and the
runtime failure for the same op cite the same site.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence


class Severity(enum.IntEnum):
    """Ordered so ``severity >= Severity.WARNING`` style filters work."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


def op_site(block_idx: Optional[int], op_idx: Optional[int],
            op_type: Optional[str],
            block_path: Optional[str] = None) -> str:
    """Canonical location string — keep in sync with executor._trace_ops.

    ``block_path`` cites the full parent chain for nested sub-blocks
    (``block 0.2, op #5``); the root block's path is ``"0"``, so root
    sites keep the historical ``block 0, op #I`` form verbatim."""
    if block_idx is None:
        return "program"
    label = block_path if block_path else block_idx
    if op_idx is None:
        return f"block {label}"
    t = f" ({op_type})" if op_type else ""
    return f"block {label}, op #{op_idx}{t}"


def block_paths(program) -> Dict[int, str]:
    """Root-to-leaf parent-chain path per block: ``{0: "0", 2: "0.2",
    5: "0.2.5"}``.  Defensive against malformed parent indices (cycles,
    out-of-range) — the verifier reports those; this must not crash."""
    blocks = getattr(program, "blocks", None) or []
    out: Dict[int, str] = {}
    for b in blocks:
        chain = []
        idx = b.idx
        guard = len(blocks) + 1
        while (isinstance(idx, int) and 0 <= idx < len(blocks)
               and idx not in chain and guard):
            guard -= 1
            chain.append(idx)
            p = blocks[idx].parent_idx
            if not isinstance(p, int) or p < 0:
                break
            idx = p
        out[b.idx] = ".".join(str(i) for i in reversed(chain))
    return out


@dataclass
class Diagnostic:
    """One verifier/linter finding.

    ``code`` is stable across releases (``V0xx`` structural, ``S0xx`` shape,
    ``L0xx`` lint) so tooling can filter/suppress by id.
    """

    code: str
    severity: Severity
    message: str
    block_idx: Optional[int] = None
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None
    # which program the finding is in ("main"/"startup") when several are
    # analyzed together, e.g. by the lint CLI; block/op indices alone are
    # ambiguous across programs
    program: Optional[str] = None
    # full parent-chain path for nested sub-blocks ("0.2.5"); filled by
    # analyze_program from block_paths() so every pass cites it for free
    block_path: Optional[str] = None
    # def-use chain text for the var (`lint --explain`); None unless the
    # caller asked for explanations
    explain: Optional[str] = None

    def location(self) -> str:
        site = op_site(self.block_idx, self.op_idx, self.op_type,
                       block_path=self.block_path)
        return f"[{self.program}] {site}" if self.program else site

    def __str__(self):
        parts = [f"{self.severity}", f"[{self.code}]", self.location() + ":",
                 self.message]
        s = " ".join(parts)
        if self.hint:
            s += f"\n    hint: {self.hint}"
        if self.explain:
            s += f"\n    chain: {self.explain}"
        return s

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": str(self.severity),
                "message": self.message, "block_idx": self.block_idx,
                "op_idx": self.op_idx, "op_type": self.op_type,
                "var": self.var, "hint": self.hint, "program": self.program,
                "block_path": self.block_path, "explain": self.explain}


def errors(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity >= Severity.ERROR]


def max_severity(diags: Sequence[Diagnostic]) -> Optional[Severity]:
    return max((d.severity for d in diags), default=None)


def format_diagnostics(diags: Sequence[Diagnostic]) -> str:
    if not diags:
        return "no diagnostics"
    return "\n".join(str(d) for d in diags)


class ProgramVerificationError(ValueError):
    """Raised by ``Executor.run(verify=True)`` / ``check_or_raise`` when a
    program has error-severity diagnostics.  ``.diagnostics`` holds the full
    list (warnings included) for tooling."""

    def __init__(self, diags: Sequence[Diagnostic]):
        self.diagnostics = list(diags)
        errs = errors(diags)
        super().__init__(
            f"program verification failed with {len(errs)} error(s):\n"
            + format_diagnostics(errs))
