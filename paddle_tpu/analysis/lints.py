"""Configurable lint catalogue over Program IR.

Lints are advisory by default (WARNING/INFO); the CLI's ``--fail-on`` and
:func:`lint_program`'s ``severity_overrides`` promote or demote them.  IDs:

- **L001 dead-op** (warning): an op none of whose outputs is ever read by a
  later op (in any block), fetched, or synced to the scope (persistable).
  The traced XLA graph silently drops it, so it is almost always a builder
  bug.  The last op of a block is exempt when no fetch list is given — its
  outputs are the block's results.
- **L002 unused-variable** (info): a declared var no op reads or writes and
  nobody fetches — desc noise that bloats serialized programs.
- **L003 trace-safety** (warning): attrs that break jit tracing or program
  serialization — host callables outside ``fill_init.init`` (cannot
  round-trip through ``Program.to_dict``; if they close over arrays the op
  becomes trace-dependent) and array-valued attrs (constants baked into the
  desc make the compiled fn shape-dependent on builder state).
- **L004 sharding-consistency** (error): a ``Variable.sharding`` annotation
  or op-level ``sharding`` attr that repeats an axis or has more entries
  than the tensor has dims — XLA would reject or mis-partition it at
  compile time.  An axis name outside the valid set is an ERROR when the
  caller pins ``mesh_axes`` explicitly, but only a WARNING against the
  default ``parallel.mesh.CANONICAL_ORDER`` (``make_mesh`` accepts custom
  axis names, so an unknown name may be a real custom axis).  A malformed
  spec (non-string entries, a non-sequence) is reported, never raised on.
- **L005 metric-naming** (warning): an observability metric name that
  breaks the public naming contract (docs/design/observability.md):
  shape ``subsystem.noun_qualifier`` (one dot, snake_case), counters end
  ``_total``, histograms end ``_seconds``/``_bytes``/``_total``, gauges
  claim no reserved suffix.  Runs over :data:`paddle_tpu.obs.CATALOGUE`
  in the ``paddle_tpu lint`` CLI (:func:`lint_metric_names`) — metric
  names are API surface; a drive-by rename breaks dashboards silently.
- **L006 shape-churn** (warning): a Program is being run with feeds whose
  shapes keep changing and no bucket spec — every distinct shape pays a
  fresh trace + XLA compile.  Unlike L001–L005 this has no static
  signature (the desc can't see future feed shapes), so it is emitted *at
  run time* by ``fluid.Executor`` as a ``RuntimeWarning`` naming this id,
  on a streak of compiled-fn cache misses (``executor._CHURN_STREAK``)
  with ``Executor(buckets=None)``.  Fix: pass a
  :class:`~paddle_tpu.data.feeder.BucketSpec`
  (docs/design/executor_perf.md).
- **L009 alert-rules** (warning): an alert rule
  (:mod:`paddle_tpu.obs.alerts`) referencing a metric name the catalogue
  does not declare, filtering on a label key the metric's catalogue entry
  does not carry (``worker`` is always legal — the merged-view label
  contract), or applying a kind that cannot evaluate against the metric's
  kind (``burn_rate`` needs a histogram; ``threshold`` needs a
  counter/gauge value).  Rules are config pointed at the catalogue's API
  surface — a rule naming a typo'd metric silently never fires, which is
  the worst possible alerting failure.  Runs over the shipped default
  rule set in ``paddle_tpu lint`` (:func:`lint_alert_rules`) and the obs
  test-suite.
- **L007 catalogue-drift** (warning): an emit site in ``paddle_tpu/``
  (``obs.count/gauge_set/observe``, ``registry.counter/gauge/histogram``,
  a span's ``metric=``) passes a string-literal metric name that is not
  declared in ``obs/catalogue.py`` — or, vice versa, a catalogue entry no
  emit site ever names (an orphan that documents a series which cannot
  exist).  The catalogue is the metrics API surface; drift in either
  direction means dashboards and docs lie.  Runs over the source tree in
  the ``paddle_tpu lint`` CLI and the obs test-suite
  (:func:`lint_catalogue_drift`).
- **L010 dead-write** (warning), **L011 donation-hazard** (error), **L012
  alias-escape** (warning): the dataflow-backed lints — def-use chains,
  alias roots, and the donation-safety proof from
  :mod:`paddle_tpu.analysis.dataflow` (see :func:`_lint_dataflow` and
  docs/design/analysis.md "Dataflow & liveness").
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

from .diagnostics import Diagnostic, Severity
from .verify import BLOCK_ATTR_KEYS, _ATTR_BIND_KEYS, _ATTR_READ_KEYS, _names

LINT_CATALOGUE = {
    "L001": ("dead-op", Severity.WARNING),
    "L002": ("unused-variable", Severity.INFO),
    "L003": ("trace-safety", Severity.WARNING),
    "L004": ("sharding-consistency", Severity.ERROR),
    "L005": ("metric-naming", Severity.WARNING),
    # L006 is runtime-emitted by fluid.Executor (cache-miss streak with no
    # bucket spec) — catalogued here so the id/severity live in one table
    "L006": ("shape-churn", Severity.WARNING),
    "L007": ("catalogue-drift", Severity.WARNING),
    "L008": ("autotune-staleness", Severity.WARNING),
    "L009": ("alert-rules", Severity.WARNING),
    # L010-L012 are dataflow-backed (analysis.dataflow): def-use chains,
    # alias roots, and the donation-safety proof, not per-block scans
    "L010": ("dead-write", Severity.WARNING),
    "L011": ("donation-hazard", Severity.ERROR),
    "L012": ("alias-escape", Severity.WARNING),
}

# control-flow / executor-lowered ops act through sub-blocks, not outputs
_STRUCTURAL_OPS = {"while", "conditional_block", "static_rnn",
                   "beam_search_gen", "autodiff_grad", "feed", "fetch"}

# env-read attr keys beyond verify's tables (names read at lowering time)
_EXTRA_READ_KEYS = ("mem_update_names", "step_out_names", "prob_name",
                    "token_embed_name", "last_mem_outputs", "loss", "params")


def _attr_read_names(op) -> Set[str]:
    reads: Set[str] = set()
    for table in (_ATTR_READ_KEYS, _ATTR_BIND_KEYS):
        for key in table.get(op.type, ()):
            reads.update(_names(op.attrs.get(key)))
    for key in _EXTRA_READ_KEYS:
        if key in op.attrs:
            reads.update(_names(op.attrs.get(key)))
    return reads


def _all_reads(program) -> Set[str]:
    reads: Set[str] = set()
    for block in program.blocks:
        for op in block.ops:
            reads.update(op.input_vars())
            reads.update(_attr_read_names(op))
    return reads


def lint_program(program, fetch: Iterable[str] = (),
                 mesh_axes: Optional[Sequence[str]] = None,
                 enable: Optional[Iterable[str]] = None,
                 severity_overrides: Optional[Dict[str, Severity]] = None,
                 feed: Iterable[str] = (),
                 donate: Optional[bool] = None,
                 diags: Optional[List[Diagnostic]] = None) -> List[Diagnostic]:
    """Run the lint catalogue; returns the diagnostic list.

    ``fetch`` — names the caller will fetch (liveness roots for L001/L002,
    donation exclusions for L011).  ``feed`` — names the caller feeds
    (donation exclusions).  ``mesh_axes`` — valid sharding axis names;
    defaults to ``parallel.mesh.CANONICAL_ORDER``.  ``enable`` — subset of
    lint IDs to run (default: all).  ``severity_overrides`` — e.g. promote
    ``{"L001": Severity.ERROR}`` to make dead ops hard failures.
    ``donate`` — the executor's donation switch: ``True`` makes L011 an
    ERROR (the run WILL donate hazardous buffers), ``None`` (static /CLI
    context) demotes it to an advisory WARNING, ``False`` skips it.
    """
    diags = [] if diags is None else diags
    enabled = set(enable) if enable is not None else set(LINT_CATALOGUE)
    overrides = severity_overrides or {}

    def emit(code: str, message: str, severity: Optional[Severity] = None,
             **kw):
        sev = overrides.get(
            code, severity if severity is not None
            else LINT_CATALOGUE[code][1])
        diags.append(Diagnostic(code, sev, message, **kw))

    fetch = set(fetch)
    reads = _all_reads(program)
    persistables = {name for block in program.blocks
                    for name, v in block.vars.items() if v.persistable}

    if "L001" in enabled:
        _lint_dead_ops(program, reads, fetch, persistables, emit)
    if "L002" in enabled:
        _lint_unused_vars(program, reads, fetch, emit)
    if "L003" in enabled:
        _lint_trace_safety(program, emit)
    if "L004" in enabled:
        _lint_sharding(program, mesh_axes, emit)
    if enabled & {"L010", "L011", "L012"}:
        _lint_dataflow(program, fetch, set(feed), donate, enabled, emit)
    return diags


def _lint_dataflow(program, fetch, feed, donate, enabled, emit):
    """The dataflow-backed lints (analysis.dataflow consumers).

    - **L010 dead-write**: a Def with zero recorded Uses that a later Def
      of the same name kills before the end of the program.  Same-block
      linear kills are V003's domain (an ERROR there) and skipped here;
      L010 owns the cross-block cases V003's per-block pending scan cannot
      see (a sub-block write overwritten after the loop, a branch write
      overwritten by the parent).
    - **L011 donation-hazard**: :func:`analysis.dataflow.donation_hazards`
      found a donated persistable whose entry value may be read after its
      overwrite — an ERROR when ``donate=True`` (the run corrupts), an
      advisory WARNING in static/CLI context (``donate=None``), skipped
      when donation is off.
    - **L012 alias-escape**: a sub-block op writes a name that aliases an
      outer-scope var (through assign/reshape/... view roots) while the
      base var itself is never updated in that control region: the write
      rebinds only the view name — under the reference's shared-buffer
      semantics the base would change, under traced semantics it silently
      does not.
    """
    from . import dataflow as D
    df = D.analyze_dataflow(program, feed=feed, fetch=fetch)
    paths = df.block_paths

    if "L010" in enabled:
        for d in df.defs:
            if d.kind != "op" or d.uses or d.name in fetch:
                continue
            if d in df.final_env.get(d.name, ()):
                continue          # reaches the end: fetchable/synced, live
            killers = sorted((k for k in df.defs
                              if k.name == d.name and k.kind == "op"
                              and k.pos > d.pos), key=lambda k: k.pos)
            if not killers:
                continue          # never overwritten: L001's dead-op case
            if killers[0].block_idx == d.block_idx:
                continue          # same-block linear kill: V003's ERROR
            emit("L010",
                 f"dead write: '{d.name}' written here is overwritten at "
                 f"{killers[0].site(paths)} before any read",
                 block_idx=d.block_idx, op_idx=d.op_idx, op_type=d.op_type,
                 var=d.name,
                 hint="read the value before the overwrite, or drop the "
                      "first write — the traced computation discards it")

    if "L011" in enabled and donate is not False:
        sev = (LINT_CATALOGUE["L011"][1] if donate
               else Severity.WARNING)
        for hz in D.donation_hazards(program, feed=feed, fetch=fetch, df=df):
            first_ow = hz.overwrites[0]
            qualifier = ("" if donate else
                         " (advisory: hazardous if run with donate=True, "
                         "the Executor default)")
            emit("L011", hz.describe(paths) + qualifier, severity=sev,
                 block_idx=first_ow.block_idx, op_idx=first_ow.op_idx,
                 op_type=first_ow.op_type, var=hz.name,
                 hint="move the read before the update, fetch the var "
                      "(fetched persistables are never donated), or run "
                      "with donate=False; the Executor auto-downgrades "
                      "this var's donation when verify is off")

    if "L012" in enabled:
        for esc in df.alias_escapes:
            emit("L012",
                 f"sub-block write to '{esc['name']}' only rebinds a view "
                 f"of outer var '{esc['base']}' (aliased at "
                 f"{esc['view_def'].site(paths)}); the base var is never "
                 "updated in this control region",
                 block_idx=esc["block_idx"], op_idx=esc["op_idx"],
                 op_type=esc["op_type"], var=esc["name"],
                 hint=f"write '{esc['base']}' itself (sub-block writes "
                      "propagate by name through the loop carry), or use "
                      "a fresh local name for the rebound value")


def _lint_dead_ops(program, reads, fetch, persistables, emit):
    live = reads | fetch | persistables
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            if op.type in _STRUCTURAL_OPS or any(
                    key in op.attrs for key in BLOCK_ATTR_KEYS):
                continue
            outs = op.output_vars()
            if not outs:
                continue
            if not fetch and idx == len(block.ops) - 1:
                continue  # a block's final op produces its implicit result
            if not any(n in live for n in outs):
                emit("L001",
                     f"dead op: outputs {outs} are never read, fetched, or "
                     "persisted — the compiled computation drops this op",
                     block_idx=block.idx, op_idx=idx, op_type=op.type,
                     hint="fetch the result, feed it to another op, or "
                          "delete the op")


def _lint_unused_vars(program, reads, fetch, emit):
    touched: Set[str] = set(reads)
    for block in program.blocks:
        for op in block.ops:
            touched.update(op.output_vars())
    for block in program.blocks:
        for name, v in block.vars.items():
            if name in touched or name in fetch or name == "__step__":
                continue
            kind = "feed slot" if v.is_data else "variable"
            emit("L002", f"unused {kind} '{name}' (no op reads or writes it)",
                 block_idx=block.idx, var=name,
                 hint="remove the declaration or wire it into the program")


def _lint_trace_safety(program, emit):
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            for key, val in op.attrs.items():
                if callable(val) and not (op.type == "fill_init"
                                          and key == "init"):
                    emit("L003",
                         f"attr '{key}' is a host callable "
                         f"({getattr(val, '__name__', type(val).__name__)}): "
                         "it cannot serialize and, if it closes over traced "
                         "arrays, makes the op trace-dependent",
                         block_idx=block.idx, op_idx=idx, op_type=op.type,
                         hint="pass data through inputs and plain attrs; "
                              "host init callables belong on fill_init only")
                elif getattr(val, "shape", None) and hasattr(val, "dtype"):
                    # non-scalar ndarray / jax array baked into the desc
                    emit("L003",
                         f"attr '{key}' holds an array baked into the desc; "
                         "under jit its value is frozen at trace time "
                         "(shape/data changes will not recompile)",
                         block_idx=block.idx, op_idx=idx, op_type=op.type,
                         hint="feed arrays through op inputs instead")


#: kind -> allowed name suffixes (None entry = no suffix requirement)
_METRIC_SUFFIXES = {
    "counter": ("_total",),
    "histogram": ("_seconds", "_bytes", "_total"),
}
_RESERVED_SUFFIXES = ("_total", "_seconds", "_bytes", "_bucket", "_sum",
                      "_count")

# label keys whose values are, in practice, unbounded identifier spaces: a
# per-path / per-payload / per-uuid label mints a new time series per value
# and melts whatever stores the metrics (the Prometheus cardinality
# failure mode). Bounded enums — op/type/site/action/rpc/worker — are fine.
_UNBOUNDED_LABEL_KEYS = frozenset((
    "path", "file", "filename", "dir", "payload", "task", "task_id",
    "id", "uuid", "trace", "trace_id", "span", "span_id", "addr",
    "address", "url", "host", "endpoint", "user", "query"))

#: value-shape heuristics (applied when live samples are linted): a label
#: value longer than this, or containing a path separator, is almost
#: certainly a raw identifier rather than a bounded enum
_MAX_LABEL_VALUE_LEN = 64
#: distinct values per (metric, label key) before the series space is
#: called unbounded
_MAX_LABEL_CARDINALITY = 32


def lint_metric_names(catalogue, severity: Severity = None,
                      samples=None) -> List[Diagnostic]:
    """L005: validate metric names against the ``subsystem.noun_qualifier``
    contract (paddle_tpu.obs.metrics.METRIC_NAME_RE) plus the suffix-per-
    kind conventions, and flag unbounded-cardinality labels.

    ``catalogue`` is a mapping ``name -> (kind, help[, labels])`` (the
    shape of :data:`paddle_tpu.obs.CATALOGUE`), ``name -> kind``, or a
    plain iterable of names (then only the shape is checked). Declared
    label *keys* are checked against the known-unbounded set (a raw path
    or task payload as a label value explodes the series space).

    ``samples`` optionally takes live ``MetricsRegistry.collect()``
    output; label *values* are then also checked — path-like or very long
    values, and per-key cardinality beyond a bounded-enum's plausible
    size, are flagged even when the key name looks innocent.

    Standalone on purpose: metric names live in instrumented *code*, not
    Program IR, so this lint is driven by the CLI and the obs test-suite
    rather than ``lint_program``.
    """
    from ..obs.metrics import METRIC_NAME_RE   # lazy: keeps analysis light
    sev = severity if severity is not None else LINT_CATALOGUE["L005"][1]
    diags: List[Diagnostic] = []

    def emit(msg: str, name: str, hint: str):
        diags.append(Diagnostic("L005", sev, msg, var=name, hint=hint))

    if isinstance(catalogue, dict):
        items = []
        for name, spec in catalogue.items():
            kind = spec[0] if isinstance(spec, (tuple, list)) else spec
            labels = (tuple(spec[2]) if isinstance(spec, (tuple, list))
                      and len(spec) > 2 else ())
            items.append((name, kind, labels))
    else:
        items = [(name, None, ()) for name in catalogue]
    for name, kind, labels in items:
        for key in labels:
            if key in _UNBOUNDED_LABEL_KEYS:
                emit(f"label '{key}' on '{name}' is an unbounded-"
                     "cardinality key (each distinct value mints a new "
                     "series)", name,
                     "put identifiers in span args/logs; keep labels to "
                     "bounded enums (op, type, site, worker, ...)")
        if not METRIC_NAME_RE.match(name):
            emit(f"metric name '{name}' is not subsystem.noun_qualifier "
                 "(exactly one dot, snake_case atoms)", name,
                 "rename to e.g. 'trainer.steps_total'")
            continue
        if kind in _METRIC_SUFFIXES:
            if not name.endswith(_METRIC_SUFFIXES[kind]):
                emit(f"{kind} '{name}' must end with one of "
                     f"{'/'.join(_METRIC_SUFFIXES[kind])}", name,
                     "counters count (suffix _total); histograms measure "
                     "(suffix _seconds/_bytes)")
        elif kind == "gauge" and name.endswith(_RESERVED_SUFFIXES):
            emit(f"gauge '{name}' claims a suffix reserved for "
                 "counters/histograms", name,
                 "drop the suffix — a gauge is a point-in-time value")
    if samples:
        # live-sample pass: catch unbounded label VALUES the static
        # catalogue can't see (a bounded-sounding key fed raw paths)
        seen: Dict[tuple, Set[str]] = {}
        flagged_val: Set[tuple] = set()
        for s in samples:
            if not isinstance(s, dict):
                continue
            mname = s.get("name", "?")
            for key, value in (s.get("labels") or {}).items():
                v = str(value)
                if (key, mname) not in flagged_val and (
                        len(v) > _MAX_LABEL_VALUE_LEN or "/" in v
                        or "\\" in v):
                    flagged_val.add((key, mname))
                    emit(f"label '{key}' on '{mname}' carries a path-like "
                         f"or oversized value ({v[:40]!r}...): unbounded "
                         "cardinality", mname,
                         "record the identifier in span args or logs, not "
                         "a metric label")
                seen.setdefault((mname, key), set()).add(v)
        for (mname, key), values in sorted(seen.items()):
            if len(values) > _MAX_LABEL_CARDINALITY:
                emit(f"label '{key}' on '{mname}' has {len(values)} "
                     f"distinct values (> {_MAX_LABEL_CARDINALITY}): "
                     "series space looks unbounded", mname,
                     "bucket the value or move it out of labels")
    return diags


#: method names whose first string argument (or ``metric=`` kwarg) is a
#: metric name: the obs facade's emitters and the registry constructors
_EMIT_ATTRS = frozenset(("count", "gauge_set", "observe",
                         "counter", "gauge", "histogram"))


def _metric_literals(tree):
    """(literals, patterns) of metric names an AST emits: plain string
    constants, plus regexes for f-string names (``f"goodput.{b}_total"``
    -> ``goodput\\..*_total``) so dynamically-assembled families still
    anchor their catalogue entries."""
    import ast
    import re as _re
    literals: Set[str] = set()
    patterns: List = []

    def _collect(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            literals.add(node.value)
        elif isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(_re.escape(str(v.value)))
                else:
                    parts.append(".*")
            patterns.append(_re.compile("^" + "".join(parts) + "$"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        # `obs.count(...)`, `self._count(...)`, and the imported-alias
        # forms `count(...)` / `_gauge_set(...)` all emit; leading
        # underscores are the module-private alias convention
        fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                 else node.func.id if isinstance(node.func, ast.Name)
                 else "")
        if fname.lstrip("_") in _EMIT_ATTRS and node.args:
            _collect(node.args[0])
        for kw in node.keywords:
            if kw.arg == "metric":            # obs.span(..., metric=...)
                _collect(kw.value)
    return literals, patterns


def lint_catalogue_drift(root=None, catalogue=None,
                         severity: Severity = None) -> List[Diagnostic]:
    """L007: cross-check emit sites in the source tree against the metric
    catalogue — both directions.

    Walks every ``.py`` under ``root`` (default: the installed
    ``paddle_tpu`` package) collecting string-literal metric names passed
    to the obs emitters (``count``/``gauge_set``/``observe``, the
    registry's ``counter``/``gauge``/``histogram``, a span's ``metric=``
    kwarg). A literal that *looks like* a metric name (matches the L005
    shape — guards against ``str.count(...)`` false positives) but is
    missing from the catalogue is flagged with its file; a catalogue
    entry no site ever names (literally or via an f-string family) is
    flagged as an orphan."""
    import ast
    import os

    from ..obs.metrics import METRIC_NAME_RE
    if catalogue is None:
        from ..obs import CATALOGUE as catalogue
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sev = severity if severity is not None else LINT_CATALOGUE["L007"][1]
    diags: List[Diagnostic] = []
    literals: Dict[str, str] = {}          # name -> first file emitting it
    patterns: List = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                continue                    # unreadable: not this lint's job
            lits, pats = _metric_literals(tree)
            rel = os.path.relpath(path, root)
            for name in lits:
                literals.setdefault(name, rel)
            patterns.extend(pats)
    for name in sorted(literals):
        if not METRIC_NAME_RE.match(name):
            continue                        # not a metric-shaped literal
        if name not in catalogue:
            diags.append(Diagnostic(
                "L007", sev,
                f"emit site passes metric '{name}' "
                f"({literals[name]}) but obs/catalogue.py does not "
                "declare it", var=name,
                hint="add a CATALOGUE entry (kind, help[, labels]) — the "
                     "catalogue is the metrics API surface"))
    for name in sorted(catalogue):
        if name in literals:
            continue
        if any(p.match(name) for p in patterns):
            continue                        # an f-string family emits it
        diags.append(Diagnostic(
            "L007", sev,
            f"catalogue entry '{name}' has no emit site in the tree "
            "(orphan)", var=name,
            hint="delete the entry, or wire the metric where it was "
                 "meant to be observed"))
    return diags


def lint_alert_rules(rules=None, catalogue=None,
                     severity: Severity = None) -> List[Diagnostic]:
    """L009: alert rules vs the metric catalogue — the alerting twin of
    L005/L007.

    Checks every rule (default: the shipped
    :func:`paddle_tpu.obs.alerts.default_rules` set, which is what a
    master aggregator starts with) against the catalogue (default:
    :data:`paddle_tpu.obs.CATALOGUE`):

    * the rule's ``metric`` must be a catalogued name — a rule naming a
      typo'd or renamed metric never fires, silently;
    * every label key the rule filters on must be declared by the
      metric's catalogue entry (``worker`` is always legal: the merged
      cluster view stamps it on every pushed series);
    * ``burn_rate`` rules must target histograms (the math needs
      cumulative buckets); ``threshold`` rules must target counters or
      gauges (a histogram has no single value to compare).
    """
    if catalogue is None:
        from ..obs import CATALOGUE as catalogue
    if rules is None:
        from ..obs.alerts import default_rules
        rules = default_rules()
    sev = severity if severity is not None else LINT_CATALOGUE["L009"][1]
    diags: List[Diagnostic] = []

    def emit(msg: str, rule, hint: str):
        diags.append(Diagnostic("L009", sev, msg, var=rule.name, hint=hint))

    for rule in rules:
        spec = catalogue.get(rule.metric)
        if spec is None:
            emit(f"alert rule '{rule.name}' references metric "
                 f"'{rule.metric}' which obs/catalogue.py does not "
                 "declare — the rule can never fire", rule,
                 "fix the metric name, or catalogue the new metric first")
            continue
        kind = spec[0] if isinstance(spec, (tuple, list)) else spec
        declared = (tuple(spec[2]) if isinstance(spec, (tuple, list))
                    and len(spec) > 2 else ())
        for key in rule.labels:
            if key != "worker" and key not in declared:
                emit(f"alert rule '{rule.name}' filters on label "
                     f"'{key}' which '{rule.metric}' does not declare "
                     f"(declared: {list(declared) or 'none'})", rule,
                     "filter only on declared label keys (or 'worker')")
        if rule.kind == "burn_rate" and kind != "histogram":
            emit(f"alert rule '{rule.name}' is burn_rate over "
                 f"'{rule.metric}' ({kind}); burn-rate math needs a "
                 "histogram's cumulative buckets", rule,
                 "use a threshold rule, or target the _seconds histogram")
        elif rule.kind == "threshold" and kind == "histogram":
            emit(f"alert rule '{rule.name}' thresholds histogram "
                 f"'{rule.metric}' which has no single value", rule,
                 "use burn_rate with an slo_le bucket bound instead")
    return diags


def lint_autotune_cache(path=None,
                        severity: Severity = None) -> List[Diagnostic]:
    """L008: the autotune cache vs the CURRENT plan spaces — staleness.

    An autotune entry is only as good as the candidate set that produced
    it: when a plan space changes (``paddle_tpu.tune.spaces.SPACE_DEFS``),
    previously tuned winners may no longer exist, or better candidates may
    have appeared. Stale entries are IGNORED at consult time (the
    heuristics silently own those decisions again), so the lint is what
    makes the degradation visible: it flags a schema-version mismatch
    (whole file ignored), entries whose ``space_hash`` differs from the
    current space's hash, and entries naming unknown spaces. Fix: re-run
    ``paddle_tpu tune``. ``path=None`` resolves
    ``$PADDLE_TPU_AUTOTUNE_CACHE`` / ``~/.paddle_tpu/autotune.json``; a
    missing file is clean (nothing tuned, nothing stale)."""
    import json
    import os

    from ..tune import cache as _tcache
    from ..tune import spaces as _tspaces
    sev = severity if severity is not None else LINT_CATALOGUE["L008"][1]
    diags: List[Diagnostic] = []
    path = path or _tcache.default_cache_path()
    if not os.path.exists(path):
        return diags

    def emit(msg: str, hint: str, **kw):
        diags.append(Diagnostic("L008", sev, msg, hint=hint, **kw))

    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        emit(f"autotune cache {path} is unreadable ({e}); every consult "
             "falls back to heuristics",
             "delete the file or re-run `paddle_tpu tune`")
        return diags
    version = data.get("schema_version") if isinstance(data, dict) else None
    if version != _tcache.SCHEMA_VERSION:
        emit(f"autotune cache {path} has schema_version {version!r} "
             f"(supported: {_tcache.SCHEMA_VERSION}); the whole file is "
             "ignored at consult time",
             "re-run `paddle_tpu tune` to rewrite it")
        return diags
    entries = data.get("entries") or {}
    for key, entry in sorted(entries.items()):
        if not isinstance(entry, dict):
            emit(f"autotune entry {key!r} is not an object",
                 "re-run `paddle_tpu tune`", var=key)
            continue
        space = entry.get("space")
        if space not in _tspaces.SPACE_DEFS:
            emit(f"autotune entry {key!r} names unknown plan space "
                 f"{space!r} (known: {list(_tspaces.SPACE_NAMES)}); "
                 "ignored at consult time",
                 "the space was removed/renamed — re-run `paddle_tpu "
                 "tune` to drop it", var=key)
            continue
        current = _tspaces.space_hash(space)
        if entry.get("space_hash") != current:
            emit(f"autotune entry {key!r} was tuned under plan-space hash "
                 f"{entry.get('space_hash')!r} but the current "
                 f"{space!r} space hashes {current!r}; the entry is "
                 "STALE and ignored at consult time (heuristic applies)",
                 "re-run `paddle_tpu tune` to re-measure under the new "
                 "candidate set", var=key)
            continue
        if space == "fusion":
            _lint_fusion_entry(key, entry, emit)
        elif space == "bucket_grid":
            _lint_bucket_grid_entry(key, entry, emit)
    return diags


def _lint_fusion_entry(key, entry, emit):
    """Per-entry L008 checks specific to the ``fusion`` space: the plan
    must be the binary verdict, the dependence certificate must be
    present, and the family's program/group signature components must
    re-derive from the persisted certificate — a hand-edited or wrongly
    merged cache whose proof no longer matches its key is refused at
    consult time (``cert_invalid``), and this is what makes it visible."""
    from ..tune import fusion as _tfusion
    plan = entry.get("plan")
    if not isinstance(plan, dict) or not isinstance(plan.get("fuse"), bool):
        emit(f"fusion entry {key!r} has plan {plan!r} (expected "
             "{'fuse': true|false}); ignored at consult time",
             "re-run `paddle_tpu tune fusion`", var=key)
        return
    cert = entry.get("certificate")
    if not isinstance(cert, dict):
        emit(f"fusion entry {key!r} carries no dependence certificate; "
             "the consult cannot re-validate it against the current "
             "program and refuses it (cert_invalid)",
             "re-run `paddle_tpu tune fusion`", var=key)
        return
    family = str(entry.get("family") or "")
    parts = family.split(":")
    if len(parts) != 3:
        emit(f"fusion entry {key!r} family {family!r} is not "
             "'program_sig:shape_family:group_sig'",
             "re-run `paddle_tpu tune fusion`", var=key)
        return
    derived = _tfusion.group_signature(cert)
    if derived != parts[2]:
        emit(f"fusion entry {key!r}: group signature {parts[2]!r} in the "
             f"family key does not re-derive from the persisted "
             f"certificate (derived {derived!r}); the key and the proof "
             "disagree — ignored at consult time",
             "the cache was hand-edited or wrongly merged; re-run "
             "`paddle_tpu tune fusion`", var=key)
    prog_sig = entry.get("program_signature")
    if prog_sig is not None and prog_sig != parts[0]:
        emit(f"fusion entry {key!r}: program_signature {prog_sig!r} "
             f"disagrees with the family key's {parts[0]!r}",
             "re-run `paddle_tpu tune fusion`", var=key)


def _lint_bucket_grid_entry(key, entry, emit):
    """Per-entry L008 checks for ``bucket_grid``: the plan's grid must be
    strictly ascending unique positive ints (the same legality the
    consult enforces — an illegal grid silently falls back)."""
    plan = entry.get("plan")
    buckets = plan.get("buckets") if isinstance(plan, dict) else None
    if (not isinstance(buckets, (list, tuple)) or not buckets
            or not all(isinstance(b, int) and not isinstance(b, bool)
                       and b >= 1 for b in buckets)
            or list(buckets) != sorted(set(buckets))):
        emit(f"bucket_grid entry {key!r} has plan {plan!r} (expected "
             "{'buckets': [ascending unique positive ints]}); ignored "
             "at consult time",
             "re-run `paddle_tpu tune bucket_grid`", var=key)


def _lint_sharding(program, mesh_axes, emit):
    explicit = mesh_axes is not None
    if not explicit:
        from ..parallel.mesh import CANONICAL_ORDER
        mesh_axes = CANONICAL_ORDER
    valid = set(mesh_axes)
    # make_mesh accepts axis names beyond CANONICAL_ORDER, so an unknown
    # name is only a hard error when the caller pinned the axes
    unknown_sev = Severity.ERROR if explicit else Severity.WARNING

    def check(spec, ndim, where, **site):
        if spec is None:
            return
        if isinstance(spec, str):
            spec = (spec,)
        try:
            entries = list(spec)
        except TypeError:
            emit("L004", f"{where} is not a sharding spec "
                         f"({spec!r}); expected a sequence of axis "
                         "names / None", **site)
            return
        axes = [a for a in entries if a is not None]
        for a in axes:
            if not isinstance(a, str):
                emit("L004", f"{where} has non-string entry {a!r}", **site)
            elif a not in valid:
                emit("L004",
                     f"{where} names unknown mesh axis '{a}' "
                     f"(valid: {sorted(valid)})", severity=unknown_sev,
                     **site)
        axes = [a for a in axes if isinstance(a, str)]
        dup = {a for a in axes if axes.count(a) > 1}
        if dup:
            emit("L004",
                 f"{where} repeats mesh axes {sorted(dup)}; an axis may "
                 "shard at most one tensor dim", **site)
        if ndim is not None and len(entries) > ndim:
            emit("L004",
                 f"{where} has {len(entries)} entries for a "
                 f"{ndim}-dim tensor", **site)

    for block in program.blocks:
        for name, v in block.vars.items():
            check(getattr(v, "sharding", None), len(v.shape) or None,
                  f"sharding annotation on var '{name}'",
                  block_idx=block.idx, var=name)
        for idx, op in enumerate(block.ops):
            check(op.attrs.get("sharding"), None,
                  f"op attr 'sharding'",
                  block_idx=block.idx, op_idx=idx, op_type=op.type)
