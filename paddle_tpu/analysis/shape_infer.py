"""Abstract shape/dtype interpretation of a Program — no data, no compile.

Each op is evaluated over :class:`jax.ShapeDtypeStruct` inputs.  Per-op infer
rules live in :class:`ShapeInferRegistry`, registered alongside the op
registry via :func:`register_shape_infer`; any op WITHOUT an explicit rule
falls back to ``jax.eval_shape`` over its registered compute — the traced
rule IS the infer rule, so the two can never drift.  Rank/dtype mismatches
(a matmul contraction that cannot work, a concat of incompatible trailing
dims) therefore surface as **S001** error diagnostics before any XLA compile
is attempted.

Codes:

- **S001** op fails shape inference (the abstract evaluation raised).
- **S002** inferred shape disagrees with the var's declared desc shape
  (warning — declared shapes are builder bookkeeping, the traced value wins).
- **S003** a control-flow carried var changes shape/dtype across the loop
  body or between cond branches (XLA loop carries must be invariant).

Dynamic (-1) dims in feed declarations are substituted with small concrete
placeholders (batch=2, other dynamic dims=3) unless the caller provides real
feed shapes; every other shape is *derived*, not read from the desc.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .diagnostics import Diagnostic, Severity

_DEFAULT_BATCH = 2
_DEFAULT_DYN = 3


class _Unknown:
    """Sentinel for values shape inference cannot determine; ops consuming
    an unknown input are skipped silently (no cascading diagnostics)."""

    def __repr__(self):
        return "<unknown shape>"


UNKNOWN = _Unknown()


class ShapeInferRegistry:
    """op type -> infer rule.  A rule has signature
    ``rule(op, ins, ctx) -> {slot: [ShapeDtypeStruct, ...]}`` where ``ins``
    maps input slots to struct lists and ``ctx`` is the :class:`InferContext`
    (program + env access for control-flow rules; ``ctx.site`` carries the
    op's location kwargs for Diagnostics)."""

    _rules: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_type: str):
        def deco(fn):
            cls._rules[op_type] = fn
            return fn
        return deco

    @classmethod
    def has(cls, op_type: str) -> bool:
        return op_type in cls._rules

    @classmethod
    def get(cls, op_type: str) -> Callable:
        return cls._rules[op_type]


def register_shape_infer(op_type: str):
    """Public decorator: register a shape-infer rule for a (possibly custom)
    op — see docs/design/analysis.md for the contract."""
    return ShapeInferRegistry.register(op_type)


class InferContext:
    def __init__(self, program, env: Dict[str, Any],
                 diags: List[Diagnostic], site: Optional[dict] = None):
        self.program = program
        self.env = env
        self.diags = diags
        self.site = site or {}


def _struct(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                np.dtype(dtype))


def _feed_struct(var, feed_shapes: Dict[str, Tuple]):
    """Concrete struct for a feed slot: real feed shape when given, else the
    declared shape with dynamic dims substituted."""
    if var.name in feed_shapes:
        shape, dtype = feed_shapes[var.name]
        return _struct(shape, dtype)
    shape = [(_DEFAULT_BATCH if i == 0 else _DEFAULT_DYN) if s < 0 else s
             for i, s in enumerate(var.shape)]
    return _struct(shape, var.dtype)


def _first_line(e: Exception) -> str:
    s = str(e).strip() or type(e).__name__
    return s.splitlines()[0]


# --------------------------------------------------------------------------
# explicit rules for ops the eval_shape fallback cannot handle (host-side
# callables, executor-lowered control flow, autodiff)
# --------------------------------------------------------------------------

@register_shape_infer("fill_init")
def _infer_fill_init(op, ins, ctx):
    a = op.attrs
    return {"Out": [_struct(a["shape"], a.get("dtype", "float32"))]}


@register_shape_infer("autodiff_grad")
def _infer_autodiff(op, ins, ctx):
    grads = []
    for p in op.attrs.get("params", []):
        v = ctx.env.get(p, UNKNOWN)
        grads.append(v if isinstance(v, _Unknown)
                     else _struct(v.shape, v.dtype))
    return {"Grads": grads}


def _check_carried(op, ctx, before: Dict[str, Any], after: Dict[str, Any],
                   what: str, site):
    for name, prev in before.items():
        new = after.get(name, prev)
        if isinstance(prev, _Unknown) or isinstance(new, _Unknown):
            continue
        if prev.shape != new.shape or prev.dtype != new.dtype:
            ctx.diags.append(Diagnostic(
                "S003", Severity.ERROR,
                f"{what} var '{name}' changes from "
                f"{prev.shape}:{prev.dtype} to {new.shape}:{new.dtype} "
                "(XLA loop/branch carries must keep shape and dtype)",
                var=name, **site))


def _infer_sub_block(op, ctx, sub_idx, bind: Dict[str, Any], site):
    """Infer a sub-block on a copy of env; returns the sub-env."""
    if not isinstance(sub_idx, int) or not 0 < sub_idx < len(ctx.program.blocks):
        return None
    sub_env = dict(ctx.env)
    sub_env.update(bind)
    infer_block(ctx.program, ctx.program.blocks[sub_idx], sub_env, ctx.diags)
    return sub_env


@register_shape_infer("while")
def _infer_while(op, ins, ctx):
    site = ctx.site
    sub_env = _infer_sub_block(op, ctx, op.attrs.get("sub_block_idx"), {}, site)
    if sub_env is not None:
        _check_carried(op, ctx, ctx.env, sub_env, "while loop-carried", site)
    return {}


@register_shape_infer("conditional_block")
def _infer_cond(op, ins, ctx):
    site = ctx.site
    for key in ("true_block_idx", "false_block_idx"):
        idx = op.attrs.get(key)
        if idx is None:
            continue
        sub_env = _infer_sub_block(op, ctx, idx, {}, site)
        if sub_env is not None:
            _check_carried(op, ctx, ctx.env, sub_env, "branch-carried", site)
    return {}


@register_shape_infer("static_rnn")
def _infer_static_rnn(op, ins, ctx):
    site = ctx.site
    a = op.attrs
    env = ctx.env
    bind: Dict[str, Any] = {}
    T = None
    for outer, step in zip(a.get("outer_inputs", []),
                           a.get("step_in_names", [])):
        v = env.get(outer, UNKNOWN)
        if isinstance(v, _Unknown) or len(v.shape) < 2:
            bind[step] = UNKNOWN
        else:
            T = v.shape[1]
            bind[step] = _struct((v.shape[0],) + v.shape[2:], v.dtype)
    for boot, mem in zip(a.get("boot_mems", []), a.get("mem_names", [])):
        bind[mem] = env.get(boot, UNKNOWN)
    sub_env = _infer_sub_block(op, ctx, a.get("sub_block_idx"), bind, site)
    outs: Dict[str, List[Any]] = {"Out": []}
    if sub_env is None:
        sub_env = {}
    # scan carry invariance: each memory's update must match its boot
    _check_carried(op, ctx,
                   {m: bind.get(m, UNKNOWN) for m in a.get("mem_names", [])},
                   {m: sub_env.get(u, UNKNOWN)
                    for m, u in zip(a.get("mem_names", []),
                                    a.get("mem_update_names", []))},
                   "scan memory", site)
    for name in a.get("step_out_names", []):
        v = sub_env.get(name, UNKNOWN)
        if isinstance(v, _Unknown) or T is None or not v.shape:
            outs["Out"].append(UNKNOWN)
        else:
            outs["Out"].append(_struct((v.shape[0], T) + v.shape[1:], v.dtype))
    # last_mem_outputs are attr-defined extra results (written straight
    # into env here; they are not part of op.outputs)
    for mem, last in zip(a.get("mem_names", []),
                         a.get("last_mem_outputs", [])):
        if last is not None:
            ctx.env[last] = bind.get(mem, UNKNOWN)
    return outs


@register_shape_infer("beam_search_gen")
def _infer_beam(op, ins, ctx):
    # the decode's output layout is owned by ops/beam_search.py; keep the
    # interpreter honest and mark it unknown rather than guessing
    return {"Tokens": [UNKNOWN], "Scores": [UNKNOWN]}


# --------------------------------------------------------------------------
# the interpreter
# --------------------------------------------------------------------------

def infer_block(program, block, env: Dict[str, Any],
                diags: List[Diagnostic]) -> Dict[str, Any]:
    """Infer one block's ops in order over ``env`` (name -> struct|UNKNOWN),
    mutating env with every output.  Recurses into sub-blocks via the
    registered control-flow rules."""
    import jax

    from ..fluid.registry import OpRegistry

    for idx, op in enumerate(block.ops):
        site = dict(block_idx=block.idx, op_idx=idx, op_type=op.type)
        if not OpRegistry.has(op.type):
            continue  # verifier's V002; nothing to infer
        ins: Dict[str, List[Any]] = {}
        missing = False
        for slot, names in op.inputs.items():
            vals = [env.get(n, UNKNOWN) for n in names]
            if any(isinstance(v, _Unknown) for v in vals):
                missing = True
            ins[slot] = vals
        if missing:
            for n in op.output_vars():
                env[n] = UNKNOWN
            continue
        try:
            if ShapeInferRegistry.has(op.type):
                rule = ShapeInferRegistry.get(op.type)
                outs = rule(op, ins, InferContext(program, env, diags, site))
            else:
                compute = OpRegistry.get(op.type)
                outs = jax.eval_shape(lambda i: compute(i, op.attrs), ins)
        except Exception as e:  # abstract evaluation rejected the op
            diags.append(Diagnostic(
                "S001", Severity.ERROR,
                f"shape inference failed: {_first_line(e)}",
                hint="input shapes/dtypes are incompatible with this op's "
                     "contract; fix the producing layer before tracing",
                **site))
            for n in op.output_vars():
                env[n] = UNKNOWN
            continue
        for slot, names in op.outputs.items():
            vals = outs.get(slot) if isinstance(outs, dict) else None
            for i, n in enumerate(names):
                v = vals[i] if vals is not None and i < len(vals) else UNKNOWN
                env[n] = v
                _check_declared(block, n, v, diags, site)
    return env


def _check_declared(block, name, inferred, diags, site):
    """S002: declared desc shape disagrees with the inferred one (concrete
    dims only; -1 dims and rank growth from builders are bookkeeping)."""
    if isinstance(inferred, _Unknown):
        return
    var = block.vars.get(name)
    if var is None or not var.shape:
        return
    decl = tuple(var.shape)
    got = tuple(inferred.shape)
    if len(decl) != len(got):
        return  # builders frequently declare collapsed ranks; not a finding
    for d, g in zip(decl, got):
        if d >= 0 and d != g:
            diags.append(Diagnostic(
                "S002", Severity.WARNING,
                f"var '{name}' declared as {decl} but traces to {got}",
                var=name, **site))
            return


def infer_program_shapes(program, feed_shapes: Optional[Dict[str, Tuple]] = None,
                         diags: Optional[List[Diagnostic]] = None
                         ) -> Tuple[Dict[str, Any], List[Diagnostic]]:
    """Infer the whole program from its global block.

    ``feed_shapes`` — optional ``{name: (shape, dtype)}`` overrides from a
    real feed dict; unfed data vars use placeholder dims.  Returns
    ``(env, diagnostics)``.
    """
    diags = [] if diags is None else diags
    env: Dict[str, Any] = {}
    block = program.blocks[0]
    feed_shapes = feed_shapes or {}
    for name, v in block.vars.items():
        if v.is_data:
            env[name] = _feed_struct(v, feed_shapes)
        elif v.persistable:
            if any(s < 0 for s in v.shape):
                env[name] = UNKNOWN
            else:
                env[name] = _struct(v.shape, v.dtype)
    for name, (shape, dtype) in feed_shapes.items():
        if name not in env:
            env[name] = _struct(shape, dtype)
    infer_block(program, block, env, diags)
    return env, diags
