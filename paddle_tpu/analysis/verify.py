"""Structural verifier over ``Program``/``Block``/``Operator``.

Checks, per block and in op order (codes are stable):

- **V001** read of an undefined variable — def-before-use with correct
  parent-scope lookup: a sub-block sees (a) names defined in an ancestor
  block *at the point its control-flow op appears*, (b) feed/data and
  persistable vars, and (c) names its own earlier ops wrote.  A var declared
  only in a *sibling* branch block is NOT visible.
- **V002** op type not registered in ``OpRegistry``.  Note
  ``Operator.__init__`` already rejects unregistered types at build /
  ``Program.from_dict`` time, so V002 fires for programs whose op types were
  mutated after construction or built through a bypassing code path.
- **V003** duplicate output write: a var written twice within one block with
  no intervening read (the first write is silently lost), or the same var
  listed twice in one op's outputs.
- **V004** sub-block reference invalid: index out of range, pointing at the
  global block / itself, or cyclic (a block that transitively contains
  itself).  **V007** (warning) sub-block parent index inconsistent with the
  block its op lives in.
- **V005** ``while`` condition var never written inside the loop body
  (would loop forever — the executor's trace-time ValueError, caught
  statically).
- **V006** fetch of a variable the program never defines.

The verifier never imports jax and never traces — it is pure desc-level
analysis, safe to run on any host.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic, Severity

# attr keys through which an op references a sub-block
BLOCK_ATTR_KEYS = ("sub_block_idx", "true_block_idx", "false_block_idx")

# attr keys whose (string / list-of-string) values name OUTER vars the
# executor reads from env when lowering the op — they are reads even though
# they do not appear in op.inputs
_ATTR_READ_KEYS = {
    "autodiff_grad": ("loss", "params"),
    "static_rnn": ("boot_mems",),
    "beam_search_gen": ("boot_mems", "static_outer", "embed_param"),
}

# attr keys naming sub-block vars the executor BINDS before tracing the
# sub-block (scan carries / step slices) — they are defined-on-entry there
_ATTR_BIND_KEYS = {
    "static_rnn": ("step_in_names", "mem_names"),
    "beam_search_gen": ("mem_names", "static_in_names", "token_embed_name"),
}

# attr keys naming PARENT vars the op defines beyond op.outputs
_ATTR_DEFINE_KEYS = {
    "static_rnn": ("last_mem_outputs",),
}


def _names(value) -> List[str]:
    """Normalize a str-or-list-of-str attr value to a name list."""
    if value is None:
        return []
    if isinstance(value, str):
        return [value]
    return [n for n in value if isinstance(n, str)]


def _attr_names(op, table) -> List[str]:
    out: List[str] = []
    for key in table.get(op.type, ()):
        out.extend(_names(op.attrs.get(key)))
    return out


def _seed_block_vars(block, defined: Set[str]):
    """Feed slots and persistables are available on block entry (feeds come
    from the caller, persistables from the scope)."""
    for name, v in block.vars.items():
        if v.is_data or v.persistable:
            defined.add(name)


def _transitive_writes(program, block, seen: Optional[Set[int]] = None) -> Set[str]:
    """All var names (transitively) written by a block — mirrors the
    executor's loop-carry derivation (executor._sub_block_written)."""
    seen = set() if seen is None else seen
    if block.idx in seen:
        return set()
    seen.add(block.idx)
    written: Set[str] = set()
    for op in block.ops:
        written.update(op.output_vars())
        written.update(_attr_names(op, _ATTR_DEFINE_KEYS))
        for key in BLOCK_ATTR_KEYS:
            idx = op.attrs.get(key)
            if isinstance(idx, int) and 0 < idx < len(program.blocks):
                written |= _transitive_writes(program, program.blocks[idx], seen)
    return written


def verify_program(program, feed: Iterable[str] = (),
                   fetch: Iterable[str] = (),
                   diags: Optional[List[Diagnostic]] = None) -> List[Diagnostic]:
    """Run every structural check; returns the diagnostic list (never raises).

    ``feed`` — extra var names supplied by the caller at run time (actual
    feed dict keys); data vars are always assumed fed.  ``fetch`` — names the
    caller will fetch (checked to exist).
    """
    diags = [] if diags is None else diags
    blocks = program.blocks
    if not blocks:
        diags.append(Diagnostic("V004", Severity.ERROR,
                                "program has no blocks"))
        return diags
    for b in blocks:
        if b.parent_idx >= 0 and (b.parent_idx >= len(blocks)
                                  or b.parent_idx == b.idx):
            diags.append(Diagnostic(
                "V004", Severity.ERROR,
                f"block {b.idx} has invalid parent_idx {b.parent_idx}",
                block_idx=b.idx))
    root = blocks[0]
    defined: Set[str] = set(feed)
    _seed_block_vars(root, defined)
    _verify_ops(program, root, defined, {}, [], diags, visiting=(0,))
    for name in fetch:
        if name not in defined:
            diags.append(Diagnostic(
                "V006", Severity.ERROR,
                f"fetch of undefined variable '{name}'", block_idx=0,
                var=name,
                hint="fetch vars must be produced by an op, fed, or "
                     "persistable in the global block"))
    return diags


def _verify_ops(program, block, defined: Set[str],
                pending: Dict[str, int],
                outer_pendings: List[Dict[str, int]],
                diags: List[Diagnostic], visiting: Tuple[int, ...]):
    """Walk a block's ops in order.

    ``defined`` — names available at the current point (mutated in place).
    ``pending`` — name -> op idx of a write not yet read (duplicate-write
    detection); reads and sub-block activity clear entries.
    """
    from ..fluid.registry import OpRegistry

    for idx, op in enumerate(block.ops):
        site = dict(block_idx=block.idx, op_idx=idx, op_type=op.type)

        if not OpRegistry.has(op.type):
            diags.append(Diagnostic(
                "V002", Severity.ERROR,
                f"op type '{op.type}' is not registered in OpRegistry",
                hint="register a compute with OpRegistry.register"
                     f"('{op.type}') before building this program", **site))
            # still mark outputs defined so later ops don't cascade V001
            for n in op.output_vars():
                defined.add(n)
            continue

        # ---- reads (op.inputs + env-read attr names) --------------------
        reads = op.input_vars() + _attr_names(op, _ATTR_READ_KEYS)
        for n in reads:
            if n not in defined:
                hint = ("define it in this block or an enclosing one before "
                        "this op; vars declared only in a sibling branch "
                        "block are not in scope")
                diags.append(Diagnostic(
                    "V001", Severity.ERROR,
                    f"op reads undefined variable '{n}'",
                    var=n, hint=hint, **site))
            pending.pop(n, None)
            for p in outer_pendings:
                p.pop(n, None)

        # ---- sub-blocks -------------------------------------------------
        for key in BLOCK_ATTR_KEYS:
            if key not in op.attrs:
                continue
            sub_idx = op.attrs[key]
            if sub_idx is None:
                continue  # e.g. an else-less conditional_block
            if (not isinstance(sub_idx, int) or sub_idx <= 0
                    or sub_idx >= len(program.blocks)):
                diags.append(Diagnostic(
                    "V004", Severity.ERROR,
                    f"attr '{key}'={sub_idx!r} is not a valid sub-block "
                    f"index (program has {len(program.blocks)} blocks; "
                    "the global block cannot be a sub-block)", **site))
                continue
            if sub_idx in visiting:
                diags.append(Diagnostic(
                    "V004", Severity.ERROR,
                    f"attr '{key}'={sub_idx} creates a block cycle "
                    f"(path {' -> '.join(map(str, visiting))} -> {sub_idx})",
                    **site))
                continue
            sub = program.blocks[sub_idx]
            if sub.parent_idx != block.idx:
                diags.append(Diagnostic(
                    "V007", Severity.WARNING,
                    f"sub-block {sub_idx} declares parent {sub.parent_idx} "
                    f"but its op lives in block {block.idx} "
                    "(parent-scope lookup may resolve the wrong vars)",
                    **site))
            sub_defined = set(defined)
            for n in _attr_names(op, _ATTR_BIND_KEYS):
                sub_defined.add(n)
            _seed_block_vars(sub, sub_defined)
            _verify_ops(program, sub, sub_defined, {},
                        outer_pendings + [pending], diags,
                        visiting + (sub_idx,))

        # ---- while: the condition must be updated in the body -----------
        if op.type == "while":
            cond = (op.inputs.get("Condition") or [None])[0]
            sub_idx = op.attrs.get("sub_block_idx")
            if (cond is not None and isinstance(sub_idx, int)
                    and 0 < sub_idx < len(program.blocks)
                    and sub_idx not in visiting):
                body_writes = _transitive_writes(
                    program, program.blocks[sub_idx])
                if cond not in body_writes:
                    diags.append(Diagnostic(
                        "V005", Severity.ERROR,
                        f"while condition '{cond}' is never updated in the "
                        "loop body (would loop forever)",
                        var=cond,
                        hint="write it inside the body, e.g. "
                             "less_than(i, n, cond=cond)", **site))

        # ---- writes -----------------------------------------------------
        seen_out: Set[str] = set()
        for n in op.output_vars():
            if n in seen_out:
                diags.append(Diagnostic(
                    "V003", Severity.ERROR,
                    f"op lists output variable '{n}' twice",
                    var=n, **site))
                continue
            seen_out.add(n)
            if n in pending:
                diags.append(Diagnostic(
                    "V003", Severity.ERROR,
                    f"duplicate write to '{n}': op #{pending[n]} in this "
                    "block already wrote it and no op read it in between "
                    "(the first write is lost)",
                    var=n,
                    hint="write to a fresh var, or read the first result "
                         "before overwriting", **site))
        for n in seen_out:
            defined.add(n)
            pending[n] = idx
            for p in outer_pendings:
                p.pop(n, None)
        for n in _attr_names(op, _ATTR_DEFINE_KEYS):
            defined.add(n)
