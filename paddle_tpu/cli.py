"""Command-line driver — the `paddle` CLI analog.

Reference surface (paddle/scripts/submit_local.sh.in:3-16 + TrainerMain.cpp
job types): train / test / time / version / dump_config / merge_model.

The config file is a Python script (the reference's config style,
config_parser.py executing user configs) that builds a model through the v2
or fluid front end and exposes module-level names:

    cost       — v2 LayerOutput or fluid Variable to minimize
    optimizer  — paddle_tpu.v2.optimizer.* (or fluid optimizer)
    train_reader() / test_reader() — batched reader creators
    feeding    — list of v2 data layers in row order (v2 configs)
    outputs    — optional list of layers to export for inference

Usage: python -m paddle_tpu train --config cfg.py --num_passes 2 --save_dir out
"""

from __future__ import annotations

import argparse
import json
import runpy
import sys
import time
from typing import Any, Dict


def _load_config(path: str) -> Dict[str, Any]:
    from . import fluid
    fluid.reset_default_programs()
    return runpy.run_path(path)


def _make_trainer(cfg):
    from . import v2
    cost = cfg["cost"]
    opt = cfg.get("optimizer") or v2.optimizer.SGD(0.01)
    if not hasattr(opt, "fluid_opt"):
        opt = type("O", (), {"fluid_opt": opt})()
    return v2.SGD(cost, opt)


def cmd_train(args):
    from .trainer import event
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    costs = []

    def handler(e):
        if isinstance(e, event.EndIteration):
            costs.append(e.cost)
            if args.log_period and (e.batch_id + 1) % args.log_period == 0:
                print(f"pass {e.pass_id} batch {e.batch_id} cost {e.cost:.6f}")
        elif isinstance(e, event.EndPass):
            print(f"pass {e.pass_id} done; last cost "
                  f"{costs[-1] if costs else float('nan'):.6f}")
            if args.save_dir:
                import os

                from .trainer.checkpoint import pass_dir
                d = pass_dir(args.save_dir, e.pass_id)
                os.makedirs(d, exist_ok=True)
                with open(os.path.join(d, "params.tar"), "wb") as f:
                    trainer.parameters.to_tar(f)

    trainer.train(cfg["train_reader"], num_passes=args.num_passes,
                  event_handler=handler, feeding=cfg.get("feeding"))
    if args.save_dir and "outputs" in cfg:
        from . import fluid
        fluid.io.export_inference_model(
            args.save_dir + "/inference",
            [dl.var.name for dl in cfg.get("feeding", [])],
            [o.var for o in cfg["outputs"]], trainer.exe)
    return 0


def cmd_test(args):
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    if args.init_model_path:
        with open(args.init_model_path, "rb") as f:
            trainer.parameters.from_tar(f)
    res = trainer.test(cfg.get("test_reader", cfg["train_reader"]),
                       feeding=cfg.get("feeding"))
    print(json.dumps({"cost": res.cost}))
    return 0


def cmd_time(args):
    """--job=time analog (TrainerBenchmark.cpp): steady-state ms/batch."""
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    batches = list(cfg["train_reader"]())[: max(args.iters + args.warmup, 1)]
    from .v2.trainer import _V2Feeder
    feeder = _V2Feeder(cfg["feeding"]) if cfg.get("feeding") else None
    fetch = [cfg["cost"].var]
    i = 0
    for _ in range(args.warmup):
        feed = feeder(batches[i % len(batches)]) if feeder else batches[i % len(batches)]
        trainer.exe.run(feed=feed, fetch_list=fetch)
        i += 1
    t0 = time.perf_counter()
    for _ in range(args.iters):
        feed = feeder(batches[i % len(batches)]) if feeder else batches[i % len(batches)]
        trainer.exe.run(feed=feed, fetch_list=fetch)
        i += 1
    ms = (time.perf_counter() - t0) / args.iters * 1e3
    print(json.dumps({"ms_per_batch": round(ms, 3)}))
    return 0


def cmd_dump_config(args):
    """Print the built Program IR as JSON (dump_config / make_diagram data)."""
    cfg = _load_config(args.config)
    from . import fluid
    print(json.dumps(fluid.default_main_program().to_dict(), indent=2,
                     default=str))
    return 0


def cmd_merge_model(args):
    """Merge a params tar + config into one inference bundle
    (trainer/MergeModel.cpp:29 analog)."""
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    with open(args.model_path, "rb") as f:
        trainer.parameters.from_tar(f)
    from . import fluid
    outs = cfg.get("outputs") or [cfg["cost"]]
    fluid.io.export_inference_model(
        args.output_dir, [dl.var.name for dl in cfg.get("feeding", [])],
        [o.var for o in outs], trainer.exe)
    print(f"merged model written to {args.output_dir}")
    return 0


def cmd_version(args):
    from . import __version__
    import jax
    print(f"paddle_tpu {__version__} (jax {jax.__version__}, "
          f"backend {jax.default_backend()})")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu")
    sub = p.add_subparsers(dest="job", required=True)

    def common(sp):
        sp.add_argument("--config", required=True)

    t = sub.add_parser("train")
    common(t)
    t.add_argument("--num_passes", type=int, default=1)
    t.add_argument("--save_dir", default=None)
    t.add_argument("--log_period", type=int, default=0)
    t.set_defaults(fn=cmd_train)

    te = sub.add_parser("test")
    common(te)
    te.add_argument("--init_model_path", default=None)
    te.set_defaults(fn=cmd_test)

    tm = sub.add_parser("time")
    common(tm)
    tm.add_argument("--warmup", type=int, default=2)
    tm.add_argument("--iters", type=int, default=10)
    tm.set_defaults(fn=cmd_time)

    dc = sub.add_parser("dump_config")
    common(dc)
    dc.set_defaults(fn=cmd_dump_config)

    mm = sub.add_parser("merge_model")
    common(mm)
    mm.add_argument("--model_path", required=True)
    mm.add_argument("--output_dir", required=True)
    mm.set_defaults(fn=cmd_merge_model)

    v = sub.add_parser("version")
    v.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
