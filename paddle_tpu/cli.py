"""Command-line driver — the `paddle` CLI analog.

Reference surface (paddle/scripts/submit_local.sh.in:3-16 + TrainerMain.cpp
job types): train / test / time / version / dump_config / merge_model — plus
``lint`` (static Program verification, paddle_tpu.analysis).

The config file is a Python script (the reference's config style,
config_parser.py executing user configs) that builds a model through the v2
or fluid front end and exposes module-level names:

    cost       — v2 LayerOutput or fluid Variable to minimize
    optimizer  — paddle_tpu.v2.optimizer.* (or fluid optimizer)
    train_reader() / test_reader() — batched reader creators
    feeding    — list of v2 data layers in row order (v2 configs)
    outputs    — optional list of layers to export for inference

Usage: python -m paddle_tpu train --config cfg.py --num_passes 2 --save_dir out
"""

from __future__ import annotations

import argparse
import json
import runpy
import sys
import time
from typing import Any, Dict


def _load_config(path: str) -> Dict[str, Any]:
    from . import fluid
    fluid.reset_default_programs()
    return runpy.run_path(path)


def _make_trainer(cfg):
    from . import v2
    cost = cfg["cost"]
    opt = cfg.get("optimizer") or v2.optimizer.SGD(0.01)
    if not hasattr(opt, "fluid_opt"):
        opt = type("O", (), {"fluid_opt": opt})()
    return v2.SGD(cost, opt)


def _parse_hostport(addr, default_host="127.0.0.1", default_port=0):
    """host:port with the `obs serve --master` validation discipline:
    bracket-stripped IPv6 literals, and None on anything malformed so the
    caller answers with a clear exit-2 instead of a ValueError traceback.
    Returns (host, port) or None."""
    if not addr:
        return default_host, default_port
    host, _, port = addr.rpartition(":")
    try:
        return (host.strip("[]") or default_host), int(port)
    except ValueError:
        return None


def _cmd_train_elastic(args):
    """``train --elastic master|worker`` — the elastic data-parallel mode
    (docs/design/elastic.md). The config script defines
    ``elastic_workload()`` returning ``{"loss_fn", "params", "optimizer",
    "batches"}`` (params/batches as host arrays; workers only need
    loss_fn)."""
    import runpy
    import signal
    import threading

    from .trainer.elastic import ElasticMaster, ElasticWorker
    cfg = runpy.run_path(args.config)
    wl_fn = cfg.get("elastic_workload")
    if not callable(wl_fn):
        print(f"error: --elastic needs the config to define "
              f"elastic_workload(); {args.config} does not", file=sys.stderr)
        return 2
    wl = wl_fn()
    parsed = _parse_hostport(args.master_addr)
    if parsed is None:
        print(f"error: --master_addr must be host:port, got "
              f"{args.master_addr!r}", file=sys.stderr)
        return 2
    host, port = parsed
    if args.elastic == "worker":
        if not args.master_addr or not port:
            print("error: --elastic worker needs --master_addr HOST:PORT",
                  file=sys.stderr)
            return 2
        worker = ElasticWorker(wl["loss_fn"], (host, port),
                               worker=args.worker_id)
        # drain-at-barrier (ISSUE 18): the fleet actor's subprocess
        # backend drains with SIGTERM — finish the in-flight shard, push
        # its gradient, leave membership, then exit, so a drained worker
        # never costs the step a discarded shard
        stop = threading.Event()
        try:
            signal.signal(signal.SIGTERM, lambda *_: stop.set())
            signal.signal(signal.SIGINT, lambda *_: stop.set())
        except ValueError:
            pass     # not the main thread (embedded runs): no handler
        summary = worker.run(stop=stop)
        print(f"elastic worker {summary['worker']} served "
              f"{summary['shards']} shard(s); job done: {summary['done']}")
        return 0 if summary["done"] else 2
    em = ElasticMaster(wl["loss_fn"], wl["optimizer"], host=host, port=port,
                       shards_per_step=args.shards_per_step,
                       min_workers=args.min_workers, ttl=args.heartbeat_ttl,
                       snapshot_dir=args.save_dir or None)
    em.start()
    completed = False
    try:
        print(f"ELASTIC MASTER {em.address[0]} {em.address[1]}", flush=True)
        params, _, loss = em.fit(wl["batches"], wl.get("params"),
                                 num_passes=args.num_passes)
        completed = True
        print(f"elastic training done: {args.num_passes} pass(es), "
              f"final loss {loss:.6f}, membership epoch "
              f"{em.membership.epoch}")
        if args.save_dir:
            print(f"state checkpoints under {args.save_dir}")
    finally:
        # drain only after a COMPLETED run: workers leave once they
        # observe the done signal, which a failed fit never sets — the
        # error path must surface the traceback now, not after 10s of
        # waiting for departures that cannot happen
        em.stop(drain_s=10.0 if completed else 0.0)
    return 0


def cmd_train(args):
    from .trainer import event
    if getattr(args, "elastic", None):
        return _cmd_train_elastic(args)
    if getattr(args, "compile_cache", None):
        # persistent XLA compile cache BEFORE the config builds/compiles
        # anything: a preemption-resume of this same command re-loads its
        # executables from disk instead of re-paying the compiles
        from . import enable_compile_cache
        enable_compile_cache(args.compile_cache)
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    costs = []

    def handler(e):
        if isinstance(e, event.EndIteration):
            costs.append(e.cost)
            if args.log_period and (e.batch_id + 1) % args.log_period == 0:
                print(f"pass {e.pass_id} batch {e.batch_id} cost {e.cost:.6f}")
        elif isinstance(e, event.EndPass):
            print(f"pass {e.pass_id} done; last cost "
                  f"{costs[-1] if costs else float('nan'):.6f}")
            if args.save_dir:
                import io
                import json as _json

                from .trainer.checkpoint import (FORMAT_VERSION,
                                                 publish_members)
                # the same tmp-dir + CRC manifest + atomic-rename protocol
                # as save_checkpoint: a crash mid-dump leaves no dir that
                # latest_pass would mistake for a checkpoint. state.json
                # rides along so load_checkpoint can read the dir, not
                # just verify it
                buf = io.BytesIO()
                trainer.parameters.to_tar(buf)
                state = _json.dumps({"pass_id": e.pass_id,
                                     "version": FORMAT_VERSION,
                                     "pass_complete": True}).encode()
                publish_members(args.save_dir, e.pass_id,
                                [("params.tar", buf.getvalue()),
                                 ("state.json", state)])

    train_reader = cfg["train_reader"]
    srv = None
    obs_session = None
    flight = None
    pusher = None
    if getattr(args, "obs_out", None):
        from . import obs as _obs
        obs_session = _obs.ObsSession().install()
        # crash flight recorder: until the clean save below runs, any
        # death mode (SIGTERM, injected fault, uncaught exception) leaves
        # the span ring + counter deltas at --obs_out for post-mortem
        flight = _obs.FlightRecorder(obs_session, args.obs_out).arm()
    if getattr(args, "local_master", False):
        # One-binary bring-up (TrainerMain.cpp:32-49 --start_pserver analog):
        # self-host the ENTIRE data-dispatch cluster in this process — the
        # native task master + its TCP service on a background thread, the
        # trainer as its first consumer. Same code paths as the real
        # multi-host deployment (chunk dump, get_task RPC, timeout
        # re-dispatch), zero extra processes: the local dev mode.
        import os
        import tempfile

        from .data.chunks import cloud_reader, dump_to_chunks
        from .runtime.master_service import MasterClient, MasterServer

        chunk_dir = (os.path.join(args.save_dir, "chunks") if args.save_dir
                     else tempfile.mkdtemp(prefix="paddle_tpu_chunks_"))
        os.makedirs(chunk_dir, exist_ok=True)
        paths = dump_to_chunks(train_reader, chunk_dir,
                               samples_per_chunk=args.samples_per_chunk)
        srv = MasterServer().start()
        client = MasterClient(*srv.address)
        client.set_dataset(paths)
        print(f"local master: {len(paths)} chunks on "
              f"{srv.address[0]}:{srv.address[1]}")
        train_reader = cloud_reader(client, new_pass_at_end=True)
        if obs_session is not None:
            # exercise the real cluster-telemetry path even in the one-
            # binary mode: this consumer obs_pushes its snapshots to the
            # in-process master exactly as a remote worker would. Own
            # fail-fast client: _call holds a per-client lock across its
            # retry budget, so sharing the data-plane client would let a
            # slow push stall the trainer's get_task behind it
            from .obs.aggregate import ObsPusher, telemetry_client
            pusher = ObsPusher(telemetry_client(*srv.address),
                               worker=f"local-{os.getpid()}",
                               interval=2.0).start()
    try:
        trainer.train(train_reader, num_passes=args.num_passes,
                      event_handler=handler, feeding=cfg.get("feeding"))
    finally:
        # dump FIRST: a failed run is exactly the one whose telemetry the
        # user asked for, and a server-teardown error must not discard it
        if pusher is not None:
            pusher.stop()
            pusher.client.close()
        if obs_session is not None:
            if flight is not None:
                # clean(ish) exit: the full session dump below supersedes
                # the ring; disarm so atexit can't overwrite it later
                flight.disarm()
            obs_session.uninstall()
            try:
                obs_session.save(args.obs_out)
            except Exception as e:
                # telemetry loss must not mask the training outcome
                print(f"warning: could not write obs dump {args.obs_out}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
            else:
                print(f"observability dump written to {args.obs_out} "
                      f"(inspect: paddle_tpu obs summary --input "
                      f"{args.obs_out})")
        if srv is not None:
            srv.stop()
    if args.save_dir and "outputs" in cfg:
        from . import fluid
        fluid.io.export_inference_model(
            args.save_dir + "/inference",
            [dl.var.name for dl in cfg.get("feeding", [])],
            [o.var for o in cfg["outputs"]], trainer.exe)
    return 0


def cmd_test(args):
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    if args.init_model_path:
        with open(args.init_model_path, "rb") as f:
            trainer.parameters.from_tar(f)
    res = trainer.test(cfg.get("test_reader", cfg["train_reader"]),
                       feeding=cfg.get("feeding"))
    print(json.dumps({"cost": res.cost}))
    return 0


def _config_workload(config_path, n_batches):
    """The shared --config training-step setup ``time`` and ``profile``
    drive: load the config, build its trainer, materialize up to
    ``n_batches`` reader batches, and close over feeder + fetch list.
    Returns ``(one, batches)`` where ``one(i)`` runs step *i* (batches
    recycle)."""
    cfg = _load_config(config_path)
    trainer = _make_trainer(cfg)
    batches = list(cfg["train_reader"]())[: max(n_batches, 1)]
    from .v2.trainer import _V2Feeder
    feeder = _V2Feeder(cfg["feeding"]) if cfg.get("feeding") else None
    fetch = [cfg["cost"].var]

    def one(i):
        rows = batches[i % len(batches)]
        trainer.exe.run(feed=feeder(rows) if feeder else rows,
                        fetch_list=fetch)
    return one, batches


def cmd_time(args):
    """--job=time analog (TrainerBenchmark.cpp): steady-state ms/batch."""
    one, _ = _config_workload(args.config, args.iters + args.warmup)
    i = 0
    for _ in range(args.warmup):
        one(i)
        i += 1
    t0 = time.perf_counter()
    for _ in range(args.iters):
        one(i)
        i += 1
    ms = (time.perf_counter() - t0) / args.iters * 1e3
    print(json.dumps({"ms_per_batch": round(ms, 3)}))
    return 0


def cmd_profile(args):
    """``paddle_tpu profile`` — run N profiled steps of a workload under
    ``jax.profiler.trace`` and print the top-k per-op device-time report,
    HLO ops attributed back to the analysis plane's ``block B, op #I
    (type)`` sites (the fluid Executor's named-scope stamps, inverted by
    obs/xplane.py).

    Workloads: ``--config cfg.py`` profiles the config's training step
    (the ``time`` command's loop, traced); ``--decode B,PROMPT,NEW``
    profiles a fused-decode serve workload on a randomly-initialized
    TransformerLM built from the model flags + ``--seed``.

    Warmup steps run before the trace so compiles stay out of the
    profile. The raw ``.xplane.pb`` path prints at the end — feed it to
    ``paddle_tpu obs export --xplane`` to merge the device lanes into a
    host-span Perfetto timeline.
    """
    import glob
    import os
    import tempfile

    import jax

    if not args.config and not args.decode:
        print("profile: pass --config cfg.py or --decode B,PROMPT,NEW",
              file=sys.stderr)
        return 2
    if args.config:
        one, batches = _config_workload(args.config,
                                        args.steps + args.warmup)
        if not batches:
            print(f"profile: {args.config!r} train_reader yielded no "
                  "batches — nothing to profile", file=sys.stderr)
            return 2
    else:
        try:
            b, prompt_len, new = (int(x) for x in args.decode.split(","))
        except ValueError:
            print(f"profile: --decode must be B,PROMPT,NEW integers, got "
                  f"{args.decode!r}", file=sys.stderr)
            return 2
        from .models import TransformerLM
        model = TransformerLM(args.vocab, d_model=args.d_model,
                              n_heads=args.n_heads, n_layers=args.n_layers,
                              max_len=args.max_len)
        params = model.init(jax.random.PRNGKey(args.seed))
        prompt = jax.random.randint(jax.random.PRNGKey(args.seed + 1),
                                    (b, prompt_len), 0, args.vocab)

        def one(i):
            model.generate_fused(params, prompt, new,
                                 kv_dtype=args.kv_dtype)

    for i in range(args.warmup):          # compiles stay out of the trace
        one(i)
    out_dir = args.trace_dir or tempfile.mkdtemp(prefix="paddle_tpu_profile_")
    with jax.profiler.trace(out_dir):
        for j in range(args.steps):
            one(args.warmup + j)
    pbs = sorted(glob.glob(os.path.join(out_dir, "**", "*.xplane.pb"),
                           recursive=True), key=os.path.getmtime)
    if not pbs:
        print(f"profile: profiler wrote no .xplane.pb under {out_dir}",
              file=sys.stderr)
        return 2
    from .obs import xplane as _xp
    space = _xp.read_xspace(pbs[-1])
    print(_xp.top_ops_report(space, topk=args.topk, steps=args.steps))
    print(f"\ntrace: {pbs[-1]}")
    print("merge: paddle_tpu obs export --format=chrome "
          f"--xplane {pbs[-1]} [--input obs.jsonl] --output trace.json")
    return 0


def cmd_dump_config(args):
    """Print the built Program IR as JSON (dump_config / make_diagram data)."""
    cfg = _load_config(args.config)
    from . import fluid
    print(json.dumps(fluid.default_main_program().to_dict(), indent=2,
                     default=str))
    return 0


def cmd_lint(args):
    """Static verification + lint of a config's Program IR — rejects
    malformed programs (undefined vars, unregistered ops, duplicate writes,
    broken sub-block scoping, shape mismatches) with precise diagnostics
    BEFORE any trace/compile, and reports the advisory lint catalogue
    (dead ops, unused vars, trace-safety, sharding consistency).

    Exit-code contract (stable, scripts may rely on it):
      0 — clean: no finding at or above the --fail-on threshold
      1 — findings at or above the threshold (or invalid bench rows)
      2 — usage error: missing/broken config or unreadable inputs

    ``--format=json`` emits the stable machine schema on a pure-JSON
    stdout: ``{"version": 1, "findings": [{code, severity, message,
    hint, explain, site: {program, block, block_path, op, op_type,
    var}}], "summary": {errors, warnings, info, total}}`` (human
    summary goes to stderr).  The legacy ``--json`` flat list of
    Diagnostic dicts is kept for old pipelines.  ``--explain``
    annotates each finding's variable with its def-use chain from the
    dataflow plane (where it is defined, redefined, and last read).

    ``--bench-rows FILE...`` additionally (or, without --config, ONLY)
    validates saved bench rows — JSON or JSONL of bench.py output lines —
    against the bench-row schema (analysis/bench_schema.py: required keys
    per row, roofline columns per metric family), so a benchmark that
    drops a column fails in CI instead of silently thinning the trend
    data."""
    from . import analysis, fluid
    if args.bench_rows and args.config is None:
        rc = _lint_bench_rows(args.bench_rows,
                              as_json=args.json or
                              getattr(args, "format", "text") == "json")
        if getattr(args, "autotune_cache", None):
            rc = max(rc, _lint_autotune_only(args))
        return rc
    if args.config is None and getattr(args, "autotune_cache", None):
        # autotune staleness can lint standalone — CI checks the cache
        # file without needing a model config on hand
        return _lint_autotune_only(args)
    if args.config is None:
        print("lint: --config is required (or pass --bench-rows and/or "
              "--autotune-cache alone)", file=sys.stderr)
        return 2
    try:
        cfg = _load_config(args.config)
    except Exception as e:
        print(f"lint: cannot load config {args.config!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    # liveness roots: the config's cost + declared outputs are what a
    # trainer/exporter would fetch
    fetch = []
    for key in ("cost",):
        if key in cfg:
            v = cfg[key]
            fetch.append(v.var.name if hasattr(v, "var") else v.name)
    for o in cfg.get("outputs") or []:
        fetch.append(o.var.name if hasattr(o, "var") else o.name)
    threshold = {"error": analysis.Severity.ERROR,
                 "warning": analysis.Severity.WARNING,
                 "info": analysis.Severity.INFO}[args.fail_on]
    mesh_axes = args.mesh_axes.split(",") if args.mesh_axes else None
    all_diags = []
    for label, prog in (("main", fluid.default_main_program()),
                        ("startup", fluid.default_startup_program())):
        prog_fetch = fetch if label == "main" else []
        diags = analysis.analyze_program(prog, fetch=prog_fetch,
                                         mesh_axes=mesh_axes)
        for d in diags:
            d.program = label
        if getattr(args, "explain", False) and any(d.var for d in diags):
            # --explain: cite each flagged var's def-use chain so the
            # reader sees WHY (where defined/redefined/last read), not
            # just WHERE.  Dataflow may legitimately fail on programs
            # with structural errors — the findings still stand alone.
            try:
                df = analysis.analyze_dataflow(prog, fetch=prog_fetch)
                for d in diags:
                    if d.var:
                        d.explain = analysis.explain_var(df, d.var)
            except Exception:
                pass
        all_diags.extend(diags)
    # L005: the obs metric catalogue is part of the lint surface — a PR
    # adding an off-contract metric name fails here, not on a dashboard
    from . import obs as _obs
    for d in analysis.lint_metric_names(_obs.CATALOGUE):
        d.program = "obs"
        all_diags.append(d)
    # L007: catalogue drift — emit sites and catalogue.py must agree in
    # both directions (an undeclared emit or an orphaned entry fails CI)
    for d in analysis.lint_catalogue_drift():
        d.program = "obs"
        all_diags.append(d)
    # L008: autotune-cache staleness — stale entries silently fall back
    # to heuristics at consult time; the lint is where that surfaces
    for d in analysis.lint_autotune_cache(args.autotune_cache):
        d.program = "autotune"
        all_diags.append(d)
    # L009: the shipped alert rules must reference catalogued metrics —
    # a rule naming a typo'd metric silently never fires
    for d in analysis.lint_alert_rules():
        d.program = "obs"
        all_diags.append(d)
    n_err = len(analysis.errors(all_diags))
    n_warn = sum(1 for d in all_diags
                 if d.severity == analysis.Severity.WARNING)
    summary = (f"lint: {n_err} error(s), {n_warn} warning(s), "
               f"{len(all_diags) - n_err - n_warn} info over "
               f"{sum(len(b.ops) for b in fluid.default_main_program().blocks)} "
               "main-program op(s)")
    as_json = args.json or getattr(args, "format", "text") == "json"
    if getattr(args, "format", "text") == "json":
        # the STABLE machine schema (version-gated; see docstring) —
        # stdout stays pure JSON so `lint --format=json | jq` works
        print(json.dumps(_lint_json_payload(all_diags, n_err, n_warn),
                         indent=1, sort_keys=True))
        print(summary, file=sys.stderr)
    elif args.json:
        # legacy flat list of Diagnostic dicts, kept verbatim for old
        # pipelines; new tooling should use --format=json
        print(json.dumps([d.to_dict() for d in all_diags], indent=1))
        print(summary, file=sys.stderr)
    else:
        if all_diags:
            print(analysis.format_diagnostics(all_diags))
        print(summary)
    failed = any(d.severity >= threshold for d in all_diags)
    if args.bench_rows:
        # under either json mode, bench-row findings go to STDERR so
        # stdout stays the pure diagnostics JSON (`| jq` contract)
        rc = _lint_bench_rows(args.bench_rows,
                              stream=sys.stderr if as_json
                              else sys.stdout)
        failed = failed or rc != 0
    return 1 if failed else 0


def _lint_json_payload(diags, n_err: int, n_warn: int) -> dict:
    """The ``lint --format=json`` schema.  STABLE: additions only, and a
    shape change bumps ``version``.  Every finding has every key (null
    when absent) so consumers can index without guards."""
    return {
        "version": 1,
        "findings": [{
            "code": d.code,
            "severity": str(d.severity),
            "message": d.message,
            "hint": d.hint,
            "explain": d.explain,
            "site": {
                "program": d.program,
                "block": d.block_idx,
                "block_path": d.block_path,
                "op": d.op_idx,
                "op_type": d.op_type,
                "var": d.var,
            },
        } for d in diags],
        "summary": {"errors": n_err, "warnings": n_warn,
                    "info": len(diags) - n_err - n_warn,
                    "total": len(diags)},
    }


def _lint_autotune_only(args) -> int:
    """The config-less `lint --autotune-cache FILE` path: L008 findings
    only. 0 clean, 1 findings at or above --fail-on."""
    from . import analysis
    diags = analysis.lint_autotune_cache(args.autotune_cache)
    threshold = {"error": analysis.Severity.ERROR,
                 "warning": analysis.Severity.WARNING,
                 "info": analysis.Severity.INFO}[args.fail_on]
    if args.json:
        print(json.dumps([d.to_dict() for d in diags], indent=1))
    elif diags:
        print(analysis.format_diagnostics(diags))
    print(f"lint: autotune cache — {len(diags)} finding(s)",
          file=sys.stderr if args.json else sys.stdout)
    return 1 if any(d.severity >= threshold for d in diags) else 0


def cmd_tune(args):
    """Measured autotuning (ROADMAP item 3): enumerate candidate plans per
    (kernel, shape family, device_kind), measure each on the CURRENT
    backend through the roofline-plane timing discipline (warmup outside
    the window, best-of-reps, methodology="measured"), and persist
    winners in the versioned autotune cache the routing entries consult
    (ops/rnn.py fused plans, ops/pallas_kernels.py decode routing,
    serving paged block size). Off-TPU the sweep runs the same kernels
    through the Pallas interpreter at proxy dims — the whole loop is
    CI-exercisable; an on-chip run only changes the numbers.

    ``--check`` is the CI smoke: a seconds-long sweep into --cache (or a
    temp file), then proof the loop closes — the written entries reload
    and the consult functions resolve them. Exit 0 healthy, 1 broken."""
    import os
    import tempfile

    from . import tune
    spaces = (tuple(s for s in args.spaces.split(",") if s)
              if args.spaces else None)
    profile = args.profile
    cache_path = args.cache
    if args.check:
        if args.dry_run:
            # --check's whole point is proving the written cache reloads
            # and consults; with nothing written there is nothing to check
            print("tune: --check writes a cache to verify the loop; drop "
                  "--dry-run (or point --cache at a scratch file)",
                  file=sys.stderr)
            return 2
        profile = profile or "smoke"
        if cache_path is None:
            cache_path = os.path.join(tempfile.mkdtemp(prefix="pt_tune_"),
                                      "autotune.json")
    try:
        report = tune.run_tune(spaces=spaces, profile=profile,
                               cache_path=cache_path, reps=args.reps,
                               save=not args.dry_run,
                               from_ledger=args.from_ledger,
                               ledger_topk=args.ledger_topk)
    except (OSError, ValueError, KeyError) as e:
        print(f"tune: {e}", file=sys.stderr)
        return 2
    if not args.json and report.get("ledger"):
        led = report["ledger"]
        names = [s["op"] for s in led["sites"] if s.get("space")]
        print(f"tune: ledger {led['path']}: top-{led['topk']} sites "
              f"implicate spaces {led['seeded_spaces'] or 'none'} "
              f"(hot ops: {names[:4] or 'no matches'}); sweeping "
              f"{led['swept_spaces']}")
    if args.json:
        print(json.dumps(report, indent=1))
    elif args.markdown:
        print(tune.results_markdown(report))
    else:
        for r in report["results"]:
            if r.get("plan") is None and "skipped" in r:
                print(f"tune: {r['space']}/{r['kernel']} {r['family']}: "
                      f"{r['skipped']}")
                continue
            extra = ""
            if r.get("speedup") is not None:
                extra = (f"  ({r['tuned_ms']} ms vs heuristic "
                         f"{r['heuristic_ms']} ms, {r['speedup']}x)")
            print(f"tune: {r['space']}/{r['kernel']} {r['family']}: "
                  f"plan {r['plan']}{extra}")
        print(f"tune: device_kind={report['device_kind']} "
              f"backend={report['backend']} profile={report['profile']}"
              + (f" -> {report['cache_path']}" if report["cache_path"]
                 else " (dry run, nothing written)"))
    if not args.check:
        return 0
    # --check: prove the loop closes — reload the file, then consult it
    # through the SAME entry points the routers use
    problems = []
    path = report["cache_path"]
    try:
        cache = tune.load_cache(path)
    except (OSError, ValueError) as e:
        print(f"tune: --check FAILED: written cache does not reload: {e}",
              file=sys.stderr)
        return 1
    prev_env = os.environ.get(tune.CACHE_ENV)
    os.environ[tune.CACHE_ENV] = path
    tune.reset()
    try:
        for r in report["results"]:
            if r.get("plan") is None and "skipped" in r:
                continue
            if cache.get(r["space"], r["kernel"], report["device_kind"],
                         r["family"]) is None:
                problems.append(f"{r['space']}/{r['family']}: entry "
                                "missing after reload")
            if r["space"] == "fused_rnn":
                fam = next(
                    f for f in tune.PROFILES[report["profile"]]
                    ["fused_families"]
                    if f["kernel"] == r["kernel"]
                    and tune.fused_family(gates=f["gates"], T=f["T"],
                                          H=f["H"], batch=f["batch"])
                    == r["family"])
                got = tune.fused_plan(
                    r["kernel"], T=fam["T"], H=fam["H"],
                    gates=fam["gates"],
                    seq_h_units=fam.get("seq_h_units",
                                        fam["gates"] + 1),
                    batch=fam["batch"])
                if got != tuple(r["plan"]):
                    problems.append(f"fused_rnn/{r['family']}: consult "
                                    f"returned {got}, tuned {r['plan']}")
            elif r["space"] == "decode_route":
                if tune.decode_kernel_min_len() is tune.MISS:
                    problems.append("decode_route: consult missed the "
                                    "tuned entry")
            elif r["space"] == "page_block":
                bs = r["plan"]["page_block"]
                if tune.page_block(bs * 8, bs * 4) != bs:
                    problems.append("page_block: consult missed the "
                                    "tuned entry")
            elif r["space"] == "bucket_grid":
                got = tune.bucket_grid(r["family"])
                if got != tuple(r["plan"]["buckets"]):
                    problems.append(f"bucket_grid/{r['family']}: consult "
                                    f"returned {got}, tuned "
                                    f"{r['plan']['buckets']}")
        # fusion: rebuild the proxy program the sweep measured and prove
        # plan_for resolves every persisted family through the full
        # consult chain (cert re-validation included) — a winner must
        # activate, a measured loser must refuse with measured_slower
        fusion_rows = [r for r in report["results"]
                       if r["space"] == "fusion" and r.get("plan")]
        if fusion_rows:
            from .tune import fusion as _fusion
            fcfg = tune.PROFILES[report["profile"]]["fusion"]
            main, _startup, feed, fetch = _fusion.build_proxy_program(
                batch=fcfg["batch"], width=fcfg["width"],
                depth=fcfg["depth"])
            plan = _fusion.plan_for(
                main, {k: v.shape for k, v in feed.items()},
                fetch=fetch, feed=list(feed))
            refused = dict(plan.rejected)
            for r in fusion_rows:
                fam = r["family"]
                if r["plan"]["fuse"]:
                    if fam not in plan.families:
                        problems.append(
                            f"fusion/{fam}: measured winner did not "
                            f"activate (rejected: "
                            f"{refused.get(fam, 'missing')})")
                elif refused.get(fam) != "measured_slower":
                    problems.append(
                        f"fusion/{fam}: measured loser should refuse "
                        f"with measured_slower, got "
                        f"{refused.get(fam, 'activated')}")
        if tune.plan_source() != "tuned":
            problems.append("plan_source() != 'tuned' with a fresh cache")
    finally:
        if prev_env is None:
            os.environ.pop(tune.CACHE_ENV, None)
        else:
            os.environ[tune.CACHE_ENV] = prev_env
        tune.reset()
    if problems:
        for p in problems:
            print(f"tune: --check FAILED: {p}", file=sys.stderr)
        return 1
    print(f"tune: --check OK ({len(report['results'])} plan-space "
          f"sweeps measured, persisted, reloaded, and consulted)")
    return 0


def _lint_bench_rows(paths, as_json: bool = False, stream=None) -> int:
    """Validate bench-row files (JSON array/object or JSONL) against the
    bench-row schema; 0 clean, 1 findings, 2 unreadable input.
    ``as_json`` (the bench-rows-only ``--json`` path) emits the findings
    as a JSON array on stdout instead of text lines."""
    from .analysis.bench_schema import validate_row
    stream = stream if stream is not None else sys.stdout
    findings = []

    def emit(path, ln, name, problem):
        findings.append({"code": "B001", "path": path, "line": ln,
                         "metric": name, "message": problem})
        if not as_json:
            print(f"{path}:{ln}: B001 bench-row-schema: {name}: {problem}",
                  file=stream)

    n_rows = n_bad = 0
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            print(f"lint: cannot read bench rows {path!r}: {e}",
                  file=sys.stderr)
            return 2
        rows = []
        try:
            data = json.loads(text)
            if isinstance(data, dict) and "metric" not in data \
                    and isinstance(data.get("tail"), str):
                # a driver record (BENCH_r0x.json): the rows live as JSONL
                # inside its "tail" field
                text = data["tail"]
                raise ValueError("driver record: parse tail as JSONL")
            rows = data if isinstance(data, list) else [data]
        except ValueError:
            for ln, line in enumerate(text.splitlines(), 1):
                line = line.strip()
                if not line.startswith("{"):
                    continue      # log noise / truncated tail heads
                try:
                    rows.append((ln, json.loads(line)))
                except ValueError as e:
                    emit(path, ln, "<no metric>", f"not valid JSON: {e}")
                    n_bad += 1
        rows = [r if isinstance(r, tuple) else (i + 1, r)
                for i, r in enumerate(rows)]
        for ln, row in rows:
            n_rows += 1
            for problem in validate_row(row):
                name = (row.get("metric", "<no metric>")
                        if isinstance(row, dict) else "<not a dict>")
                emit(path, ln, name, problem)
                n_bad += 1
    if as_json:
        print(json.dumps(findings, indent=1))
        print(f"lint: bench rows — {n_bad} problem(s) over {n_rows} "
              "row(s)", file=sys.stderr)
    else:
        print(f"lint: bench rows — {n_bad} problem(s) over {n_rows} "
              "row(s)", file=stream)
    return 1 if n_bad else 0


def cmd_merge_model(args):
    """Merge a params tar + config into one inference bundle
    (trainer/MergeModel.cpp:29 analog)."""
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    with open(args.model_path, "rb") as f:
        trainer.parameters.from_tar(f)
    from . import fluid
    outs = cfg.get("outputs") or [cfg["cost"]]
    fluid.io.export_inference_model(
        args.output_dir, [dl.var.name for dl in cfg.get("feeding", [])],
        [o.var for o in outs], trainer.exe)
    print(f"merged model written to {args.output_dir}")
    return 0


def cmd_checkgrad(args):
    """--job=checkgrad (TrainerMain.cpp:54 / Trainer::checkGradient): compare
    the program's autodiff gradients against central differences on sampled
    parameter entries, through the executor (LayerGradUtil semantics)."""
    import numpy as np

    from . import fluid
    cfg = _load_config(args.config)
    trainer = _make_trainer(cfg)
    feeder = None
    if cfg.get("feeding"):
        from .v2.trainer import _V2Feeder
        feeder = _V2Feeder(cfg["feeding"])
    rows = next(iter(cfg["train_reader"]()))
    feed = feeder(rows) if feeder else rows
    exe = trainer.exe
    prog = fluid.default_main_program()
    cost_name = cfg["cost"].var.name
    params = [v.name for v in prog.global_block().all_parameters()]
    # pruned programs: running the full program would fire the optimizer
    # update ops and mutate params between evaluations. Stochastic ops key
    # off the implicit __step__ feed — pin it so every evaluation sees the
    # SAME dropout masks / negative samples.
    feed = dict(feed)
    feed["__step__"] = 0
    cost_prog = prog.prune([cost_name])
    grad_names = [p + "@GRAD" for p in params]
    grad_prog = prog.prune(grad_names)
    all_grads = exe.run(grad_prog, feed=feed, fetch_list=grad_names)
    rs = np.random.RandomState(0)
    eps = args.eps
    worst = 0.0
    ok = True
    for pname, grad in zip(params, all_grads):
        grad = np.asarray(grad)
        base = np.asarray(exe.scope.get(pname)).copy()
        flat = base.reshape(-1)
        for idx in rs.choice(flat.size,
                             size=min(args.checks_per_param, flat.size),
                             replace=False):
            orig = flat[idx]
            vals = {}
            for sign in (+1, -1):
                flat[idx] = orig + sign * eps
                exe.scope.set(pname, base.reshape(base.shape))
                vals[sign], = exe.run(cost_prog, feed=feed,
                                      fetch_list=[cost_name])
            flat[idx] = orig
            exe.scope.set(pname, base.reshape(base.shape))
            numeric = (float(vals[+1]) - float(vals[-1])) / (2 * eps)
            analytic = float(grad.reshape(-1)[idx])
            denom = max(abs(numeric), abs(analytic), 1e-6)
            rel = abs(numeric - analytic) / denom
            worst = max(worst, rel)
            if rel > args.rtol:
                print(f"MISMATCH {pname}[{idx}]: numeric {numeric:.6g} "
                      f"analytic {analytic:.6g} rel {rel:.3g}")
                ok = False
    print(f"checkgrad {'PASS' if ok else 'FAIL'} "
          f"({len(params)} params, worst rel err {worst:.3g})")
    return 0 if ok else 1


def _poll_job(procs, timeout: float, grace: float) -> int:
    """Shared failure-detection loop: the moment ANY worker fails (or the
    deadline passes), SIGTERM survivors with a teardown grace, then SIGKILL
    stragglers. Returns the job rc."""
    import time as _time
    rc = 0
    deadline = _time.time() + timeout
    try:
        # poll-all: the moment ANY worker fails, tear the job down (the
        # docstring's failure-detection contract); one shared deadline
        pending = list(procs)
        while pending:
            for p in list(pending):
                code = p.poll()
                if code is not None:
                    pending.remove(p)
                    if code and not rc:
                        rc = code
                        print(f"cluster_train: worker {procs.index(p)} "
                              f"exited rc={code}; tearing the job down "
                              f"(survivors get SIGTERM, {grace:.0f}s "
                              f"grace).", file=sys.stderr)
            if not rc and _time.time() > deadline:
                rc = 124
                print(f"cluster_train: --timeout {timeout:.0f}s "
                      f"exceeded; tearing the job down.", file=sys.stderr)
            if rc:     # peer failure or timeout -> graceful teardown
                for p in pending:
                    if p.poll() is None:
                        p.terminate()   # survivors run their teardown hook
                grace_end = _time.time() + grace
                while (any(p.poll() is None for p in pending)
                       and _time.time() < grace_end):
                    _time.sleep(0.1)
                break
            _time.sleep(0.2)
    finally:
        for p in procs:           # a dead/hung peer must not strand the rest
            if p.poll() is None:
                p.kill()
    return rc


def _cluster_attempt(args, attempt: int) -> int:
    """One full local-job launch: spawn all workers on a fresh coordinator
    port, then run the shared failure-detection loop."""
    import os
    import socket
    import subprocess

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    procs = []
    for i in range(args.num_workers):
        env = dict(os.environ)
        env["PADDLE_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["PADDLE_TPU_NUM_PROCESSES"] = str(args.num_workers)
        env["PADDLE_TPU_PROCESS_ID"] = str(i)
        env["PADDLE_TPU_RESTART_COUNT"] = str(attempt)
        if args.devices_per_worker:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count="
                                f"{args.devices_per_worker}").strip()
            env["JAX_PLATFORMS"] = "cpu"
        procs.append(subprocess.Popen(
            [sys.executable, args.script] + (args.script_args or []),
            env=env))
    return _poll_job(procs, args.timeout, args.grace)


def _cluster_hosts(args):
    """Host list from --hosts (comma-separated) or --hostfile (one host per
    line, '#' comments) — the conf.py HOSTS list of the reference launcher
    (scripts/cluster_train/conf.py)."""
    hosts = []
    if getattr(args, "hosts", None):
        hosts += [h.strip() for h in args.hosts.split(",") if h.strip()]
    if getattr(args, "hostfile", None):
        with open(args.hostfile) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if line:
                    hosts.append(line)
    return hosts


def _render_host_commands(args, hosts, attempt: int = 0,
                          job_id: str = "dryrun"):
    """Render the per-host launch command lines for a real multi-host
    jax.distributed job — the capability of the reference's fabric/ssh
    launcher (scripts/cluster_train/paddle.py job_prepare+job_start;
    cluster_train_v2/fabric), re-targeted at jax.distributed membership:
    node 0's host serves the coordinator, every node gets its process id
    and the world size via PADDLE_TPU_* env (consumed by
    parallel/multihost.py initialize()).

    ``--ssh-template`` wraps each per-node command; placeholders ``{host}``
    and ``{cmd}`` (shell-quoted). Default: ssh <host> '<cmd>'.

    Each node runs inside a tiny bash supervisor whose command line carries
    ``PADDLE_TPU_JOB_ID=<id>`` and which forwards SIGTERM to the python
    child — that is what makes the job remotely reapable
    (``pkill -f PADDLE_TPU_JOB_ID=<id>``, see :func:`_reap_remote_job`),
    since signalling an ssh client does not signal the remote process
    (the reference's kill_process grep-marker trick, paddle.py:51-60).

    The coordinator address strips an ssh ``user@`` login prefix from
    node 0's host, and its port is offset by the attempt number so an
    elastic restart never collides with a stale coordinator socket from
    the previous generation.
    """
    import shlex

    coord_host = hosts[0].rsplit("@", 1)[-1]   # user@host is ssh login only
    coordinator = f"{coord_host}:{args.coordinator_port + attempt}"
    template = args.ssh_template or "ssh {host} {cmd}"
    lines = []
    for i, host in enumerate(hosts):
        inner = " ".join(
            [f"PADDLE_TPU_JOB_ID={job_id}",
             f"PADDLE_TPU_COORDINATOR={coordinator}",
             f"PADDLE_TPU_NUM_PROCESSES={len(hosts)}",
             f"PADDLE_TPU_PROCESS_ID={i}",
             f"PADDLE_TPU_RESTART_COUNT={attempt}",
             args.remote_python, shlex.quote(args.script)]
            + [shlex.quote(a) for a in (args.script_args or [])])
        # supervisor: its /proc cmdline contains the job id (the exec'd
        # python's does not); TERM/INT forward to the child
        wrapped = ("bash -c " + shlex.quote(
            'trap "kill -TERM $c 2>/dev/null" TERM INT; '
            + inner + " & c=$!; wait $c"))
        lines.append(template.format(host=shlex.quote(host),
                                     cmd=shlex.quote(wrapped)))
    return lines


def _reap_remote_job(args, hosts, job_id: str):
    """Best-effort remote teardown: ssh a targeted pkill to every host so a
    crashed job's survivors do not keep the accelerators (the reference's
    paddle.py kill_process). TERM first (teardown hooks run), then KILL."""
    import shlex
    import subprocess

    import shlex as _shlex

    template = args.ssh_template or "ssh {host} {cmd}"
    # bracket the first id char: the regex still matches the literal job id
    # in the supervisors' cmdlines, but the REAPING shell's own cmdline
    # (which contains the pattern text "…=[x]yz") does not match it — so
    # pkill never TERMs the shell running the sleep+KILL escalation (the
    # reference's grep -v marker trick, paddle.py kill_process)
    pat = f"PADDLE_TPU_JOB_ID=[{job_id[0]}]{job_id[1:]}"
    kill = (f"pkill -TERM -f {_shlex.quote(pat)}; sleep 2; "
            f"pkill -KILL -f {_shlex.quote(pat)}; true")
    for host in hosts:
        cmd = template.format(host=shlex.quote(host), cmd=shlex.quote(kill))
        try:
            subprocess.run(cmd, shell=True, timeout=30,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
        except Exception:
            pass                       # a dead host cannot be reaped anyway


def _multihost_attempt(args, hosts, attempt: int) -> int:
    """One multi-host launch: run every rendered per-host command (ssh by
    default) and apply the same any-failure-tears-all-down contract the
    local path uses — the analog of paddle.py's job_all + kill-on-failure,
    including reaping the REMOTE worker processes, not just the local ssh
    clients."""
    import os
    import subprocess

    # dot-free id: it doubles as a pkill -f regex literal in the reaper
    job_id = f"{os.getpid():x}x{attempt}"
    cmds = _render_host_commands(args, hosts, attempt, job_id)
    procs = [subprocess.Popen(c, shell=True) for c in cmds]
    rc = _poll_job(procs, args.timeout, args.grace)
    if rc:
        _reap_remote_job(args, hosts, job_id)
    return rc


def cmd_cluster_train(args):
    """Local cluster launcher — the scripts/cluster_train/paddle.py (ssh) and
    cluster_train_v2 fabric/openmpi analog, process-model edition.

    Spawns ``--num_workers`` worker processes that join one jax.distributed
    job (coordinator on localhost; PADDLE_TPU_* env carries the membership
    that etcd/MPI carried for the reference) and each execute the training
    SCRIPT. The script calls ``paddle_tpu.parallel.multihost.initialize()``
    to join, then trains over the global mesh. A failing worker tears the
    job down (failure detection; rc propagated).

    ``--restart-on-failure N``: elastic recovery (the reference's
    trainers-are-stateless-consumers design, go/master/service.go:311-321 +
    doc/design/cluster_train/README.md). A synchronous SPMD job cannot
    continue minus one collective participant, so recovery is job-grained:
    tear down, then relaunch ALL workers on a fresh coordinator, up to N
    times. Scripts resume from their latest pass checkpoint (the trainer's
    pass-%05d discipline); a ``--local_master`` data plane requeues the dead
    consumer's pending task chunks by lease timeout automatically
    (native/task_master.cc), so no sample is lost or double-trained across
    the restart. ``PADDLE_TPU_RESTART_COUNT`` tells the script which
    attempt it is on. Timeouts are per-attempt.

    With ``--hosts``/``--hostfile`` the same job shape targets REAL
    machines: per-host launch commands are rendered (``--ssh-template``)
    around jax.distributed membership env — node 0's host carries the
    coordinator at ``--coordinator-port`` — and executed (ssh by default),
    or just printed with ``--dry-run`` for inspection/external schedulers.
    The reference capability: scripts/cluster_train/paddle.py (fabric/ssh)
    and cluster_train_v2/{fabric,openmpi}."""
    hosts = _cluster_hosts(args)
    if hosts:
        # world size is the host list in this mode; flag the conflict
        # instead of silently dropping an explicitly-passed local option
        if args.num_workers is not None:
            print(f"cluster_train: --hosts mode runs one node per host "
                  f"({len(hosts)}); ignoring --num_workers "
                  f"{args.num_workers}.", file=sys.stderr)
        if args.devices_per_worker:
            print("cluster_train: --devices_per_worker is a local-mode "
                  "testing option; ignored with --hosts (set XLA_FLAGS on "
                  "the remote hosts instead).", file=sys.stderr)
    if getattr(args, "dry_run", False):
        if not hosts:
            print("cluster_train: --dry-run needs --hosts/--hostfile",
                  file=sys.stderr)
            return 2
        for line in _render_host_commands(args, hosts):
            print(line)
        return 0
    if args.num_workers is None:
        args.num_workers = 2             # local-mode default world size
    restarts = max(0, getattr(args, "restart_on_failure", 0) or 0)
    for attempt in range(restarts + 1):
        rc = (_multihost_attempt(args, hosts, attempt) if hosts
              else _cluster_attempt(args, attempt))
        if rc == 0:
            return 0
        if attempt < restarts:
            print(f"cluster_train: attempt {attempt} failed rc={rc}; "
                  f"relaunching from the latest checkpoint "
                  f"({restarts - attempt} restart(s) left).", file=sys.stderr)
        else:
            print("cluster_train: restart budget exhausted."
                  if restarts else
                  "cluster_train: failed (pass --restart-on-failure N for "
                  "elastic recovery).", file=sys.stderr)
    return rc


def cmd_make_diagram(args):
    """Model visualization (scripts/submit_local.sh.in:13 make_diagram):
    emit a graphviz .dot of the config's Program — ops as boxes, data flow
    as edges, parameters dashed."""
    from . import fluid
    _load_config(args.config)
    prog = fluid.default_main_program()
    lines = ["digraph G {", "  rankdir=TB;",
             '  node [fontsize=10, fontname="Helvetica"];']
    params = {v.name for v in prog.global_block().all_parameters()}
    var_nodes = set()
    for bi, block in enumerate(prog.blocks):
        for oi, op in enumerate(block.ops):
            op_id = f"op_{bi}_{oi}"
            lines.append(f'  {op_id} [shape=box, style=filled, '
                         f'fillcolor="#DDEEFF", label="{op.type}"];')
            for names in op.inputs.values():
                for n in names:
                    var_nodes.add(n)
                    lines.append(f'  "{n}" -> {op_id};')
            for names in op.outputs.values():
                for n in names:
                    var_nodes.add(n)
                    lines.append(f'  {op_id} -> "{n}";')
    for n in sorted(var_nodes):          # one declaration per variable
        style = ", style=dashed" if n in params else ""
        lines.append(f'  "{n}" [shape=ellipse{style}];')
    lines.append("}")
    import os
    out = args.output or (os.path.splitext(args.config)[0] + ".dot")
    with open(out, "w") as f:
        f.write("\n".join(lines))
    n_ops = sum(len(b.ops) for b in prog.blocks)
    print(f"wrote {out} ({n_ops} ops, {len(params)} parameters)")
    return 0


def _read_obs_inputs(inputs):
    """Load one or more JSONL dumps as a list (the caller merges —
    cmd_obs appends xplane-derived dumps first). Errors name the
    failing file."""
    from . import obs
    dumps = []
    for p in inputs:
        try:
            dumps.append(obs.read_jsonl(p))
        except (OSError, ValueError) as e:
            raise OSError(f"{p}: {e}") from e
    return dumps


def cmd_obs(args):
    """``paddle_tpu obs`` — inspect/convert observability dumps (the JSONL
    written by ``ObsSession.save`` / ``train --obs_out`` / the flight
    recorder). ``--input`` may repeat: several dumps merge into one
    cluster view (distributed-trace stitching).

    * ``summary``: the human table (counters, gauges, histograms with
      p50/p99, span totals) — the ``StatSet.report()`` successor.
    * ``export --format=chrome``: Chrome ``trace_event`` JSON; load the
      file in Perfetto (ui.perfetto.dev) or chrome://tracing to see the
      nested trainer -> checkpoint/rpc span timeline — with several
      inputs, one lane per process plus client->server flow arrows.
    * ``export --format=prom``: Prometheus text exposition — serve it or
      drop it where a textfile collector scrapes.
    * ``export --format=jsonl``: normalized event stream (re-emits the
      dump; useful to strip a corrupt tail or persist a merge).
    """
    from . import obs
    inputs = list(args.input or ())
    xplanes = list(getattr(args, "xplane", None) or ())
    if not inputs and not xplanes:
        print("obs: pass --input dump.jsonl (repeatable) and/or "
              "--xplane trace.xplane.pb", file=sys.stderr)
        return 2
    try:
        dumps = _read_obs_inputs(inputs)
        if xplanes:
            # device timelines: each .xplane.pb becomes one dump whose
            # lanes merge beside the host spans. Anchored at the earliest
            # host dump's clock origin — XLine clocks are backend-
            # dependent, so the alignment is coarse but the lanes always
            # render (obs/xplane.py states the contract)
            from .obs import xplane as _xp
            origins = [(d.get("meta") or {}).get("clock_origin_unix")
                       for d in dumps]
            origins = [o for o in origins if o is not None]
            anchor = min(origins) if origins else None
            for path in xplanes:
                try:
                    space = _xp.read_xspace(path)
                except (OSError, ValueError) as e:
                    raise OSError(f"{path}: {e}") from e
                dumps.append(_xp.xplane_dump(space, anchor_unix=anchor))
        dump = dumps[0] if len(dumps) == 1 else obs.merge_dumps(dumps)
    except (OSError, ValueError) as e:
        print(f"obs: cannot read dump: {e}", file=sys.stderr)
        return 2
    if args.obs_cmd == "summary":
        print(obs.summary(dump))
        return 0
    if args.format == "chrome":
        out = json.dumps(obs.chrome_trace(dump), indent=1)
    elif args.format == "prom":
        out = obs.prometheus_text(dump)
    else:                                  # jsonl: normalized re-emit
        if args.output:
            obs.write_jsonl(args.output, dump)
            print(f"wrote {args.output}")
            return 0
        from .obs.export import jsonl_lines
        out = "\n".join(jsonl_lines(dump)) + "\n"
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
        print(f"wrote {args.output}")
    else:
        print(out, end="" if out.endswith("\n") else "\n")
    return 0


def cmd_obs_serve(args):
    """``paddle_tpu obs serve`` — read-only HTTP view over dumps and/or a
    live master's merged fleet metrics:

    * ``/metrics`` — Prometheus text exposition (point a scraper here)
    * ``/trace``   — Chrome trace_event JSON (load in Perfetto)
    * ``/summary`` (and ``/``) — the human table

    Sources re-read per request, so a dump being appended to (or a live
    master) always serves its current state. ``--master host:port`` polls
    ``MasterClient.obs_stats()`` — the worker-tagged merged registry the
    ``obs_push`` RPC accumulates.
    """
    from . import obs
    from .obs.aggregate import ObsHttpServer
    inputs = list(args.input or ())
    master = getattr(args, "master", None)
    if not inputs and not master:
        print("obs serve: pass --input dump.jsonl (repeatable) and/or "
              "--master host:port", file=sys.stderr)
        return 2
    master_addr = None
    if master:
        # validate ONCE at startup: a malformed flag must be a clear exit-2
        # here, not a ValueError 500ing every later scrape inside provider
        master_addr = _parse_hostport(master)
        if master_addr is None:
            print(f"obs serve: --master must be host:port, got {master!r}",
                  file=sys.stderr)
            return 2

    def provider():
        dumps = [obs.read_jsonl(p) for p in inputs]
        if master_addr is not None:
            # fail-fast telemetry client — a down master must not wedge
            # every scrape for the data plane's full retry budget
            from .obs.aggregate import telemetry_client
            client = telemetry_client(*master_addr)
            try:
                workers, samples = client.obs_stats()
                try:
                    h = client.obs_health()
                except (OSError, ConnectionError):
                    # a master predating obs_health still serves metrics
                    h = {"health": {}, "active": [], "events": [],
                         "actions": []}
                dumps.append({"meta": {"process": "master",
                                       "obs_workers": workers},
                              "metrics": samples,
                              "events": h["events"],
                              "alerts": h["active"],
                              "health": h["health"],
                              "actions": h.get("actions", []),
                              "requests": h.get("requests", []),
                              "exemplars": h.get("exemplars", [])})
            except (OSError, ConnectionError) as e:
                # keep serving whatever dumps we do have; a master-only
                # serve surfaces the outage as a 500 with the cause
                if not dumps:
                    raise
                print(f"obs serve: master {master} unreachable: {e}",
                      file=sys.stderr)
            finally:
                client.close()
        if len(dumps) == 1:
            return dumps[0]
        merged = obs.merge_dumps(dumps)
        # merge_dumps knows meta/metrics/events; the health-plane extras
        # (live alerts, derived health) carry through for /alerts and the
        # /summary fleet table
        for d in dumps:
            if d.get("alerts"):
                merged.setdefault("alerts", []).extend(d["alerts"])
            if d.get("health"):
                merged.setdefault("health", {}).update(d["health"])
            if d.get("actions"):
                merged.setdefault("actions", []).extend(d["actions"])
            if d.get("exemplars"):
                merged.setdefault("exemplars", []).extend(d["exemplars"])
        return merged

    srv = ObsHttpServer(provider, host=args.host, port=args.port).start()
    # machine-parseable address line first (port 0 binds an ephemeral one)
    print(f"SERVING {srv.address[0]} {srv.address[1]}", flush=True)
    print(f"  http://{srv.address[0]}:{srv.address[1]}/metrics  (prometheus)")
    print(f"  http://{srv.address[0]}:{srv.address[1]}/trace    (chrome json)")
    print(f"  http://{srv.address[0]}:{srv.address[1]}/requests (request "
          f"timelines)")
    print(f"  http://{srv.address[0]}:{srv.address[1]}/summary")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


def cmd_obs_top(args):
    """``paddle_tpu obs top`` — the live fleet terminal view: one row per
    worker (goodput ratio, mfu, queue depth, straggler score, heartbeat
    jitter, active alerts) over a live master's health plane
    (``--master`` → ``obs_stats`` + ``obs_health``) and/or dump files
    (``--input``, re-read per refresh). ``--once`` prints a single table
    and exits (tests, scripts); otherwise the view refreshes every
    ``--interval`` seconds until Ctrl-C.
    """
    from . import obs
    from .obs.health import health_table
    inputs = list(args.input or ())
    master = getattr(args, "master", None)
    if not inputs and not master:
        print("obs top: pass --input dump.jsonl (repeatable) and/or "
              "--master host:port", file=sys.stderr)
        return 2
    master_addr = None
    if master:
        master_addr = _parse_hostport(master)
        if master_addr is None:
            print(f"obs top: --master must be host:port, got {master!r}",
                  file=sys.stderr)
            return 2

    def fetch():
        samples, alerts, health, actions = [], [], {}, []
        if inputs:
            dumps = _read_obs_inputs(inputs)
            # always merge (even one dump): the merge stamps the worker
            # label every per-worker cell keys on
            merged = obs.merge_dumps(dumps)
            samples.extend(merged.get("metrics", ()))
            alerts.extend(e for e in merged.get("events", ())
                          if e.get("name") == "alert")
        if master_addr is not None:
            from .obs.aggregate import telemetry_client
            client = telemetry_client(*master_addr)
            try:
                _, live = client.obs_stats()
                samples.extend(live)
                try:
                    h = client.obs_health()
                except (OSError, ConnectionError):
                    # a master predating obs_health still serves metrics
                    h = {"health": {}, "active": [], "events": [],
                         "actions": []}
                health = h["health"]
                actions = h.get("actions", [])
                # transitions first (chronological fold), live state last
                alerts.extend(h["events"])
                alerts.extend(h["active"])
            finally:
                client.close()
        return samples, alerts, health, actions

    def render():
        try:
            samples, alerts, health, actions = fetch()
        except (OSError, ConnectionError) as e:
            return None, f"obs top: source unavailable: {e}"
        from .obs.health import fold_alert_stream
        table = health_table(samples, alerts=alerts, health=health,
                             actions=actions)
        firing = fold_alert_stream(alerts)
        head = (f"fleet: {len(health) if health else '-'} worker(s) in "
                f"health view, {len(firing)} alert(s) firing")
        return table, head

    once = bool(getattr(args, "once", False))
    try:
        while True:
            table, head = render()
            if table is None:
                print(head, file=sys.stderr)
                if once:
                    return 2
            else:
                if not once:
                    print("\x1b[2J\x1b[H", end="")   # clear + home
                print(head)
                print(table if table else "(no per-worker series yet)")
            if once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_obs_trace(args):
    """``paddle_tpu obs trace <submit_key>`` — print one request's
    stitched cross-worker timeline: every phase record the fabric wrote
    for that submit_key (admitted → queued/prefill/ship/adopt →
    first_token → decode segments → done), legs from a mid-stream
    re-route (``<key>#r<n>``) merged onto one clock, the phase breakdown
    that reconciles with TTFT, and the dominant phase.

    Sources: ``--input`` JSONL dumps (``--obs_out`` files, flight rings)
    and/or ``--master host:port`` (the live aggregator's request store
    via ``obs_health``). Passing a leg key resolves to its base request.
    """
    from . import obs
    from .obs.requests import base_key, format_timeline, group_legs, stitch
    inputs = list(args.input or ())
    master = getattr(args, "master", None)
    if not inputs and not master:
        print("obs trace: pass --input dump.jsonl (repeatable) and/or "
              "--master host:port", file=sys.stderr)
        return 2
    timelines = []
    try:
        for d in _read_obs_inputs(inputs):
            timelines.extend(d.get("requests") or ())
    except (OSError, ValueError) as e:
        print(f"obs trace: cannot read dump: {e}", file=sys.stderr)
        return 2
    if master:
        try:
            addr = _parse_hostport(master)
        except ValueError:
            print(f"obs trace: --master must be host:port, got {master!r}",
                  file=sys.stderr)
            return 2
        from .obs.aggregate import telemetry_client
        client = telemetry_client(*addr)
        try:
            h = client.obs_health()
            timelines.extend(h.get("requests") or ())
        except (OSError, ConnectionError) as e:
            print(f"obs trace: master {master} unreachable: {e}",
                  file=sys.stderr)
            if not timelines:
                return 2
        finally:
            client.close()
    groups = group_legs(timelines)
    want = base_key(args.key)
    legs = groups.get(want)
    if not legs:
        print(f"obs trace: no timeline for {args.key!r} "
              f"({len(groups)} request(s) in the sources)", file=sys.stderr)
        for k in sorted(groups)[:16]:
            print(f"  known: {k}", file=sys.stderr)
        return 1
    print(format_timeline(stitch(legs)))
    return 0


def cmd_serve(args):
    """``paddle_tpu serve`` — the production serving daemon: a paged
    KV-cache continuous-batching engine behind the native RPC plane
    (srv_submit / srv_poll / srv_cancel / srv_stats; see
    docs/design/serving.md and :class:`paddle_tpu.serving.ServingClient`).

    The model comes from ``--config`` (a Python script exposing module-
    level ``model`` — a TransformerLM-compatible object — and ``params``)
    or, without one, a randomly-initialized TransformerLM built from the
    ``--vocab/--d_model/...`` flags and ``--seed`` (the bring-up and e2e
    test mode: the same flags + seed reproduce the exact weights).

    The address line ``SERVING <host> <port>`` prints first and flushed
    (machine-parseable, same contract as ``obs serve``); the process then
    serves until SIGTERM/SIGINT, drains, and (with ``--obs_out``) saves
    the metric/span dump — TTFT/TPOT histograms included.
    """
    import signal

    from . import obs as _obs
    from .serving import ServingDaemon, ServingEngine
    if args.config:
        cfg = _load_config(args.config)
        if "model" not in cfg or "params" not in cfg:
            print("serve: --config must expose module-level `model` and "
                  "`params`", file=sys.stderr)
            return 2
        model, params = cfg["model"], cfg["params"]
    else:
        import jax

        from .models import TransformerLM
        model = TransformerLM(args.vocab, d_model=args.d_model,
                              n_heads=args.n_heads, n_layers=args.n_layers,
                              max_len=args.max_len)
        params = model.init(jax.random.PRNGKey(args.seed))
    session = _obs.ObsSession().install()
    flight = None
    if args.obs_out:
        flight = _obs.FlightRecorder(session, args.obs_out).arm()
    if args.role == "prefill":
        # a prefill-only worker (disaggregated serving): pool + ship, no
        # decode scheduler — it MUST join a router to be useful
        if not args.router:
            if flight is not None:
                flight.disarm()
            session.uninstall()
            print("serve: --role prefill requires --router HOST:PORT "
                  "(a prefill worker only receives work via the router)",
                  file=sys.stderr)
            return 2
        return _serve_prefill(args, model, params, session, flight)
    try:
        engine = ServingEngine(
            model, params, slots=args.slots, segment=args.segment,
            page_block=args.page_block, pages=args.pages,
            cache_bucket=args.cache_bucket, kv_dtype=args.kv_dtype,
            queue_cap=args.queue_cap,
            default_timeout_s=args.request_timeout,
            prefix_cache=not args.no_prefix_cache,
            class_weights={"interactive": args.interactive_weight,
                           "batch": args.batch_weight},
            max_tenants=args.max_tenants)
    except ValueError as e:
        # bad flag combinations (page_block not dividing max_len, a
        # cache_bucket off the page grid, ...) get the same structured
        # refusal as a bad --config, not a construction traceback
        if flight is not None:
            flight.disarm()
        session.uninstall()
        print(f"serve: {e}", file=sys.stderr)
        return 2
    try:
        daemon = ServingDaemon(engine, args.host, args.port).start()
    except OSError as e:
        # bind failures (port in use, bad host) get the structured refusal
        # too — and nothing half-started may outlive it: the engine's
        # scheduler thread stops, the armed recorder must not write a
        # spurious death dump
        engine.stop()
        if flight is not None:
            flight.disarm()
        session.uninstall()
        print(f"serve: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 2
    host, port = daemon.address
    _role_name_session(session, "decode", args.worker or f"serve-{port}")
    print(f"SERVING {host} {port}", flush=True)
    if args.router:
        try:
            epoch = daemon.join_router(
                _parse_hostport(args.router),
                args.worker or f"serve-{port}", role="decode")
        except Exception as e:
            daemon.stop()
            if flight is not None:
                flight.disarm()
            session.uninstall()
            print(f"serve: cannot join router {args.router}: {e}",
                  file=sys.stderr)
            return 2
        print(f"JOINED {args.router} epoch {epoch}", flush=True)
    print(f"  slots={args.slots} segment={args.segment} "
          f"page_block={engine.pool.bs} "
          f"pages={engine.pool.pages} queue_cap={args.queue_cap} "
          f"prefix_cache={'off' if args.no_prefix_cache else 'on'} "
          f"weights=interactive:{args.interactive_weight:g}/"
          f"batch:{args.batch_weight:g}"
          + (f" kv_dtype={args.kv_dtype}" if args.kv_dtype else ""),
          flush=True)
    import threading
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        daemon.stop(drain_s=args.drain)
        if flight is not None:
            flight.disarm()
        if args.obs_out:
            # save BEFORE uninstall: the dump captures the request ledger
            # (per-request timelines) only while the plane is installed
            try:
                session.save(args.obs_out)
                print(f"observability dump written to {args.obs_out}",
                      flush=True)
            except Exception as e:
                print(f"warning: could not write obs dump: {e}",
                      file=sys.stderr)
        session.uninstall()
    return 0


def _parse_hostport(s: str):
    host, _, port = str(s).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"expected HOST:PORT, got {s!r}")
    return host, int(port)


def _role_name_session(session, role, worker=None):
    """Rename an installed ObsSession after its serving role (``router``,
    ``prefill:<worker>``, ``decode:<worker>``) — the lane name the Chrome
    exporter ranks router-above-prefill-above-decode and the worker id
    merged request timelines stitch under. An explicit
    PADDLE_TPU_OBS_PROCESS wins (operator override)."""
    import os
    if os.environ.get("PADDLE_TPU_OBS_PROCESS"):
        return
    session.process = f"{role}:{worker}" if worker else role


def _serve_prefill(args, model, params, session, flight):
    """The ``--role prefill`` half of cmd_serve: a pool-only worker that
    admits+exports KV pages and ships them to the router-chosen decode
    worker (serving/daemon.py PrefillDaemon)."""
    import signal
    import threading

    from .serving import PagePool, PrefillDaemon

    def _teardown():
        if flight is not None:
            flight.disarm()
        session.uninstall()

    try:
        pool = PagePool(model, params, slots=args.slots,
                        segment=args.segment, page_block=args.page_block,
                        pages=args.pages, cache_bucket=args.cache_bucket,
                        kv_dtype=args.kv_dtype,
                        prefix_cache=not args.no_prefix_cache)
    except ValueError as e:
        _teardown()
        print(f"serve: {e}", file=sys.stderr)
        return 2
    try:
        daemon = PrefillDaemon(pool, args.host, args.port).start()
    except OSError as e:
        _teardown()
        print(f"serve: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 2
    host, port = daemon.address
    _role_name_session(session, "prefill", args.worker or f"prefill-{port}")
    print(f"SERVING {host} {port}", flush=True)
    try:
        epoch = daemon.join_router(_parse_hostport(args.router),
                                   args.worker or f"prefill-{port}",
                                   role="prefill")
    except Exception as e:
        daemon.stop()
        _teardown()
        print(f"serve: cannot join router {args.router}: {e}",
              file=sys.stderr)
        return 2
    print(f"JOINED {args.router} epoch {epoch}", flush=True)
    print(f"  role=prefill slots={args.slots} page_block={pool.bs} "
          f"pages={pool.pages} "
          f"prefix_cache={'off' if args.no_prefix_cache else 'on'}"
          + (f" kv_dtype={args.kv_dtype}" if args.kv_dtype else ""),
          flush=True)
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        daemon.stop()
        if args.obs_out:
            # before _teardown: the dump captures the request ledger only
            # while the plane is installed
            try:
                session.save(args.obs_out)
                print(f"observability dump written to {args.obs_out}",
                      flush=True)
            except Exception as e:
                print(f"warning: could not write obs dump: {e}",
                      file=sys.stderr)
        _teardown()
    return 0


def cmd_route(args):
    """``paddle_tpu route`` — the serving router daemon: model-free
    placement over a membership table of prefill/decode serving workers
    (docs/design/serving.md "Disaggregation & routing"). Workers join
    with ``paddle_tpu serve --router HOST:PORT --role decode|prefill``;
    clients point :class:`paddle_tpu.serving.RouterClient` here.

    The address line ``ROUTER <host> <port>`` prints first and flushed
    (machine-parseable, the ``SERVING``/``MASTER`` contract)."""
    import signal
    import threading

    from . import obs as _obs
    from .serving import ServingRouter

    session = _obs.ObsSession().install()
    _role_name_session(session, "router")
    try:
        router = ServingRouter(args.host, args.port, ttl=args.ttl,
                               scrape_interval_s=args.scrape_interval
                               ).start()
    except OSError as e:
        session.uninstall()
        print(f"route: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 2
    host, port = router.address
    print(f"ROUTER {host} {port}", flush=True)
    print(f"  ttl={args.ttl:g} scrape_interval={args.scrape_interval:g}",
          flush=True)
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        router.stop()
        if args.obs_out:
            # before uninstall: the dump captures the request ledger only
            # while the plane is installed
            try:
                session.save(args.obs_out)
                print(f"observability dump written to {args.obs_out}",
                      flush=True)
            except Exception as e:
                print(f"warning: could not write obs dump: {e}",
                      file=sys.stderr)
        session.uninstall()
    return 0


def cmd_cluster_autoscale(args):
    """``paddle_tpu cluster autoscale`` — the fleet actor (ISSUE 18,
    docs/design/fleet.md): watch the membership + health planes and
    DRIVE the fleet to them — spawn workers on a sustained join
    recommendation or an SLO burn, drain them gracefully on leave /
    scale-in, yield training capacity to serving under a shared
    ``--total-workers`` budget.

    Populations come from the flags: ``--train-master HOST:PORT`` +
    ``--train-cmd`` (a launch template with ``{worker}`` — and
    optionally ``{python}`` — placeholders) drives an elastic-DP
    training pool; ``--router HOST:PORT`` + ``--decode-cmd`` drives a
    decode serving pool toward ``--decode-target``. At least one
    population is required. Spawned processes must join the matching
    membership plane under the worker name the actor passed — that
    (never the subprocess's exit status alone) is the success oracle."""
    import signal
    import threading

    from . import obs as _obs
    from .cluster import (ActorReporter, FleetActor, MasterProbe,
                          Population, RouterProbe, SubprocessSpawnBackend)

    populations, closers = [], []
    for flag, cmd_flag, name, probe_cls, target in (
            ("train_master", "train_cmd", "train", MasterProbe, None),
            ("router", "decode_cmd", "serve", RouterProbe,
             args.decode_target)):
        addr = getattr(args, flag, None)
        if not addr:
            continue
        try:
            parsed = _parse_hostport(addr)
        except ValueError:
            parsed = None
        if parsed is None or not parsed[1]:
            print(f"cluster autoscale: --{flag.replace('_', '-')} must be "
                  f"host:port, got {addr!r}", file=sys.stderr)
            return 2
        template = getattr(args, cmd_flag, None)
        if not template or "{worker}" not in template:
            print(f"cluster autoscale: --{cmd_flag.replace('_', '-')} must "
                  f"be a launch template containing {{worker}}",
                  file=sys.stderr)
            return 2
        host, port = parsed
        probe = probe_cls(host, port)
        reporter = ActorReporter(host, port, args.actor)
        closers.extend((probe, reporter))
        populations.append(Population(
            name=name, backend=SubprocessSpawnBackend(template),
            probe=probe, reporter=reporter,
            min_workers=getattr(args, f"{name}_min"),
            max_workers=getattr(args, f"{name}_max"),
            target=target))
    if not populations:
        print("cluster autoscale: pass --train-master/--train-cmd and/or "
              "--router/--decode-cmd", file=sys.stderr)
        return 2

    session = _obs.ObsSession().install()
    actor = FleetActor(populations, total_workers=args.total_workers,
                       interval_s=args.interval, cooldown_s=args.cooldown,
                       max_churn=args.max_churn,
                       spawn_grace_s=args.spawn_grace,
                       drain_grace_s=args.drain_grace, name=args.actor)
    pops = ", ".join(f"{q.name}[{q.min_workers}..{q.max_workers}"
                     + (f"->{q.target}]" if q.target is not None else "]")
                     for q in populations)
    print(f"AUTOSCALE ACTOR {args.actor}", flush=True)
    print(f"  populations: {pops}  interval={args.interval:g} "
          f"cooldown={args.cooldown:g} max_churn={args.max_churn}"
          + (f" total={args.total_workers}" if args.total_workers else ""),
          flush=True)
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except ValueError:
        pass
    try:
        if args.once:
            for entry in actor.step():
                print(f"  {entry['action']} {entry['population']}/"
                      f"{entry['worker']}: {entry['reason']}", flush=True)
        else:
            actor.run(stop=stop)
            if actor.deposed:
                print("cluster autoscale: deposed by a newer actor "
                      "registration; exiting", file=sys.stderr)
                return 2
    finally:
        for c in closers:
            try:
                c.close()
            except Exception:
                pass
        session.uninstall()
    return 0


def cmd_version(args):
    from . import __version__
    import jax
    print(f"paddle_tpu {__version__} (jax {jax.__version__}, "
          f"backend {jax.default_backend()})")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu")
    sub = p.add_subparsers(dest="job", required=True)

    def common(sp):
        sp.add_argument("--config", required=True)

    t = sub.add_parser("train")
    common(t)
    t.add_argument("--num_passes", type=int, default=1)
    t.add_argument("--save_dir", default=None)
    t.add_argument("--log_period", type=int, default=0)
    t.add_argument("--local_master", action="store_true",
                   help="self-host the task-master data plane in-process "
                        "(TrainerMain --start_pserver analog): dump the "
                        "reader to chunks, serve them over the real RPC "
                        "plane, train as its first consumer")
    t.add_argument("--samples_per_chunk", type=int, default=64,
                   help="reader items per dispatched chunk (--local_master)")
    t.add_argument("--obs_out", default=None,
                   help="install an observability session for the run and "
                        "write its JSONL dump here (inspect with "
                        "'paddle_tpu obs summary/export')")
    t.add_argument("--compile_cache", default=None,
                   help="directory for the persistent XLA compilation "
                        "cache: a preemption-resume (or any re-run) loads "
                        "its compiled executables from here instead of "
                        "recompiling ($PADDLE_TPU_COMPILE_CACHE_DIR analog)")
    t.add_argument("--elastic", choices=["master", "worker"], default=None,
                   help="elastic data-parallel mode (docs/design/elastic.md): "
                        "'master' serves membership + shard dispatch and "
                        "applies the updates; 'worker' joins a master under "
                        "a heartbeat lease and computes shard gradients. "
                        "The config must define elastic_workload() -> "
                        "{loss_fn, params, optimizer, batches}")
    t.add_argument("--master_addr", default=None,
                   help="--elastic worker: HOST:PORT of the elastic master "
                        "to join; --elastic master: bind address "
                        "(default 127.0.0.1:0 — the chosen port is printed "
                        "as 'ELASTIC MASTER host port')")
    t.add_argument("--min_workers", type=int, default=1,
                   help="--elastic master: members required before the "
                        "first step dispatches")
    t.add_argument("--shards_per_step", type=int, default=4,
                   help="--elastic master: fixed shard tasks per global "
                        "batch (the elasticity quantum; membership-"
                        "independent so the reduce stays byte-stable)")
    t.add_argument("--heartbeat_ttl", type=float, default=5.0,
                   help="--elastic master: seconds without a heartbeat "
                        "before a worker is evicted and its in-flight "
                        "shards re-bucket")
    t.add_argument("--worker_id", default=None,
                   help="--elastic worker: stable membership name (a "
                        "re-join under the same name fences the old "
                        "incarnation)")
    t.set_defaults(fn=cmd_train)

    te = sub.add_parser("test")
    common(te)
    te.add_argument("--init_model_path", default=None)
    te.set_defaults(fn=cmd_test)

    tm = sub.add_parser("time")
    common(tm)
    tm.add_argument("--warmup", type=int, default=2)
    tm.add_argument("--iters", type=int, default=10)
    tm.set_defaults(fn=cmd_time)

    dc = sub.add_parser("dump_config")
    common(dc)
    dc.set_defaults(fn=cmd_dump_config)

    lt = sub.add_parser("lint", help="statically verify + lint the config's "
                                     "Program IR (no trace, no compile)")
    lt.add_argument("--config", required=False, default=None,
                    help="config to verify (optional when --bench-rows "
                         "is given alone)")
    lt.add_argument("--bench-rows", nargs="+", default=None,
                    dest="bench_rows", metavar="FILE",
                    help="also validate saved bench rows (BENCH_*.json / "
                         "bench.py JSONL) against the bench-row schema")
    lt.add_argument("--fail-on", choices=["error", "warning", "info"],
                    default="error", dest="fail_on",
                    help="lowest severity that makes the exit code nonzero")
    lt.add_argument("--json", action="store_true",
                    help="emit diagnostics as a flat JSON list (legacy; "
                         "prefer --format=json)")
    lt.add_argument("--format", choices=["text", "json"], default="text",
                    help="output format; json emits the stable schema "
                         "{version, findings[], summary} on pure stdout "
                         "(exit codes: 0 clean, 1 findings at/above "
                         "--fail-on, 2 usage error)")
    lt.add_argument("--explain", action="store_true",
                    help="annotate each finding's variable with its "
                         "def-use chain (defined / redefined / last "
                         "read sites) from the dataflow plane")
    lt.add_argument("--mesh-axes", default=None, dest="mesh_axes",
                    help="comma-separated valid sharding axis names "
                         "(default: parallel.mesh.CANONICAL_ORDER, with "
                         "unknown axes reported as warnings)")
    lt.add_argument("--autotune-cache", default=None, dest="autotune_cache",
                    metavar="FILE",
                    help="autotune cache to check for staleness (L008; "
                         "default: $PADDLE_TPU_AUTOTUNE_CACHE / "
                         "~/.paddle_tpu/autotune.json — a missing file "
                         "is clean). Works standalone without --config.")
    lt.set_defaults(fn=cmd_lint)

    tu = sub.add_parser("tune", help="measure candidate kernel plans "
                                     "(fused-RNN tiles, decode routing, "
                                     "paged block size, graph fusion, "
                                     "serving bucket grids) and persist "
                                     "winners in the autotune cache the "
                                     "routers consult")
    tu.add_argument("--spaces", default=None,
                    help="comma-separated plan spaces (default: all of "
                         "bucket_grid,decode_route,fused_rnn,fusion,"
                         "page_block)")
    tu.add_argument("--from-ledger", default=None, dest="from_ledger",
                    metavar="FILE",
                    help="seed the sweep from a profile ledger (xplane "
                         ".pb or JSON op rows): the hottest op sites "
                         "pick which plan spaces get swept — tuning "
                         "effort follows the measured time (an explicit "
                         "--spaces list overrides the seeding)")
    tu.add_argument("--ledger-topk", type=int, default=8,
                    dest="ledger_topk", metavar="N",
                    help="how many top self-time op sites seed the "
                         "sweep (default 8)")
    tu.add_argument("--profile", choices=["smoke", "cpu", "bench"],
                    default=None,
                    help="measurement profile (default: bench on TPU, "
                         "cpu elsewhere; --check defaults to smoke)")
    tu.add_argument("--cache", default=None, metavar="FILE",
                    help="cache file to merge winners into (default: "
                         "$PADDLE_TPU_AUTOTUNE_CACHE / "
                         "~/.paddle_tpu/autotune.json)")
    tu.add_argument("--reps", type=int, default=None,
                    help="timing repetitions per candidate (default: "
                         "the profile's)")
    tu.add_argument("--check", action="store_true",
                    help="CI smoke: tiny sweep, then verify the written "
                         "cache reloads and the routing consults resolve "
                         "it (exit 1 on any break)")
    tu.add_argument("--dry-run", action="store_true", dest="dry_run",
                    help="measure and report, write nothing")
    tu.add_argument("--markdown", action="store_true",
                    help="print the winners as the markdown crossover "
                         "table docs/design/kernels.md embeds")
    tu.add_argument("--json", action="store_true",
                    help="print the full report (sweeps included) as "
                         "JSON")
    tu.set_defaults(fn=cmd_tune)

    mm = sub.add_parser("merge_model")
    common(mm)
    mm.add_argument("--model_path", required=True)
    mm.add_argument("--output_dir", required=True)
    mm.set_defaults(fn=cmd_merge_model)

    md = sub.add_parser("make_diagram")
    common(md)
    md.add_argument("--output", default=None)
    md.set_defaults(fn=cmd_make_diagram)

    pf = sub.add_parser("profile", help="run N profiled steps and print a "
                        "top-k per-op device report with Program-site "
                        "attribution (obs/xplane.py; docs/design/"
                        "observability.md)")
    pf.add_argument("--config", default=None,
                    help="profile this config's training step")
    pf.add_argument("--decode", default=None, metavar="B,PROMPT,NEW",
                    help="profile a fused-decode serve workload instead: "
                         "batch, prompt length, new tokens (random-init "
                         "TransformerLM from the model flags + --seed)")
    pf.add_argument("--steps", type=int, default=3,
                    help="profiled steps (the report amortizes over them)")
    pf.add_argument("--warmup", type=int, default=2,
                    help="unprofiled steps first, so compiles stay out")
    pf.add_argument("--topk", type=int, default=15)
    pf.add_argument("--trace-dir", default=None, dest="trace_dir",
                    help="keep the raw profiler output here (default: a "
                         "fresh temp dir; the .xplane.pb path prints)")
    pf.add_argument("--vocab", type=int, default=256)
    pf.add_argument("--d_model", type=int, default=128)
    pf.add_argument("--n_heads", type=int, default=4)
    pf.add_argument("--n_layers", type=int, default=2)
    pf.add_argument("--max_len", type=int, default=512)
    pf.add_argument("--kv_dtype", choices=["int8"], default=None)
    pf.add_argument("--seed", type=int, default=0)
    pf.set_defaults(fn=cmd_profile)

    cg = sub.add_parser("checkgrad")
    common(cg)
    cg.add_argument("--eps", type=float, default=5e-3)
    cg.add_argument("--rtol", type=float, default=5e-2)
    cg.add_argument("--checks_per_param", type=int, default=3)
    cg.set_defaults(fn=cmd_checkgrad)

    ct = sub.add_parser("cluster_train")
    ct.add_argument("script", help="training script run by every worker")
    ct.add_argument("script_args", nargs="*",
                    help="args passed through to the script (put them after "
                         "a -- separator if they start with a dash)")
    # None default = "not passed": --hosts mode warns on ANY explicit value
    # (a hard-coded sentinel of 2 could not tell `--num_workers 2` from the
    # default); local mode resolves it to 2
    ct.add_argument("--num_workers", type=int, default=None)
    ct.add_argument("--devices_per_worker", type=int, default=0,
                    help="force N virtual CPU devices per worker (testing; "
                         "0 = use the worker's real accelerators)")
    ct.add_argument("--timeout", type=float, default=600.0)
    ct.add_argument("--grace", type=float, default=10.0,
                    help="seconds survivors get to run their teardown hook "
                         "(SIGTERM) before SIGKILL when a peer fails")
    ct.add_argument("--restart-on-failure", type=int, default=0,
                    metavar="N", dest="restart_on_failure",
                    help="elastic recovery: relaunch the whole job (fresh "
                         "coordinator, scripts resume from their latest "
                         "checkpoint) up to N times after a worker failure")
    ct.add_argument("--hosts", default=None,
                    help="comma-separated host list: launch one node per "
                         "host over ssh (multi-host jax.distributed mode)")
    ct.add_argument("--hostfile", default=None,
                    help="file with one host per line ('#' comments) — the "
                         "reference launcher's conf.py HOSTS")
    ct.add_argument("--ssh-template", default=None, dest="ssh_template",
                    help="per-host command template with {host} and {cmd} "
                         "placeholders (default: \"ssh {host} {cmd}\"); "
                         "e.g. \"ssh -p 2222 -i key {host} {cmd}\" or "
                         "\"bash -c {cmd}\" for local testing")
    ct.add_argument("--coordinator-port", type=int, default=7164,
                    dest="coordinator_port",
                    help="jax.distributed coordinator port on node 0's host "
                         "(the reference's PADDLE_PORT)")
    ct.add_argument("--remote-python", default="python3",
                    dest="remote_python",
                    help="python interpreter to invoke on each host")
    ct.add_argument("--dry-run", action="store_true", dest="dry_run",
                    help="print the rendered per-host commands and exit "
                         "(for inspection or external schedulers)")
    ct.set_defaults(fn=cmd_cluster_train)

    ob = sub.add_parser("obs", help="inspect/convert/serve observability "
                                    "dumps (JSONL from ObsSession.save / "
                                    "train --obs_out / flight recorder)")
    obsub = ob.add_subparsers(dest="obs_cmd", required=True)
    os_ = obsub.add_parser("summary", help="human metric/span table "
                                           "(subsumes StatSet.report)")
    os_.add_argument("--input", required=True, action="append",
                     help="JSONL dump to summarize (repeat to merge a "
                          "multi-process run into one cluster view)")
    os_.set_defaults(fn=cmd_obs)
    oe = obsub.add_parser("export", help="convert the dump(s) for other "
                                         "tools")
    oe.add_argument("--input", action="append",
                    help="JSONL dump to convert (repeat to merge: one "
                         "Chrome lane per process + client->server flow "
                         "arrows)")
    oe.add_argument("--xplane", action="append", metavar="TRACE.xplane.pb",
                    help="merge a jax.profiler trace's device planes as "
                         "extra process lanes beside the host spans "
                         "(paddle_tpu profile writes one)")
    oe.add_argument("--format", choices=["chrome", "prom", "jsonl"],
                    default="chrome",
                    help="chrome: trace_event JSON for Perfetto; prom: "
                         "Prometheus text; jsonl: normalized stream")
    oe.add_argument("--output", default=None,
                    help="output path (default: stdout)")
    oe.set_defaults(fn=cmd_obs)
    osv = obsub.add_parser("serve", help="read-only HTTP endpoint: /metrics "
                                         "(prometheus), /trace (chrome "
                                         "json), /summary")
    osv.add_argument("--input", action="append",
                     help="JSONL dump(s) to serve (re-read per request)")
    osv.add_argument("--master", default=None,
                     help="host:port of a live MasterServer — serve its "
                          "merged obs_push fleet view")
    osv.add_argument("--host", default="127.0.0.1")
    osv.add_argument("--port", type=int, default=0,
                     help="0 binds an ephemeral port (printed on start)")
    osv.set_defaults(fn=cmd_obs_serve)
    otr = obsub.add_parser("trace", help="print one request's stitched "
                                         "cross-worker timeline (phases, "
                                         "re-route legs, TTFT breakdown)")
    otr.add_argument("key", help="submit_key to trace (a re-route leg key "
                                 "like KEY#r1 resolves to its base request)")
    otr.add_argument("--input", action="append",
                     help="JSONL dump(s) holding request timelines "
                          "(--obs_out files, flight rings)")
    otr.add_argument("--master", default=None,
                     help="host:port of a live MasterServer — trace from "
                          "its aggregated request store")
    otr.set_defaults(fn=cmd_obs_trace)
    ot = obsub.add_parser("top", help="live per-worker fleet table: "
                                      "goodput, mfu, queue, straggler "
                                      "score, active alerts")
    ot.add_argument("--input", action="append",
                    help="JSONL dump(s) to read (re-read per refresh)")
    ot.add_argument("--master", default=None,
                    help="host:port of a live MasterServer — renders its "
                         "obs_stats + obs_health fleet view")
    ot.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds")
    ot.add_argument("--once", action="store_true",
                    help="print one table and exit (scripts, tests)")
    ot.set_defaults(fn=cmd_obs_top)

    sv = sub.add_parser("serve", help="serving daemon: paged KV-cache "
                        "continuous batching behind the native RPC plane "
                        "(srv_submit/srv_poll/srv_cancel; "
                        "docs/design/serving.md)")
    sv.add_argument("--config", default=None,
                    help="Python script exposing `model` and `params`; "
                    "omitted = random-init TransformerLM from the flags")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=0)
    sv.add_argument("--vocab", type=int, default=50257)
    sv.add_argument("--d_model", type=int, default=768)
    sv.add_argument("--n_heads", type=int, default=12)
    sv.add_argument("--n_layers", type=int, default=12)
    sv.add_argument("--max_len", type=int, default=1024)
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--slots", type=int, default=8)
    sv.add_argument("--segment", type=int, default=32)
    sv.add_argument("--page_block", type=int, default=None,
                    help="KV page size; default consults the autotune "
                         "cache (paddle_tpu tune) and falls back to 64")
    sv.add_argument("--pages", type=int, default=None,
                    help="pool pages incl. the null page (default: worst "
                    "case slots*max_len/page_block + 1)")
    sv.add_argument("--cache_bucket", type=int, default=256)
    sv.add_argument("--kv_dtype", choices=["int8"], default=None)
    sv.add_argument("--queue_cap", type=int, default=64)
    sv.add_argument("--no_prefix_cache", action="store_true",
                    help="disable the copy-on-write prefix radix index "
                    "(default ON for the daemon: requests sharing a "
                    "prompt prefix share KV pages and prefill only the "
                    "suffix; docs/design/serving.md)")
    sv.add_argument("--interactive_weight", type=float, default=4.0,
                    help="weighted-fair service share of slo=interactive "
                    "requests vs slo=batch (deficit scheduling at slot "
                    "assignment)")
    sv.add_argument("--batch_weight", type=float, default=1.0)
    sv.add_argument("--max_tenants", type=int, default=32,
                    help="distinct tenant labels this daemon will mint "
                    "metric series for (bounded-cardinality contract; "
                    "further tenants are refused at submit)")
    sv.add_argument("--request_timeout", type=float, default=None,
                    help="default per-request deadline (seconds); "
                    "timed-out requests free their slot and pages")
    sv.add_argument("--drain", type=float, default=10.0,
                    help="seconds to let in-flight requests finish (and "
                    "clients collect them) on SIGTERM before severing "
                    "connections; 0 = stop immediately")
    sv.add_argument("--obs_out", default=None)
    sv.add_argument("--router", default=None, metavar="HOST:PORT",
                    help="join this serving router's membership table "
                    "(paddle_tpu route); the router then places client "
                    "submits here by windowed health trends")
    sv.add_argument("--role", choices=["decode", "prefill"],
                    default="decode",
                    help="decode (default): the full engine; prefill: a "
                    "pool-only worker that admits prompts, exports the "
                    "KV pages and ships them to the router-chosen "
                    "decode worker (requires --router)")
    sv.add_argument("--worker", default=None,
                    help="membership worker name (default: "
                    "serve-<port> / prefill-<port>)")
    sv.set_defaults(fn=cmd_serve)

    rt = sub.add_parser("route", help="serving router: model-free "
                        "placement over joined prefill/decode serving "
                        "workers — health-trend spread, backpressure "
                        "aggregation, re-route on eviction "
                        "(docs/design/serving.md)")
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument("--port", type=int, default=0)
    rt.add_argument("--ttl", type=float, default=3.0,
                    help="membership lease TTL (seconds); workers "
                    "heartbeat at ttl/3 and are evicted — their streams "
                    "re-routed — after ttl without one")
    rt.add_argument("--scrape_interval", type=float, default=0.25,
                    help="seconds between srv_stats health scrapes (the "
                    "windowed trend data placement scores read)")
    rt.add_argument("--obs_out", default=None)
    rt.set_defaults(fn=cmd_route)

    cl = sub.add_parser("cluster", help="fleet lifecycle: the actor that "
                        "closes the autoscale loop (docs/design/fleet.md)")
    clsub = cl.add_subparsers(dest="cluster_cmd", required=True)
    ca = clsub.add_parser("autoscale", help="watch the membership + "
                          "health planes and spawn/drain workers to the "
                          "hysteresis-stable recommendation and SLO "
                          "burn-rate alerts")
    ca.add_argument("--actor", default="autoscale-actor",
                    help="actor name for act_register (single-writer: a "
                    "newer registration deposes this one)")
    ca.add_argument("--train-master", dest="train_master", default=None,
                    metavar="HOST:PORT",
                    help="elastic master whose membership/recommendation "
                    "drives the training population")
    ca.add_argument("--train-cmd", dest="train_cmd", default=None,
                    help="training-worker launch template with a {worker} "
                    "placeholder ({python} expands to this interpreter), "
                    "e.g. '{python} -m paddle_tpu train --config c.py "
                    "--elastic worker --master_addr H:P "
                    "--worker_id {worker}'")
    ca.add_argument("--train-min", dest="train_min", type=int, default=1)
    ca.add_argument("--train-max", dest="train_max", type=int, default=8)
    ca.add_argument("--router", default=None, metavar="HOST:PORT",
                    help="serving router whose decode pool the actor "
                    "keeps at --decode-target (scaling out on TTFT/TPOT "
                    "SLO burn)")
    ca.add_argument("--decode-cmd", dest="decode_cmd", default=None,
                    help="decode-worker launch template with a {worker} "
                    "placeholder, e.g. '{python} -m paddle_tpu serve "
                    "--router H:P --worker {worker} ...'")
    ca.add_argument("--decode-target", dest="decode_target", type=int,
                    default=1, help="steady-state decode pool size")
    ca.add_argument("--serve-min", dest="serve_min", type=int, default=1)
    ca.add_argument("--serve-max", dest="serve_max", type=int, default=8)
    ca.add_argument("--interval", type=float, default=1.0,
                    help="seconds between actor ticks")
    ca.add_argument("--cooldown", type=float, default=5.0,
                    help="per-(population, action) cooldown: damping on "
                    "top of the recommendation's hysteresis")
    ca.add_argument("--max-churn", dest="max_churn", type=int, default=1,
                    help="max concurrent in-flight spawns+drains across "
                    "the whole fleet")
    ca.add_argument("--spawn-grace", dest="spawn_grace", type=float,
                    default=30.0, help="seconds a spawned worker gets to "
                    "appear in membership before the spawn counts failed")
    ca.add_argument("--drain-grace", dest="drain_grace", type=float,
                    default=30.0, help="seconds a draining worker gets to "
                    "leave membership before escalation to kill")
    ca.add_argument("--total-workers", dest="total_workers", type=int,
                    default=None,
                    help="shared fleet budget: when set, populations "
                    "compete through the weighted-fair deficit scheduler "
                    "and training yields to serving on SLO burn")
    ca.add_argument("--once", action="store_true",
                    help="run one control tick, print committed actions, "
                    "exit (scripts, tests)")
    ca.set_defaults(fn=cmd_cluster_autoscale)

    v = sub.add_parser("version")
    v.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
