"""paddle_tpu.cluster — the fleet actor subsystem (ISSUE 18).

Closes the autoscale loop: :class:`FleetActor` polls the membership +
health planes and converts hysteresis-stable recommendations and SLO
burn-rate alerts into worker spawns/drains through the injectable
:class:`SpawnBackend` seam, sharing one fleet budget across training and
serving populations via the :class:`FleetScheduler` (PR 12's
weighted-fair deficit scheduler, generalized to workers). See
docs/design/fleet.md.
"""
from .actor import (ActorReporter, FleetActor, MasterProbe, Population,
                    RouterProbe, SLO_BURN_RULES)
from .scheduler import DEFAULT_WEIGHTS, FleetScheduler
from .spawn import (HookSpawnBackend, SpawnBackend, SpawnHandle,
                    SubprocessSpawnBackend)

__all__ = [
    "ActorReporter", "DEFAULT_WEIGHTS", "FleetActor", "FleetScheduler",
    "HookSpawnBackend", "MasterProbe", "Population", "RouterProbe",
    "SLO_BURN_RULES", "SpawnBackend", "SpawnHandle",
    "SubprocessSpawnBackend",
]
