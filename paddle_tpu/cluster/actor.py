"""The fleet actor: the loop that ACTS on the autoscale signals.

PR 14's membership plane recommends (``autoscale_recommendation``,
hysteresis-stable since PR 15), PR 15's alert engine pages on SLO burn,
PR 17's router re-routes around departures — and until now an operator
closed every one of those loops by hand. :class:`FleetActor` closes them
in software:

* each tick it POLLS every :class:`Population`'s control plane
  (``mbr_view`` for the member list + recommendation, ``obs_health`` for
  firing alerts, backlog/in-flight probes for busyness), so the actor
  holds no state the fleet cannot re-derive after an actor restart;
* non-``hold`` recommendations and TTFT/TPOT burn-rate alerts become
  spawns/drains through the injectable :class:`~.spawn.SpawnBackend`
  seam, gated by a per-(population, action) COOLDOWN and a fleet-wide
  max-concurrent-CHURN cap — hysteresis upstream, damping here, so the
  chaos bar's "zero flapping" holds end to end;
* drains are GRACEFUL-BEFORE-EVICT: the backend's drain (SIGTERM
  locally) lets the worker finish in-flight work and leave via
  membership (the router re-routes live streams, the elastic worker
  finishes its shard at the barrier); only a drain that overstays its
  grace is escalated to ``kill`` and journaled as an eviction;
* under a ``total_workers`` budget the populations share capacity
  through :class:`~.scheduler.FleetScheduler` — batch training soaks
  idle workers and YIELDS one to serving when an SLO burns, reclaiming
  it on resolve (the train/serve unification protocol);
* every COMMITTED action lands in the actor's bounded journal and — via
  the population's reporter — in the master's ``act_report`` ext-op,
  which drives the ``cluster.autoscale_committed`` gauge so operators
  can tell "recommendation held" from "actor acted". A second actor
  registering against the same master deposes the first (single-writer
  fencing): the deposed actor's next report raises
  :class:`StaleMemberError` and its loop exits rather than fight.

Safety invariant (the graceful-leave-storm bar): a rolling drain never
retires the LAST live worker of a population while that population is
busy (in-flight elastic shard, live decode stream), and never drains
below ``min_workers``.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs
from ..faults import inject as faults
from ..runtime.master_service import StaleMemberError
from .scheduler import FleetScheduler
from .spawn import SpawnBackend, SpawnHandle

log = logging.getLogger("paddle_tpu.cluster")

#: alert rules whose firing marks a population URGENT (head-of-line in
#: the fleet scheduler, allowed to pull yielded workers from batch pops)
SLO_BURN_RULES = ("serving_ttft_slo_burn", "serving_tpot_slo_burn")

#: journal action -> the cluster.autoscale_committed gauge encoding
ACTION_SIGNAL = {"spawn": 1.0, "drain": -1.0, "evict": -1.0,
                 "spawn_failed": 0.0}


@dataclass
class Population:
    """One scalable pool the actor drives (elastic-DP training workers,
    a router's decode pool, ...).

    ``probe`` is a zero-arg callable returning the observation dict
    (:class:`MasterProbe` / :class:`RouterProbe`, or a fake in tests)::

        {"members": [{"worker": str, "token": int}, ...],
         "recommendation": {"action": "join"|"leave"|"hold", ...} | None,
         "alerts": [rule_name, ...],    # currently-firing alert rules
         "busy": bool}                  # in-flight work exists

    ``target`` pins a steady-state size (serve pools); None means the
    recommendation alone moves the size (train pools). ``reporter`` is
    an optional callable(entry) that journals committed actions to the
    population's master (``act_report``).
    """
    name: str
    backend: SpawnBackend
    probe: Callable[[], Dict[str, Any]]
    reporter: Optional[Callable[[Dict[str, Any]], None]] = None
    min_workers: int = 0
    max_workers: int = 8
    target: Optional[int] = None
    worker_prefix: Optional[str] = None

    def prefix(self) -> str:
        return self.worker_prefix or f"{self.name}-w"


@dataclass
class _Pending:
    handle: SpawnHandle
    deadline: float


class FleetActor:
    """See module docstring. Tests drive :meth:`step` directly under a
    fake clock; deployments call :meth:`run`."""

    def __init__(self, populations: List[Population], *,
                 scheduler: Optional[FleetScheduler] = None,
                 total_workers: Optional[int] = None,
                 interval_s: float = 1.0, cooldown_s: float = 5.0,
                 max_churn: int = 1, spawn_grace_s: float = 30.0,
                 drain_grace_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "autoscale-actor"):
        if not populations:
            raise ValueError("FleetActor needs at least one population")
        names = [p.name for p in populations]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate population names: {names}")
        self.populations = list(populations)
        self.scheduler = scheduler or FleetScheduler()
        self.total_workers = total_workers
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.max_churn = int(max_churn)
        self.spawn_grace_s = float(spawn_grace_s)
        self.drain_grace_s = float(drain_grace_s)
        self.name = name
        self._clock = clock
        self.journal: deque = deque(maxlen=128)
        self.deposed = False
        self._spawn_seq = 0
        self._last_action: Dict[Tuple[str, str], float] = {}
        self._spawning: Dict[str, Dict[str, _Pending]] = \
            {p.name: {} for p in populations}
        #: every handle this actor ever spawned, so a later drain can
        #: signal the right process (bounded by max_workers per pop)
        self._handles: Dict[str, Dict[str, SpawnHandle]] = \
            {p.name: {} for p in populations}
        self._draining: Dict[str, Dict[str, _Pending]] = \
            {p.name: {} for p in populations}
        #: workers each population yielded to an urgent peer and may
        #: reclaim once budget frees up (train/serve unification)
        self._yielded: Dict[str, int] = {p.name: 0 for p in populations}

    # -- observation --------------------------------------------------------
    def _observe(self) -> Dict[str, Optional[Dict[str, Any]]]:
        out: Dict[str, Optional[Dict[str, Any]]] = {}
        for pop in self.populations:
            try:
                out[pop.name] = pop.probe()
            except Exception as e:  # noqa: BLE001 - a down plane skips a tick
                log.warning("population %s probe failed: %s", pop.name, e)
                out[pop.name] = None
        return out

    @staticmethod
    def _member_names(ob: Dict[str, Any]) -> List[str]:
        names = []
        for m in ob.get("members") or ():
            names.append(m["worker"] if isinstance(m, dict) else str(m))
        return names

    def _churn(self) -> int:
        return (sum(len(d) for d in self._spawning.values())
                + sum(len(d) for d in self._draining.values()))

    def _cooled(self, pop: Population, action: str, now: float) -> bool:
        last = self._last_action.get((pop.name, action))
        return last is None or now - last >= self.cooldown_s

    # -- the tick -----------------------------------------------------------
    def step(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One control tick; returns the journal entries it committed."""
        now = self._clock() if now is None else now
        committed: List[Dict[str, Any]] = []
        observations = self._observe()
        demands: Dict[str, int] = {}
        urgent: set = set()
        live: Dict[str, int] = {}
        effective: Dict[str, int] = {}
        for pop in self.populations:
            ob = observations[pop.name]
            if ob is None:
                continue
            names = set(self._member_names(ob))
            self._reap(pop, names, now, committed)
            n_live = len(names)
            draining_live = sum(1 for w in self._draining[pop.name]
                                if w in names)
            eff = n_live + len(self._spawning[pop.name]) - draining_live
            live[pop.name] = n_live
            effective[pop.name] = eff
            base = pop.target if pop.target is not None else n_live
            rec = ob.get("recommendation") or None
            action = (rec or {}).get("action")
            if action == "join":
                base = max(base, n_live + 1)
            elif action == "leave":
                base = min(base, n_live - 1)
            if any(r in SLO_BURN_RULES for r in ob.get("alerts") or ()):
                urgent.add(pop.name)
                base = max(base, n_live + 1)
            desired = max(pop.min_workers, min(pop.max_workers, base))
            delta = desired - eff
            if delta > 0:
                demands[pop.name] = delta
            elif delta < 0:
                # a spawn still inside its grace window counts toward
                # `eff` (it is capacity in flight) but is NOT a drainable
                # worker: clamp the drain to the LIVE surplus so a
                # `leave` racing a very slow boot never double-counts the
                # unjoined spawn and retires an extra live member. The
                # spawn either joins (next tick re-evaluates the real
                # surplus) or its grace reaps it.
                surplus_live = max(0, (n_live - draining_live) - desired)
                want = min(-delta, surplus_live)
                if want > 0:
                    self._drain_surplus(pop, ob, want, now, committed,
                                        reason=self._drain_reason(pop, rec))
        self._spawn_demand(demands, urgent, effective, observations, now,
                           committed)
        self.journal.extend(committed)
        self._report(committed)
        return committed

    def _drain_reason(self, pop: Population, rec) -> str:
        if rec is not None and rec.get("action") == "leave":
            return f"recommendation: {rec.get('reason', 'leave')}"
        return "over target (scale in)"

    # -- reaping in-flight churn --------------------------------------------
    def _reap(self, pop: Population, names: set, now: float,
              committed: List[Dict[str, Any]]) -> None:
        spawning = self._spawning[pop.name]
        for w in list(spawning):
            pend = spawning[w]
            if w in names:
                del spawning[w]            # joined: spawn confirmed
            elif not pop.backend.alive(pend.handle) or now >= pend.deadline:
                del spawning[w]
                obs.count("cluster.actor_failures_total", action="spawn")
                committed.append(self._entry(
                    now, "spawn_failed", pop.name, w,
                    "process died or never joined within grace"))
                self._last_action[(pop.name, "spawn")] = now
        draining = self._draining[pop.name]
        for w in list(draining):
            pend = draining[w]
            if w not in names and not pop.backend.alive(pend.handle):
                del draining[w]            # left AND exited: drain done
            elif w not in names:
                del draining[w]            # left; the lease reaps the rest
            elif now >= pend.deadline:
                del draining[w]
                pop.backend.kill(pend.handle)
                obs.count("cluster.actor_failures_total", action="drain")
                committed.append(self._entry(
                    now, "evict", pop.name, w,
                    "drain overstayed grace; escalated to kill"))

    # -- scale in -----------------------------------------------------------
    def _drain_surplus(self, pop: Population, ob: Dict[str, Any],
                       want: int, now: float,
                       committed: List[Dict[str, Any]], *,
                       reason: str) -> None:
        for _ in range(want):
            if not self._drain_one(pop, ob, now, committed, reason=reason):
                return

    def _drain_one(self, pop: Population, ob: Dict[str, Any], now: float,
                   committed: List[Dict[str, Any]], *,
                   reason: str) -> bool:
        """Gated graceful drain of the newest live member; False when a
        gate (cooldown / churn cap / safety floor) refuses."""
        if self._churn() >= self.max_churn or \
                not self._cooled(pop, "drain", now):
            return False
        draining = self._draining[pop.name]
        members = [m for m in ob.get("members") or ()
                   if isinstance(m, dict)
                   and m.get("worker") not in draining]
        if not members:
            return False
        remaining = len(members) - 1 + \
            sum(1 for w in draining
                if w in set(self._member_names(ob)))
        if remaining < pop.min_workers:
            return False
        if remaining < 1 and ob.get("busy"):
            return False   # never retire the last busy worker
        # newest incarnation leaves first (max token): deterministic, and
        # the longest-lived member keeps any warmed caches
        victim = max(members, key=lambda m: (m.get("token") or 0,
                                             m["worker"]))["worker"]
        handle = self._find_handle(pop, victim) or SpawnHandle(
            worker=victim, population=pop.name)
        try:
            faults.fire("actor.drain")
            pop.backend.drain(handle)
        except Exception as e:  # noqa: BLE001 - chaos or backend refusal
            obs.count("cluster.actor_failures_total", action="drain")
            log.warning("drain of %s (%s) failed: %s", victim, pop.name, e)
            self._last_action[(pop.name, "drain")] = now
            return False
        draining[victim] = _Pending(handle=handle,
                                    deadline=now + self.drain_grace_s)
        self._last_action[(pop.name, "drain")] = now
        committed.append(self._entry(now, "drain", pop.name, victim, reason))
        return True

    def _find_handle(self, pop: Population,
                     worker: str) -> Optional[SpawnHandle]:
        return self._handles[pop.name].get(worker)

    # -- scale out ----------------------------------------------------------
    def _spawn_demand(self, demands: Dict[str, int], urgent: set,
                      effective: Dict[str, int],
                      observations: Dict[str, Optional[Dict[str, Any]]],
                      now: float,
                      committed: List[Dict[str, Any]]) -> None:
        if not demands:
            return
        if self.total_workers is None:
            supply = sum(demands.values())
        else:
            supply = max(0, self.total_workers
                         - sum(effective.values()))
        grants = self.scheduler.allocate(supply, demands, urgent)
        by_name = {p.name: p for p in self.populations}
        for pname in sorted(demands, key=lambda q: (q not in urgent, q)):
            pop = by_name[pname]
            granted = grants.get(pname, 0)
            # the cooldown gates the TICK, not each spawn within it: a
            # granted batch (e.g. restoring a half-killed pool) commits
            # together under the churn cap, then the pop cools down
            if granted > 0 and self._cooled(pop, "spawn", now):
                for _ in range(granted):
                    if not self._spawn_one(pop, now, committed):
                        break
            unmet = demands[pname] - granted
            if unmet > 0 and pname in urgent and \
                    self.total_workers is not None:
                self._yield_for(pop, effective, urgent, now, committed)

    def _spawn_one(self, pop: Population, now: float,
                   committed: List[Dict[str, Any]]) -> bool:
        if self._churn() >= self.max_churn:
            return False
        self._spawn_seq += 1
        worker = f"{pop.prefix()}{self._spawn_seq}"
        reason = "scale out"
        if self._yielded[pop.name] > 0:
            reason = "reclaim: capacity yielded to serving returns"
        try:
            faults.fire("actor.spawn")
            handle = pop.backend.spawn(worker, pop.name)
        except Exception as e:  # noqa: BLE001 - chaos or backend refusal
            obs.count("cluster.actor_failures_total", action="spawn")
            self._last_action[(pop.name, "spawn")] = now
            committed.append(self._entry(
                now, "spawn_failed", pop.name, worker, f"spawn raised: {e}"))
            return False
        if self._yielded[pop.name] > 0:
            self._yielded[pop.name] -= 1
        self._spawning[pop.name][worker] = _Pending(
            handle=handle, deadline=now + self.spawn_grace_s)
        self._handles[pop.name][worker] = handle
        while len(self._handles[pop.name]) > 4 * pop.max_workers:
            self._handles[pop.name].pop(next(iter(self._handles[pop.name])))
        self._last_action[(pop.name, "spawn")] = now
        committed.append(self._entry(now, "spawn", pop.name, worker, reason))
        return True

    def _yield_for(self, pop: Population, effective: Dict[str, int],
                   urgent: set, now: float,
                   committed: List[Dict[str, Any]]) -> None:
        """Budget exhausted and ``pop`` is burning its SLO: drain one
        worker from the lowest-weight non-urgent population over its
        floor, freeing a slot the next tick's allocation will grant."""
        by_name = {p.name: p for p in self.populations}
        floors = {p.name: p.min_workers for p in self.populations}
        victim_name = self.scheduler.preempt(effective, floors, pop.name,
                                             urgent)
        if victim_name is None:
            return
        victim_pop = by_name[victim_name]
        ob = None
        try:
            ob = victim_pop.probe()
        except Exception:  # noqa: BLE001
            return
        if self._drain_one(victim_pop, ob, now, committed,
                           reason=f"yield: {pop.name} SLO burn pre-empts "
                                  f"batch capacity"):
            self._yielded[victim_name] += 1

    # -- journal + reporting ------------------------------------------------
    def _entry(self, now: float, action: str, population: str,
               worker: str, reason: str) -> Dict[str, Any]:
        return {"ts": now, "actor": self.name, "action": action,
                "population": population, "worker": worker,
                "reason": reason,
                "signal": ACTION_SIGNAL.get(action, 0.0)}

    def _report(self, committed: List[Dict[str, Any]]) -> None:
        by_name = {p.name: p for p in self.populations}
        for entry in committed:
            pop = by_name.get(entry["population"])
            if pop is None or pop.reporter is None:
                continue
            try:
                pop.reporter(entry)
            except StaleMemberError:
                # a newer actor registered: single-writer fencing — stop
                # acting rather than fight it for the fleet
                log.error("actor %s deposed (a newer actor registered); "
                          "stopping", self.name)
                self.deposed = True
                return
            except Exception as e:  # noqa: BLE001 - telemetry best-effort
                log.warning("act_report failed: %s", e)

    # -- the loop -----------------------------------------------------------
    def run(self, stop: Optional[threading.Event] = None,
            max_ticks: Optional[int] = None) -> None:
        stop = stop or threading.Event()
        ticks = 0
        while not stop.is_set() and not self.deposed:
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                return
            stop.wait(self.interval_s)


# -- control-plane probes ---------------------------------------------------

class MasterProbe:
    """Observation off an elastic master (or any membership-bearing
    MasterServer): ``mbr_view`` supplies members + the hysteresis-stable
    recommendation, ``obs_health`` the firing alerts, and the
    recommendation's own backlog field answers busyness."""

    def __init__(self, host: str, port: int, *, client=None):
        from ..runtime.membership import MembershipClient
        self._client = client or MembershipClient(
            host, int(port), retries=1, call_timeout=3.0)

    def __call__(self) -> Dict[str, Any]:
        view = self._client.cluster_view()
        rec = view.get("recommendation") or None
        alerts: List[str] = []
        try:
            h = self._client.obs_health()
            alerts = [str(a.get("rule")) for a in h.get("active", ())]
        except Exception:  # noqa: BLE001 - health plane optional
            pass
        busy = bool(rec and (rec.get("backlog") or 0) > 0)
        return {"members": view.get("members") or [],
                "recommendation": rec, "alerts": alerts, "busy": busy}

    def close(self) -> None:
        self._client.close()


class RouterProbe:
    """Observation off a PR 17 router's decode pool.

    The router's membership answers who is in the pool; ``route_stats``
    answers busyness (in-flight streams). The TTFT/TPOT burn-rate
    alerts, though, fire on each DAEMON's own aggregator (the daemon
    self-pushes its serving histograms) — so the probe polls every
    member's rpc endpoint (from its join caps) for ``obs_health`` and
    merges the firing rule names, caching one fail-fast telemetry client
    per endpoint."""

    def __init__(self, host: str, port: int, *, role: str = "decode",
                 client=None):
        from ..runtime.membership import MembershipClient
        self.role = role
        self._client = client or MembershipClient(
            host, int(port), retries=1, call_timeout=3.0)
        self._workers: Dict[Tuple[str, int], Any] = {}

    def _worker_client(self, host: str, port: int):
        from ..obs.aggregate import telemetry_client
        key = (host, int(port))
        if key not in self._workers:
            self._workers[key] = telemetry_client(*key)
        return self._workers[key]

    def __call__(self) -> Dict[str, Any]:
        view = self._client.cluster_view()
        members = [m for m in view.get("members") or ()
                   if (m.get("caps") or {}).get("role") == self.role]
        alerts: List[str] = []
        for m in members:
            caps = m.get("caps") or {}
            host, port = caps.get("rpc_host"), caps.get("rpc_port")
            if not host or not port:
                continue
            try:
                h = self._worker_client(host, port).obs_health()
                alerts.extend(str(a.get("rule"))
                              for a in h.get("active", ()))
            except Exception:  # noqa: BLE001 - a dead member answers nothing
                continue
        try:
            h = self._client.obs_health()
            alerts.extend(str(a.get("rule")) for a in h.get("active", ()))
        except Exception:  # noqa: BLE001
            pass
        busy = False
        try:
            rs = self._client._call({"op": "route_stats"})
            busy = int(rs.get("inflight", 0)) > 0
        except Exception:  # noqa: BLE001
            pass
        return {"members": members,
                "recommendation": view.get("recommendation") or None,
                "alerts": sorted(set(alerts)), "busy": busy}

    def close(self) -> None:
        self._client.close()
        for c in self._workers.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        self._workers.clear()


class ActorReporter:
    """Per-population action reporter: registers this actor with the
    population's master (``act_register``, single-writer) and forwards
    each committed action through ``act_report`` so the master journals it
    (the ``cluster.autoscale_committed`` satellite). A fencing refusal
    (a newer actor took over) propagates as StaleMemberError — the
    actor's cue to stand down."""

    def __init__(self, host: str, port: int, actor: str, *, client=None):
        from ..runtime.membership import MembershipClient
        self.actor = actor
        self._client = client or MembershipClient(
            host, int(port), retries=1, call_timeout=3.0)
        self._token: Optional[int] = None

    def __call__(self, entry: Dict[str, Any]) -> None:
        if self._token is None:
            self._token, _ = self._client.act_register(self.actor)
        self._client.act_report(
            self.actor, self._token, action=entry.get("action", ""),
            population=entry.get("population", ""),
            worker=entry.get("worker", ""),
            reason=entry.get("reason", ""),
            signal=float(entry.get("signal", 0.0)))

    def close(self) -> None:
        self._client.close()
