"""Weighted-fair deficit scheduling for WORKERS-WITHIN-A-FLEET.

This is PR 12's slots-within-an-engine scheduler
(:meth:`paddle_tpu.serving.engine.ServingEngine.admit_prefill`) lifted one
level up: the resource is no longer a decode slot but a whole worker
process, the classes are no longer SLO tenants but fleet POPULATIONS
(elastic-DP training, decode-pool serving), and the quantum is one
worker. The invariants carry over unchanged:

* each population with unmet demand accrues ``weight * quantum`` credit
  per scheduling round, capped at ``8 * quantum * weight`` (no unbounded
  banking across idle stretches);
* credit resets while a population has nothing to ask for
  (work-conserving — batch training soaks ALL idle capacity when serving
  is quiet, at zero stored debt);
* grants debit the winner's balance by the worker cost, so interactive
  serving pre-empts queued batch growth at the weight ratio without ever
  idling a free worker;
* URGENT populations (a firing TTFT/TPOT burn-rate alert) are served
  before any credit comparison — an SLO burn is the fleet-level analogue
  of interactive head-of-line traffic.

:meth:`FleetScheduler.preempt` is the piece slots never needed: when an
urgent population wants a worker and the fleet budget is exhausted, it
names the victim population (lowest weight first, never urgent, never
below its floor) whose worker the actor should drain — the train/serve
YIELD protocol (docs/design/fleet.md).
"""
from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional, Tuple

#: default population weights — interactive serving outweighs batch
#: training 4:1, the same ratio PR 12 ships for slots
DEFAULT_WEIGHTS = {"serve": 4.0, "train": 1.0}

#: cost of one grant, in credit units (one worker)
WORKER_COST = 1.0


class FleetScheduler:
    """Deficit round-robin over fleet populations.

    Deterministic: ties break on population name, and the credit state
    is exposed (``credits()``) so tests can assert the banking bounds.
    """

    def __init__(self, weights: Optional[Mapping[str, float]] = None,
                 *, quantum: float = 1.0):
        self.weights: Dict[str, float] = dict(weights or DEFAULT_WEIGHTS)
        self.quantum = float(quantum)
        self._credit: Dict[str, float] = {}

    def weight(self, population: str) -> float:
        return float(self.weights.get(population, 1.0))

    def credits(self) -> Dict[str, float]:
        return dict(self._credit)

    # -- allocation ---------------------------------------------------------
    def allocate(self, supply: int, demands: Mapping[str, int],
                 urgent: Iterable[str] = ()) -> Dict[str, int]:
        """Split ``supply`` spawnable workers across ``demands``.

        ``demands`` maps population -> workers wanted (non-positive
        entries are treated as no demand and reset that population's
        bank). ``urgent`` populations are granted first, before any
        deficit comparison. Returns population -> granted count; the sum
        never exceeds ``supply``.
        """
        urgent = set(urgent)
        want = {p: int(n) for p, n in demands.items() if int(n) > 0}
        grants = {p: 0 for p in demands}
        for p in set(self._credit) | set(demands):
            if p not in want:
                self._credit[p] = 0.0          # no banking while idle
        supply = max(0, int(supply))
        # urgent head-of-line: an SLO burn never waits on credit
        for p in sorted(want, key=lambda q: (q not in urgent, q)):
            if supply <= 0 or p not in urgent:
                break
            take = min(want[p], supply)
            grants[p] += take
            supply -= take
            self._credit[p] = self._credit.get(p, 0.0) - take * WORKER_COST
        # deficit rounds over whatever budget is left
        while supply > 0:
            avail = [p for p in sorted(want)
                     if want[p] - grants[p] > 0]
            if not avail:
                break
            for p in avail:
                w = self.weight(p)
                self._credit[p] = min(
                    self._credit.get(p, 0.0) + self.quantum * w,
                    8 * self.quantum * w)
            p = max(avail, key=lambda q: (self._credit[q], q))
            grants[p] += 1
            supply -= 1
            self._credit[p] -= WORKER_COST
        return grants

    # -- preemption (the yield protocol) ------------------------------------
    def preempt(self, current: Mapping[str, int],
                floors: Mapping[str, int], for_population: str,
                urgent: Iterable[str] = ()) -> Optional[str]:
        """Name the population that should YIELD one worker to
        ``for_population``, or None when nobody legally can.

        A victim must not be the requester, must not itself be urgent,
        and must hold more workers than its floor (``min_workers`` — the
        byte-stable training floor is still a floor). Lowest weight
        loses first; ties break on name for determinism.
        """
        urgent = set(urgent)
        candidates = [
            p for p, n in current.items()
            if p != for_population and p not in urgent
            and int(n) > int(floors.get(p, 0))]
        if not candidates:
            return None
        return min(candidates, key=lambda p: (self.weight(p), p))
