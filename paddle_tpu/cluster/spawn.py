"""The injectable spawn seam: how the fleet actor creates and retires
worker PROCESSES.

The actor (actor.py) never talks to an OS or an orchestrator directly —
it calls the four-method :class:`SpawnBackend` protocol and lets the
backend own process lifecycle. That makes a k8s/cloud backend a CONFIG
(hand :class:`HookSpawnBackend` four callables that wrap your API), not
a fork of the actor loop, and lets every chaos test drive the actor with
an in-memory backend under a fake clock.

Contract (docs/design/fleet.md):

* ``spawn(worker, population)`` starts a process that will JOIN the
  population's membership plane under exactly ``worker`` — the actor's
  success oracle is the name appearing in ``mbr_view``, never the
  backend's own opinion;
* ``drain(handle)`` requests a GRACEFUL stop (SIGTERM locally): the
  worker finishes in-flight work, leaves via membership, then exits.
  Must be non-blocking and idempotent;
* ``kill(handle)`` is the escalation after the drain grace expires
  (SIGKILL locally) — membership's TTL lease reaps the corpse;
* ``alive(handle)`` answers whether the process still exists; a dead
  handle whose worker never joined is a SPAWN FAILURE.

Both actor-side call sites fire the ``actor.spawn`` / ``actor.drain``
fault sites first, so spawn failures and hung drains are chaos-injectable
(faults.md) no matter which backend is plugged in.
"""
from __future__ import annotations

import shlex
import signal
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class SpawnHandle:
    """What a backend returns from ``spawn``: the worker name the process
    must join membership under, plus backend-private state."""
    worker: str
    population: str
    payload: Any = None
    meta: Dict[str, Any] = field(default_factory=dict)


class SpawnBackend:
    """Abstract process-lifecycle seam (see module docstring)."""

    def spawn(self, worker: str, population: str) -> SpawnHandle:
        raise NotImplementedError

    def drain(self, handle: SpawnHandle) -> None:
        raise NotImplementedError

    def kill(self, handle: SpawnHandle) -> None:
        raise NotImplementedError

    def alive(self, handle: SpawnHandle) -> bool:
        raise NotImplementedError


class HookSpawnBackend(SpawnBackend):
    """The config-not-a-fork backend: four injected callables.

    ``spawn_fn(worker, population) -> payload`` (stored on the handle),
    ``drain_fn(handle)``, ``kill_fn(handle)``, ``alive_fn(handle) ->
    bool``. Unset hooks degrade safely: drain/kill become no-ops and
    alive answers True (membership remains the authority).
    """

    def __init__(self, spawn_fn: Callable[[str, str], Any],
                 drain_fn: Optional[Callable[[SpawnHandle], None]] = None,
                 kill_fn: Optional[Callable[[SpawnHandle], None]] = None,
                 alive_fn: Optional[Callable[[SpawnHandle], bool]] = None):
        self._spawn = spawn_fn
        self._drain = drain_fn
        self._kill = kill_fn
        self._alive = alive_fn

    def spawn(self, worker: str, population: str) -> SpawnHandle:
        payload = self._spawn(worker, population)
        return SpawnHandle(worker=worker, population=population,
                           payload=payload)

    def drain(self, handle: SpawnHandle) -> None:
        if self._drain is not None:
            self._drain(handle)

    def kill(self, handle: SpawnHandle) -> None:
        if self._kill is not None:
            self._kill(handle)

    def alive(self, handle: SpawnHandle) -> bool:
        return True if self._alive is None else bool(self._alive(handle))


class SubprocessSpawnBackend(SpawnBackend):
    """Local deployment: one OS process per worker.

    ``template`` is the launch command with a ``{worker}`` placeholder,
    e.g. ``"{python} -m paddle_tpu serve --router H:P --worker {worker}
    ..."`` — ``{python}`` expands to the running interpreter. Drain is
    SIGTERM (both the elastic worker and the serving daemon translate it
    into finish-in-flight → membership leave → exit), kill is SIGKILL.
    """

    def __init__(self, template: str, *, popen=subprocess.Popen,
                 stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL):
        self.template = template
        self._popen = popen
        self._stdout = stdout
        self._stderr = stderr
        self.procs: List[subprocess.Popen] = []

    def argv(self, worker: str) -> List[str]:
        return shlex.split(self.template.format(
            worker=worker, python=sys.executable))

    def spawn(self, worker: str, population: str) -> SpawnHandle:
        proc = self._popen(self.argv(worker), stdout=self._stdout,
                           stderr=self._stderr)
        self.procs.append(proc)
        return SpawnHandle(worker=worker, population=population,
                           payload=proc)

    def drain(self, handle: SpawnHandle) -> None:
        proc = handle.payload
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except (OSError, ValueError):
                pass

    def kill(self, handle: SpawnHandle) -> None:
        proc = handle.payload
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass

    def alive(self, handle: SpawnHandle) -> bool:
        proc = handle.payload
        return proc is not None and proc.poll() is None

    def reap(self) -> None:
        """Wait out exited children (no zombies in long actor runs)."""
        for proc in self.procs:
            if proc.poll() is not None:
                try:
                    proc.wait(timeout=0)
                except Exception:
                    pass
        self.procs = [p for p in self.procs if p.poll() is None]
