from .lod import (NestedSeqBatch, SeqBatch, bucket_length, lengths_from_lod,
                  lod_from_lengths, pack_nested_sequences, pack_sequences,
                  sequence_mask)
from .place import CPUPlace, DeviceContext, Place, TPUPlace, default_place

__all__ = [
    "SeqBatch", "NestedSeqBatch", "sequence_mask", "pack_sequences",
    "pack_nested_sequences", "bucket_length",
    "lod_from_lengths", "lengths_from_lod",
    "Place", "TPUPlace", "CPUPlace", "DeviceContext", "default_place",
]
