from .lod import (LoDBatch, NestedSeqBatch, SeqBatch, bucket_length,
                  lengths_from_lod, lod_batch_from_offsets,
                  lod_batch_to_offsets, lod_from_lengths, pack_lod,
                  pack_nested_sequences, pack_sequences, sequence_mask,
                  unpack_lod)
from .place import CPUPlace, DeviceContext, Place, TPUPlace, default_place

__all__ = [
    "SeqBatch", "NestedSeqBatch", "LoDBatch", "sequence_mask",
    "pack_sequences", "pack_nested_sequences", "pack_lod", "unpack_lod",
    "lod_batch_from_offsets", "lod_batch_to_offsets", "bucket_length",
    "lod_from_lengths", "lengths_from_lod",
    "Place", "TPUPlace", "CPUPlace", "DeviceContext", "default_place",
]
