"""Variable-length (LoD) sequence batches under XLA's static-shape regime.

The reference represents ragged minibatches without padding:
``Argument.sequenceStartPositions`` / ``subSequenceStartPositions``
(paddle/parameter/Argument.h:84-90) in gen-1 and ``LoDTensor`` — tensor + level-of-detail
nested offsets — in gen-2 (paddle/framework/lod_tensor.h:57,82). Layers then re-pack
sequences to step-major batches (gserver/layers/SequenceToBatch.cpp,
operators/math/sequence2batch.cc).

On TPU, compiled shapes must be static, so the canonical batch form here is
**padded-dense + lengths (+ nested lod kept host-side)**:

* ``data``:   [batch, max_len, ...] padded along the time axis
* ``lengths``:[batch] int32 valid lengths
* ``lod``:    optional tuple of host-side offset tuples for nesting levels >= 2
              (level 0 is implied by ``lengths``)

``SeqBatch`` is a pytree, so it flows through jit/grad/pjit. Masking helpers replace the
reference's shrink-live-batch machinery (lod_rank_table + shrink_rnn_memory_op):
sorting-by-length is unnecessary when every step is masked, and XLA pads the cost away
in fused elementwise work.

Bucketing (``bucket_length``) bounds the number of distinct compiled shapes — the analog
of the reference's shape-keyed recompile avoidance concern (SURVEY §7 hard parts).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclass
class SeqBatch:
    """A padded ragged batch: data [B, T, ...] + lengths [B]. Two-level
    nesting lives in :class:`NestedSeqBatch` below."""

    data: jax.Array
    lengths: jax.Array

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, lengths = children
        return cls(data, lengths)

    # -- shape helpers -----------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, T] 1.0 where a timestep is valid."""
        return sequence_mask(self.lengths, self.max_len, dtype)


def sequence_mask(lengths: jax.Array, max_len: int, dtype=jnp.float32) -> jax.Array:
    """[B, T] validity mask from lengths — the workhorse replacing LoD offsets on device."""
    pos = jnp.arange(max_len, dtype=lengths.dtype)
    return (pos[None, :] < lengths[:, None]).astype(dtype)


def bucket_length(n: int, buckets: Sequence[int] = (8, 16, 32, 64, 128, 256, 512, 1024)) -> int:
    """Round a max sequence length up to a fixed bucket to bound recompiles."""
    for b in buckets:
        if n <= b:
            return b
    return int(n)


def pack_sequences(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
                   pad_value=0, bucket: bool = True) -> SeqBatch:
    """Host-side: list of per-example [len, ...] arrays -> padded SeqBatch.

    The feeder-side analog of DataProviderConverter building an Argument
    (py_paddle/dataprovider_converter.py:247).
    """
    if not seqs:
        raise ValueError("pack_sequences: empty sequence list")
    seqs = [np.asarray(s) for s in seqs]
    lengths = np.array([s.shape[0] for s in seqs], dtype=np.int32)
    tmax = int(max_len if max_len is not None else max(1, lengths.max(initial=1)))
    if bucket and max_len is None:
        tmax = bucket_length(tmax)
    feat_shape = seqs[0].shape[1:]
    out = np.full((len(seqs), tmax) + feat_shape, pad_value, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], tmax)
        out[i, :n] = s[:n]
        lengths[i] = n
    return SeqBatch(jnp.asarray(out), jnp.asarray(lengths))


def lod_from_lengths(lengths: Sequence[int]) -> Tuple[int, ...]:
    """Offsets vector from lengths — same shape as LoD level offsets
    (framework/lod_tensor.h:57)."""
    off = [0]
    for n in lengths:
        off.append(off[-1] + int(n))
    return tuple(off)


def lengths_from_lod(offsets: Sequence[int]) -> Tuple[int, ...]:
    return tuple(int(offsets[i + 1] - offsets[i]) for i in range(len(offsets) - 1))


# =============================================================================
# Nested sequences (2-level LoD) — the reference's subSequenceStartPositions
# (parameter/Argument.h:84-90) / multi-level LoDTensor (framework/lod_tensor.h:57)
# under the static-shape regime: one more padded axis instead of offset vectors.
# =============================================================================

@jax.tree_util.register_pytree_node_class
@dataclass
class NestedSeqBatch:
    """A padded batch of sequences of sub-sequences.

    * ``data``:        [B, S, T, ...] — S = max sub-sequences per example,
                       T = max sub-sequence length
    * ``sub_lengths``: [B, S] int32 — valid length of each sub-sequence
                       (0 for padding sub-sequences)
    * ``seq_lengths``: [B] int32 — number of valid sub-sequences per example

    The sub-sequence axis IS a sequence axis: after per-sub-sequence reduction
    (pool / last-step / inner RNN) the result [B, S, D] + seq_lengths is an
    ordinary :class:`SeqBatch` over sub-sequence summaries — this is how the
    reference's nested recurrent_group composes (config_parser.py:319-387).
    """

    data: jax.Array
    sub_lengths: jax.Array
    seq_lengths: jax.Array

    def tree_flatten(self):
        return (self.data, self.sub_lengths, self.seq_lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- shape helpers -----------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_subseqs(self) -> int:
        return self.data.shape[1]

    @property
    def max_sublen(self) -> int:
        return self.data.shape[2]

    def inner_mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, S, T] validity of each timestep."""
        pos = jnp.arange(self.max_sublen, dtype=self.sub_lengths.dtype)
        return (pos[None, None, :] < self.sub_lengths[:, :, None]).astype(dtype)

    def outer_mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, S] validity of each sub-sequence."""
        return sequence_mask(self.seq_lengths, self.max_subseqs, dtype)

    # -- level moves -------------------------------------------------------
    def inner_flat(self) -> SeqBatch:
        """View sub-sequences as a flat batch [B*S, T, ...] — the input shape
        for any single-level sequence op (inner RNN, pooling, conv). Padding
        sub-sequences ride along with length 0 and mask to nothing."""
        d = self.data.reshape((self.batch_size * self.max_subseqs,)
                              + self.data.shape[2:])
        return SeqBatch(d, self.sub_lengths.reshape(-1))

    def outer(self, per_subseq: jax.Array) -> SeqBatch:
        """Lift per-sub-sequence values [B*S, ...] (from an op applied to
        ``inner_flat()``) to the outer sequence [B, S, ...] + seq_lengths."""
        return SeqBatch(
            per_subseq.reshape((self.batch_size, self.max_subseqs)
                               + per_subseq.shape[1:]),
            self.seq_lengths)


def pack_nested_sequences(nested, max_subseqs: Optional[int] = None,
                          max_sublen: Optional[int] = None, pad_value=0,
                          bucket: bool = True) -> NestedSeqBatch:
    """Host-side: list (batch) of lists (sub-sequences) of [len, ...] arrays
    -> NestedSeqBatch. The 2-level analog of :func:`pack_sequences`."""
    if not nested:
        raise ValueError("pack_nested_sequences: empty batch")
    nested = [[np.asarray(s) for s in sample] for sample in nested]
    B = len(nested)
    S = max(1, max(len(sample) for sample in nested))
    T = max(1, max((s.shape[0] for sample in nested for s in sample),
                   default=1))
    if max_subseqs is not None:
        S = max_subseqs
    elif bucket:
        # bucket the sub-seq axis too — every distinct S is a new compiled shape
        S = bucket_length(S, buckets=(2, 4, 8, 16, 32, 64))
    if max_sublen is not None:
        T = max_sublen
    elif bucket:
        T = bucket_length(T)
    # feature shape/dtype from the first NON-empty sub-sequence (an empty
    # leading sub-sequence must not dictate the layout)
    first = next((s for sample in nested for s in sample if s.shape[0] > 0),
                 None)
    if first is None:
        first = next((s for sample in nested for s in sample), None)
    if first is None:
        raise ValueError("pack_nested_sequences: no sub-sequences in batch")
    feat = first.shape[1:]
    data = np.full((B, S, T) + feat, pad_value, dtype=first.dtype)
    sub_lengths = np.zeros((B, S), np.int32)
    seq_lengths = np.zeros((B,), np.int32)
    for b, sample in enumerate(nested):
        seq_lengths[b] = min(len(sample), S)
        for s, sub in enumerate(sample[:S]):
            n = min(sub.shape[0], T)
            if n > 0:
                data[b, s, :n] = sub[:n]
            sub_lengths[b, s] = n
    return NestedSeqBatch(jnp.asarray(data), jnp.asarray(sub_lengths),
                          jnp.asarray(seq_lengths))
