"""Variable-length (LoD) sequence batches under XLA's static-shape regime.

The reference represents ragged minibatches without padding:
``Argument.sequenceStartPositions`` / ``subSequenceStartPositions``
(paddle/parameter/Argument.h:84-90) in gen-1 and ``LoDTensor`` — tensor + level-of-detail
nested offsets — in gen-2 (paddle/framework/lod_tensor.h:57,82). Layers then re-pack
sequences to step-major batches (gserver/layers/SequenceToBatch.cpp,
operators/math/sequence2batch.cc).

On TPU, compiled shapes must be static, so the canonical batch form here is
**padded-dense + lengths (+ nested lod kept host-side)**:

* ``data``:   [batch, max_len, ...] padded along the time axis
* ``lengths``:[batch] int32 valid lengths
* ``lod``:    optional tuple of host-side offset tuples for nesting levels >= 2
              (level 0 is implied by ``lengths``)

``SeqBatch`` is a pytree, so it flows through jit/grad/pjit. Masking helpers replace the
reference's shrink-live-batch machinery (lod_rank_table + shrink_rnn_memory_op):
sorting-by-length is unnecessary when every step is masked, and XLA pads the cost away
in fused elementwise work.

Bucketing (``bucket_length``) bounds the number of distinct compiled shapes — the analog
of the reference's shape-keyed recompile avoidance concern (SURVEY §7 hard parts).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclass
class SeqBatch:
    """A padded ragged batch: data [B, T, ...] + lengths [B]."""

    data: jax.Array
    lengths: jax.Array
    # host-side nested offsets for sub-sequences (gen-2 LoD levels beyond the first);
    # static metadata, not traced.
    lod: Optional[Tuple[Tuple[int, ...], ...]] = None

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.lengths), self.lod

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, lengths = children
        return cls(data, lengths, aux)

    # -- shape helpers -----------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, T] 1.0 where a timestep is valid."""
        return sequence_mask(self.lengths, self.max_len, dtype)


def sequence_mask(lengths: jax.Array, max_len: int, dtype=jnp.float32) -> jax.Array:
    """[B, T] validity mask from lengths — the workhorse replacing LoD offsets on device."""
    pos = jnp.arange(max_len, dtype=lengths.dtype)
    return (pos[None, :] < lengths[:, None]).astype(dtype)


def bucket_length(n: int, buckets: Sequence[int] = (8, 16, 32, 64, 128, 256, 512, 1024)) -> int:
    """Round a max sequence length up to a fixed bucket to bound recompiles."""
    for b in buckets:
        if n <= b:
            return b
    return int(n)


def pack_sequences(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
                   pad_value=0, bucket: bool = True) -> SeqBatch:
    """Host-side: list of per-example [len, ...] arrays -> padded SeqBatch.

    The feeder-side analog of DataProviderConverter building an Argument
    (py_paddle/dataprovider_converter.py:247).
    """
    if not seqs:
        raise ValueError("pack_sequences: empty sequence list")
    seqs = [np.asarray(s) for s in seqs]
    lengths = np.array([s.shape[0] for s in seqs], dtype=np.int32)
    tmax = int(max_len if max_len is not None else max(1, lengths.max(initial=1)))
    if bucket and max_len is None:
        tmax = bucket_length(tmax)
    feat_shape = seqs[0].shape[1:]
    out = np.full((len(seqs), tmax) + feat_shape, pad_value, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], tmax)
        out[i, :n] = s[:n]
        lengths[i] = n
    return SeqBatch(jnp.asarray(out), jnp.asarray(lengths))


def lod_from_lengths(lengths: Sequence[int]) -> Tuple[int, ...]:
    """Offsets vector from lengths — same shape as LoD level offsets
    (framework/lod_tensor.h:57)."""
    off = [0]
    for n in lengths:
        off.append(off[-1] + int(n))
    return tuple(off)


def lengths_from_lod(offsets: Sequence[int]) -> Tuple[int, ...]:
    return tuple(int(offsets[i + 1] - offsets[i]) for i in range(len(offsets) - 1))
