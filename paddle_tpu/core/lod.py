"""Variable-length (LoD) sequence batches under XLA's static-shape regime.

The reference represents ragged minibatches without padding:
``Argument.sequenceStartPositions`` / ``subSequenceStartPositions``
(paddle/parameter/Argument.h:84-90) in gen-1 and ``LoDTensor`` — tensor + level-of-detail
nested offsets — in gen-2 (paddle/framework/lod_tensor.h:57,82). Layers then re-pack
sequences to step-major batches (gserver/layers/SequenceToBatch.cpp,
operators/math/sequence2batch.cc).

On TPU, compiled shapes must be static, so the canonical batch form here is
**padded-dense + lengths (+ nested lod kept host-side)**:

* ``data``:   [batch, max_len, ...] padded along the time axis
* ``lengths``:[batch] int32 valid lengths
* ``lod``:    optional tuple of host-side offset tuples for nesting levels >= 2
              (level 0 is implied by ``lengths``)

``SeqBatch`` is a pytree, so it flows through jit/grad/pjit. Masking helpers replace the
reference's shrink-live-batch machinery (lod_rank_table + shrink_rnn_memory_op):
sorting-by-length is unnecessary when every step is masked, and XLA pads the cost away
in fused elementwise work.

Bucketing (``bucket_length``) bounds the number of distinct compiled shapes — the analog
of the reference's shape-keyed recompile avoidance concern (SURVEY §7 hard parts).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclass
class SeqBatch:
    """A padded ragged batch: data [B, T, ...] + lengths [B]. Two-level
    nesting lives in :class:`NestedSeqBatch` below."""

    data: jax.Array
    lengths: jax.Array

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, lengths = children
        return cls(data, lengths)

    # -- shape helpers -----------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, T] 1.0 where a timestep is valid."""
        return sequence_mask(self.lengths, self.max_len, dtype)


def sequence_mask(lengths: jax.Array, max_len: int, dtype=jnp.float32) -> jax.Array:
    """[B, T] validity mask from lengths — the workhorse replacing LoD offsets on device."""
    pos = jnp.arange(max_len, dtype=lengths.dtype)
    return (pos[None, :] < lengths[:, None]).astype(dtype)


def bucket_length(n: int, buckets: Sequence[int] = (8, 16, 32, 64, 128, 256, 512, 1024),
                  overflow: str = "exact") -> int:
    """Round a max sequence length up to a fixed bucket to bound recompiles.

    ``buckets`` must be ascending. Past the largest bucket, ``overflow``
    picks the policy: ``"exact"`` returns ``n`` itself (the historical
    packing behavior), ``"pow2"`` rounds up to the next power of two so
    even outlier lengths land in a bounded shape family — the executor
    :class:`~paddle_tpu.data.feeder.BucketSpec` policy. One helper owns
    both rules so no second bucket-rounding scan can drift."""
    for b in buckets:
        if n <= b:
            return int(b)
    if overflow == "pow2":
        p = 1
        while p < n:
            p <<= 1
        return p
    return int(n)


def pack_sequences(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
                   pad_value=0, bucket: bool = True) -> SeqBatch:
    """Host-side: list of per-example [len, ...] arrays -> padded SeqBatch.

    The feeder-side analog of DataProviderConverter building an Argument
    (py_paddle/dataprovider_converter.py:247).
    """
    if not seqs:
        raise ValueError("pack_sequences: empty sequence list")
    seqs = [np.asarray(s) for s in seqs]
    lengths = np.array([s.shape[0] for s in seqs], dtype=np.int32)
    tmax = int(max_len if max_len is not None else max(1, lengths.max(initial=1)))
    if bucket and max_len is None:
        tmax = bucket_length(tmax)
    feat_shape = seqs[0].shape[1:]
    out = np.full((len(seqs), tmax) + feat_shape, pad_value, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], tmax)
        out[i, :n] = s[:n]
        lengths[i] = n
    return SeqBatch(jnp.asarray(out), jnp.asarray(lengths))


def lod_from_lengths(lengths: Sequence[int]) -> Tuple[int, ...]:
    """Offsets vector from lengths — same shape as LoD level offsets
    (framework/lod_tensor.h:57)."""
    off = [0]
    for n in lengths:
        off.append(off[-1] + int(n))
    return tuple(off)


def lengths_from_lod(offsets: Sequence[int]) -> Tuple[int, ...]:
    return tuple(int(offsets[i + 1] - offsets[i]) for i in range(len(offsets) - 1))


# =============================================================================
# Nested sequences (2-level LoD) — the reference's subSequenceStartPositions
# (parameter/Argument.h:84-90) / multi-level LoDTensor (framework/lod_tensor.h:57)
# under the static-shape regime: one more padded axis instead of offset vectors.
# =============================================================================

@jax.tree_util.register_pytree_node_class
@dataclass
class NestedSeqBatch:
    """A padded batch of sequences of sub-sequences.

    * ``data``:        [B, S, T, ...] — S = max sub-sequences per example,
                       T = max sub-sequence length
    * ``sub_lengths``: [B, S] int32 — valid length of each sub-sequence
                       (0 for padding sub-sequences)
    * ``seq_lengths``: [B] int32 — number of valid sub-sequences per example

    The sub-sequence axis IS a sequence axis: after per-sub-sequence reduction
    (pool / last-step / inner RNN) the result [B, S, D] + seq_lengths is an
    ordinary :class:`SeqBatch` over sub-sequence summaries — this is how the
    reference's nested recurrent_group composes (config_parser.py:319-387).
    """

    data: jax.Array
    sub_lengths: jax.Array
    seq_lengths: jax.Array

    def tree_flatten(self):
        return (self.data, self.sub_lengths, self.seq_lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- shape helpers -----------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_subseqs(self) -> int:
        return self.data.shape[1]

    @property
    def max_sublen(self) -> int:
        return self.data.shape[2]

    def inner_mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, S, T] validity of each timestep."""
        pos = jnp.arange(self.max_sublen, dtype=self.sub_lengths.dtype)
        return (pos[None, None, :] < self.sub_lengths[:, :, None]).astype(dtype)

    def outer_mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, S] validity of each sub-sequence."""
        return sequence_mask(self.seq_lengths, self.max_subseqs, dtype)

    # -- level moves -------------------------------------------------------
    def inner_flat(self) -> SeqBatch:
        """View sub-sequences as a flat batch [B*S, T, ...] — the input shape
        for any single-level sequence op (inner RNN, pooling, conv). Padding
        sub-sequences ride along with length 0 and mask to nothing."""
        d = self.data.reshape((self.batch_size * self.max_subseqs,)
                              + self.data.shape[2:])
        return SeqBatch(d, self.sub_lengths.reshape(-1))

    def outer(self, per_subseq: jax.Array) -> SeqBatch:
        """Lift per-sub-sequence values [B*S, ...] (from an op applied to
        ``inner_flat()``) to the outer sequence [B, S, ...] + seq_lengths."""
        return SeqBatch(
            per_subseq.reshape((self.batch_size, self.max_subseqs)
                               + per_subseq.shape[1:]),
            self.seq_lengths)


def pack_nested_sequences(nested, max_subseqs: Optional[int] = None,
                          max_sublen: Optional[int] = None, pad_value=0,
                          bucket: bool = True) -> NestedSeqBatch:
    """Host-side: list (batch) of lists (sub-sequences) of [len, ...] arrays
    -> NestedSeqBatch. The 2-level analog of :func:`pack_sequences`."""
    if not nested:
        raise ValueError("pack_nested_sequences: empty batch")
    nested = [[np.asarray(s) for s in sample] for sample in nested]
    B = len(nested)
    S = max(1, max(len(sample) for sample in nested))
    T = max(1, max((s.shape[0] for sample in nested for s in sample),
                   default=1))
    if max_subseqs is not None:
        S = max_subseqs
    elif bucket:
        # bucket the sub-seq axis too — every distinct S is a new compiled shape
        S = bucket_length(S, buckets=(2, 4, 8, 16, 32, 64))
    if max_sublen is not None:
        T = max_sublen
    elif bucket:
        T = bucket_length(T)
    # feature shape/dtype from the first NON-empty sub-sequence (an empty
    # leading sub-sequence must not dictate the layout)
    first = next((s for sample in nested for s in sample if s.shape[0] > 0),
                 None)
    if first is None:
        first = next((s for sample in nested for s in sample), None)
    if first is None:
        raise ValueError("pack_nested_sequences: no sub-sequences in batch")
    feat = first.shape[1:]
    data = np.full((B, S, T) + feat, pad_value, dtype=first.dtype)
    sub_lengths = np.zeros((B, S), np.int32)
    seq_lengths = np.zeros((B,), np.int32)
    for b, sample in enumerate(nested):
        seq_lengths[b] = min(len(sample), S)
        for s, sub in enumerate(sample[:S]):
            n = min(sub.shape[0], T)
            if n > 0:
                data[b, s, :n] = sub[:n]
            sub_lengths[b, s] = n
    return NestedSeqBatch(jnp.asarray(data), jnp.asarray(sub_lengths),
                          jnp.asarray(seq_lengths))


# =============================================================================
# N-level LoD — the general form of the reference's LoDTensor
# (framework/lod_tensor.h:57,82: a Vector<Vector<size_t>> of offset levels
# over a flat tensor). Static-shape regime: level k of raggedness becomes
# padded axis k+1, with a lengths array per level. SeqBatch/NestedSeqBatch
# above stay as the hand-tuned 1-/2-level cases every layer consumes;
# LoDBatch is the depth-generic container that converts losslessly to and
# from the reference's offset-vector representation at any depth.
# =============================================================================

@jax.tree_util.register_pytree_node_class
@dataclass
class LoDBatch:
    """An N-level ragged batch, padded dense.

    * ``data``: [B, S1, S2, ..., S_{L-1}, T, *feat] — one axis per nesting
      level; the innermost ragged axis is time.
    * ``level_lengths``: tuple of L int32 arrays; ``level_lengths[i]`` has
      shape ``data.shape[:i+1]`` and counts the valid entries along axis
      ``i+1`` (sub-sequences for i < L-1, timesteps for i = L-1). Padding
      entries carry length 0.

    Level numbering matches the reference's LoD: level 0 is the outermost.
    A pytree, so it flows through jit/grad/pjit like SeqBatch.
    """

    data: jax.Array
    level_lengths: Tuple[jax.Array, ...]

    def tree_flatten(self):
        return (self.data,) + tuple(self.level_lengths), len(self.level_lengths)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], tuple(children[1:1 + aux]))

    # -- shape helpers -----------------------------------------------------
    @property
    def nlevels(self) -> int:
        return len(self.level_lengths)

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    def mask(self, level: int = -1, dtype=jnp.float32) -> jax.Array:
        """Validity of entries along ragged axis ``level``: shape
        ``data.shape[:level+2]``."""
        level = range(self.nlevels)[level]
        lens = self.level_lengths[level]
        size = self.data.shape[level + 1]
        pos = jnp.arange(size, dtype=lens.dtype)
        return (pos[(None,) * lens.ndim] < lens[..., None]).astype(dtype)

    # -- level moves (generalize NestedSeqBatch.inner_flat / outer) --------
    def innermost_flat(self) -> SeqBatch:
        """Collapse every outer ragged axis: [prod(B..S_{L-1}), T, *feat]
        + innermost lengths — the input shape for any single-level sequence
        op (RNN, sequence pool/conv). Padding sequences ride along with
        length 0 and mask to nothing."""
        lead = int(np.prod(self.data.shape[:self.nlevels]))
        d = self.data.reshape((lead,) + self.data.shape[self.nlevels:])
        return SeqBatch(d, self.level_lengths[-1].reshape(-1))

    def lift(self, per_seq: jax.Array) -> "LoDBatch":
        """Lift per-innermost-sequence values [prod(...), *feat] (from an op
        applied to ``innermost_flat()``) back one level: the result is an
        (L-1)-level LoDBatch whose time axis is the old sub-sequence axis.
        With L-1 == 1 the result is equivalent to a SeqBatch (see
        ``as_seq_batch``)."""
        if self.nlevels < 2:
            raise ValueError("lift() needs >= 2 levels; innermost_flat() of "
                             "a 1-level batch is already a SeqBatch")
        shape = self.data.shape[:self.nlevels] + per_seq.shape[1:]
        return LoDBatch(per_seq.reshape(shape), self.level_lengths[:-1])

    def as_seq_batch(self) -> SeqBatch:
        if self.nlevels != 1:
            raise ValueError(f"{self.nlevels}-level batch is not a SeqBatch")
        return SeqBatch(self.data, self.level_lengths[0])

    def as_nested(self) -> NestedSeqBatch:
        if self.nlevels != 2:
            raise ValueError(f"{self.nlevels}-level batch is not a "
                             "NestedSeqBatch")
        return NestedSeqBatch(self.data, self.level_lengths[1],
                              self.level_lengths[0])


def pack_lod(nested, levels: int, pad_value=0) -> LoDBatch:
    """Host-side: depth-``levels`` nested python lists of [len, *feat]
    arrays -> LoDBatch. ``levels=1`` expects ``[arr, ...]``, ``levels=2``
    ``[[arr, ...], ...]`` etc. — the N-level analog of
    :func:`pack_sequences` / :func:`pack_nested_sequences`."""
    if levels < 1:
        raise ValueError("levels must be >= 1")
    if not nested:
        raise ValueError("pack_lod: empty batch")

    def _leaves(node, depth):
        if depth == levels:
            yield np.asarray(node)
        else:
            for child in node:
                yield from _leaves(child, depth + 1)

    leaves = [a for sample in nested for a in _leaves(sample, 1)]
    first = next((a for a in leaves if a.shape[0] > 0),
                 leaves[0] if leaves else None)
    if first is None:
        raise ValueError("pack_lod: no sequences in batch")

    # axis sizes: max fan-out per depth (axis 0 = batch, axis L = time)
    sizes = [len(nested)] + [1] * levels

    def _measure(node, depth):
        if depth == levels:
            sizes[levels] = max(sizes[levels], int(np.asarray(node).shape[0]))
        else:
            sizes[depth] = max(sizes[depth], len(node))
            for child in node:
                _measure(child, depth + 1)

    for sample in nested:
        _measure(sample, 1)

    feat = first.shape[1:]
    data = np.full(tuple(sizes) + feat, pad_value, dtype=first.dtype)
    lens = [np.zeros(tuple(sizes[:i + 1]), np.int32) for i in range(levels)]

    def _fill(node, depth, idx):
        if depth == levels:
            arr = np.asarray(node)
            n = int(arr.shape[0])
            lens[levels - 1][idx] = n
            if n:
                data[idx + (slice(0, n),)] = arr
        else:
            lens[depth - 1][idx] = len(node)
            for j, child in enumerate(node):
                _fill(child, depth + 1, idx + (j,))

    for b, sample in enumerate(nested):
        _fill(sample, 1, (b,))
    return LoDBatch(jnp.asarray(data), tuple(jnp.asarray(l) for l in lens))


def unpack_lod(batch: LoDBatch):
    """Inverse of :func:`pack_lod`: LoDBatch -> nested python lists of
    numpy [len, *feat] arrays, padding stripped. Round-trip exact."""
    data = np.asarray(batch.data)
    lens = [np.asarray(l) for l in batch.level_lengths]
    L = batch.nlevels

    def _build(depth, idx):
        if depth == L:
            return data[idx][: int(lens[L - 1][idx])]
        return [_build(depth + 1, idx + (j,))
                for j in range(int(lens[depth - 1][idx]))]

    return [_build(1, (b,)) for b in range(batch.batch_size)]


def lod_batch_from_offsets(flat: np.ndarray, lod) -> LoDBatch:
    """Reference LoDTensor form -> LoDBatch: ``flat`` is the row-major
    concatenation of innermost sequences and ``lod`` the offset levels
    (framework/lod_tensor.h:57 — level k's offsets index level k+1's
    entries; the last level's offsets index rows of ``flat``)."""
    flat = np.asarray(flat)
    lod = [list(map(int, level)) for level in lod]
    L = len(lod)
    if L == 0:
        raise ValueError("lod_batch_from_offsets: need >= 1 LoD level")
    # validate the offset chain before building: level k's last offset must
    # cover exactly level k+1's entry count (rows of ``flat`` for the last
    # level) — numpy slicing would otherwise clamp and corrupt silently
    for k, level in enumerate(lod):
        if not level or level[0] != 0:
            raise ValueError(f"lod_batch_from_offsets: level {k} offsets "
                             f"must start at 0, got {level[:1]}")
        if any(level[j] > level[j + 1] for j in range(len(level) - 1)):
            raise ValueError(f"lod_batch_from_offsets: level {k} offsets "
                             "must be non-decreasing")
        extent = (flat.shape[0] if k == L - 1 else len(lod[k + 1]) - 1)
        if level[-1] != extent:
            what = "rows of flat" if k == L - 1 else f"level {k + 1} entries"
            raise ValueError(
                f"lod_batch_from_offsets: level {k} covers {level[-1]} "
                f"entries but there are {extent} {what}")

    def _build(level, j):
        lo, hi = lod[level][j], lod[level][j + 1]
        if level == L - 1:
            return flat[lo:hi]
        return [_build(level + 1, t) for t in range(lo, hi)]

    nested = [_build(0, i) for i in range(len(lod[0]) - 1)]
    return pack_lod(nested, L)


def lod_batch_to_offsets(batch: LoDBatch):
    """LoDBatch -> (flat rows, offset levels): the exact reference
    LoDTensor representation (lod_tensor.h:82 LoD + flat tensor)."""
    nested = unpack_lod(batch)
    L = batch.nlevels
    lod = [[0] for _ in range(L)]
    rows = []

    def _walk(node, depth):
        if depth == L:
            rows.append(np.asarray(node))
            lod[L - 1].append(lod[L - 1][-1] + node.shape[0])
        else:
            lod[depth - 1].append(lod[depth - 1][-1] + len(node))
            for child in node:
                _walk(child, depth + 1)

    for sample in nested:
        _walk(sample, 1)
    feat = batch.data.shape[batch.nlevels + 1:]
    flat = (np.concatenate(rows, axis=0) if rows
            else np.zeros((0,) + tuple(feat), np.asarray(batch.data).dtype))
    return flat, [tuple(level) for level in lod]
