"""Device placement.

TPU-native analog of the reference's ``Place`` variant (paddle/platform/place.h:
CPUPlace/GPUPlace) and ``DeviceContext`` (paddle/platform/device_context.h:38-74).
Under JAX/PJRT a "place" resolves to a ``jax.Device``; the stream/handle machinery of
CUDADeviceContext is owned by XLA, so the context here only carries the device plus the
default matmul precision/dtype policy used when lowering ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax


@dataclass(frozen=True)
class Place:
    """A logical device slot: platform + index."""

    platform: str  # "tpu" | "cpu" | "gpu"
    index: int = 0

    def device(self) -> jax.Device:
        devs = [d for d in jax.devices() if d.platform == self.platform]
        if not devs:
            # CPU is always constructible even when the default platform differs.
            devs = jax.devices("cpu") if self.platform == "cpu" else devs
        if not devs:
            raise RuntimeError(f"no devices for platform '{self.platform}'")
        return devs[self.index % len(devs)]

    @property
    def is_tpu(self) -> bool:
        return self.platform == "tpu"


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def CPUPlace(index: int = 0) -> Place:
    return Place("cpu", index)


def default_place() -> Place:
    d = jax.devices()[0]
    # treat any accelerator platform (tpu under axon tunnels included) as "tpu-like"
    return Place(d.platform, 0)


@dataclass
class DeviceContext:
    """Per-place execution context (ref: platform/device_context.h).

    XLA owns streams/handles; what remains host-side is the device binding and the
    numeric policy every kernel lowers with.
    """

    place: Place
    matmul_precision: str = "default"
    compute_dtype: Optional[str] = None  # e.g. "bfloat16" to run matmuls in bf16

    def device(self) -> jax.Device:
        return self.place.device()
