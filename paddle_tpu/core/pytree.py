"""Path-keyed pytree codec shared by sharding rules and checkpoints.

One canonical mapping between nested params structures and flat
``{"a/b/w": leaf}`` dicts (lists/tuples encode as ``@i`` segments), so
placement rules (parallel/sharding.py) and serialization
(trainer/checkpoint.py) agree on path names.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

_IDX = re.compile(r"@\d+")


def _walk(tree, prefix: str = ""):
    """Single traversal defining the path grammar (dict keys joined with '/',
    list/tuple indices as '@i'). Yields (path, kind, node) with kind in
    {'dict', 'list', 'tuple', 'leaf'} — every other walker derives from this
    so the grammar can't desynchronize."""
    if isinstance(tree, dict):
        yield prefix, "dict", tree
        for k, v in tree.items():
            yield from _walk(v, f"{prefix}/{k}" if prefix else str(k))
    elif isinstance(tree, (list, tuple)):
        yield prefix, "tuple" if isinstance(tree, tuple) else "list", tree
        for i, v in enumerate(tree):
            yield from _walk(v, f"{prefix}/@{i}" if prefix else f"@{i}")
    else:
        yield prefix, "leaf", tree


def flatten_path_tree(tree, prefix: str = "") -> List[Tuple[str, Any]]:
    return [(p, node) for p, kind, node in _walk(tree, prefix) if kind == "leaf"]


def tree_spec(tree, prefix: str = "") -> Dict[str, str]:
    """Record container kinds by path — including *empty* dicts/lists/tuples,
    which carry no leaves and would otherwise vanish in a flatten/unflatten
    round-trip (e.g. SGD optimizer slots are ``{}`` per param)."""
    return {p: kind for p, kind, _ in _walk(tree, prefix) if kind != "leaf"}


def unflatten_path_tree(flat: Dict[str, Any], spec: Dict[str, str] | None = None):
    """Rebuild a nested tree from ``{path: leaf}``.

    With a ``spec`` from :func:`tree_spec`, empty containers are recreated and
    list-vs-tuple identity is preserved; without one, containers are inferred
    (all-``@i`` keys become lists).
    """
    root: Dict[str, Any] = {}

    def ensure(path):
        node = root
        if path:
            for k in path.split("/"):
                node = node.setdefault(k, {})
        return node

    if spec:
        for p in spec:
            ensure(p)
    for path, leaf in flat.items():
        keys = path.split("/")
        node = ensure("/".join(keys[:-1]))
        node[keys[-1]] = leaf

    def fix(node, p):
        if isinstance(node, dict):
            kind = spec.get(p) if spec else None
            if kind is None:
                kind = "list" if node and all(_IDX.fullmatch(k) for k in node) else "dict"
            if kind in ("list", "tuple"):
                items = [fix(node[f"@{i}"], f"{p}/@{i}" if p else f"@{i}")
                         for i in range(len(node))]
                return tuple(items) if kind == "tuple" else items
            return {k: fix(v, f"{p}/{k}" if p else k) for k, v in node.items()}
        return node

    return fix(root, "")
