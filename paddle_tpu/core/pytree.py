"""Path-keyed pytree codec shared by sharding rules and checkpoints.

One canonical mapping between nested params structures and flat
``{"a/b/w": leaf}`` dicts (lists/tuples encode as ``@i`` segments), so
placement rules (parallel/sharding.py) and serialization
(trainer/checkpoint.py) agree on path names.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

_IDX = re.compile(r"@\d+")


def flatten_path_tree(tree, prefix: str = "") -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.extend(flatten_path_tree(v, f"{prefix}/{k}" if prefix else str(k)))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.extend(flatten_path_tree(v, f"{prefix}/@{i}" if prefix else f"@{i}"))
    else:
        out.append((prefix, tree))
    return out


def unflatten_path_tree(flat: Dict[str, Any]):
    root: Dict[str, Any] = {}
    for path, leaf in flat.items():
        keys = path.split("/")
        node = root
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = leaf

    def fix(node):
        if isinstance(node, dict):
            if node and all(_IDX.fullmatch(k) for k in node):
                return [fix(node[f"@{i}"]) for i in range(len(node))]
            return {k: fix(v) for k, v in node.items()}
        return node

    return fix(root)
