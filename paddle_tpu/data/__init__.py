"""Data layer: readers, decorators, datasets, feeder, prefetch.

Re-provides the reference's data stack (SURVEY.md §2.4):
* reader protocol + decorators  (python/paddle/v2/reader/decorator.py:26-233)
* ``batch``                     (python/paddle/v2/minibatch.py)
* dataset zoo                   (python/paddle/v2/dataset/*) — synthetic generators
  here (no network egress); same shapes/vocab semantics as the originals.
* DataFeeder                    (python/paddle/v2/data_feeder.py + py_paddle
  DataProviderConverter) — converts row batches into device-ready arrays under the
  feature-type taxonomy of SURVEY §8.2 (dense / index / sparse / sequence).
* DoubleBuffer prefetch         (gserver/dataproviders/DataProvider.h:249) — a
  background-thread pipeline overlapping host batch prep with device steps.
"""

from .reader import (map_readers, shuffle, chain, compose, buffered, firstn,
                     xmap_readers, cache, batch, mix)
from .feeder import (DataFeeder, DenseSlot, IndexSlot, SeqSlot, SparseSlot,
                     to_lod_batch)
from .prefetch import DoubleBuffer
from . import dataset, format, parsers
from .provider import CacheType, provider

__all__ = ["parsers", "provider", "CacheType", "map_readers", "shuffle", "chain", "compose", "buffered", "firstn",
           "xmap_readers", "cache", "batch", "mix",
           "DataFeeder", "DenseSlot", "IndexSlot", "SeqSlot", "SparseSlot",
           "to_lod_batch", "DoubleBuffer", "dataset", "format"]
