"""Reader <-> recordio-chunk bridge + the fault-tolerant cloud reader.

Reference pipeline being re-provided: datasets are converted to RecordIO
chunks, the master shards chunk ranges into tasks, and trainers read via
``cloud_reader`` (python/paddle/v2/reader/creator.py:91-109 +
python/paddle/v2/master/client.py:15-80). Sample payloads are pickled tuples
(the reference pickles through its recordio client the same way); files are
the CRC-checked chunk format of native/recordio.cc.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Iterable, List, Optional

from .reader import Reader


def dump_to_chunks(reader_creator: Reader, dirname: str, *,
                   samples_per_chunk: int = 1024,
                   prefix: str = "chunk") -> List[str]:
    """Materialise a reader into chunk files; returns the paths
    (dataset/common.py convert + recordio writer analog)."""
    from ..runtime.recordio import RecordWriter
    os.makedirs(dirname, exist_ok=True)
    paths: List[str] = []
    writer = None
    count = 0
    for sample in reader_creator():
        if writer is None:
            path = os.path.join(dirname, f"{prefix}-{len(paths):05d}.ptr")
            writer = RecordWriter(path)
            paths.append(path)
        writer.write(pickle.dumps(sample, protocol=4))
        count += 1
        if count >= samples_per_chunk:
            writer.close()
            writer = None
            count = 0
    if writer is not None:
        writer.close()
    return paths


def chunk_reader(paths: Iterable[str]) -> Reader:
    """Reader creator over chunk files (recordio.creator analog)."""
    paths = list(paths)

    def reader():
        from ..runtime.recordio import RecordReader
        for path in paths:
            with RecordReader(path) as r:
                for payload in r:
                    yield pickle.loads(payload)

    return reader


def cloud_reader(master_client, *, pass_end_sentinel: bool = False,
                 poll_interval: float = 0.1,
                 max_idle_polls: int = 600,
                 new_pass_at_end: bool = False) -> Reader:
    """Fault-tolerant distributed reader (creator.py:91 cloud_reader): pull
    chunk tasks from the master service, stream their samples, report
    finished/failed. One pass = until the master says the pass is done.

    ``new_pass_at_end`` cycles the master's pass when this reader drains it,
    so the next ``reader()`` call streams a fresh pass — correct for a
    single consumer (the --local_master dev mode); multi-consumer jobs
    coordinate the pass transition externally (e.g. rank 0 only).
    """
    import time

    def reader():
        idle = 0
        while True:
            task = master_client.get_task()
            if task is None:
                todo, pending, done, disc, epoch = master_client.stats()
                if todo == 0 and pending == 0:
                    if new_pass_at_end:
                        master_client.new_pass()
                    return                      # pass complete
                idle += 1
                if idle > max_idle_polls:
                    raise TimeoutError("master starved the reader")
                time.sleep(poll_interval)
                continue
            idle = 0
            task_id, path = task
            try:
                yield from chunk_reader([path])()
            except Exception:
                master_client.task_failed(task_id)
                continue
            master_client.task_finished(task_id)

    return reader
