"""Reader <-> recordio-chunk bridge + the fault-tolerant cloud reader.

Reference pipeline being re-provided: datasets are converted to RecordIO
chunks, the master shards chunk ranges into tasks, and trainers read via
``cloud_reader`` (python/paddle/v2/reader/creator.py:91-109 +
python/paddle/v2/master/client.py:15-80). Sample payloads are pickled tuples
(the reference pickles through its recordio client the same way); files are
the CRC-checked chunk format of native/recordio.cc.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Iterable, List, Optional

from .. import faults, obs
from ..utils.retry import RetryBudgetExceeded, RetryPolicy
from .reader import Reader


def dump_to_chunks(reader_creator: Reader, dirname: str, *,
                   samples_per_chunk: int = 1024,
                   prefix: str = "chunk") -> List[str]:
    """Materialise a reader into chunk files; returns the paths
    (dataset/common.py convert + recordio writer analog)."""
    from ..runtime.recordio import RecordWriter
    os.makedirs(dirname, exist_ok=True)
    paths: List[str] = []
    writer = None
    count = 0
    for sample in reader_creator():
        if writer is None:
            path = os.path.join(dirname, f"{prefix}-{len(paths):05d}.ptr")
            writer = RecordWriter(path)
            paths.append(path)
        writer.write(pickle.dumps(sample, protocol=4))
        count += 1
        if count >= samples_per_chunk:
            writer.close()
            writer = None
            count = 0
    if writer is not None:
        writer.close()
    return paths


def chunk_reader(paths: Iterable[str]) -> Reader:
    """Reader creator over chunk files (recordio.creator analog)."""
    paths = list(paths)

    def reader():
        from ..runtime.recordio import RecordReader
        for path in paths:
            with RecordReader(path) as r:
                for payload in r:
                    yield pickle.loads(payload)

    return reader


class _Starved(Exception):
    """Internal: the master had no task for us but the pass is not done."""


def cloud_reader(master_client, *, pass_end_sentinel: bool = False,
                 poll_interval: float = 0.1,
                 max_idle_polls: int = 600,
                 new_pass_at_end: bool = False,
                 poll_policy: Optional[RetryPolicy] = None) -> Reader:
    """Fault-tolerant distributed reader (creator.py:91 cloud_reader): pull
    chunk tasks from the master service, stream their samples, report
    finished/failed. One pass = until the master says the pass is done.

    ``new_pass_at_end`` cycles the master's pass when this reader drains it,
    so the next ``reader()`` call streams a fresh pass — correct for a
    single consumer (the --local_master dev mode); multi-consumer jobs
    coordinate the pass transition externally (e.g. rank 0 only).

    Idle polling (other consumers hold every pending task) runs under a
    :class:`RetryPolicy` — gentle exponential backoff instead of a fixed
    busy-poll, bounded by an overall starvation deadline equivalent to the
    legacy ``max_idle_polls * poll_interval`` budget. Pass ``poll_policy``
    to tune it (a fake-clock policy makes tests sleepless).
    """

    _END = object()

    def reader():
        if poll_policy is not None:
            # starvation is the only retryable event at this site; a caller
            # tunes the schedule/deadline and must not need to know about
            # the module-private _Starved marker
            import copy
            policy = copy.copy(poll_policy)
            policy.retryable = _Starved
        else:
            policy = RetryPolicy(
                max_attempts=None, base_delay=poll_interval, multiplier=1.5,
                max_delay=max(poll_interval * 10, poll_interval),
                deadline=max_idle_polls * poll_interval,
                jitter=0.1, retryable=_Starved)
        if policy.observer is None:
            # idle-poll telemetry: data.retries_total / giveups / backoff
            policy.observer = obs.retry_observer("data")

        def poll_once():
            task = master_client.get_task()
            if task is not None:
                return task
            todo, pending, done, disc, epoch = master_client.stats()
            if todo == 0 and pending == 0:
                return _END                     # pass complete
            raise _Starved()

        while True:
            try:
                task = policy.call(poll_once, describe="task poll")
            except RetryBudgetExceeded as e:
                raise TimeoutError(
                    f"master starved the reader "
                    f"({e.attempts} idle polls)") from e
            if task is _END:
                if new_pass_at_end:
                    master_client.new_pass()
                return
            task_id, path = task
            obs.count("data.tasks_total")
            try:
                faults.fire("reader.next")      # chaos: per-task failure
                yield from chunk_reader([path])()
            except Exception:
                # the elastic contract (go/master re-dispatch): report the
                # task failed and let the master hand it to a healthy
                # consumer (or discard after failure_max strikes)
                obs.count("data.task_failures_total")
                master_client.task_failed(task_id)
                continue
            master_client.task_finished(task_id)

    return reader
