"""Dataset zoo — synthetic, deterministic, egress-free stand-ins.

The reference ships downloaders for 11 datasets (python/paddle/v2/dataset/*:
mnist, cifar, imdb, imikolov, movielens, conll05, sentiment, uci_housing, wmt14,
flowers, voc2012, mq2007; cache in dataset/common.py). This environment has no
network, so each dataset here is a *deterministic synthetic generator with the
same sample schema and reader API* (``train()``/``test()`` reader creators) —
structured so models actually learn (class-conditional patterns, latent-factor
ratings, reversible translation), which is what the book-style end-to-end tests
need (SURVEY.md §4.4).
"""

from __future__ import annotations

import numpy as np

from .reader import Reader


def _state(seed: int) -> np.random.RandomState:
    return np.random.RandomState(seed)


# ---------------------------------------------------------------- mnist ------
class mnist:
    """28x28 digit classification. Sample: (image[784] float in [-1,1], label)."""

    IMAGE_DIM, CLASSES = 784, 10

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        protos = _state(1234).randn(10, 784).astype(np.float32)
        labels = rs.randint(0, 10, n)
        imgs = (0.7 * protos[labels] + 0.7 * rs.randn(n, 784)).astype(np.float32)
        imgs = np.tanh(imgs)
        return imgs, labels.astype(np.int32)

    @staticmethod
    def train(n: int = 2048) -> Reader:
        def reader():
            imgs, labels = mnist._make(n, 0)
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader

    @staticmethod
    def test(n: int = 512) -> Reader:
        def reader():
            imgs, labels = mnist._make(n, 1)
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader


# ---------------------------------------------------------------- cifar ------
class cifar:
    """32x32x3 image classification (cifar10 schema): (image[3072], label)."""

    CLASSES = 10

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        protos = _state(99).randn(10, 3072).astype(np.float32)
        labels = rs.randint(0, 10, n)
        imgs = np.tanh(0.6 * protos[labels] + 0.8 * rs.randn(n, 3072)).astype(np.float32)
        return imgs, labels.astype(np.int32)

    @staticmethod
    def train10(n: int = 1024) -> Reader:
        def reader():
            imgs, labels = cifar._make(n, 10)
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader

    @staticmethod
    def test10(n: int = 256) -> Reader:
        def reader():
            imgs, labels = cifar._make(n, 11)
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader


# ----------------------------------------------------------- uci_housing -----
class uci_housing:
    """13-feature regression: (features[13], price[1])."""

    FEATURE_DIM = 13
    _W = _state(7).randn(13).astype(np.float32)

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        x = rs.randn(n, 13).astype(np.float32)
        y = (x @ uci_housing._W + 0.1 * rs.randn(n)).astype(np.float32)
        return x, y[:, None]

    @staticmethod
    def train(n: int = 404) -> Reader:
        def reader():
            x, y = uci_housing._make(n, 20)
            for i in range(n):
                yield x[i], y[i]
        return reader

    @staticmethod
    def test(n: int = 102) -> Reader:
        def reader():
            x, y = uci_housing._make(n, 21)
            for i in range(n):
                yield x[i], y[i]
        return reader


# ---------------------------------------------------------------- imdb -------
class imdb:
    """Binary sentiment over id sequences: (word_ids list, label 0/1).

    Class-conditional unigram distributions -> linearly separable by embedding
    pooling, like the quick_start text-classification demo data.
    """

    VOCAB = 2000

    @staticmethod
    def _dists():
        rs = _state(5)
        base = rs.dirichlet(np.ones(imdb.VOCAB) * 0.1)
        tilt = rs.randn(imdb.VOCAB) * 2.0
        pos = base * np.exp(tilt)
        neg = base * np.exp(-tilt)
        return pos / pos.sum(), neg / neg.sum()

    @staticmethod
    def _make(n, seed, min_len=8, max_len=64):
        rs = _state(seed)
        pos, neg = imdb._dists()
        for _ in range(n):
            label = int(rs.randint(0, 2))
            ln = int(rs.randint(min_len, max_len + 1))
            dist = pos if label == 1 else neg
            ids = rs.choice(imdb.VOCAB, size=ln, p=dist).astype(np.int32)
            yield list(map(int, ids)), label

    @staticmethod
    def train(n: int = 1024) -> Reader:
        return lambda: imdb._make(n, 30)

    @staticmethod
    def test(n: int = 256) -> Reader:
        return lambda: imdb._make(n, 31)


# -------------------------------------------------------------- imikolov -----
class imikolov:
    """N-gram LM (word2vec book test schema): tuples of N consecutive ids from a
    synthetic order-1 Markov chain (so context genuinely predicts the target)."""

    VOCAB = 512

    @staticmethod
    def _chain():
        rs = _state(40)
        T = rs.dirichlet(np.ones(imikolov.VOCAB) * 0.05, size=imikolov.VOCAB)
        return T

    @staticmethod
    def _make(n, seed, ngram=5):
        rs = _state(seed)
        T = imikolov._chain()
        w = int(rs.randint(imikolov.VOCAB))
        seq = [w]
        for _ in range(n + ngram):
            w = int(rs.choice(imikolov.VOCAB, p=T[w]))
            seq.append(w)
        for i in range(n):
            yield tuple(seq[i:i + ngram])

    @staticmethod
    def train(n: int = 2048, ngram: int = 5) -> Reader:
        return lambda: imikolov._make(n, 41, ngram)

    @staticmethod
    def test(n: int = 256, ngram: int = 5) -> Reader:
        return lambda: imikolov._make(n, 42, ngram)


# -------------------------------------------------------------- movielens ----
class movielens:
    """Recommender schema: (user_id, gender, age, job, movie_id, category_multihot,
    rating). Ratings from latent factors -> learnable."""

    USERS, MOVIES, CATEGORIES, JOBS, AGES = 944, 1683, 19, 21, 7

    @staticmethod
    def _factors():
        rs = _state(50)
        return (rs.randn(movielens.USERS, 8).astype(np.float32),
                rs.randn(movielens.MOVIES, 8).astype(np.float32))

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        U, M = movielens._factors()
        for _ in range(n):
            u = int(rs.randint(movielens.USERS))
            m = int(rs.randint(movielens.MOVIES))
            cats = sorted(set(map(int, rs.randint(0, movielens.CATEGORIES,
                                                  rs.randint(1, 4)))))
            score = float(U[u] @ M[m]) / 8.0
            rating = float(np.clip(3.0 + 2.0 * np.tanh(score) + 0.2 * rs.randn(),
                                   1.0, 5.0))
            yield (u, int(rs.randint(0, 2)), int(rs.randint(movielens.AGES)),
                   int(rs.randint(movielens.JOBS)), m, cats, rating)

    @staticmethod
    def train(n: int = 2048) -> Reader:
        return lambda: movielens._make(n, 51)

    @staticmethod
    def test(n: int = 256) -> Reader:
        return lambda: movielens._make(n, 52)


# ---------------------------------------------------------------- wmt14 ------
class wmt14:
    """Seq2seq NMT schema: (src_ids, trg_ids_in, trg_ids_out) with <s>=0, <e>=1,
    <unk>=2. Synthetic task: target = reversed source mapped through a fixed
    permutation — non-trivial but exactly learnable, standard toy-NMT practice."""

    SRC_VOCAB, TRG_VOCAB = 300, 300
    START, END, UNK = 0, 1, 2

    @staticmethod
    def _perm():
        return _state(60).permutation(np.arange(3, wmt14.TRG_VOCAB))

    @staticmethod
    def _make(n, seed, min_len=4, max_len=16):
        rs = _state(seed)
        perm = wmt14._perm()
        for _ in range(n):
            ln = int(rs.randint(min_len, max_len + 1))
            src = rs.randint(3, wmt14.SRC_VOCAB, ln).astype(np.int64)
            trg = perm[src[::-1] - 3]
            trg_in = np.concatenate([[wmt14.START], trg])
            trg_out = np.concatenate([trg, [wmt14.END]])
            yield (list(map(int, src)), list(map(int, trg_in)),
                   list(map(int, trg_out)))

    @staticmethod
    def train(n: int = 2048) -> Reader:
        return lambda: wmt14._make(n, 61)

    @staticmethod
    def test(n: int = 256) -> Reader:
        return lambda: wmt14._make(n, 62)


# --------------------------------------------------------------- conll05 -----
class conll05:
    """Sequence-labeling schema (SRL/NER style): (word_ids, tag_ids) from an HMM
    so tag context matters — exercises the CRF layers."""

    VOCAB, TAGS = 800, 9

    @staticmethod
    def _hmm():
        rs = _state(70)
        trans = rs.dirichlet(np.ones(conll05.TAGS) * 0.2, size=conll05.TAGS)
        emit = rs.dirichlet(np.ones(conll05.VOCAB) * 0.05, size=conll05.TAGS)
        return trans, emit

    @staticmethod
    def _make(n, seed, min_len=5, max_len=30):
        rs = _state(seed)
        trans, emit = conll05._hmm()
        for _ in range(n):
            ln = int(rs.randint(min_len, max_len + 1))
            t = int(rs.randint(conll05.TAGS))
            words, tags = [], []
            for _ in range(ln):
                words.append(int(rs.choice(conll05.VOCAB, p=emit[t])))
                tags.append(t)
                t = int(rs.choice(conll05.TAGS, p=trans[t]))
            yield words, tags

    @staticmethod
    def train(n: int = 512) -> Reader:
        return lambda: conll05._make(n, 71)

    @staticmethod
    def test(n: int = 128) -> Reader:
        return lambda: conll05._make(n, 72)


# --------------------------------------------------------------- sentiment ---
class sentiment(imdb):
    """Alias schema of imdb (the reference ships both, dataset/sentiment.py)."""


# ----------------------------------------------------------------- mq2007 ----
class mq2007:
    """Learning-to-rank schema: (query_id, features[46], relevance 0..2),
    grouped by query; relevance from a hidden linear scorer."""

    FEATURES = 46
    _W = _state(80).randn(46).astype(np.float32)

    @staticmethod
    def _make(n_queries, seed, docs_per_query=10):
        rs = _state(seed)
        for q in range(n_queries):
            x = rs.randn(docs_per_query, mq2007.FEATURES).astype(np.float32)
            score = x @ mq2007._W + 0.3 * rs.randn(docs_per_query)
            rel = np.digitize(score, np.quantile(score, [0.5, 0.8])).astype(np.int32)
            for d in range(docs_per_query):
                yield q, x[d], int(rel[d])

    @staticmethod
    def train(n_queries: int = 128) -> Reader:
        return lambda: mq2007._make(n_queries, 81)

    @staticmethod
    def test(n_queries: int = 32) -> Reader:
        return lambda: mq2007._make(n_queries, 82)


# ------------------------------------------------------------------ criteo ---
class criteo:
    """CTR schema (DeepFM target): (dense[13], sparse_ids[26], click) — the
    Criteo layout; click prob from a factorization-machine teacher so FM-style
    models fit it."""

    DENSE, FIELDS, HASH = 13, 26, 1000

    @staticmethod
    def _teacher():
        rs = _state(90)
        return (rs.randn(criteo.HASH).astype(np.float32) * 0.3,
                rs.randn(criteo.HASH, 4).astype(np.float32) * 0.3,
                rs.randn(criteo.DENSE).astype(np.float32) * 0.5)

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        w1, v, wd = criteo._teacher()
        for _ in range(n):
            dense = rs.randn(criteo.DENSE).astype(np.float32)
            ids = rs.randint(0, criteo.HASH, criteo.FIELDS).astype(np.int32)
            lin = w1[ids].sum() + dense @ wd
            vi = v[ids]
            fm = 0.5 * (np.square(vi.sum(0)) - np.square(vi).sum(0)).sum()
            p = 1.0 / (1.0 + np.exp(-(lin + fm)))
            yield dense, list(map(int, ids)), int(rs.rand() < p)

    @staticmethod
    def train(n: int = 2048) -> Reader:
        return lambda: criteo._make(n, 91)

    @staticmethod
    def test(n: int = 256) -> Reader:
        return lambda: criteo._make(n, 92)


# --------------------------------------------------------------- flowers -----
class flowers:
    """Oxford-102 flowers schema (dataset/flowers.py): readers yield
    (HWC uint8 image, label in [0, 102)) through the standard train/test
    mapper pipeline (resize-short 256 -> crop 224 -> normalize handled by the
    caller's mapper, as in flowers.default_mapper)."""

    CLASSES = 102
    HW = 64          # synthetic images are small; schema (HWC uint8) matches

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        protos = _state(77).randint(0, 255, (flowers.CLASSES, 8, 8, 3))
        labels = rs.randint(0, flowers.CLASSES, n)
        imgs = []
        for i in range(n):
            base = protos[labels[i]].astype(np.float32)
            up = np.kron(base, np.ones((flowers.HW // 8, flowers.HW // 8, 1)))
            noise = rs.randn(flowers.HW, flowers.HW, 3) * 12
            imgs.append(np.clip(up + noise, 0, 255).astype(np.uint8))
        return imgs, labels.astype(np.int32)

    @staticmethod
    def train(n: int = 512, mapper=None) -> Reader:
        def reader():
            imgs, labels = flowers._make(n, 70)
            for im, lb in zip(imgs, labels):
                sample = (im, int(lb))
                yield mapper(sample) if mapper else sample
        return reader

    @staticmethod
    def test(n: int = 128, mapper=None) -> Reader:
        def reader():
            imgs, labels = flowers._make(n, 71)
            for im, lb in zip(imgs, labels):
                sample = (im, int(lb))
                yield mapper(sample) if mapper else sample
        return reader

    valid = test


# --------------------------------------------------------------- voc2012 -----
class voc2012:
    """VOC2012 segmentation schema (dataset/voc2012.py): readers yield
    (HWC uint8 image, HW int32 mask with classes in [0, 21))."""

    CLASSES = 21
    HW = 64

    @staticmethod
    def _make(n, seed):
        rs = _state(seed)
        samples = []
        for _ in range(n):
            img = rs.randint(0, 255, (voc2012.HW, voc2012.HW, 3)).astype(np.uint8)
            mask = np.zeros((voc2012.HW, voc2012.HW), np.int32)
            # a few rectangular object regions with class-correlated pixels
            for _ in range(rs.randint(1, 4)):
                c = rs.randint(1, voc2012.CLASSES)
                y, x = rs.randint(0, voc2012.HW - 16, 2)
                h, w = rs.randint(8, 16, 2)
                mask[y:y + h, x:x + w] = c
                img[y:y + h, x:x + w] = (c * 11) % 255
            samples.append((img, mask))
        return samples

    @staticmethod
    def train(n: int = 256) -> Reader:
        def reader():
            for img, mask in voc2012._make(n, 80):
                yield img, mask
        return reader

    @staticmethod
    def test(n: int = 64) -> Reader:
        def reader():
            for img, mask in voc2012._make(n, 81):
                yield img, mask
        return reader

    val = test
