"""DataFeeder: row-tuples -> device-ready arrays under the slot-type taxonomy.

The reference's canonical feature types (SURVEY.md §8.2: proto/DataFormat.proto
SlotType; PyDataProvider2.py input_types; LayerGradUtil.h:23-34):
dense / index / sparse-binary / sparse-value, each optionally (nested) sequence.
The converter to engine buffers is DataProviderConverter
(py_paddle/dataprovider_converter.py:247) + DataFeeder (v2/data_feeder.py:112).

TPU-native: the target layout is static-shaped —
* DenseSlot  -> float [B, dim]
* IndexSlot  -> int32 [B]
* SeqSlot    -> SeqBatch (padded [B, T(bucketed), ...] + lengths)  — LoD analog
* SparseSlot -> padded COO per row: (ids [B, K], vals [B, K], mask) with K the
  bucketed max-nnz; embedding-sum consumes it directly (SelectedRows analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.lod import (NestedSeqBatch, SeqBatch, bucket_length,
                        pack_nested_sequences, pack_sequences)


# -- shape bucketing (Executor feed policy) ------------------------------------

def next_bucket(n: int, buckets: Sequence[int] = ()) -> int:
    """Smallest listed bucket >= n (``buckets`` ascending); beyond the
    largest (or with no list), the next power of two — so an unforeseen
    length still lands in a bounded shape family instead of minting its
    own compile.  Thin alias: :func:`~paddle_tpu.core.lod.bucket_length`
    owns the rounding policy."""
    return bucket_length(n, tuple(buckets), overflow="pow2")


def pad_to_bucket(arr, axis: int, buckets: Sequence[int] = ()):
    """Zero-pad ``arr`` along ``axis`` up to :func:`next_bucket`.

    Returns ``(padded, true_len)`` — the caller feeds the true length
    alongside so masked ops can ignore the tail. Host (numpy) inputs pad on
    the host; device (jax) arrays pad on device (no round-trip).
    """
    if not hasattr(arr, "shape"):
        arr = np.asarray(arr)
    n = int(arr.shape[axis])
    b = next_bucket(n, buckets)
    if b == n:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, b - n)
    if isinstance(arr, np.ndarray):
        return np.pad(arr, widths), n
    return jnp.pad(arr, widths), n


class BucketSpec:
    """Per-feed shape-bucketing policy for :class:`~paddle_tpu.fluid.Executor`.

    ``spec`` maps a feed name to its bucket boundaries::

        BucketSpec({"words": (32, 64, 128)})                  # axis inferred
        BucketSpec({"words": {"axis": 2, "buckets": (8, 16)}})  # pinned axis
        BucketSpec({"words": "tuned"})          # tune.bucket_grid("prompt")

    A feed axis is padded up to the next listed bucket (falling back to the
    next power of two past the largest), the true length is fed alongside
    as ``<name>@LEN`` (int32 scalar), and the executor's compiled-fn cache
    keys on the *bucketed* shape — a varied-length workload compiles at
    most ``len(buckets) + 1`` times per feed instead of once per distinct
    length. The axis defaults to the feed Variable's declared
    ``bucket_axis``, else its first dynamic (``-1``) non-batch dim, else
    axis 1 (axis 0 for rank-1 feeds).
    """

    def __init__(self, spec: Dict[str, Any]):
        self.spec: Dict[str, Tuple[Optional[int], Tuple[int, ...]]] = {}
        for name, v in dict(spec).items():
            axis: Optional[int] = None
            if isinstance(v, dict):
                axis = v.get("axis")
                buckets = v.get("buckets", ())
            else:
                buckets = v
            if buckets == "tuned":
                # the measured ``bucket_grid`` winner (validated by the
                # consult); without one, the serving-default grid
                from .. import tune
                buckets = (tune.bucket_grid("prompt")
                           or (32, 64, 128, 256, 512))
            self.spec[name] = (axis, tuple(sorted(int(b) for b in buckets)))

    def names(self):
        return self.spec.keys()

    def pinned_axis(self, name: str) -> Optional[int]:
        """The axis the spec pins for ``name`` (None = caller infers)."""
        return self.spec[name][0]

    def pad(self, name: str, arr, default_axis: Optional[int] = None):
        """(padded, true_len) for one feed; see :func:`pad_to_bucket`."""
        axis, buckets = self.spec[name]
        if axis is None:
            axis = (default_axis if default_axis is not None
                    else (1 if getattr(arr, "ndim", 1) >= 2 else 0))
        return pad_to_bucket(arr, axis, buckets)


@dataclass
class DenseSlot:
    dim: int
    dtype: Any = np.float32


@dataclass
class IndexSlot:
    dtype: Any = np.int32


@dataclass
class SeqSlot:
    """A variable-length sequence of scalars (ids) or vectors.

    elem_dim None -> id sequence (int32); else vector sequence [len, elem_dim].
    nested=True accepts list-of-list-of-elem and produces a NestedSeqBatch
    ([B, S, T] + sub/seq lengths — the 2-level-LoD analog).
    """
    elem_dim: Optional[int] = None
    nested: bool = False
    dtype: Any = None

    @property
    def np_dtype(self):
        if self.dtype is not None:
            return self.dtype
        return np.int32 if self.elem_dim is None else np.float32


@dataclass
class SparseSlot:
    """Sparse row features: sample = list of ids or list of (id, value)."""
    dim: int
    with_values: bool = False


class DataFeeder:
    """feed(rows) -> tuple of arrays, one per slot.

    rows: list of sample tuples, sample[i] belongs to slots[i].
    """

    def __init__(self, slots: Sequence[Any]):
        self.slots = list(slots)

    def __call__(self, rows: Sequence[Tuple]) -> Tuple:
        return self.feed(rows)

    def feed(self, rows: Sequence[Tuple]) -> Tuple:
        cols = list(zip(*rows))
        if len(cols) != len(self.slots):
            raise ValueError(f"sample width {len(cols)} != #slots {len(self.slots)}")
        return tuple(self._convert(slot, col) for slot, col in zip(self.slots, cols))

    # ------------------------------------------------------------------
    def _convert(self, slot, col):
        if isinstance(slot, DenseSlot):
            arr = np.asarray(col, dtype=slot.dtype).reshape(len(col), slot.dim)
            return jnp.asarray(arr)
        if isinstance(slot, IndexSlot):
            return jnp.asarray(np.asarray(col, dtype=slot.dtype).reshape(len(col)))
        if isinstance(slot, SeqSlot):
            return self._convert_seq(slot, col)
        if isinstance(slot, SparseSlot):
            return self._convert_sparse(slot, col)
        raise TypeError(f"unknown slot {slot!r}")

    def _convert_seq(self, slot: SeqSlot, col):
        if slot.nested:
            # 2-level LoD: padded [B, S, T] + per-subseq and per-seq lengths
            # (subSequenceStartPositions analog, Argument.h:84-90)
            nested = [[np.asarray(sub, dtype=slot.np_dtype) for sub in sample]
                      for sample in col]
            return pack_nested_sequences(nested)
        seqs = [np.asarray(s, dtype=slot.np_dtype) for s in col]
        return pack_sequences(seqs)

    def _convert_sparse(self, slot: SparseSlot, col):
        if slot.with_values:
            ids_list = [[int(i) for i, _ in s] for s in col]
            val_list = [[float(v) for _, v in s] for s in col]
        else:
            ids_list = [[int(i) for i in s] for s in col]
            val_list = [[1.0] * len(s) for s in col]
        k = bucket_length(max(1, max((len(s) for s in ids_list), default=1)),
                          buckets=(4, 8, 16, 32, 64, 128, 256))
        B = len(col)
        ids = np.zeros((B, k), np.int32)
        vals = np.zeros((B, k), np.float32)
        for r, (ii, vv) in enumerate(zip(ids_list, val_list)):
            n = min(len(ii), k)
            ids[r, :n] = ii[:n]
            vals[r, :n] = vv[:n]
        return jnp.asarray(ids), jnp.asarray(vals)


def to_lod_batch(seqs, max_len: Optional[int] = None) -> SeqBatch:
    """Convenience: list of sequences -> SeqBatch (bucketed padding)."""
    return pack_sequences([np.asarray(s) for s in seqs], max_len=max_len)
