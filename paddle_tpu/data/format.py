"""Binary sample format — the proto DataProvider's DataFormat re-provision.

Reference (SURVEY §8.2, proto/DataFormat.proto): a stream of
``DataHeader{repeated SlotDef}`` then ``DataSample``s, where
``SlotDef.SlotType`` ∈ {VECTOR_DENSE, VECTOR_SPARSE_NON_VALUE,
VECTOR_SPARSE_VALUE, INDEX, VAR_MDIM_DENSE, VAR_MDIM_INDEX, STRING}, with
sequence starts flagged per sample and nested sequences via SubseqSlot.
That slot taxonomy is the framework's canonical feature-type system (it
reappears in PyDataProvider2 input_types and LayerGradUtil's InputType) and
maps 1:1 onto :mod:`paddle_tpu.data.feeder`'s slot classes.

This implementation keeps the header+samples stream shape with a compact
struct-based encoding (no protobuf dependency): little-endian, length-
prefixed. Files round-trip through :class:`DataWriter`/:class:`DataReader`;
``reader_creator`` adapts a file straight into the reader-decorator
pipeline (batch/shuffle/map) and DataFeeder.

Layout::

    magic  b"PTDF1\\n"
    header: u32 n_slots, then per slot: u8 type, u8 seq_flag, u32 dim
    samples: u32 record_len, then per slot the type-specific payload
    (samples for seq slots carry a u32 count prefix; nested slots a
     u32 sub-seq count then per-sub-seq u32 count + payloads)

Slot types (u8): 0 dense, 1 sparse-non-value, 2 sparse-value, 3 index,
4 string. seq_flag (u8): 0 none, 1 sequence, 2 nested (sub-sequences).
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, List, Sequence, Tuple

import numpy as np

MAGIC = b"PTDF1\n"

DENSE, SPARSE_NON_VALUE, SPARSE_VALUE, INDEX, STRING = range(5)
NO_SEQ, SEQ, SUB_SEQ = range(3)


class SlotDef:
    """One slot's schema (DataFormat.proto SlotDef)."""

    def __init__(self, slot_type: int, dim: int = 0, seq: int = NO_SEQ):
        self.type = slot_type
        self.dim = dim
        self.seq = seq

    def __eq__(self, other):
        if not isinstance(other, SlotDef):
            return NotImplemented
        return (self.type, self.dim, self.seq) == \
            (other.type, other.dim, other.seq)

    def __hash__(self):
        return hash((self.type, self.dim, self.seq))

    def __repr__(self):
        return f"SlotDef(type={self.type}, dim={self.dim}, seq={self.seq})"


def _pack_elem(slot: SlotDef, value, out: List[bytes]):
    if slot.type == DENSE:
        arr = np.asarray(value, np.float32).reshape(-1)
        if slot.dim and arr.size != slot.dim:
            raise ValueError(f"dense slot dim {slot.dim} got {arr.size}")
        out.append(struct.pack("<I", arr.size))
        out.append(arr.tobytes())
    elif slot.type == SPARSE_NON_VALUE:
        ids = np.asarray(value, np.int32).reshape(-1)
        if slot.dim and ids.size and int(ids.max()) >= slot.dim:
            raise ValueError(f"sparse id {int(ids.max())} >= dim {slot.dim}")
        out.append(struct.pack("<I", ids.size))
        out.append(ids.tobytes())
    elif slot.type == SPARSE_VALUE:
        ids = np.asarray([i for i, _ in value], np.int32)
        vals = np.asarray([v for _, v in value], np.float32)
        if slot.dim and ids.size and int(ids.max()) >= slot.dim:
            raise ValueError(f"sparse id {int(ids.max())} >= dim {slot.dim}")
        out.append(struct.pack("<I", ids.size))
        out.append(ids.tobytes())
        out.append(vals.tobytes())
    elif slot.type == INDEX:
        out.append(struct.pack("<i", int(value)))
    elif slot.type == STRING:
        raw = value.encode() if isinstance(value, str) else bytes(value)
        out.append(struct.pack("<I", len(raw)))
        out.append(raw)
    else:
        raise ValueError(f"unknown slot type {slot.type}")


def _need(buf, off, nbytes):
    """Bounds check: a corrupt count must fail loudly, not truncate."""
    if off + nbytes > len(buf):
        raise IOError("corrupt record (count exceeds record length)")


def _unpack_elem(slot: SlotDef, buf: memoryview, off: int) -> Tuple[Any, int]:
    if slot.type == DENSE:
        _need(buf, off, 4)
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        _need(buf, off, 4 * n)
        arr = np.frombuffer(buf, np.float32, n, off).copy()
        return arr, off + 4 * n
    if slot.type == SPARSE_NON_VALUE:
        _need(buf, off, 4)
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        _need(buf, off, 4 * n)
        ids = np.frombuffer(buf, np.int32, n, off).copy()
        return list(ids), off + 4 * n
    if slot.type == SPARSE_VALUE:
        _need(buf, off, 4)
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        _need(buf, off, 8 * n)
        ids = np.frombuffer(buf, np.int32, n, off)
        off += 4 * n
        vals = np.frombuffer(buf, np.float32, n, off)
        return list(zip((int(i) for i in ids), (float(v) for v in vals))), \
            off + 4 * n
    if slot.type == INDEX:
        _need(buf, off, 4)
        (v,) = struct.unpack_from("<i", buf, off)
        return int(v), off + 4
    if slot.type == STRING:
        _need(buf, off, 4)
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        _need(buf, off, n)
        return bytes(buf[off:off + n]).decode(), off + n
    raise ValueError(f"unknown slot type {slot.type}")


class DataWriter:
    """Write a header + sample stream (ProtoDataProvider writer analog)."""

    def __init__(self, f: BinaryIO, slots: Sequence[SlotDef]):
        self.f = f
        self.slots = list(slots)
        f.write(MAGIC)
        f.write(struct.pack("<I", len(self.slots)))
        for s in self.slots:
            f.write(struct.pack("<BBI", s.type, s.seq, s.dim))

    def write(self, sample: Sequence[Any]):
        """One sample: a value per slot. Non-seq slots take a bare element;
        seq slots a list of elements; nested slots a list of lists."""
        if len(sample) != len(self.slots):
            raise ValueError(f"sample has {len(sample)} values for "
                             f"{len(self.slots)} slots")
        parts: List[bytes] = []
        for slot, value in zip(self.slots, sample):
            if slot.seq == NO_SEQ:
                _pack_elem(slot, value, parts)
            elif slot.seq == SEQ:
                parts.append(struct.pack("<I", len(value)))
                for el in value:
                    _pack_elem(slot, el, parts)
            else:
                parts.append(struct.pack("<I", len(value)))
                for sub in value:
                    parts.append(struct.pack("<I", len(sub)))
                    for el in sub:
                        _pack_elem(slot, el, parts)
        payload = b"".join(parts)
        self.f.write(struct.pack("<I", len(payload)))
        self.f.write(payload)


class DataReader:
    """Iterate samples from a header + stream file."""

    def __init__(self, f: BinaryIO):
        self.f = f
        if f.read(len(MAGIC)) != MAGIC:
            raise IOError("not a PTDF file (bad magic)")
        hdr = f.read(4)
        if len(hdr) < 4:
            raise IOError("truncated header")
        (n,) = struct.unpack("<I", hdr)
        self.slots = []
        for _ in range(n):
            raw = f.read(6)
            if len(raw) < 6:
                raise IOError("truncated header")
            t, seq, dim = struct.unpack("<BBI", raw)
            self.slots.append(SlotDef(t, dim, seq))

    def __iter__(self):
        while True:
            hdr = self.f.read(4)
            if len(hdr) < 4:
                return
            (rec_len,) = struct.unpack("<I", hdr)
            payload = self.f.read(rec_len)
            if len(payload) < rec_len:
                raise IOError("truncated record")
            yield self._decode(memoryview(payload))

    @staticmethod
    def _read_count(buf: memoryview, off: int) -> int:
        """Bounds-checked SEQ/SUB_SEQ count prefix: corruption surfaces as
        the documented IOError, and an absurd count (larger than the record
        could possibly hold at 1 byte/element) fails before looping."""
        _need(buf, off, 4)
        (n,) = struct.unpack_from("<I", buf, off)
        if n > len(buf):
            raise IOError("corrupt record (count exceeds record length)")
        return n

    def _decode(self, buf: memoryview):
        off = 0
        sample = []
        for slot in self.slots:
            if slot.seq == NO_SEQ:
                v, off = _unpack_elem(slot, buf, off)
            elif slot.seq == SEQ:
                n = self._read_count(buf, off)
                off += 4
                v = []
                for _ in range(n):
                    el, off = _unpack_elem(slot, buf, off)
                    v.append(el)
            else:
                ns = self._read_count(buf, off)
                off += 4
                v = []
                for _ in range(ns):
                    n = self._read_count(buf, off)
                    off += 4
                    sub = []
                    for _ in range(n):
                        el, off = _unpack_elem(slot, buf, off)
                        sub.append(el)
                    v.append(sub)
            sample.append(v)
        return tuple(sample)


def reader_creator(path: str):
    """A reader() over a PTDF file — plugs into batch/shuffle/DataFeeder
    like any decorator-pipeline reader (ProtoDataProvider's role)."""
    def reader():
        with open(path, "rb") as f:
            yield from DataReader(f)
    return reader
