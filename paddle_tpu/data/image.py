"""Image preprocessing/augmentation (python/paddle/v2/image.py analog).

The reference wraps cv2; this is pure numpy (no cv2 in the TPU image): resize
(bilinear), center/random crop, horizontal flip, channel-mean normalize —
the standard ImageNet training pipeline pieces. All functions take HWC
float/uint8 arrays.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the short edge equals ``size`` (image.py resize_short)."""
    h, w = im.shape[:2]
    if h <= w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return _bilinear(im, nh, nw)


def _bilinear(im: np.ndarray, nh: int, nw: int) -> np.ndarray:
    h, w = im.shape[:2]
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    im = im.astype(np.float32)
    if im.ndim == 2:
        im = im[..., None]
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def center_crop(im: np.ndarray, size: int) -> np.ndarray:
    h, w = im.shape[:2]
    y = max(0, (h - size) // 2)
    x = max(0, (w - size) // 2)
    return im[y:y + size, x:x + size]


def random_crop(im: np.ndarray, size: int,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    y = rng.randint(0, max(h - size, 0) + 1)
    x = rng.randint(0, max(w - size, 0) + 1)
    return im[y:y + size, x:x + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def normalize(im: np.ndarray, mean: Sequence[float],
              std: Optional[Sequence[float]] = None) -> np.ndarray:
    out = im.astype(np.float32) - np.asarray(mean, np.float32)
    if std is not None:
        out = out / np.asarray(std, np.float32)
    return out


def simple_transform(im: np.ndarray, resize: int, crop: int, is_train: bool,
                     mean: Optional[Sequence[float]] = None,
                     rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    """The canonical train/test pipeline (image.py simple_transform):
    resize-short -> random/center crop -> (train) flip -> normalize."""
    im = resize_short(im, resize)
    im = random_crop(im, crop, rng) if is_train else center_crop(im, crop)
    if is_train and (rng or np.random).rand() < 0.5:
        im = left_right_flip(im)
    if mean is not None:
        im = normalize(im, mean)
    return im
