"""Image preprocessing/augmentation (python/paddle/v2/image.py analog).

The reference wraps cv2; this is pure numpy (no cv2 in the TPU image): resize
(bilinear), center/random crop, horizontal flip, channel-mean normalize —
the standard ImageNet training pipeline pieces. All functions take HWC
float/uint8 arrays.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the short edge equals ``size`` (image.py resize_short)."""
    h, w = im.shape[:2]
    if h <= w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return _bilinear(im, nh, nw)


def _bilinear(im: np.ndarray, nh: int, nw: int) -> np.ndarray:
    h, w = im.shape[:2]
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    im = im.astype(np.float32)
    if im.ndim == 2:
        im = im[..., None]
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def center_crop(im: np.ndarray, size: int) -> np.ndarray:
    h, w = im.shape[:2]
    y = max(0, (h - size) // 2)
    x = max(0, (w - size) // 2)
    return im[y:y + size, x:x + size]


def random_crop(im: np.ndarray, size: int,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    y = rng.randint(0, max(h - size, 0) + 1)
    x = rng.randint(0, max(w - size, 0) + 1)
    return im[y:y + size, x:x + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def normalize(im: np.ndarray, mean: Sequence[float],
              std: Optional[Sequence[float]] = None) -> np.ndarray:
    out = im.astype(np.float32) - np.asarray(mean, np.float32)
    if std is not None:
        out = out / np.asarray(std, np.float32)
    return out


def simple_transform(im: np.ndarray, resize: int, crop: int, is_train: bool,
                     mean: Optional[Sequence[float]] = None,
                     rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    """The canonical train/test pipeline (image.py simple_transform):
    resize-short -> random/center crop -> (train) flip -> normalize."""
    im = resize_short(im, resize)
    im = random_crop(im, crop, rng) if is_train else center_crop(im, crop)
    if is_train and (rng or np.random).rand() < 0.5:
        im = left_right_flip(im)
    if mean is not None:
        im = normalize(im, mean)
    return im


def to_chw(im: np.ndarray, order: Tuple[int, int, int] = (2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (image.py to_chw) — the layout the reference's conv layers
    ate; paddle_tpu convs are NHWC-native, so use this only for exported
    compatibility paths."""
    return im.transpose(order)


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an encoded (PNG/JPEG/...) image from bytes -> HWC uint8
    (image.py load_image_bytes; PIL replaces the reference's cv2)."""
    import io

    from PIL import Image
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    arr = np.asarray(img)
    if not is_color:
        arr = arr[..., None]
    return arr


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    """image.py load_image."""
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def load_and_transform(path: str, resize: int, crop: int, is_train: bool,
                       is_color: bool = True,
                       mean: Optional[Sequence[float]] = None) -> np.ndarray:
    """image.py load_and_transform: decode + simple_transform."""
    return simple_transform(load_image(path, is_color), resize, crop,
                            is_train, mean=mean)


def batch_images_from_tar(tar_path: str, dataset_name: str,
                          img2label: dict, num_per_batch: int = 1024,
                          out_path: Optional[str] = None) -> str:
    """Pre-batch a tar of encoded images into pickled numpy batches
    (image.py batch_images_from_tar): each output batch file holds
    {'data': [raw bytes...], 'label': [...]}; returns the batch-list file."""
    import pickle
    import tarfile

    out_path = out_path or (tar_path + "_batch")
    import os
    os.makedirs(out_path, exist_ok=True)
    data, labels, names = [], [], []
    with tarfile.open(tar_path) as tf:
        for m in tf.getmembers():
            if m.name not in img2label:
                continue
            data.append(tf.extractfile(m).read())
            labels.append(img2label[m.name])
            if len(data) == num_per_batch:
                names.append(_dump_batch(out_path, dataset_name, len(names),
                                         data, labels))
                data, labels = [], []
    if data:
        names.append(_dump_batch(out_path, dataset_name, len(names), data,
                                 labels))
    listfile = f"{out_path}/{dataset_name}.batch_list"
    with open(listfile, "w") as f:
        f.write("\n".join(names))
    return listfile


def _dump_batch(out_path, name, idx, data, labels):
    import pickle
    fname = f"{out_path}/{name}_batch_{idx:04d}"
    with open(fname, "wb") as f:
        pickle.dump({"data": list(data), "label": list(labels)}, f)
    return fname
