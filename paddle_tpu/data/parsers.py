"""Real-format dataset parsers — the file-reading half of the reference's
dataset zoo.

The reference's datasets download real archives and parse real bytes
(python/paddle/v2/dataset/common.py:33-64 download+md5 cache; mnist.py:42-75
idx parsing; cifar.py pickled tar members; conll05.py column corpus;
wmt14.py tokenized parallel text). This sandbox has no egress, so the
*download* half is stubbed loudly (see :func:`download`) — but the parsers
are real and tested against checked-in fixtures (tests/fixtures/), so a
deployment with data on disk feeds real bytes through the same reader API
the synthetic generators expose.
"""

from __future__ import annotations

import gzip
import hashlib
import os
import pickle
import struct
import tarfile
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------- common ----
# dataset/common.py analog: cache layout + md5 discipline; download is a
# loud offline stub (file:// and existing-file paths still work).

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def md5file(path: str) -> str:
    """dataset/common.py md5file: streaming md5 of a file."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: Optional[str] = None) -> str:
    """Cache-or-fail (dataset/common.py:33-64 role). A cached file with a
    matching md5 is returned; otherwise this raises — the sandbox has no
    egress, and silently truncated datasets are worse than loud ones."""
    cache_dir = os.path.join(DATA_HOME, module_name)
    os.makedirs(cache_dir, exist_ok=True)
    filename = os.path.join(cache_dir, url.split("/")[-1])
    if url.startswith("file://"):
        filename = url[len("file://"):]
    if os.path.exists(filename):
        if md5sum is not None and md5file(filename) != md5sum:
            raise IOError(f"{filename}: md5 mismatch (corrupt cache); "
                          "delete it and re-provision")
        return filename
    raise IOError(
        f"{url} is not cached at {filename} and this environment has no "
        "network egress; place the file there (or use a file:// url) — "
        "the parser side is fully supported")


def _open_maybe_gzip(path: str):
    with open(path, "rb") as probe:
        magic = probe.read(2)
    return gzip.open(path, "rb") if magic == b"\x1f\x8b" else open(path, "rb")


# ----------------------------------------------------------------- MNIST ----

def parse_idx_images(path: str) -> np.ndarray:
    """idx3-ubyte (optionally gzipped) -> float32 [N, rows*cols] scaled to
    [-1, 1] (the reference's normalization, mnist.py:59-63)."""
    with _open_maybe_gzip(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise IOError(f"{path}: bad idx3 magic {magic} (want 2051)")
        buf = f.read(n * rows * cols)
        if len(buf) < n * rows * cols:
            raise IOError(f"{path}: truncated idx3 body")
        imgs = np.frombuffer(buf, np.uint8).reshape(n, rows * cols)
        return (imgs.astype(np.float32) / 255.0) * 2.0 - 1.0


def parse_idx_labels(path: str) -> np.ndarray:
    """idx1-ubyte (optionally gzipped) -> int32 [N]."""
    with _open_maybe_gzip(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise IOError(f"{path}: bad idx1 magic {magic} (want 2049)")
        buf = f.read(n)
        if len(buf) < n:
            raise IOError(f"{path}: truncated idx1 body")
        return np.frombuffer(buf, np.uint8).astype(np.int32)


def mnist_reader(images_path: str, labels_path: str):
    """Reader over real MNIST idx files — same sample schema as the
    synthetic dataset.mnist (image[784] float, int label). Files parse
    lazily on first iteration, then cache, so multi-pass training decodes
    the ~55MB idx bodies once."""
    cache = []

    def reader():
        if not cache:
            imgs = parse_idx_images(images_path)
            labels = parse_idx_labels(labels_path)
            if len(imgs) != len(labels):
                raise IOError("mnist: image/label count mismatch "
                              f"({len(imgs)} vs {len(labels)})")
            cache.append((imgs, labels))
        imgs, labels = cache[0]
        for i in range(len(imgs)):
            yield imgs[i], int(labels[i])
    return reader


# ----------------------------------------------------------------- CIFAR ----

def cifar_reader(archive_path: str, member_prefix: str = "data_batch"):
    """Reader over a real CIFAR tar.gz of pickled batches
    (cifar.py reader_creator: dict[b'data'] [N,3072] uint8,
    dict[b'labels']). Yields (image[3072] float in [-1,1], int label)."""
    def reader():
        with tarfile.open(archive_path, "r:*") as tar:
            names = sorted(m.name for m in tar.getmembers()
                           if member_prefix in m.name)
            if not names:
                raise IOError(f"{archive_path}: no members matching "
                              f"{member_prefix!r}")
            for name in names:
                batch = pickle.load(tar.extractfile(name), encoding="bytes")
                data = batch[b"data"].astype(np.float32) / 255.0 * 2.0 - 1.0
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                if labels is None:
                    raise IOError(f"{archive_path}:{name}: batch dict has "
                                  "neither b'labels' nor b'fine_labels' "
                                  "(corrupt or foreign pickle)")
                for i in range(len(data)):
                    yield data[i], int(labels[i])
    return reader


# ----------------------------------------------------------- CoNLL column ---

def parse_conll_columns(path: str, word_col: int = 0,
                        tag_col: int = -1) -> Iterator[Tuple[List[str], List[str]]]:
    """Classic CoNLL column corpus: one token per line, whitespace-separated
    columns, blank line ends a sentence (conll05.py corpus layout).
    Yields (words, tags) per sentence."""
    words: List[str] = []
    tags: List[str] = []
    with _open_maybe_gzip(path) as f:
        for raw in f:
            line = raw.decode("utf-8").strip()
            if not line:
                if words:
                    yield words, tags
                    words, tags = [], []
                continue
            cols = line.split()
            words.append(cols[word_col])
            tags.append(cols[tag_col])
    if words:
        yield words, tags


def build_dict(tokens: Iterator[str], min_count: int = 0,
               specials: Tuple[str, ...] = ("<unk>",)) -> Dict[str, int]:
    """Frequency-ordered token dict (dataset/common.py word-dict role)."""
    counts: Dict[str, int] = {}
    for t in tokens:
        counts[t] = counts.get(t, 0) + 1
    vocab = list(specials) + [
        t for t, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if c > min_count and t not in specials]
    return {t: i for i, t in enumerate(vocab)}


def conll_reader(path: str, word_dict: Optional[Dict[str, int]] = None,
                 tag_dict: Optional[Dict[str, int]] = None,
                 word_col: int = 0, tag_col: int = -1):
    """Reader over a real CoNLL column file: (word_ids, tag_ids) int lists
    — the conll05 sample schema. Dicts are built from the file when not
    given (pass the TRAIN dicts when reading test)."""
    sents = list(parse_conll_columns(path, word_col, tag_col))
    if word_dict is None:
        word_dict = build_dict(w for ws, _ in sents for w in ws)
    if tag_dict is None:
        tag_dict = build_dict((t for _, ts in sents for t in ts),
                              specials=())
    unk = word_dict.get("<unk>")

    def lookup_word(w):
        wid = word_dict.get(w, unk)
        if wid is None:
            raise ValueError(
                f"conll: word {w!r} not in the supplied word_dict and the "
                "dict has no '<unk>' entry — add one (build_dict does) or "
                "pass a dict covering this split")
        return wid

    def lookup_tag(t):
        tid = tag_dict.get(t)
        if tid is None:
            raise ValueError(
                f"conll: tag {t!r} not in the supplied tag_dict "
                f"({len(tag_dict)} tags) — tag sets must cover every split "
                "(build the dict over train+test or extend it)")
        return tid

    def reader():
        for ws, ts in sents:
            yield ([lookup_word(w) for w in ws],
                   [lookup_tag(t) for t in ts])
    reader.word_dict = word_dict
    reader.tag_dict = tag_dict
    return reader


# ------------------------------------------------------ parallel corpora ----

BOS, EOS, UNK = "<s>", "<e>", "<unk>"


def parallel_text_reader(src_path: str, trg_path: str,
                         src_dict: Optional[Dict[str, int]] = None,
                         trg_dict: Optional[Dict[str, int]] = None):
    """Reader over aligned plain-text files (wmt14.py corpus semantics):
    per line, whitespace-tokenized; yields the reference's NMT triple
    (src_ids, trg_ids_with_bos, trg_ids_with_eos)."""
    def lines(p):
        # keep blank lines so positions stay aligned; pairs where either
        # side is empty are dropped TOGETHER below
        with _open_maybe_gzip(p) as f:
            return [l.decode("utf-8").split() for l in f]

    src_all, trg_all = lines(src_path), lines(trg_path)
    if len(src_all) != len(trg_all):
        raise IOError(f"parallel corpus misaligned: {len(src_all)} src vs "
                      f"{len(trg_all)} trg lines")
    pairs = [(s, t) for s, t in zip(src_all, trg_all) if s and t]
    src_lines = [s for s, _ in pairs]
    trg_lines = [t for _, t in pairs]
    if src_dict is None:
        src_dict = build_dict((t for l in src_lines for t in l),
                              specials=(BOS, EOS, UNK))
    if trg_dict is None:
        trg_dict = build_dict((t for l in trg_lines for t in l),
                              specials=(BOS, EOS, UNK))
    s_unk, t_unk = src_dict[UNK], trg_dict[UNK]
    t_bos, t_eos = trg_dict[BOS], trg_dict[EOS]

    def reader():
        for s, t in zip(src_lines, trg_lines):
            sid = [src_dict.get(w, s_unk) for w in s]
            tid = [trg_dict.get(w, t_unk) for w in t]
            yield sid, [t_bos] + tid, tid + [t_eos]
    reader.src_dict = src_dict
    reader.trg_dict = trg_dict
    return reader
