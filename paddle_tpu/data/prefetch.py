"""Double-buffered host->device prefetch.

The reference overlaps batch production with training via a dedicated thread and
a two-slot queue (``DoubleBuffer``, gserver/dataproviders/DataProvider.h:249,
enabled per-provider). TPU-native: the same thread structure, but the payload is
already-converted jax arrays, so a device transfer can be in flight while the
previous step computes (jax dispatch is async; this hides the *host* conversion
cost too).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional

from .. import obs


class DoubleBuffer:
    """Wrap a batch iterable; a worker thread keeps ``depth`` batches ready.

    Usage::
        for batch in DoubleBuffer(lambda: feeder_batches(), depth=2):
            step(*batch)
    """

    def __init__(self, batches: Callable[[], Iterable[Any]], depth: int = 2,
                 transform: Optional[Callable[[Any], Any]] = None,
                 timeout: Optional[float] = None):
        self.batches = batches
        self.depth = depth
        self.transform = transform
        # watchdog: a producer that silently wedges (dead data source, hung
        # filesystem) must surface as TimeoutError, not hang the train loop
        self.timeout = timeout

    def __iter__(self) -> Iterator[Any]:
        from .reader import buffered, map_readers
        # queue health (data.queue_depth / data.starved_total) is reported
        # by the underlying buffered() consumer loop — one implementation
        # for both the per-reader decorator and this trainer-facing wrapper
        obs.count("data.prefetch_iters_total")
        creator = self.batches
        if self.transform is not None:
            # transform runs on the worker thread, overlapping host conversion
            # with device compute
            creator = map_readers(self.transform, creator)
        return iter(buffered(creator, self.depth, timeout=self.timeout)())
