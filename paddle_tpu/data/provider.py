"""The ``@provider`` decorator protocol — PyDataProvider2 parity.

Reference surface (python/paddle/trainer/PyDataProvider2.py:55): a user
writes ``def process(settings, filename)`` yielding rows, decorates it with
``@provider(input_types=..., cache=..., init_hook=...)``, and the trainer
pulls batches per file. TPU-native mapping: the decorated function becomes
a READER CREATOR factory — ``process(f1, f2, ...)`` returns a creator
compatible with every reader decorator/DataFeeder in :mod:`paddle_tpu.data`
— so legacy provider code ports by changing only how the result is handed
to the trainer. ``cache=CacheType.CACHE_PASS_IN_MEM`` materializes rows on
the first pass (the reference's in-memory pass cache); ``init_hook`` runs
once with the settings object before any row is produced.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class _Settings:
    """The ``settings`` object handed to init_hook and process: carries
    input_types (+ anything init_hook attaches — the reference's
    settings.slots idiom)."""

    def __init__(self, input_types):
        self.input_types = input_types
        self.slots = input_types
        self.logger = None


def provider(input_types: Optional[Sequence] = None,
             cache: int = CacheType.NO_CACHE,
             init_hook: Optional[Callable] = None,
             should_shuffle: bool = False,
             **hook_kwargs: Any):
    """Decorate ``process(settings, source)`` into a reader-creator factory.

    ``process("a.txt", "b.txt")`` -> reader creator yielding every row of
    every source, optionally shuffled per pass (should_shuffle) and cached
    in memory after the first pass (CACHE_PASS_IN_MEM).
    """

    def deco(process: Callable):
        def make_reader(*sources):
            settings = _Settings(list(input_types or []))
            if init_hook is not None:
                init_hook(settings, **hook_kwargs)
            srcs = list(sources) if sources else [None]
            cached: list = []

            def reader():
                if cache == CacheType.CACHE_PASS_IN_MEM and cached:
                    rows = cached
                else:
                    rows = []
                    for src in srcs:
                        for row in process(settings, src):
                            if cache == CacheType.CACHE_PASS_IN_MEM:
                                rows.append(row)
                            elif should_shuffle:
                                rows.append(row)
                            else:
                                yield row
                    # fill only while still empty: two generators started
                    # against an empty cache (a partially-consumed pass
                    # resumed alongside a full one) must not both extend,
                    # duplicating every row in subsequent passes
                    if cache == CacheType.CACHE_PASS_IN_MEM and not cached:
                        cached.extend(rows)
                if cache == CacheType.CACHE_PASS_IN_MEM or should_shuffle:
                    if should_shuffle:
                        import random
                        rows = list(rows)
                        random.shuffle(rows)
                    yield from rows

            reader.settings = settings
            return reader

        make_reader.__name__ = getattr(process, "__name__", "provider")
        make_reader.settings_factory = lambda: _Settings(
            list(input_types or []))
        return make_reader

    return deco
