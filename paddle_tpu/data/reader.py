"""Reader protocol + decorators.

A *reader creator* is a zero-arg callable returning an iterable of samples —
identical protocol to the reference (python/paddle/v2/reader/decorator.py:26-233,
minibatch.py). Decorators compose creators; everything is lazy.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable, List, Sequence

from .. import obs

Reader = Callable[[], Iterable[Any]]


def map_readers(func: Callable, *readers: Reader) -> Reader:
    """Apply func elementwise across the outputs of several readers
    (decorator.py:26 map_readers)."""

    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader_creator: Reader, buf_size: int, seed: int = None) -> Reader:
    """Pool-shuffle with a bounded buffer (decorator.py:62 shuffle)."""

    def reader():
        rng = _random.Random(seed)
        buf: List[Any] = []
        for e in reader_creator():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return reader


def chain(*readers: Reader) -> Reader:
    """Concatenate readers end-to-end (decorator.py:90 chain)."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip readers into tuple samples (decorator.py:118 compose)."""

    def _to_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        for items in (zip(*its) if not check_alignment
                      else itertools.zip_longest(*its, fillvalue=_SENTINEL)):
            if check_alignment and any(i is _SENTINEL for i in items):
                raise ValueError("composed readers have different lengths")
            yield sum((_to_tuple(i) for i in items), ())

    return reader


_SENTINEL = object()


def buffered(reader_creator: Reader, size: int,
             timeout: float = None) -> Reader:
    """Background-thread read-ahead of up to ``size`` samples — the per-reader
    analog of the C++ DoubleBuffer (DataProvider.h:249).

    ``timeout`` is a watchdog: if the producer thread delivers nothing for
    that many seconds, the consumer raises TimeoutError instead of blocking
    forever behind a wedged data source."""

    def reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        end = object()
        err: List[BaseException] = []

        def worker():
            try:
                for s in reader_creator():
                    q.put(s)
            except BaseException as e:  # propagate into the consumer
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        warmed = False
        while True:
            if obs.is_active() and warmed:
                # consumer-side queue health: depth at consume (peak rides
                # the gauge's high-water) and how often the producer was
                # behind — the starvation signal that says "the input
                # pipeline, not the device, is the bottleneck". The first
                # get is skipped: the worker thread just started, so an
                # empty queue there is startup, not starvation (counting
                # it would report ~1 phantom starve per stream).
                depth = q.qsize()
                obs.gauge_set("data.queue_depth", depth)
                if depth == 0:
                    obs.count("data.starved_total")
            warmed = True
            try:
                s = q.get(timeout=timeout)
            except queue.Empty:
                obs.count("data.timeouts_total")
                raise TimeoutError(
                    f"prefetch watchdog: no batch within {timeout}s "
                    "(data source wedged?)") from None
            if s is end:
                if err:
                    raise err[0]
                return
            yield s

    return reader


def firstn(reader_creator: Reader, n: int) -> Reader:
    """Take the first n samples (decorator.py:172 firstn)."""

    def reader():
        return itertools.islice(reader_creator(), n)

    return reader


def xmap_readers(mapper: Callable, reader_creator: Reader, process_num: int,
                 buffer_size: int, order: bool = False) -> Reader:
    """Parallel map over a thread pool (decorator.py:190 xmap_readers)."""

    def reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        end = object()

        def feeder():
            for i, s in enumerate(reader_creator()):
                in_q.put((i, s))
            for _ in range(process_num):
                in_q.put(end)

        def worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, s = item
                out_q.put((i, mapper(s)))

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                else:
                    yield item[1]
        else:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                pending[item[0]] = item[1]
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            while want in pending:
                yield pending.pop(want)
                want += 1

    return reader


def cache(reader_creator: Reader) -> Reader:
    """Materialise once, replay from memory (PyDataProvider2 CacheType.CACHE_PASS
    analog, python/paddle/trainer/PyDataProvider2.py:55)."""
    data: List[Any] = []
    filled = [False]

    def reader():
        if not filled[0]:
            data.extend(reader_creator())
            filled[0] = True
        return iter(data)

    return reader


def batch(reader_creator: Reader, batch_size: int, drop_last: bool = False) -> Reader:
    """Group samples into lists of batch_size (v2/minibatch.py paddle.batch)."""

    def reader():
        b: List[Any] = []
        for s in reader_creator():
            b.append(s)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return reader


def mix(readers_with_ratios, seed: int = 0) -> Reader:
    """Ratio-mixed interleave of sub-readers — the MultiDataProvider.cpp
    analog (gserver/dataproviders/MultiDataProvider: sub-providers sampled by
    configured ratios). ``readers_with_ratios``: [(reader, weight), ...] with
    strictly positive weights; exhausted sub-readers drop out and the rest
    renormalise."""
    if any(w <= 0 for _, w in readers_with_ratios):
        raise ValueError("mix() weights must be strictly positive")

    def reader():
        rng = _random.Random(seed)
        its = [iter(r()) for r, _ in readers_with_ratios]
        weights = [float(w) for _, w in readers_with_ratios]
        while its:
            i = rng.choices(range(len(its)), weights=weights)[0]
            try:
                yield next(its[i])
            except StopIteration:
                del its[i], weights[i]

    return reader
