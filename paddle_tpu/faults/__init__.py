"""paddle_tpu.faults — deterministic fault injection for the runtime.

See :mod:`paddle_tpu.faults.inject` for the site catalogue and semantics,
and docs/design/faults.md for the design contract.
"""

from .inject import (SITES, Fault, FaultError, FaultPlan, filter_bytes,
                     filter_value, fire, is_active)

__all__ = ["FaultPlan", "Fault", "FaultError", "SITES",
           "fire", "filter_bytes", "filter_value", "is_active"]
