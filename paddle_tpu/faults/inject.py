"""Deterministic, seedable fault injection for the runtime's failure paths.

The reference stack's fault tolerance (Go master task re-dispatch, etcd lease
failover, CRC-checked pserver checkpoints — PAPER.md §5) is only trustworthy
if it can be *exercised*: a checkpoint writer that is never killed mid-write,
an RPC client whose responses are never dropped, and a lease keeper whose
renewals never stall are all untested code. This module is the chaos plane:
a process-global :class:`FaultPlan` holding :class:`Fault` rules keyed by
*injection site* — a short dotted name marking one failure-prone operation:

========================  =====================================================
site                      where it fires
========================  =====================================================
``ckpt.write``            per checkpoint member written (trainer/checkpoint.py)
``rpc.send``              per request frame sent (runtime/master_service.py)
``rpc.recv``              per response frame received (master + coord clients)
``lease.renew``           per lease renewal (runtime/lease.py, runtime/coord.py)
``reader.next``           per chunk-task stream opened (data/chunks.py)
``step.grad``             per train-step loss produced (trainer/trainer.py)
                          and per elastic shard gradient (trainer/elastic.py)
``mbr.heartbeat``         per membership heartbeat sent (runtime/membership.py)
``srv.ship``              per KV-page chunk serialized for shipping
                          (serving/ship.py — corrupt/truncate mangle the raw
                          chunk bytes AFTER the CRC was stamped, so the
                          receiver detects the damage and refuses structured)
``srv.adopt``             per shipped-slot adoption attempted on a decode
                          worker (serving/daemon.py srv_adopt_pages)
``route.submit``          per submit forwarded by the serving router
                          (serving/router.py — raise models a worker hop
                          dying mid-placement; the router retries the next
                          candidate)
``actor.spawn``           per worker spawn the fleet actor commits
                          (cluster/actor.py — raise models the launch
                          failing; the actor journals spawn_failed, counts
                          the failure and keeps the loop alive)
``actor.drain``           per graceful drain the fleet actor commits
                          (cluster/actor.py — delay models a hung drain,
                          which the grace deadline escalates to kill)
========================  =====================================================

``step.grad`` caveat: the hook filters the HOST-observed loss value after
the jitted step ran — it drives the detection/raise/halt machinery, but it
cannot reach inside the XLA graph, so the in-step non-finite select (the
``skip``/``halt`` update-drop) only reacts to a *genuinely* non-finite
loss. To chaos-test skip-accounting byte-identity, poison the batch data
(see tests/test_faults.py) rather than corrupting ``step.grad``.

Rules trigger on the Nth hit of their site (and optionally for ``count``
consecutive hits after that) and perform one action: ``raise`` an exception,
``delay`` (sleep), ``truncate`` a byte payload, or ``corrupt`` a value.
Determinism: hit counters are exact, and any randomness (corruption bytes)
comes from a ``random.Random(seed)`` owned by the plan — the same plan
replays the same failure sequence every run, which is what lets the chaos
tests in tests/test_faults.py assert byte-identical recovery.

Zero cost when disabled: every hook first checks a module-level ``_PLAN is
None`` — one attribute load and branch on the hot path, no locks, no dict
lookups. Production code never pays for the harness it ships with.

Usage::

    plan = FaultPlan(seed=7)
    plan.add("rpc.send", action="raise", nth=1, count=2,
             exc=ConnectionError("injected"))
    with plan.installed():
        ...   # the first two rpc.send hits raise ConnectionError
    assert plan.fired  # [('rpc.send', 1, 'raise'), ('rpc.send', 2, 'raise')]
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs

SITES = ("ckpt.write", "rpc.send", "rpc.recv", "lease.renew",
         "reader.next", "step.grad", "mbr.heartbeat", "srv.ship",
         "srv.adopt", "route.submit", "actor.spawn", "actor.drain")

#: process-global active plan; None = harness disabled (the fast path)
_PLAN: Optional["FaultPlan"] = None


class FaultError(RuntimeError):
    """Default exception raised by a ``raise`` fault with no ``exc``."""


class Fault:
    """One injection rule: at hits ``nth .. nth+count-1`` of ``site``, do
    ``action``. Actions:

    * ``raise``    — raise ``exc`` (an exception instance or zero-arg factory)
    * ``delay``    — sleep ``delay_s`` seconds
    * ``truncate`` — cut a byte payload to ``truncate_to`` bytes (or by
      ``truncate_frac`` of its length)
    * ``corrupt``  — XOR one plan-seeded byte of a payload, or pass a value
      through ``mutate`` (default for non-bytes: float('nan'))
    """

    __slots__ = ("site", "action", "nth", "count", "exc", "delay_s",
                 "truncate_to", "truncate_frac", "mutate")

    def __init__(self, site: str, action: str = "raise", *, nth: int = 1,
                 count: int = 1, exc=None, delay_s: float = 0.05,
                 truncate_to: Optional[int] = None,
                 truncate_frac: float = 0.5,
                 mutate: Optional[Callable[[Any], Any]] = None):
        if site not in SITES:
            raise ValueError(f"unknown injection site {site!r}; "
                             f"known sites: {', '.join(SITES)}")
        if action not in ("raise", "delay", "truncate", "corrupt"):
            raise ValueError(f"unknown fault action {action!r}")
        if nth < 1 or count < 1:
            raise ValueError("nth and count are 1-based and positive")
        self.site = site
        self.action = action
        self.nth = nth
        self.count = count
        self.exc = exc
        self.delay_s = delay_s
        self.truncate_to = truncate_to
        self.truncate_frac = truncate_frac
        self.mutate = mutate

    def matches(self, hit: int) -> bool:
        return self.nth <= hit < self.nth + self.count


class FaultPlan:
    """A set of :class:`Fault` rules plus the hit/fire bookkeeping.

    Thread-safe: hit counters and the fired log are guarded by one lock
    (checkpoint writers, lease keepers and prefetch threads all hit sites
    concurrently). Install with :meth:`install`/:meth:`uninstall` or the
    :meth:`installed` context manager; only one plan is active at a time.
    """

    def __init__(self, seed: int = 0,
                 sleep: Optional[Callable[[float], None]] = None):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: List[Fault] = []
        self.hits: Dict[str, int] = {}
        #: chronological (site, hit_number, action) log of every fault fired
        self.fired: List[Tuple[str, int, str]] = []
        self._lock = threading.Lock()
        # injectable sleeper for `delay` actions: fake-clock chaos tests
        # (ISSUE 15 straggler detection) advance a counter instead of
        # stalling the suite — the utils/retry clock discipline
        self._sleep = sleep or time.sleep

    # -- authoring ----------------------------------------------------------
    def add(self, site: str, action: str = "raise", **kw) -> "FaultPlan":
        self.faults.append(Fault(site, action, **kw))
        return self

    # -- lifecycle ----------------------------------------------------------
    def install(self) -> "FaultPlan":
        global _PLAN
        if _PLAN is not None and _PLAN is not self:
            raise RuntimeError("another FaultPlan is already installed")
        _PLAN = self
        return self

    def uninstall(self):
        global _PLAN
        if _PLAN is self:
            _PLAN = None

    @contextlib.contextmanager
    def installed(self):
        self.install()
        try:
            yield self
        finally:
            self.uninstall()

    def reset(self):
        """Clear counters and the fired log (rules stay)."""
        with self._lock:
            self.hits.clear()
            self.fired.clear()
            self.rng = random.Random(self.seed)

    # -- firing -------------------------------------------------------------
    def _hit(self, site: str) -> Tuple[int, List[Fault]]:
        with self._lock:
            n = self.hits.get(site, 0) + 1
            self.hits[site] = n
            due = [f for f in self.faults if f.site == site and f.matches(n)]
            for f in due:
                self.fired.append((site, n, f.action))
        # outside the plan lock (obs has its own): per-site injected-fault
        # counters make a chaos run self-describing — the exported metrics
        # say exactly which failures the run was subjected to
        for f in due:
            obs.count("faults.injected_total", site=site, action=f.action)
        return n, due

    def fire(self, site: str):
        """Side-effect-only hook: raise or delay. Truncation/corruption of
        payloads goes through :func:`filter_bytes` / :func:`filter_value`."""
        _, due = self._hit(site)
        for f in due:
            if f.action == "delay":
                self._sleep(f.delay_s)
            elif f.action == "raise":
                # flight recorder (obs/flight.py): persist the span ring
                # BEFORE the injected exception starts unwinding — even if
                # a retry layer later swallows it and the process is then
                # SIGKILLed, the chaos run's tail is already on disk
                obs.flight_dump(f"fault:{site}")
                raise self._make_exc(f, site)
        # truncate/corrupt rules at a fire-only site are authoring errors we
        # surface loudly instead of silently ignoring
        for f in due:
            if f.action in ("truncate", "corrupt"):
                raise FaultError(
                    f"fault at {site} wants action {f.action!r} but the site "
                    "only supports raise/delay (no payload flows through it)")

    def filter_bytes(self, site: str, data: bytes) -> bytes:
        """Payload hook: apply raise/delay plus truncate/corrupt to bytes."""
        _, due = self._hit(site)
        for f in due:
            if f.action == "delay":
                self._sleep(f.delay_s)
            elif f.action == "raise":
                obs.flight_dump(f"fault:{site}")
                raise self._make_exc(f, site)
            elif f.action == "truncate":
                cut = (f.truncate_to if f.truncate_to is not None
                       else int(len(data) * f.truncate_frac))
                data = data[:max(0, cut)]
            elif f.action == "corrupt":
                if data:
                    b = bytearray(data)
                    with self._lock:   # serialize rng draws across threads
                        i = self.rng.randrange(len(b))
                    b[i] ^= 0xFF
                    data = bytes(b)
        return data

    def filter_value(self, site: str, value):
        """Value hook: raise/delay plus ``corrupt`` (mutate or NaN)."""
        _, due = self._hit(site)
        for f in due:
            if f.action == "delay":
                self._sleep(f.delay_s)
            elif f.action == "raise":
                obs.flight_dump(f"fault:{site}")
                raise self._make_exc(f, site)
            elif f.action == "corrupt":
                value = (f.mutate(value) if f.mutate is not None
                         else float("nan"))
            elif f.action == "truncate":
                raise FaultError(
                    f"fault at {site} wants 'truncate' but the site carries "
                    "a value, not bytes — use 'corrupt' with mutate=")
        return value

    @staticmethod
    def _make_exc(f: Fault, site: str) -> BaseException:
        if f.exc is None:
            return FaultError(f"injected fault at {site}")
        if isinstance(f.exc, BaseException):
            return f.exc
        if isinstance(f.exc, type) and issubclass(f.exc, BaseException):
            return f.exc(f"injected fault at {site}")
        return f.exc()   # zero-arg factory


# -- module-level hooks (what instrumented code calls) --------------------------
# Each first checks `_PLAN is None`: one load + branch when the harness is off.

def is_active() -> bool:
    return _PLAN is not None


def fire(site: str) -> None:
    if _PLAN is None:
        return
    _PLAN.fire(site)


def filter_bytes(site: str, data: bytes) -> bytes:
    if _PLAN is None:
        return data
    return _PLAN.filter_bytes(site, data)


def filter_value(site: str, value):
    if _PLAN is None:
        return value
    return _PLAN.filter_value(site, value)
