"""fluid — the Program-IR front end (gen-2 analog, SURVEY.md §2.2/§2.4).

Build a Program of ops via ``layers``, differentiate with
``backward.append_backward`` (or optimizer.minimize), and run it with
``Executor`` — which compiles each block to a single cached XLA computation.
"""

from . import (backward, evaluator, executor, io, layers, nets, optimizer,
               registry, regularizer)
from ..nn import initializer
from .backward import append_backward
from .evaluator import Accuracy as AccuracyEvaluator
from .evaluator import ChunkEvaluator
from ..data.feeder import BucketSpec
from .executor import Executor, Scope, global_scope
from .framework import (Block, Operator, Program, Variable,
                        default_main_program, default_startup_program,
                        program_guard, reset_default_programs)
from .layers import Cond, StaticRNN, While
from .optimizer import (AdadeltaOptimizer, AdagradOptimizer, AdamaxOptimizer,
                        AdamOptimizer, DecayedAdagradOptimizer, FtrlOptimizer,
                        MomentumOptimizer, RMSPropOptimizer, SGDOptimizer)
from .registry import OpRegistry
from .regularizer import L1Decay, L2Decay, append_regularization_ops

__all__ = ["layers", "backward", "io", "optimizer", "registry", "executor",
           "nets", "regularizer", "evaluator", "initializer",
           "append_backward", "Executor", "Scope", "global_scope",
           "BucketSpec",
           "Program", "Block", "Operator", "Variable",
           "default_main_program", "default_startup_program", "program_guard",
           "reset_default_programs", "While", "Cond", "StaticRNN",
           "SGDOptimizer", "MomentumOptimizer", "AdamOptimizer",
           "AdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
           "AdamaxOptimizer", "DecayedAdagradOptimizer", "FtrlOptimizer",
           "L1Decay", "L2Decay", "append_regularization_ops",
           "AccuracyEvaluator", "ChunkEvaluator", "OpRegistry"]
