"""fluid — the Program-IR front end (gen-2 analog, SURVEY.md §2.2/§2.4).

Build a Program of ops via ``layers``, differentiate with
``backward.append_backward`` (or optimizer.minimize), and run it with
``Executor`` — which compiles each block to a single cached XLA computation.
"""

from . import backward, io, layers, optimizer, registry
from .backward import append_backward
from .executor import Executor, Scope, global_scope
from .framework import (Block, Operator, Program, Variable,
                        default_main_program, default_startup_program,
                        program_guard, reset_default_programs)
from .optimizer import AdamOptimizer, MomentumOptimizer, SGDOptimizer
from .registry import OpRegistry

__all__ = ["layers", "backward", "io", "optimizer", "registry",
           "append_backward", "Executor", "Scope", "global_scope",
           "Program", "Block", "Operator", "Variable",
           "default_main_program", "default_startup_program", "program_guard",
           "reset_default_programs",
           "SGDOptimizer", "MomentumOptimizer", "AdamOptimizer", "OpRegistry"]
