"""append_backward — add gradient computation to a program.

Reference: fluid/backward.py:6 append_backward_ops -> C++ AppendBackward
(framework/backward.cc:343,414) emits one grad-op per forward op plus sum-ops
for fan-in. TPU-native redesign: ONE ``autodiff_grad`` op marks 'differentiate
the forward prefix w.r.t. these parameters'; the executor lowers it through
jax.grad at trace time (executor._trace_autodiff). Grad vars are still real
descs named ``<param>@GRAD`` (the reference's GradVarName convention,
framework/operator.h) so optimizer ops wire up identically.
"""

from __future__ import annotations

from typing import List, Optional

from .framework import Program, Variable, default_main_program


def append_backward(loss: Variable, parameter_list: Optional[List[str]] = None,
                    program: Optional[Program] = None) -> List[tuple]:
    """Append grad computation for ``loss``; returns [(param_var, grad_var)]."""
    program = program or default_main_program()
    block = program.global_block()
    if parameter_list is None:
        parameter_list = [v.name for v in block.all_parameters()]
    grad_vars = []
    for pname in parameter_list:
        pvar = block.var(pname)
        gvar = block.create_var(name=pname + "@GRAD", shape=pvar.shape,
                                dtype=pvar.dtype)
        grad_vars.append((pvar, gvar))
    block.append_op(
        "autodiff_grad",
        inputs={"Loss": [loss.name], "Params": list(parameter_list)},
        outputs={"Grads": [p + "@GRAD" for p in parameter_list]},
        attrs={"loss": loss.name, "params": list(parameter_list),
               "num_fwd_ops": len(block.ops)})
    return grad_vars
