"""Fluid evaluators: metric ops + cross-batch accumulator state.

Analog of python/paddle/v2/fluid/evaluator.py — an Evaluator owns persistable
state vars accumulated every batch inside the SAME compiled train step, plus
a host-side ``eval()`` that combines them and ``reset()`` that zeroes them
(the reference resets by re-running the state init ops).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..nn import initializer as I
from .framework import Variable, default_main_program, default_startup_program


class Evaluator:
    def __init__(self, name: str):
        main = default_main_program()
        self.name = main.unique_name(name)
        self._states: List[Variable] = []

    def _create_state(self, suffix: str, shape, dtype="float32") -> Variable:
        main = default_main_program()
        name = f"{self.name}_{suffix}"
        v = main.global_block().create_var(name=name, shape=shape, dtype=dtype,
                                           persistable=True, trainable=False)
        sb = default_startup_program().global_block()
        sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
        sb.append_op("fill_init", {}, {"Out": [name]},
                     {"shape": tuple(shape), "dtype": dtype,
                      "init": I.constant(0.0), "seed": 0})
        self._states.append(v)
        return v

    def reset(self, executor):
        for v in self._states:
            import jax.numpy as jnp
            executor.scope.set(v.name, jnp.zeros(v.shape, v.dtype))

    def eval(self, executor) -> float:
        raise NotImplementedError


class Accuracy(Evaluator):
    """Streaming accuracy (fluid/evaluator.py Accuracy): per-batch correct and
    total accumulate into persistable states updated by IR ops."""

    def __init__(self, input: Variable, label: Variable):
        super().__init__("accuracy")
        main = default_main_program()
        b = main.global_block()
        correct = b.create_var(shape=(), dtype="float32")
        total = b.create_var(shape=(), dtype="float32")
        acc = b.create_var(shape=(), dtype="float32")
        b.append_op("accuracy", {"Out": [input.name], "Label": [label.name]},
                    {"Accuracy": [acc.name], "Correct": [correct.name],
                     "Total": [total.name]})
        self.batch_acc = acc
        self._tot_correct = self._create_state("correct", ())
        self._tot_total = self._create_state("total", ())
        for state, batch in ((self._tot_correct, correct),
                             (self._tot_total, total)):
            b.append_op("elementwise_add",
                        {"X": [state.name], "Y": [batch.name]},
                        {"Out": [state.name]})

    def eval(self, executor) -> float:
        c = float(np.asarray(executor.scope.get(self._tot_correct.name)))
        t = float(np.asarray(executor.scope.get(self._tot_total.name)))
        return c / max(t, 1.0)


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (fluid evaluator ChunkEvaluator; ChunkEvaluator.cpp)."""

    def __init__(self, inference: Variable, label: Variable, lengths: Variable,
                 chunk_scheme: str = "IOB", num_chunk_types: int = 1):
        super().__init__("chunk")
        from . import layers
        b = default_main_program().global_block()
        c, p, l = layers.chunk_eval(inference, label, lengths,
                                    chunk_scheme, num_chunk_types)
        self._c = self._create_state("correct", ())
        self._p = self._create_state("predicted", ())
        self._l = self._create_state("labeled", ())
        for state, batch in ((self._c, c), (self._p, p), (self._l, l)):
            b.append_op("elementwise_add",
                        {"X": [state.name], "Y": [batch.name]},
                        {"Out": [state.name]})

    def eval(self, executor) -> float:
        c = float(np.asarray(executor.scope.get(self._c.name)))
        p = float(np.asarray(executor.scope.get(self._p.name)))
        l = float(np.asarray(executor.scope.get(self._l.name)))
        precision = c / p if p else 0.0
        recall = c / l if l else 0.0
        return (2 * precision * recall / (precision + recall)
                if precision + recall else 0.0)
