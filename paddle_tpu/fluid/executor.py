"""Executor: run a Program's blocks as ONE compiled XLA computation.

Reference: framework/executor.cc:87 ``Executor::Run`` creates vars then
interprets ops sequentially (:120-124). TPU-native redesign (SURVEY.md §7): the
op list is *traced* through the registry's jax computes into a single function,
jitted and cached keyed on (program fingerprint, feed shapes/dtypes) — the
shape-keyed executable cache that makes repeated `run` calls free of Python op
dispatch. Feed/fetch (feed_op.cc/fetch_op.cc) become function inputs/outputs.

Autodiff: a block may contain one ``autodiff_grad`` op (appended by
backward.append_backward). During tracing it replays the forward prefix as a
closure over the parameter leaves and calls jax.grad — XLA CSE merges the
replayed forward with the primal one, recovering the classic single
forward+backward graph (replacing backward.cc:414 AppendBackward's explicit
grad-op emission).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .framework import Block, Program, Variable
from .registry import OpRegistry


class Scope:
    """Runtime variable store (scope.h analog); persistables live here across
    run() calls. Child scopes see parent vars."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, Any] = {}

    def set(self, name: str, value):
        self.vars[name] = value

    def get(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        raise KeyError(name)

    def has(self, name: str) -> bool:
        try:
            self.get(name)
            return True
        except KeyError:
            return False

    def new_child(self) -> "Scope":
        return Scope(self)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _trace_ops(ops, env: Dict[str, Any]):
    """Symbolically run an op list over env (name -> traced array)."""
    for op in ops:
        if op.type == "autodiff_grad":
            _trace_autodiff(op, ops, env)
            continue
        compute = OpRegistry.get(op.type)
        ins = {k: [env[n] for n in vs] for k, vs in op.inputs.items()}
        outs = compute(ins, op.attrs)
        for k, names in op.outputs.items():
            vals = outs[k]
            for n, v in zip(names, vals):
                env[n] = v
    return env


def _trace_autodiff(op, ops, env):
    loss_name = op.attrs["loss"]
    param_names = list(op.attrs["params"])
    n_fwd = op.attrs["num_fwd_ops"]
    init_env = op.attrs["_init_env"]  # captured block-entry env

    def replay(param_vals):
        env2 = dict(init_env)
        for name, val in zip(param_names, param_vals):
            env2[name] = val
        _trace_ops(ops[:n_fwd], env2)
        return env2[loss_name]

    grads = jax.grad(replay)([env[n] for n in param_names])
    for name, g in zip(param_names, grads):
        env[name + "@GRAD"] = g


class Executor:
    """exe.run(program, feed=..., fetch_list=...) (fluid/executor.py:7-20)."""

    def __init__(self, place=None, scope: Optional[Scope] = None):
        self.place = place
        self.scope = scope if scope is not None else global_scope()
        self._cache: Dict[Tuple, Any] = {}
        self._step = 0   # feeds the implicit '__step__' var (stochastic ops)

    # ------------------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            use_cache: bool = True) -> List[np.ndarray]:
        from .framework import default_main_program
        program = program or default_main_program()
        feed = {k: jnp.asarray(v) for k, v in (feed or {}).items()}
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]
        block = program.global_block()
        if "__step__" in block.vars and "__step__" not in feed:
            feed["__step__"] = jnp.asarray(self._step, jnp.int32)
            self._step += 1

        # vars the block reads from the scope (persistables created earlier)
        persist_in = [name for name, v in block.vars.items()
                      if v.persistable and self.scope.has(name)]
        # persistable vars written by ops (optimizer updates) to sync back
        written = [n for op in block.ops for n in op.output_vars()
                   if n in block.vars and block.vars[n].persistable]
        written = list(dict.fromkeys(written))

        key = (program._serial, program.version, block.idx, tuple(fetch_names),
               tuple(persist_in),
               tuple((k, v.shape, str(v.dtype)) for k, v in sorted(feed.items())))
        fn = self._cache.get(key) if use_cache else None
        if fn is None:
            fn = self._build(program, block, list(feed), persist_in,
                             fetch_names, written)
            if use_cache:
                self._cache[key] = fn
        persist_vals = [self.scope.get(n) for n in persist_in]
        fetches, new_persist = fn(feed, persist_vals)
        for n, v in zip(written, new_persist):
            self.scope.set(n, v)
        return [np.asarray(v) for v in fetches]

    # ------------------------------------------------------------------
    def _build(self, program: Program, block: Block, feed_names, persist_in,
               fetch_names, written):
        has_host_ops = any(op.type == "fill_init" for op in block.ops)

        def raw(feed: Dict[str, Any], persist_vals: List[Any]):
            env: Dict[str, Any] = {}
            env.update(feed)
            env.update(dict(zip(persist_in, persist_vals)))
            # stash block-entry env for autodiff replay
            entry_env = dict(env)
            for op in block.ops:
                if op.type == "autodiff_grad":
                    op.attrs["_init_env"] = entry_env
            _trace_ops(block.ops, env)
            fetches = [env[n] for n in fetch_names]
            new_persist = [env.get(n) for n in written]
            return fetches, new_persist

        if has_host_ops:
            return raw  # startup programs run eagerly (host-side initializers)
        return jax.jit(raw)
