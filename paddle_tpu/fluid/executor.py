"""Executor: run a Program's blocks as ONE compiled XLA computation.

Reference: framework/executor.cc:87 ``Executor::Run`` creates vars then
interprets ops sequentially (:120-124). TPU-native redesign (SURVEY.md §7): the
op list is *traced* through the registry's jax computes into a single function,
jitted and cached keyed on (program fingerprint, feed shapes/dtypes) — the
shape-keyed executable cache that makes repeated `run` calls free of Python op
dispatch. Feed/fetch (feed_op.cc/fetch_op.cc) become function inputs/outputs.

Control flow (while_op.cc, conditional_block_op.cc, recurrent_op.cc): sub-block
ops are traced into ``lax.while_loop`` / ``lax.cond`` / ``lax.scan`` bodies.
The loop-carried state is derived from the IR: any outer variable a sub-block
writes is carried (the reference threads these through the enclosing Scope;
here they thread through the XLA loop carry, which is what the hardware wants).

Autodiff: a block may contain one ``autodiff_grad`` op (appended by
backward.append_backward). During tracing it replays the forward prefix as a
closure over the parameter leaves and calls jax.grad — XLA CSE merges the
replayed forward with the primal one, recovering the classic single
forward+backward graph (replacing backward.cc:414 AppendBackward's explicit
grad-op emission).
"""

from __future__ import annotations

import warnings
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..data.feeder import BucketSpec
from .framework import Block, Program, Variable
from .registry import OpRegistry


class _OpTraceError(RuntimeError):
    """An op failed during Program tracing; the message names the op and
    the chain leading to it (CustomStackTrace.h:51 crash-stack analog)."""


import re as _re

_SCOPE_SAFE = _re.compile(r"[^A-Za-z0-9_]")


def _scope_tag(op, idx: int) -> str:
    """The jax.named_scope stamp for one op site — the machine-parseable
    twin of analysis.diagnostics.op_site ('block B, op #I (type)'):
    obs/xplane.py's `site_of` inverts it when attributing profiled HLO
    ops back to Program sites."""
    bidx = getattr(op.block, "idx", None)
    b = bidx if bidx is not None else 0
    return f"b{b}_op{idx}_{_SCOPE_SAFE.sub('_', op.type)}"


class Scope:
    """Runtime variable store (scope.h analog); persistables live here across
    run() calls. Child scopes see parent vars."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, Any] = {}

    def set(self, name: str, value):
        self.vars[name] = value

    def get(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        raise KeyError(name)

    def has(self, name: str) -> bool:
        try:
            self.get(name)
            return True
        except KeyError:
            return False

    def new_child(self) -> "Scope":
        return Scope(self)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class TraceContext:
    """Per-trace state threaded through op lowering: the owning program (for
    sub-block lookup) and the block-entry environment (for autodiff replay).
    Replaces the former in-place ``op.attrs['_init_env']`` stash, which was
    non-reentrant and leaked traced arrays into the desc layer."""

    def __init__(self, program: Program, entry_env: Dict[str, Any]):
        self.program = program
        self.entry_env = entry_env


class _FusedRegion:
    """One activated fusion group, prepared for tracing: the member ops in
    ascending (= topological, straight-line SSA) order, the certificate's
    boundary vars, and the single named-scope tag the whole region lowers
    under — the profiler then attributes the region as ONE op site
    (``block B, op #first (fused_<kind>)``) instead of N."""

    __slots__ = ("start", "member_idxs", "ops", "inputs", "outputs", "tag")

    def __init__(self, group, block: Block):
        self.start = group.op_idxs[0]
        self.member_idxs = frozenset(group.op_idxs)
        self.ops = [(i, block.ops[i]) for i in group.op_idxs]
        self.inputs = tuple(group.inputs)
        self.outputs = tuple(group.outputs)
        self.tag = (f"b{group.block_idx}_op{self.start}_fused_"
                    f"{_SCOPE_SAFE.sub('_', group.kind)}")


def _trace_fused_region(region: _FusedRegion, env: Dict[str, Any]):
    """Trace one certified group as a single dispatch region: all member
    computes under ONE named scope, intermediates confined to a region-
    local env (the single-consumer certificate guarantees nothing outside
    reads them), only the certificate's outputs exported."""
    sub: Dict[str, Any] = {n: env[n] for n in region.inputs if n in env}
    with jax.named_scope(region.tag):
        for _idx, op in region.ops:
            compute = OpRegistry.get(op.type)
            ins = {k: [sub[n] if n in sub else env[n] for n in vs]
                   for k, vs in op.inputs.items()}
            outs = compute(ins, op.attrs)
            for k, names in op.outputs.items():
                for n, v in zip(names, outs[k]):
                    sub[n] = v
    for n in region.outputs:
        if n in sub:
            env[n] = sub[n]


def _trace_ops(ops, env: Dict[str, Any], ctx: TraceContext,
               fused: Optional[Dict[int, _FusedRegion]] = None):
    """Symbolically run an op list over env (name -> traced array).

    ``fused`` (global-block traces only) maps member op indices to their
    activated :class:`_FusedRegion`: the whole region traces at its first
    member's slot, later members are skipped.  Sub-block and autodiff-
    replay traces never pass it (their local op indices would collide),
    so a replayed forward re-traces unfused — same ops, same order, same
    values; XLA CSE merges the two as usual.

    A failing op re-raises with the op's position, type, and io names plus
    the chain of ops leading up to it — the fluid-level analog of the
    reference's crash-time layer-name stack (utils/CustomStackTrace.h:51),
    without which a shape error deep in a traced Program is anonymous.
    """
    for idx, op in enumerate(ops):
        try:
            if fused is not None and idx in fused:
                region = fused[idx]
                if idx == region.start:
                    _trace_fused_region(region, env)
                continue
            if op.type == "autodiff_grad":
                _trace_autodiff(op, ops, env, ctx)
                continue
            if op.type == "while":
                _trace_while(op, env, ctx)
                continue
            if op.type == "conditional_block":
                _trace_cond(op, env, ctx)
                continue
            if op.type == "static_rnn":
                _trace_static_rnn(op, env, ctx)
                continue
            if op.type == "beam_search_gen":
                _trace_beam_search_gen(op, env, ctx)
                continue
            compute = OpRegistry.get(op.type)
            ins = {k: [env[n] for n in vs] for k, vs in op.inputs.items()}
            # per-op-site name scope: HLO ops lowered from this compute
            # carry "b{B}_op{I}_{type}" in their metadata, so a device
            # profile (obs/xplane.py, `paddle_tpu profile`) attributes
            # hot ops back to the analysis plane's `block B, op #I
            # (type)` site — the same site runtime trace errors cite
            with jax.named_scope(_scope_tag(op, idx)):
                outs = compute(ins, op.attrs)
            for k, names in op.outputs.items():
                vals = outs[k]
                for n, v in zip(names, vals):
                    env[n] = v
        except Exception as e:
            if getattr(e, "_op_ctx", False):
                raise          # innermost op already carries its context
            chain = " -> ".join(o.type for o in ops[max(0, idx - 4):idx + 1])
            # one source of truth for the location format so a runtime
            # failure and the static diagnostic for an op cite the same site
            from ..analysis.diagnostics import block_paths, op_site
            blk = getattr(op, "block", None)
            bidx = getattr(blk, "idx", None)
            path = None
            prog = getattr(blk, "program", None)
            if prog is not None and bidx is not None:
                # nested sub-block failures cite the full parent chain
                # ("block 0.2, op #5") — same format as lint diagnostics
                path = block_paths(prog).get(bidx)
            site = op_site(bidx, idx, op.type, block_path=path)
            msg = (f"{site} failed while tracing the Program "
                   f"(inputs={op.inputs}, outputs={op.outputs})\n"
                   f"  op chain: ...{chain}")
            if hasattr(e, "add_note"):
                # annotate the ORIGINAL exception: re-constructing via
                # type(e)(msg) would drop structured args (OSError.errno,
                # KeyError's key) that callers match on
                e.add_note(msg)
                e._op_ctx = True
                raise
            try:               # pre-3.11 fallback: keep the type so callers'
                new = type(e)(f"{msg}: {type(e).__name__}: {e}")
            except Exception:
                new = _OpTraceError(f"{msg}: {type(e).__name__}: {e}")
            new._op_ctx = True
            raise new from e
    return env


def _trace_autodiff(op, ops, env, ctx: TraceContext):
    loss_name = op.attrs["loss"]
    param_names = list(op.attrs["params"])
    # forward = every op BEFORE this one in the CURRENT list (backward/
    # optimizer ops are appended after it). The op's own position — not the
    # recorded num_fwd_ops attr — stays correct after Program.prune drops
    # dangling forward ops and shifts indices (a stale count would make the
    # replay include this op itself and recurse forever).
    n_fwd = ops.index(op)
    init_env = ctx.entry_env

    def replay(param_vals):
        env2 = dict(init_env)
        for name, val in zip(param_names, param_vals):
            env2[name] = val
        _trace_ops(ops[:n_fwd], env2, ctx)
        return env2[loss_name]

    grads = jax.grad(replay)([env[n] for n in param_names])
    for name, g in zip(param_names, grads):
        env[name + "@GRAD"] = g


def _sub_block_written(sub: Block, env) -> List[str]:
    """Outer vars a sub-block (transitively) writes — the loop-carried state.

    The reference threads these through the parent Scope
    (while_op.cc's step scopes); under XLA they become the loop carry."""
    written: List[str] = []
    prog = sub.program

    def collect(block: Block):
        for o in block.ops:
            for n in o.output_vars():
                written.append(n)
            for key in ("sub_block_idx", "true_block_idx", "false_block_idx"):
                if key in o.attrs and o.attrs[key] is not None:
                    collect(prog.blocks[o.attrs[key]])

    collect(sub)
    return list(dict.fromkeys(n for n in written if n in env))


def _trace_while(op, env, ctx: TraceContext):
    """Lower a while op to lax.while_loop (while_op.cc semantics: re-run the
    sub-block until the condition var — updated inside the block — is false)."""
    sub = ctx.program.blocks[op.attrs["sub_block_idx"]]
    cond_name = op.inputs["Condition"][0]
    carried = _sub_block_written(sub, env)
    if cond_name not in carried:
        raise ValueError(
            f"while condition '{cond_name}' is never updated in the loop body "
            "(would loop forever); write it with less_than(..., cond=cond)")
    ci = carried.index(cond_name)

    def cond_fn(state):
        return jnp.reshape(state[ci], ()).astype(bool)

    def body_fn(state):
        env2 = dict(env)
        env2.update(zip(carried, state))
        _trace_ops(sub.ops, env2, ctx)
        return tuple(env2[n] for n in carried)

    init = tuple(env[n] for n in carried)
    final = jax.lax.while_loop(cond_fn, body_fn, init)
    env.update(zip(carried, final))


def _trace_cond(op, env, ctx: TraceContext):
    """Lower conditional_block(+optional else block) to lax.cond. Vars written
    by either branch must pre-exist outside so the untaken branch has a value
    to pass through (conditional_block_op.cc runs the block or skips it,
    leaving scope vars untouched)."""
    true_b = ctx.program.blocks[op.attrs["true_block_idx"]]
    false_idx = op.attrs.get("false_block_idx")
    false_b = ctx.program.blocks[false_idx] if false_idx is not None else None
    cond_name = op.inputs["Condition"][0]
    carried = _sub_block_written(true_b, env)
    if false_b is not None:
        for n in _sub_block_written(false_b, env):
            if n not in carried:
                carried.append(n)

    def make_branch(blk: Optional[Block]):
        def branch(state):
            env2 = dict(env)
            env2.update(zip(carried, state))
            if blk is not None:
                _trace_ops(blk.ops, env2, ctx)
            return tuple(env2[n] for n in carried)
        return branch

    init = tuple(env[n] for n in carried)
    pred = jnp.reshape(env[cond_name], ()).astype(bool)
    final = jax.lax.cond(pred, make_branch(true_b), make_branch(false_b), init)
    env.update(zip(carried, final))


def _trace_static_rnn(op, env, ctx: TraceContext):
    """Lower a static_rnn op (recurrent_op.cc / fluid StaticRNN) to ONE
    lax.scan over the time axis — the TPU-native form of the reference's
    per-step frame cloning (RecurrentGradientMachine.h:304)."""
    a = op.attrs
    sub = ctx.program.blocks[a["sub_block_idx"]]
    # step inputs: outer [B, T, ...] -> scan over [T, B, ...]
    xs = tuple(jnp.moveaxis(env[n], 1, 0) for n in a["outer_inputs"])
    init = tuple(env[n] for n in a["boot_mems"])

    def body(carry, xt):
        env2 = dict(env)
        env2.update(zip(a["mem_names"], carry))
        env2.update(zip(a["step_in_names"], xt))
        _trace_ops(sub.ops, env2, ctx)
        new_carry = tuple(env2[n] for n in a["mem_update_names"])
        outs = tuple(env2[n] for n in a["step_out_names"])
        return new_carry, outs

    carry, ys = jax.lax.scan(body, init, xs)
    for name, y in zip(a["outer_outputs"], ys):
        env[name] = jnp.moveaxis(y, 0, 1)            # [T, B, ...] -> [B, T, ...]
    for name, c in zip(a["last_mem_outputs"], carry):
        if name is not None:
            env[name] = c


def _trace_beam_search_gen(op, env, ctx: TraceContext):
    """Lower a beam_search_gen op: the user's step sub-block becomes the
    step_fn of the on-device masked-top-k beam decode (ops/beam_search.py).

    The reference runs beam search on CPU with per-step frame cloning and
    Python callbacks (RecurrentGradientMachine::beamSearch:1020); here the
    whole decode is one lax.scan — memories and static (encoder) inputs ride
    the beam 'cell' so they tile across beams together.
    """
    from ..ops.beam_search import beam_search
    a = op.attrs
    sub = ctx.program.blocks[a["sub_block_idx"]]
    embed_w = env[a["embed_param"]]
    boots = tuple(env[n] for n in a["boot_mems"])
    statics = tuple(env[n] for n in a["static_outer"])
    B = (boots[0].shape[0] if boots else statics[0].shape[0])
    K = a["beam_size"]
    V = embed_w.shape[0]
    # statics are invariant across beams AND steps: tile to [B*K, ...] ONCE
    # and close over them — carrying them in the scan cell would reshape and
    # beam-gather the whole encoder tensor every decode step for no effect
    tiled = tuple(jnp.broadcast_to(s[:, None], (B, K) + s.shape[1:])
                  .reshape((B * K,) + s.shape[1:]) for s in statics)

    def step_fn(mems, tokens):
        env2 = dict(env)
        env2.update(zip(a["mem_names"], mems))
        env2.update(zip(a["static_in_names"], tiled))
        env2[a["token_embed_name"]] = jnp.take(embed_w, tokens, axis=0)
        _trace_ops(sub.ops, env2, ctx)
        probs = env2[a["prob_name"]]
        logp = jnp.log(jnp.maximum(probs, 1e-9))
        new_mems = tuple(env2[n] for n in a["mem_update_names"])
        return logp, new_mems

    constraint_fn = None
    if a.get("constraint"):
        from ..ops.beam_search import CONSTRAINTS
        try:
            constraint_fn = CONSTRAINTS[a["constraint"]]
        except KeyError:
            raise KeyError(
                f"beam-search constraint {a['constraint']!r} is not "
                "registered; call paddle_tpu.ops.beam_search."
                "register_constraint(name, fn) before running the program")

    toks, scores = beam_search(
        boots, step_fn, batch_size=B,
        beam_size=K, max_len=a["max_length"], vocab_size=V,
        bos_id=a["bos_id"], eos_id=a["eos_id"],
        length_penalty=a.get("length_penalty", 0.0),
        constraint_fn=constraint_fn)
    env[op.outputs["Tokens"][0]] = toks
    env[op.outputs["Scores"][0]] = scores


class _CompiledEntry:
    """One compiled-fn cache entry: the jitted callable plus the cost
    record the roofline ledger reads (docs/design/observability.md
    "Device timelines & roofline").

    The first call under an installed obs session lowers + compiles AOT
    (``jitted.lower(...).compile()``
    — the same compile jit would pay, just held where
    ``cost_analysis()`` / ``memory_analysis()`` are reachable) and
    records the executable's :class:`~paddle_tpu.obs.roofline.Cost`.
    Installing obs AFTER an entry warmed up on the plain jit path makes
    that first session call re-pay one compile for the signature (jit's
    internal executable is not reachable for cost analysis); the
    persistent XLA compile cache turns it into a deserialize when
    enabled.
    The executor's cache key pins the argument signature, so one
    executable serves the entry for its lifetime. Any AOT
    lowering/compile failure — or the stricter AOT argument check
    rejecting a call the polymorphic jit would have accepted — falls
    back to the plain jitted callable (counted as a cost-analysis
    failure; cost stays an honest None)."""

    __slots__ = ("_jitted", "_call", "cost", "kernel_bytes")

    def __init__(self, jitted):
        self._jitted = jitted
        self._call = None
        self.cost = None
        #: {kernel: modeled bytes per dispatch} collected at trace time
        #: from note_kernel_bytes launch sites (Pallas routes) inside the
        #: program — re-emitted per run by the executor
        self.kernel_bytes = None

    def __call__(self, feed, kept_vals, donated_vals):
        call = self._call
        if call is None:
            if not obs.is_active():
                # plane off: stay on the plain jit path — no AOT compile,
                # no cost-analysis warnings in processes that never
                # installed obs (CostInstrumentedJit's discipline; an
                # entry first hit under a session records its cost)
                return self._jitted(feed, kept_vals, donated_vals)
            roofline = obs.roofline
            try:
                with roofline.collect_kernel_bytes() as col:
                    lowered = self._jitted.lower(feed, kept_vals,
                                                 donated_vals)
                if col.per_kernel:
                    self.kernel_bytes = col.per_kernel
                compiled = lowered.compile()
                self.cost = roofline.compiled_cost(compiled,
                                                   "fluid.Executor")
                call = compiled
            except Exception as e:
                roofline.cost_failure("fluid.Executor lower/compile", e)
                call = self._jitted
            self._call = call
        try:
            return call(feed, kept_vals, donated_vals)
        except TypeError as e:
            if call is self._jitted:
                raise
            # AOT argument strictness (weak types, committed devices) the
            # shape-keyed cache cannot see; the check fires BEFORE
            # dispatch, so donated buffers are intact and the jit retry
            # is safe
            obs.roofline.cost_failure("fluid.Executor (aot call)", e)
            self._call = self._jitted
            return self._jitted(feed, kept_vals, donated_vals)


#: consecutive compiled-fn cache misses before the executor warns that the
#: workload is shape-churning with no bucket spec (L006, analysis/lints.py)
_CHURN_STREAK = 4

#: default compiled-fn LRU capacity — generous (a cache entry is a traced
#: closure + XLA executable handle, not the HBM working set), but bounded so
#: unbucketed shape churn is a warning, not a slow leak
DEFAULT_CACHE_CAPACITY = 512


class Executor:
    """exe.run(program, feed=..., fetch_list=...) (fluid/executor.py:7-20).

    Hot-path contract (docs/design/executor_perf.md):

    * ``donate=True`` (default) hands persistables that the run overwrites
      (optimizer updates, BN stats) to XLA as donated buffers — the update
      happens in place, no second HBM copy per step.  A persistable that is
      also fetched (or fed) in the same run is automatically kept; pass
      ``donate=False`` (constructor or per-run) to opt out entirely.  After
      a donating run, previously-held references to the old parameter
      arrays are dead (``x.is_deleted()``) — re-read them from the scope.
    * Persistables live in the scope as **device arrays** between runs;
      ``run(..., return_numpy=False)`` returns jax arrays without blocking
      the host, so a training loop only syncs where it reads values.
    * ``buckets=...`` (a :class:`~paddle_tpu.data.feeder.BucketSpec` or its
      dict form) pads designated feed axes up to a bounded set of shapes so
      the compiled-fn cache is keyed on bucket shapes; the true length is
      fed alongside as ``<name>@LEN``.
    * The compiled-fn cache is a bounded LRU (``cache_capacity``).

    Sharding contract (docs/design/spmd.md): ``mesh=`` (a
    ``jax.sharding.Mesh``, defaulting to the ambient
    :func:`paddle_tpu.parallel.use_mesh`) makes the executor compile every
    program through ``jax.jit(..., in_shardings=..., out_shardings=...)``.
    Each persistable's ``PartitionSpec`` resolves through ``layout`` (a
    :class:`paddle_tpu.parallel.SpecLayout`; annotation > layout rule >
    replicated), parameters are *placed* sharded the first time the mesh
    executor touches them (init, load, checkpoint restore) and stay
    sharded in the device-resident scope across runs; feeds shard their
    batch dim over the ``data`` axis unless annotated otherwise. Sharding
    specs join the compiled-fn cache key, and donation keeps aliasing the
    sharded buffers in place.
    """

    def __init__(self, place=None, scope: Optional[Scope] = None, *,
                 donate: bool = True,
                 buckets: Optional[Any] = None,
                 mesh: Optional[Any] = None,
                 layout: Optional[Any] = None,
                 fuse: Optional[Any] = None,
                 cache_capacity: int = DEFAULT_CACHE_CAPACITY):
        self.place = place
        self.scope = scope if scope is not None else global_scope()
        self.donate = donate
        # graph fusion over certified groups (tune/fusion.py, ROADMAP 3c):
        # None = MEASURED-ONLY (consult the autotune cache's `fusion`
        # space; no entries for this device -> run unfused, zero analysis
        # cost), False = off, True = force-fuse every schedulable
        # certified group, a set of first-op indices = force exactly those
        # groups (the measurement harness's per-group knob). Forcing can
        # cost speed, never correctness: certification + schedulability
        # still gate every region.
        self.fuse = fuse
        self._fusion_memo: Dict[Tuple, Any] = {}
        if mesh is None:
            from ..parallel.mesh import current_mesh
            mesh = current_mesh()
        if layout is not None and mesh is None:
            raise ValueError(
                "Executor(layout=...) needs a mesh: pass mesh=... or "
                "construct inside parallel.use_mesh(...)")
        if mesh is not None and layout is None:
            from ..parallel.sharding import SpecLayout
            layout = SpecLayout()
        self.mesh = mesh
        self.layout = layout
        # device identity joins the cache key: a compiled executable is
        # pinned to its device assignment
        self._mesh_sig = (tuple(mesh.shape.items()),
                          tuple(int(d.id) for d in mesh.devices.flat)) \
            if mesh is not None else None
        self._mesh_stats_emitted = False
        # resolved-sharding memo (specs are a pure function of program +
        # mesh + layout + arg shapes): a steady-state training loop must
        # not re-walk the layout's rule table per persistable per step
        self._shard_memo: Dict[Tuple, Tuple] = {}
        if buckets is not None and not isinstance(buckets, BucketSpec):
            buckets = BucketSpec(buckets)
        self.buckets: Optional[BucketSpec] = buckets
        if cache_capacity < 1:
            raise ValueError("cache_capacity must be >= 1")
        self.cache_capacity = cache_capacity
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._verified: set = set()   # analysis pre-flights already passed
        self._step = 0   # feeds the implicit '__step__' var (stochastic ops)
        # L006 shape-churn heuristic: consecutive never-seen-key misses per
        # (program, block, fetch) signature — keyed so first-runs of
        # DIFFERENT programs (startup + train + eval) never sum to a
        # streak, with a seen-key set so LRU-eviction thrash over a
        # BOUNDED shape family (which bucketing can't improve) doesn't
        # count as churn either
        self._miss_streaks: Dict[Tuple, int] = {}
        self._seen_keys: set = set()
        self._churn_warned = False
        # L011 donation-safety: statically-proven-hazardous persistables
        # per (program serial, version) — their donation is downgraded to
        # keep (see _run), warned once per program
        self._hazard_memo: Dict[Tuple, frozenset] = {}
        self._hazard_warned: set = set()

    # ------------------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            use_cache: bool = True, verify: bool = False,
            return_numpy: bool = True,
            donate: Optional[bool] = None) -> List[Any]:
        with obs.span("fluid.run", metric="fluid.run_seconds"):
            return self._run(program, feed, fetch_list, use_cache, verify,
                             return_numpy, donate)

    # ------------------------------------------------------------------
    def _default_bucket_axis(self, block: Block, name: str,
                             ndim: int) -> Optional[int]:
        """Axis to bucket when the spec doesn't pin one: the feed Variable's
        declared ``bucket_axis``, else its first dynamic (-1) non-batch dim
        (layers.data marks the batch dim -1 at axis 0; a second -1 is the
        variable-length axis). A declared feed with NO dynamic non-batch
        dim is an error — silently guessing an axis would pad a static
        feature dim and surface as a distant shape mismatch inside the
        traced program."""
        v = block.vars.get(name)
        if v is not None:
            if getattr(v, "bucket_axis", None) is not None:
                return v.bucket_axis
            dyn = [i for i, s in enumerate(v.shape) if i > 0 and s == -1]
            if dyn and dyn[0] < ndim:
                return dyn[0]
            if ndim >= 2:
                raise ValueError(
                    f"cannot infer a bucket axis for feed '{name}': its "
                    f"declared shape {v.shape} has no dynamic (-1) non-batch "
                    "dim; pin one in the spec "
                    f"(buckets={{'{name}': {{'axis': A, 'buckets': (...)}}}}) "
                    "or declare layers.data(..., bucket_axis=A)")
        return None

    def _apply_buckets(self, feed: Dict[str, Any], block: Block) -> bool:
        """Pad spec'd feeds in place; True when any feed was bucketed."""
        applied = False
        for name in self.buckets.names():
            if name not in feed:
                continue
            arr = feed[name]
            if not hasattr(arr, "shape"):
                arr = np.asarray(arr)
            default_axis = None
            if self.buckets.pinned_axis(name) is None:
                default_axis = self._default_bucket_axis(block, name,
                                                         arr.ndim)
            padded, true_len = self.buckets.pad(name, arr, default_axis)
            feed[name] = padded
            # the true extent rides along so masked ops can ignore the pad
            # tail; scalar shape — it never perturbs the cache key
            feed[name + "@LEN"] = np.int32(true_len)
            applied = True
        return applied

    def _maybe_warn_churn(self, streak: int):
        """L006 shape-churn: a streak of never-seen-before cache keys for
        ONE (program, fetch) signature means every distinct feed shape is
        paying a fresh trace + XLA compile (warns once per executor; lint
        id in analysis/lints.py). Fires with a partial BucketSpec too —
        a spec that misses the churning feed doesn't bound anything — but
        the threshold then grows by the spec's own shape-family size, so a
        covering spec legitimately warming one compile per bucket never
        trips it."""
        threshold = _CHURN_STREAK
        if self.buckets is not None:
            threshold += sum(len(b) + 1            # +1: pow-2 overflow shape
                             for _, b in self.buckets.spec.values())
        if self._churn_warned or streak < threshold:
            return
        self._churn_warned = True
        fix = ("pass Executor(buckets={'<feed>': (32, 64, ...)})"
               if self.buckets is None else
               "extend the BucketSpec to cover the still-varying feed(s)")
        warnings.warn(
            f"L006 shape-churn: {streak} consecutive compiled-fn cache "
            "misses for the same program — each distinct feed shape pays a "
            f"fresh trace and XLA compile. If feeds vary in length, {fix} "
            "to pad onto a bounded shape family "
            "(docs/design/executor_perf.md).",
            RuntimeWarning, stacklevel=4)

    # -------------------------------------------------- sharding plane ----
    def _annotation(self, block: Block, name: str):
        """The variable's ``sharding`` annotation; optimizer accumulators
        (``param@moment1``) inherit their base parameter's annotation —
        slot layouts must follow the parameter or the update op pays a
        reshard every step."""
        v = block.vars.get(name)
        ann = getattr(v, "sharding", None) if v is not None else None
        if ann is None and "@" in name:
            base = block.vars.get(name.split("@", 1)[0])
            ann = getattr(base, "sharding", None) if base is not None else None
        return ann

    def _persist_sharding(self, block: Block, name: str, value):
        return self.layout.resolve(self.mesh, name, np.shape(value),
                                   self._annotation(block, name))

    def _feed_sharding(self, block: Block, name: str, value):
        """Feeds: annotation wins; a fed persistable resolves like a
        parameter; plain data shards its batch dim over ``data``."""
        shape = np.shape(value)
        ann = self._annotation(block, name)
        v = block.vars.get(name)
        if ann is None and v is not None and v.persistable:
            return self._persist_sharding(block, name, value)
        if ann is not None:
            return self.layout.resolve(self.mesh, name, shape, ann)
        from jax.sharding import NamedSharding
        spec = type(self.layout).fit(self.mesh,
                                     self.layout.batch_spec(len(shape)),
                                     shape)
        return NamedSharding(self.mesh, spec)

    def _place_persistables(self, persist_in, spec_of) -> None:
        """Move scope values whose live sharding differs from the resolved
        layout (host arrays from a startup program / checkpoint restore,
        or arrays placed for a previous mesh) onto the mesh — the
        init/load-time sharded placement of the GSPMD plane."""
        placed = 0
        for n in persist_in:
            cur = self.scope.get(n)
            target = spec_of[n]
            if getattr(cur, "sharding", None) == target:
                continue
            new = jax.device_put(cur, target)
            self.scope.set(n, new)
            placed += int(getattr(new, "nbytes", 0))
        if placed:
            obs.count("fluid.placed_bytes_total", placed)
            self._mesh_stats_emitted = False
        if not self._mesh_stats_emitted and obs.is_active():
            self._emit_mesh_stats(persist_in, spec_of)
            self._mesh_stats_emitted = True

    def _emit_mesh_stats(self, persist_in, spec_of) -> None:
        """Per-axis utilization through the obs plane: how much of the
        persistable footprint each mesh axis actually divides, and the
        per-device parameter bytes the layout achieves."""
        total = per_device = 0
        by_axis: Dict[str, int] = {a: 0 for a in self.mesh.shape}
        for n in persist_in:
            v = self.scope.get(n)
            nbytes = int(getattr(v, "nbytes", 0))
            total += nbytes
            ways = 1
            for entry in spec_of[n].spec:
                axes = ((entry,) if isinstance(entry, str)
                        else tuple(entry or ()))
                for a in axes:
                    by_axis[a] += nbytes
                    ways *= self.mesh.shape[a]
            per_device += nbytes // ways
        for a, size in self.mesh.shape.items():
            obs.gauge_set("mesh.axis_size", size, axis=a)
            obs.gauge_set("mesh.axis_utilization",
                          (by_axis[a] / total) if total else 0.0, axis=a)
        obs.gauge_set("fluid.param_bytes_per_device", per_device)
        obs.gauge_set("fluid.param_bytes_global", total)

    def _run(self, program, feed, fetch_list, use_cache, verify,
             return_numpy=True, donate=None):
        from .framework import default_main_program
        program = program or default_main_program()
        block = program.global_block()
        feed = dict(feed or {})
        bucketed = self.buckets is not None and self._apply_buckets(feed,
                                                                    block)
        # weak_type rides the cache key (below) instead of being stripped
        # from the value: a python-scalar feed keeps jit's exact promotion
        # semantics (weak f32 * bf16 -> bf16), and the AOT-compiled entries
        # (cost ledger) never see a weak/strong aval mismatch because the
        # weak and strong variants compile separate entries — the same
        # retrace jit itself would do
        feed = {k: jnp.asarray(v) for k, v in feed.items()}
        # anything with a .name (Variable, v2 LayerOutput) or a plain string
        fetch_names = [v if isinstance(v, str) else v.name
                       for v in (fetch_list or [])]
        if "__step__" in block.vars and "__step__" not in feed:
            feed["__step__"] = jnp.asarray(self._step, jnp.int32)
            self._step += 1
        donate = self.donate if donate is None else donate
        if verify:
            # static pre-flight: reject malformed programs with precise
            # Diagnostics BEFORE burning a trace/compile (analysis subpackage).
            # Memoized like the compiled-fn cache so a training loop pays the
            # analysis once per (program version, feed signature), not per step.
            # The donation switch rides along: with donate on, a provable
            # read-after-donate hazard (L011) is an ERROR this pre-flight
            # refuses instead of letting the run consume a donated buffer.
            from .. import analysis
            vkey = (program._serial, program.version, tuple(fetch_names),
                    bool(donate),
                    tuple((k, v.shape, str(v.dtype))
                          for k, v in sorted(feed.items())))
            if vkey not in self._verified:
                with obs.span("fluid.verify", metric="fluid.verify_seconds"):
                    analysis.check_or_raise(program, feed=feed,
                                            fetch=fetch_names,
                                            donate=bool(donate))
                self._verified.add(vkey)

        # vars the block reads from the scope (persistables created earlier)
        # — minus any the caller feeds this run: the fed value must WIN
        # (and the scope copy would otherwise ride to the device as a dead
        # argument only to be shadowed, or worse, shadow the feed)
        persist_in = [name for name, v in block.vars.items()
                      if v.persistable and name not in feed
                      and self.scope.has(name)]
        # persistable vars written by ops (optimizer updates, BN stats) synced
        # back after the run — including writes inside control-flow sub-blocks
        # (those values flow to env via the loop carry; they must also be
        # listed here or the scope silently keeps the stale value)
        top_written = {n for op in block.ops for n in op.output_vars()}
        written = list(dict.fromkeys(
            n for n in self._written_vars(program, block)
            if n in block.vars and block.vars[n].persistable))
        # a persistable written ONLY in a sub-block must already have a value
        # (scope or feed): the loop carry is derived from pre-existing env
        # entries, so an uninitialized one would be silently dropped
        for n in written:
            if n not in top_written and n not in feed and not self.scope.has(n):
                raise ValueError(
                    f"persistable '{n}' is written inside a control-flow "
                    "sub-block but has no initial value; initialize it in the "
                    "scope (or a startup program) first")

        # donation split, decided from desc-level facts so it is a pure
        # function of the cache key: a persistable the run overwrites is
        # donated to XLA (updated in place) UNLESS the same run also
        # fetches it — that needs the old buffer readable (fed persistables
        # never reach persist_in at all; the fed value wins)
        written_set, fetch_set = set(written), set(fetch_names)
        donated_in = [n for n in persist_in
                      if donate and n in written_set
                      and n not in fetch_set]
        # L011 donation-safety: a persistable whose pre-update value may
        # still be read after its overwrite (proved by the dataflow plane)
        # is downgraded to keep instead of donated — correctness beats the
        # buffer reuse.  verify=True already refused such programs above;
        # this protects verify=False runs.  Memoized per program version.
        if donated_in:
            hkey = (program._serial, program.version)
            hz = self._hazard_memo.get(hkey)
            if hz is None:
                from ..analysis.dataflow import donation_hazards
                hz = frozenset(h.name for h in donation_hazards(
                    program, feed=feed, fetch=fetch_names))
                self._hazard_memo[hkey] = hz
            hazardous = [n for n in donated_in if n in hz]
            if hazardous:
                donated_in = [n for n in donated_in if n not in hz]
                if hkey not in self._hazard_warned:
                    self._hazard_warned.add(hkey)
                    warnings.warn(
                        "L011 donation-hazard: persistable(s) "
                        f"{sorted(hazardous)} may be read after their "
                        "in-place update; donation downgraded to keep for "
                        "them (run with verify=True for the full def-use "
                        "chain)", RuntimeWarning, stacklevel=3)
        donated_set = set(donated_in)
        kept_in = [n for n in persist_in if n not in donated_set]

        # mesh path: resolve every argument's sharding, place scope
        # persistables, and extend the cache key with the resolved specs.
        # Resolution is memoized per (program version, args signature) —
        # it is a pure function of program + mesh + layout, and the rule-
        # table regex walk must not run per persistable per hot-loop step
        shardings = None
        if self.mesh is not None:
            skey = (program._serial, program.version, block.idx,
                    tuple(persist_in), tuple(written),
                    tuple((k, v.shape, str(v.dtype))
                          for k, v in sorted(feed.items())))
            memo = self._shard_memo.get(skey)
            if memo is None:
                feed_sh = {k: self._feed_sharding(block, k, v)
                           for k, v in feed.items()}
                spec_of = {n: self._persist_sharding(block, n,
                                                     self.scope.get(n))
                           for n in persist_in}
                from jax.sharding import NamedSharding, PartitionSpec
                replicated = NamedSharding(self.mesh, PartitionSpec())
                out_sh = [spec_of.get(n) or feed_sh.get(n) or replicated
                          for n in written]
                mesh_key = (self._mesh_sig,
                            tuple(sorted((k, str(s.spec))
                                         for k, s in feed_sh.items())),
                            tuple((n, str(spec_of[n].spec))
                                  for n in persist_in))
                if len(self._shard_memo) > 1024:   # unbounded-churn cap
                    self._shard_memo.clear()
                memo = (feed_sh, spec_of, out_sh, replicated, mesh_key)
                self._shard_memo[skey] = memo
            feed_sh, spec_of, out_sh, replicated, mesh_key = memo
            self._place_persistables(persist_in, spec_of)
            shardings = (feed_sh, spec_of, out_sh, replicated)
        else:
            mesh_key = None

        fusion_plan = self._fusion_plan(program, block, feed, fetch_names)
        bflag = "true" if bucketed else "false"
        key = (program._serial, program.version, block.idx, tuple(fetch_names),
               tuple(persist_in), bool(donate), mesh_key,
               fusion_plan.key() if fusion_plan is not None else None,
               tuple((k, v.shape, str(v.dtype),
                      bool(getattr(v, "weak_type", False)))
                     for k, v in sorted(feed.items())))
        fn = self._cache.get(key) if use_cache else None
        obs.count("fluid.runs_total")
        churn_key = (program._serial, block.idx, tuple(fetch_names))
        if fn is None:
            # a miss pays the trace (+ XLA compile on first call)
            obs.count("fluid.cache_misses_total", bucketed=bflag)
            # deliberate use_cache=False runs and re-compiles of a key the
            # LRU evicted (a bounded shape family thrashing a small cache)
            # are not shape churn
            if use_cache and key not in self._seen_keys:
                if len(self._seen_keys) > 4096:     # unbounded-churn cap
                    self._seen_keys.clear()
                self._seen_keys.add(key)
                if len(self._miss_streaks) > 64:    # stale program signatures
                    self._miss_streaks.clear()
                streak = self._miss_streaks.get(churn_key, 0) + 1
                self._miss_streaks[churn_key] = streak
                self._maybe_warn_churn(streak)
            fn = self._build(program, block, list(feed), kept_in, donated_in,
                             fetch_names, written, shardings, fusion_plan)
            if use_cache:
                self._cache[key] = fn
                while len(self._cache) > self.cache_capacity:
                    self._cache.popitem(last=False)   # evict the LRU entry
                    obs.count("fluid.cache_evictions_total")
        else:
            obs.count("fluid.cache_hits_total", bucketed=bflag)
            self._miss_streaks[churn_key] = 0
            self._cache.move_to_end(key)
        if use_cache:
            obs.gauge_set("fluid.cache_size", len(self._cache))
        kept_vals = [self.scope.get(n) for n in kept_in]
        donated_vals = [self.scope.get(n) for n in donated_in]
        if donated_in and obs.is_active():
            obs.count("fluid.donated_bytes_total",
                      sum(getattr(v, "nbytes", 0) for v in donated_vals))
        try:
            fetches, new_persist = fn(feed, kept_vals, donated_vals)
        except Exception:
            # a failure AFTER dispatch (e.g. jax_debug_nans) has already
            # invalidated the donated inputs but never produced outputs to
            # sync back — the scope now maps those names to dead buffers.
            # Say so here, where the cause is known; the next run would
            # otherwise fail with an anonymous 'Array has been deleted'.
            dead = [n for n, v in zip(donated_in, donated_vals)
                    if getattr(v, "is_deleted", lambda: False)()]
            if dead:
                warnings.warn(
                    f"Executor.run failed after donating {len(dead)} "
                    f"persistable buffer(s) ({dead[:4]}...): their scope "
                    "values are invalidated — reload them (startup program "
                    "/ load_persistables / checkpoint) before the next run, "
                    "or use donate=False while debugging.",
                    RuntimeWarning, stacklevel=3)
            raise
        # device cost ledger — AFTER the dispatch try/except: telemetry
        # must never discard a successful run's fetches or dress its own
        # failure up as the donated-buffer post-dispatch warning. No-op
        # when the plane is off or the analysis resolved to None.
        cost = getattr(fn, "cost", None)
        kb = getattr(fn, "kernel_bytes", None)
        if (cost is not None or kb) and obs.is_active():
            # Pallas launches inside the program are zero to XLA's
            # analysis: re-emit the trace-collected models once per run —
            # the same per-dispatch semantics as the decode sites
            obs.roofline.account(
                cost, extra_bytes=obs.roofline.emit_kernel_bytes(kb))
        for n, v in zip(written, new_persist):
            self.scope.set(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _fusion_plan(self, program, block, feed, fetch_names):
        """The (memoized) fusion decision for this run's compile key.

        The measured-only default costs nothing until an autotune cache
        with ``fusion`` entries for this device is active: without one,
        every certified group's answer is already known to be "unfused",
        so the dataflow analysis is skipped entirely. Plans memoize per
        (program version, fetch, feed shapes, fuse mode) — the counters
        inside ``plan_for`` therefore count plan DECISIONS, not runs."""
        if self.fuse is False or block.idx != 0:
            return None
        from ..tune import fusion as _fusion
        if self.fuse is None and not _fusion.cache_has_fusion_entries():
            return None
        mode = (True if self.fuse is True else
                tuple(sorted(self.fuse)) if self.fuse is not None else None)
        ctoken = None
        if self.fuse is None:
            # consults must not survive a cache swap: the active cache's
            # identity + entry count ride the memo key
            from ..tune.cache import get_cache
            c = get_cache()
            ctoken = (id(c), len(c.entries) if c is not None else 0)
        fkey = (program._serial, program.version, tuple(fetch_names), mode,
                ctoken,
                tuple((k, v.shape) for k, v in sorted(feed.items())))
        plan = self._fusion_memo.get(fkey)
        if plan is None:
            plan = _fusion.plan_for(
                program, {k: v.shape for k, v in feed.items()},
                fetch=fetch_names, feed=list(feed), force=self.fuse)
            if len(self._fusion_memo) > 256:     # unbounded-churn cap
                self._fusion_memo.clear()
            self._fusion_memo[fkey] = plan
        return plan if plan.groups else None

    # ------------------------------------------------------------------
    @staticmethod
    def _written_vars(program: Program, block: Block) -> List[str]:
        out: List[str] = []
        for op in block.ops:
            out.extend(op.output_vars())
            for key in ("sub_block_idx", "true_block_idx", "false_block_idx"):
                idx = op.attrs.get(key)
                if idx is not None:
                    out.extend(Executor._written_vars(program,
                                                      program.blocks[idx]))
        return out

    # ------------------------------------------------------------------
    def _build(self, program: Program, block: Block, feed_names, kept_in,
               donated_in, fetch_names, written, shardings=None,
               fusion_plan=None):
        has_host_ops = any(op.type == "fill_init" for op in block.ops)
        fused: Optional[Dict[int, _FusedRegion]] = None
        if fusion_plan is not None and fusion_plan.groups and not has_host_ops:
            fused = {}
            for g in fusion_plan.groups:
                region = _FusedRegion(g, block)
                for i in g.op_idxs:
                    fused[i] = region

        def raw(feed: Dict[str, Any], kept_vals: List[Any],
                donated_vals: List[Any]):
            env: Dict[str, Any] = {}
            env.update(feed)
            env.update(dict(zip(kept_in, kept_vals)))
            env.update(dict(zip(donated_in, donated_vals)))
            ctx = TraceContext(program, dict(env))
            _trace_ops(block.ops, env, ctx, fused)
            fetches = [env[n] for n in fetch_names]
            new_persist = [env.get(n) for n in written]
            return fetches, new_persist

        if has_host_ops:
            return raw  # startup programs run eagerly (host-side initializers)
        # every donated name is also written (enforced by the _run split), so
        # XLA aliases each donated input buffer with its updated output —
        # params/BN stats update in place instead of allocating a second copy
        donate_args = (2,) if donated_in else ()
        if shardings is None:
            return _CompiledEntry(jax.jit(raw, donate_argnums=donate_args))
        # GSPMD lowering: argument/result shardings pin the layout the
        # resolver chose; XLA's SPMD partitioner inserts the collectives.
        # Donated sharded buffers keep the same out-sharding, so the alias
        # holds and updates stay in place per shard. EVERY output sharding
        # is specified — fetches gather to replicated (the host reads them
        # anyway): donation pairs inputs to outputs by aval, and a
        # mesh-run with unspecified out_shardings mispairs a donated
        # shard with a fetch on this jax version (alias size mismatch).
        feed_sh, spec_of, out_sh, replicated = shardings
        in_shardings = (feed_sh,
                        [spec_of[n] for n in kept_in],
                        [spec_of[n] for n in donated_in])
        out_shardings = ([replicated] * len(fetch_names), out_sh)
        return _CompiledEntry(jax.jit(raw, in_shardings=in_shardings,
                                      out_shardings=out_shardings,
                                      donate_argnums=donate_args))
