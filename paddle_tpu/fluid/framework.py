"""Program IR: Program{Block{Operator, Variable}} — the gen-2 desc layer.

Re-provides the reference's ProgramDesc/BlockDesc/OpDesc/VarDesc IR
(paddle/framework/framework.proto; program_desc.h, block_desc.h, op_desc.h,
var_desc.h; Python mirror python/paddle/v2/fluid/framework.py) as plain Python
descs. TPU-native difference (SURVEY.md §7 mapping): the executor does NOT
interpret ops one-by-one (executor.cc:120-124's hot loop) — it *traces* a block
into one jax function and compiles it to a single XLA computation, cached by
feed-shape signature.

Serialization: ``Program.to_dict()/from_dict()`` (JSON-able) stands in for the
protobuf round-trip.
"""

from __future__ import annotations

import contextlib
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Variable:
    """VarDesc analog: name, shape (-1 = dynamic batch), dtype, persistable."""

    def __init__(self, block: "Block", name: str, shape: Sequence[int] = (),
                 dtype: str = "float32", persistable: bool = False,
                 is_data: bool = False, lod_level: int = 0,
                 trainable: bool = True,
                 sharding: Optional[Sequence[Optional[str]]] = None,
                 bucket_axis: Optional[int] = None):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype).name
        self.persistable = persistable
        self.is_data = is_data
        self.lod_level = lod_level
        # persistable state that is NOT a learnable weight (BN running stats,
        # evaluator accumulators) sets trainable=False so autodiff/optimizers
        # skip it while the executor still syncs it to the scope
        self.trainable = trainable
        # optional mesh-axis annotation, one entry per tensor dim (None =
        # replicated); validated against parallel.mesh.CANONICAL_ORDER by
        # analysis.lints L004. A bare string means one axis, not its chars.
        if isinstance(sharding, str):
            sharding = (sharding,)
        self.sharding = tuple(sharding) if sharding is not None else None
        # which axis of a feed varies in length (the executor's BucketSpec
        # pads it when no axis is pinned in the spec); rides Program JSON
        # like sharding so a deserialized program keeps its feed contract
        self.bucket_axis = (int(bucket_axis) if bucket_axis is not None
                            else None)

    def __repr__(self):
        return (f"Variable({self.name}, shape={self.shape}, dtype={self.dtype}"
                f"{', persistable' if self.persistable else ''})")

    def to_dict(self):
        d = {"name": self.name, "shape": list(self.shape),
             "dtype": self.dtype, "persistable": self.persistable,
             "is_data": self.is_data, "lod_level": self.lod_level,
             "trainable": self.trainable}
        # per-parameter attrs (ParamAttr) + sharding: only present when set
        for k in ("lr_scale", "l2_rate"):
            if getattr(self, k, None) is not None:
                d[k] = getattr(self, k)
        if self.sharding is not None:
            d["sharding"] = list(self.sharding)
        if self.bucket_axis is not None:
            d["bucket_axis"] = self.bucket_axis
        return d


class Operator:
    """OpDesc analog: type + named input/output var lists + attrs."""

    def __init__(self, block: "Block", op_type: str,
                 inputs: Dict[str, List[str]], outputs: Dict[str, List[str]],
                 attrs: Optional[Dict[str, Any]] = None):
        from .registry import OpRegistry  # late import to avoid cycle
        if not OpRegistry.has(op_type):
            raise ValueError(f"operator '{op_type}' is not registered")
        self.block = block
        self.type = op_type
        self.inputs = {k: list(v) for k, v in inputs.items()}
        self.outputs = {k: list(v) for k, v in outputs.items()}
        self.attrs = dict(attrs or {})

    def input_vars(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_vars(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def __repr__(self):
        return f"Operator({self.type}: {self.inputs} -> {self.outputs})"

    def to_dict(self):
        # callable attrs (host initializers) cannot serialize, but DROPPING
        # the key would make the serialized op lie about its attr surface —
        # diagnostics and goldens need the key, so emit a named placeholder
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs,
                "attrs": {k: (v if not callable(v) else
                              f"<callable:{getattr(v, '__name__', type(v).__name__)}>")
                          for k, v in self.attrs.items()}}


class Block:
    """BlockDesc analog: ordered op list + var table (scope.h namespace idea
    lives at runtime in executor.Scope)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    def create_var(self, name: Optional[str] = None, **kw) -> Variable:
        if name is None:
            name = self.program.unique_name("tmp")
        v = Variable(self, name, **kw)
        self.vars[name] = v
        return v

    def var(self, name: str) -> Variable:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = (self.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        raise KeyError(f"variable '{name}' not found")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def append_op(self, op_type: str, inputs, outputs, attrs=None) -> Operator:
        op = Operator(self, op_type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program.version += 1
        return op

    def all_parameters(self) -> List[Variable]:
        return [v for v in self.vars.values()
                if v.persistable and not v.is_data and v.trainable]

    def to_dict(self):
        return {"idx": self.idx, "parent_idx": self.parent_idx,
                "vars": [v.to_dict() for v in self.vars.values()],
                "ops": [o.to_dict() for o in self.ops]}


class Program:
    """ProgramDesc analog. Two default programs mirror fluid's
    default_startup_program (param init ops) + default_main_program."""

    _serial_counter = 0

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._name_counter = 0
        # Monotonic identity + mutation stamp for the executor's compiled-fn
        # cache: id(program) can be reused after GC, and an op list edited in
        # place must invalidate the cache (the reference recompiles per Run).
        Program._serial_counter += 1
        self._serial = Program._serial_counter
        self.version = 0
        # block stack for control-flow builders (While/StaticRNN/IfElse):
        # layer builders append ops to current_block(), which is the global
        # block unless a sub-block guard is active (BlockDesc nesting,
        # block_desc.h + fluid framework.py Program.current_block)
        self._block_stack: List[int] = [0]

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._block_stack[-1]]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        if parent_idx is None:
            parent_idx = self._block_stack[-1]
        b = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(b)
        return b

    @contextlib.contextmanager
    def block_guard(self, block: "Block"):
        """Append subsequent ops into ``block`` (control-flow sub-block)."""
        self._block_stack.append(block.idx)
        try:
            yield block
        finally:
            self._block_stack.pop()

    def unique_name(self, prefix: str) -> str:
        self._name_counter += 1
        return f"{prefix}_{self._name_counter}"

    def to_dict(self):
        return {"blocks": [b.to_dict() for b in self.blocks]}

    @classmethod
    def from_dict(cls, d) -> "Program":
        p = cls()
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                v = Variable(
                    b, vd["name"], vd["shape"], vd["dtype"],
                    vd["persistable"], vd["is_data"], vd.get("lod_level", 0),
                    vd.get("trainable", True), vd.get("sharding"),
                    vd.get("bucket_axis"))
                for k in ("lr_scale", "l2_rate"):
                    if k in vd:
                        setattr(v, k, vd[k])
                b.vars[vd["name"]] = v
            for od in bd["ops"]:
                b.append_op(od["type"], od["inputs"], od["outputs"], od["attrs"])
            p.blocks.append(b)
        return p

    # pruning (framework/prune.cc analog): keep only ops feeding the targets
    def prune(self, targets: Sequence[str]) -> "Program":
        block = self.global_block()
        needed = set(targets)
        keep: List[Operator] = []
        for op in reversed(block.ops):
            if needed & set(op.output_vars()) or op.type in ("feed",):
                keep.append(op)
                needed |= set(op.input_vars())
        pruned = Program()
        nb = pruned.global_block()
        nb.vars = dict(block.vars)
        nb.ops = list(reversed(keep))
        pruned._name_counter = self._name_counter
        return pruned


# -- default-program context (fluid framework.py:default_main_program) ---------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def reset_default_programs():
    global _main_program, _startup_program
    _main_program = Program()
    _startup_program = Program()
    # Also rewind the layers seed counter: initializer seeds are minted
    # from a process-global stream, so without this a program's weight
    # draws depend on how many layers the process built before it —
    # programs built after a reset would not be reproducible.
    _layers = sys.modules.get(__package__ + ".layers")
    if _layers is not None:
        _layers._seed_counter[0] = 0


@contextlib.contextmanager
def program_guard(main: Program, startup: Optional[Program] = None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main
    if startup is not None:
        _startup_program = startup
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s
