"""save/load persistables (fluid/io.py + save_op.cc/load_op.cc analog) —
reuses the CRC-checked tar format of trainer/checkpoint.py."""

from __future__ import annotations

import os
from typing import Optional

from ..trainer.checkpoint import from_tar, to_tar
from .executor import Executor, Scope, global_scope
from .framework import Program, default_main_program


def _persistable_names(program: Program):
    return [name for name, v in program.global_block().vars.items()
            if v.persistable]


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None):
    program = main_program or default_main_program()
    scope = executor.scope
    os.makedirs(dirname, exist_ok=True)
    tree = {n: scope.get(n) for n in _persistable_names(program)
            if scope.has(n)}
    with open(os.path.join(dirname, "persistables.tar"), "wb") as f:
        to_tar(f, tree)


def _restore(executor: Executor, program: Program, tree) -> None:
    """Place loaded host arrays into the scope — sharded per the
    executor's layout when it is mesh-aware (restore re-places onto the
    CURRENT mesh; a checkpoint gathered on an 8-chip job loads fine onto
    a 2-chip debug mesh because specs re-resolve against it)."""
    import jax
    import jax.numpy as jnp
    block = program.global_block()
    for name, arr in tree.items():
        if executor.mesh is not None and name in block.vars:
            sh = executor._persist_sharding(block, name, arr)
            executor.scope.set(name, jax.device_put(jnp.asarray(arr), sh))
        else:
            executor.scope.set(name, jnp.asarray(arr))


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None):
    with open(os.path.join(dirname, "persistables.tar"), "rb") as f:
        tree = from_tar(f)
    _restore(executor, main_program or default_main_program(), tree)


# -- merged inference model (capi merged-model + fluid io analog) ---------------

def export_inference_model(dirname: str, feed_names, fetch_vars,
                           executor: Executor,
                           main_program: Optional[Program] = None):
    """Save a deployable model: the program pruned to the fetch targets
    (training/backward ops dropped, framework/prune.cc analog) as JSON +
    the persistables tar — the single-artifact inference bundle of the
    reference's merge_model CLI (trainer/MergeModel.cpp:29) and the C API's
    merged model (capi/gradient_machine.h:36)."""
    import json
    program = main_program or default_main_program()
    fetch_names = [v.name if hasattr(v, "name") else str(v) for v in fetch_vars]
    pruned = program.prune(fetch_names)
    os.makedirs(dirname, exist_ok=True)
    prog_dict = pruned.to_dict()
    # recurrent ops in the bundle keep fused=auto (ops/rnn.py picks the
    # Pallas whole-sequence kernel for small latency-bound batches and
    # XLA's scan for large ones — the measured crossover is documented in
    # docs/design/fused_rnn_bench.md); ops with an explicit fused attr
    # keep it
    meta = {"program": prog_dict,
            "feed_names": list(feed_names),
            "fetch_names": fetch_names}
    with open(os.path.join(dirname, "model.json"), "w") as f:
        json.dump(meta, f)
    scope = executor.scope
    tree = {n: scope.get(n)
            for n, v in pruned.global_block().vars.items()
            if v.persistable and scope.has(n)}
    with open(os.path.join(dirname, "params.tar"), "wb") as f:
        to_tar(f, tree)


def load_inference_model(dirname: str, executor: Executor):
    """-> (program, feed_names, fetch_names); scope populated with params."""
    import json
    with open(os.path.join(dirname, "model.json")) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    with open(os.path.join(dirname, "params.tar"), "rb") as f:
        _restore(executor, program, from_tar(f))
    return program, meta["feed_names"], meta["fetch_names"]
