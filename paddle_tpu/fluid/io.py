"""save/load persistables (fluid/io.py + save_op.cc/load_op.cc analog) —
reuses the CRC-checked tar format of trainer/checkpoint.py."""

from __future__ import annotations

import os
from typing import Optional

from ..trainer.checkpoint import from_tar, to_tar
from .executor import Executor, Scope, global_scope
from .framework import Program, default_main_program


def _persistable_names(program: Program):
    return [name for name, v in program.global_block().vars.items()
            if v.persistable]


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None):
    program = main_program or default_main_program()
    scope = executor.scope
    os.makedirs(dirname, exist_ok=True)
    tree = {n: scope.get(n) for n in _persistable_names(program)
            if scope.has(n)}
    with open(os.path.join(dirname, "persistables.tar"), "wb") as f:
        to_tar(f, tree)


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None):
    import jax.numpy as jnp
    with open(os.path.join(dirname, "persistables.tar"), "rb") as f:
        tree = from_tar(f)
    for name, arr in tree.items():
        executor.scope.set(name, jnp.asarray(arr))
