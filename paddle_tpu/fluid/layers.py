"""Op-emitting layer builders (fluid/layers.py analog).

Each function appends OpDescs+VarDescs to the default main program and returns
the output Variable — the same builder pattern as python/paddle/v2/fluid/
layers.py (fc:18, embedding:90, data:179, conv2d:638). Parameter creation goes
through ``_create_parameter`` which also appends the init op to the startup
program (fluid initializer semantics).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..nn import initializer as I
from .framework import (Program, Variable, default_main_program,
                        default_startup_program)

_seed_counter = [0]


def _next_seed() -> int:
    _seed_counter[0] += 1
    return _seed_counter[0]


def _block():
    # current (possibly control-flow sub-) block — While/StaticRNN/Cond
    # builders push sub-blocks onto the program's block stack
    return default_main_program().current_block()


def _create_parameter(name_hint: str, shape, dtype="float32",
                      init: Optional[I.Initializer] = None,
                      trainable: bool = True, attr=None) -> Variable:
    """``attr`` carries ParamAttr-style per-parameter settings (the gen-1
    ParameterAttribute, trainer_config_helpers/attrs.py:52): dict keys
    ``name`` (exact name; a SECOND creation under the same name returns the
    existing parameter — the reference's name-based weight sharing between
    layers and between train/generate sub-models), ``init`` (overrides the
    layer's default initializer), ``is_static`` (frozen: no grad/update),
    ``lr_scale`` (per-param learning-rate multiplier) and ``l2_rate``
    (per-param weight decay) — the latter two consumed by
    fluid.optimizer.Optimizer.minimize — and ``sharding`` (one mesh axis
    name or None per dim; lowered by the mesh-aware Executor, linted by
    L004)."""
    main = default_main_program()
    attr = dict(attr) if attr else {}
    exact = attr.get("name")
    if exact is not None:
        existing = main.global_block().vars.get(exact)
        if existing is not None:
            if tuple(existing.shape) != tuple(shape):
                raise ValueError(
                    f"shared parameter {exact!r} shape mismatch: existing "
                    f"{existing.shape} vs requested {tuple(shape)}")
            if existing.dtype != np.dtype(dtype).name:
                raise ValueError(
                    f"shared parameter {exact!r} dtype mismatch: existing "
                    f"{existing.dtype} vs requested {dtype}")
            # behavioral attrs belong to the FIRST creation; a conflicting
            # re-declaration must fail loudly, not be silently dropped
            for key, current in (
                    ("is_static", not existing.trainable),
                    ("lr_scale", getattr(existing, "lr_scale", None)),
                    ("l2_rate", getattr(existing, "l2_rate", None)),
                    ("sharding", getattr(existing, "sharding", None))):
                if key in attr and attr[key] != current:
                    raise ValueError(
                        f"shared parameter {exact!r}: conflicting {key!r} "
                        f"({attr[key]!r} vs the creating layer's "
                        f"{current!r}); set attrs on the FIRST use only")
            return existing
        name = exact
    else:
        name = main.unique_name(name_hint)
    if attr.get("is_static"):
        trainable = False
    v = main.global_block().create_var(name=name, shape=shape, dtype=dtype,
                                       persistable=True, trainable=trainable)
    if attr.get("lr_scale") is not None:
        v.lr_scale = float(attr["lr_scale"])
    if attr.get("l2_rate") is not None:
        v.l2_rate = float(attr["l2_rate"])
    if attr.get("sharding") is not None:
        sh = attr["sharding"]
        v.sharding = (sh,) if isinstance(sh, str) else tuple(sh)
    sb = default_startup_program().global_block()
    sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
    sb.append_op("fill_init", inputs={}, outputs={"Out": [name]},
                 attrs={"shape": tuple(shape), "dtype": dtype,
                        "init": attr.get("init") or init or I.gen1_default(),
                        "seed": _next_seed()})
    return v


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0,
         sharding: Optional[Sequence[Optional[str]]] = None,
         bucket_axis: Optional[int] = None) -> Variable:
    """Feed slot (layers.py data:179); shape excludes the batch dim.

    ``sharding`` optionally names one mesh axis per dim (batch dim included,
    None = replicated), e.g. ``("data", None)`` — checked against
    parallel.mesh axis names by ``analysis.lint_program`` (L004).

    ``bucket_axis`` marks the variable-length axis (batch dim included) the
    executor's ``BucketSpec`` pads when the spec doesn't pin one — set it
    when the dynamic axis is not the feed's first ``-1`` dim."""
    return _block().create_var(name=name, shape=(-1,) + tuple(shape),
                               dtype=dtype, is_data=True, lod_level=lod_level,
                               sharding=sharding, bucket_axis=bucket_axis)


def fc(input: Variable, size: int, act: Optional[str] = None,
       bias_attr: bool = True, param_init=None, param_attr=None,
       bias_param_attr=None) -> Variable:
    # reference fc semantics (num_flatten_dims=1): everything after the batch
    # dim is flattened into the contraction, weight is [prod(rest), size]
    b = _block()
    in_dim = int(np.prod(input.shape[1:]))
    w = _create_parameter("fc_w", (in_dim, size), input.dtype, param_init,
                          attr=param_attr)
    out = b.create_var(shape=(input.shape[0], size), dtype=input.dtype)
    b.append_op("mul", {"X": [input.name], "Y": [w.name]},
                {"Out": [out.name]}, {"x_num_col_dims": 1})
    if bias_attr:
        bias = _create_parameter("fc_b", (size,), input.dtype, I.zeros,
                                 attr=bias_param_attr)
        out2 = b.create_var(shape=out.shape, dtype=out.dtype)
        b.append_op("elementwise_add", {"X": [out.name], "Y": [bias.name]},
                    {"Out": [out2.name]})
        out = out2
    if act:
        out = activation(out, act)
    return out


def embedding(input: Variable, size: Sequence[int], param_init=None,
              param_attr=None) -> Variable:
    b = _block()
    w = _create_parameter("embedding_w", tuple(size), "float32",
                          param_init or I.normal(0.0, 0.01), attr=param_attr)
    out = b.create_var(shape=input.shape + (size[1],), dtype="float32")
    b.append_op("lookup_table", {"W": [w.name], "Ids": [input.name]},
                {"Out": [out.name]})
    return out


def activation(input: Variable, act: str) -> Variable:
    b = _block()
    out = b.create_var(shape=input.shape, dtype=input.dtype)
    b.append_op(act, {"X": [input.name]}, {"Out": [out.name]})
    return out


def relu(x):
    return activation(x, "relu")


def sigmoid(x):
    return activation(x, "sigmoid")


def tanh(x):
    return activation(x, "tanh")


def softmax(x):
    return activation(x, "softmax")


def _binary(op_type: str, x: Variable, y: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape, dtype=x.dtype)
    b.append_op(op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]})
    return out


def elementwise_add(x, y):
    return _binary("elementwise_add", x, y)


def elementwise_sub(x, y):
    return _binary("elementwise_sub", x, y)


def elementwise_mul(x, y):
    return _binary("elementwise_mul", x, y)


def elementwise_div(x, y):
    return _binary("elementwise_div", x, y)


def matmul(x: Variable, y: Variable, transpose_x=False, transpose_y=False) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape[:-1] + (y.shape[-1],), dtype=x.dtype)
    b.append_op("matmul", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]},
                {"transpose_X": transpose_x, "transpose_Y": transpose_y})
    return out


def cross_entropy(input: Variable, label: Variable,
                  soft_label: bool = False) -> Variable:
    b = _block()
    out = b.create_var(shape=(input.shape[0], 1), dtype=input.dtype)
    b.append_op("cross_entropy", {"X": [input.name], "Label": [label.name]},
                {"Y": [out.name]}, {"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits: Variable, label: Variable) -> Variable:
    b = _block()
    loss = b.create_var(shape=(logits.shape[0], 1), dtype=logits.dtype)
    soft = b.create_var(shape=logits.shape, dtype=logits.dtype)
    b.append_op("softmax_with_cross_entropy",
                {"Logits": [logits.name], "Label": [label.name]},
                {"Loss": [loss.name], "Softmax": [soft.name]})
    return loss


def mean(x: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=(), dtype=x.dtype)
    b.append_op("mean", {"X": [x.name]}, {"Out": [out.name]})
    return out


def sums(xs: List[Variable]) -> Variable:
    b = _block()
    out = b.create_var(shape=xs[0].shape, dtype=xs[0].dtype)
    b.append_op("sum", {"X": [v.name for v in xs]}, {"Out": [out.name]})
    return out


def reshape(x: Variable, shape: Sequence[int]) -> Variable:
    b = _block()
    out = b.create_var(shape=tuple(shape), dtype=x.dtype)
    b.append_op("reshape", {"X": [x.name]}, {"Out": [out.name]},
                {"shape": tuple(shape)})
    return out


def concat(xs: List[Variable], axis: int = 0) -> Variable:
    b = _block()
    shape = list(xs[0].shape)
    shape[axis] = sum(v.shape[axis] for v in xs)
    out = b.create_var(shape=tuple(shape), dtype=xs[0].dtype)
    b.append_op("concat", {"X": [v.name for v in xs]}, {"Out": [out.name]},
                {"axis": axis})
    return out


def _ensure_step_var() -> str:
    """Implicit int32 step counter the Executor feeds and increments each run
    — gives stochastic ops a fresh key per batch (the reference reseeds
    per-batch via its global RNG)."""
    b = _block()
    if not b.has_var("__step__"):
        b.create_var(name="__step__", shape=(), dtype="int32", is_data=True)
    return "__step__"


def dropout(x: Variable, dropout_prob: float, is_test: bool = False) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape, dtype=x.dtype)
    inputs = {"X": [x.name]}
    if not is_test:
        inputs["Step"] = [_ensure_step_var()]
    b.append_op("dropout", inputs, {"Out": [out.name]},
                {"dropout_prob": dropout_prob, "is_test": is_test,
                 "seed": _next_seed()})
    return out


def _spatial_out(size: int, k: int, pad: int, stride: int) -> int:
    """Static conv/pool output extent; -1 propagates unknowns."""
    return (size + 2 * pad - k) // stride + 1 if size > 0 else -1


def conv2d(input: Variable, num_filters: int, filter_size: int, stride=1,
           padding=0, groups: int = 1, act: Optional[str] = None,
           bias_attr: bool = True) -> Variable:
    b = _block()
    cin = input.shape[-1]
    k = (filter_size, filter_size) if isinstance(filter_size, int) else filter_size
    w = _create_parameter("conv2d_w", k + (cin // groups, num_filters),
                          input.dtype, I.msra())
    s = (stride, stride) if isinstance(stride, int) else stride
    p = (padding, padding) if isinstance(padding, int) else padding
    oh = _spatial_out(input.shape[1], k[0], p[0], s[0])
    ow = _spatial_out(input.shape[2], k[1], p[1], s[1])
    out = b.create_var(shape=(input.shape[0], oh, ow, num_filters),
                       dtype=input.dtype)
    b.append_op("conv2d", {"Input": [input.name], "Filter": [w.name]},
                {"Out": [out.name]},
                {"strides": stride, "paddings": padding, "groups": groups})
    if bias_attr:
        bias = _create_parameter("conv2d_b", (num_filters,), input.dtype, I.zeros)
        out2 = b.create_var(shape=out.shape, dtype=out.dtype)
        b.append_op("elementwise_add", {"X": [out.name], "Y": [bias.name]},
                    {"Out": [out2.name]})
        out = out2
    if act:
        out = activation(out, act)
    return out


def pool2d(input: Variable, pool_size: int = 2, pool_type: str = "max",
           pool_stride=None, pool_padding=0,
           global_pooling: bool = False) -> Variable:
    b = _block()
    if global_pooling:
        out_shape = (input.shape[0], input.shape[-1])
    else:
        k = (pool_size, pool_size) if isinstance(pool_size, int) else pool_size
        st = pool_stride if pool_stride is not None else pool_size
        s = (st, st) if isinstance(st, int) else st
        p = ((pool_padding, pool_padding) if isinstance(pool_padding, int)
             else pool_padding)
        out_shape = (input.shape[0],
                     _spatial_out(input.shape[1], k[0], p[0], s[0]),
                     _spatial_out(input.shape[2], k[1], p[1], s[1]),
                     input.shape[-1])
    out = b.create_var(shape=out_shape, dtype=input.dtype)
    b.append_op("pool2d", {"X": [input.name]}, {"Out": [out.name]},
                {"ksize": pool_size, "pooling_type": pool_type,
                 "strides": pool_stride, "paddings": pool_padding,
                 "global_pooling": global_pooling})
    return out


def accuracy(input: Variable, label: Variable) -> Variable:
    b = _block()
    acc = b.create_var(shape=(), dtype="float32")
    cor = b.create_var(shape=(), dtype="float32")
    tot = b.create_var(shape=(), dtype="float32")
    b.append_op("accuracy", {"Out": [input.name], "Label": [label.name]},
                {"Accuracy": [acc.name], "Correct": [cor.name],
                 "Total": [tot.name]})
    return acc


# =============================================================================
# Control flow (fluid layers.py While:1163, StaticRNN:935; while_op.cc,
# conditional_block_op.cc, recurrent_op.cc) — builders emit sub-blocks the
# executor lowers to lax.while_loop / lax.cond / lax.scan.
# =============================================================================

import contextlib as _contextlib


def fill_constant(shape, dtype="float32", value=0.0) -> Variable:
    b = _block()
    out = b.create_var(shape=tuple(shape), dtype=dtype)
    b.append_op("fill_constant", {}, {"Out": [out.name]},
                {"shape": tuple(shape), "dtype": dtype, "value": value})
    return out


def increment(x: Variable, value=1, in_place: bool = True) -> Variable:
    b = _block()
    out = x if in_place else b.create_var(shape=x.shape, dtype=x.dtype)
    b.append_op("increment", {"X": [x.name]}, {"Out": [out.name]},
                {"step": value})
    return out


def _compare_layer(op_type, x: Variable, y: Variable,
                   cond: Optional[Variable] = None) -> Variable:
    b = _block()
    out = cond if cond is not None else b.create_var(shape=x.shape, dtype="bool")
    b.append_op(op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]})
    return out


def less_than(x, y, cond=None):
    return _compare_layer("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare_layer("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare_layer("greater_than", x, y, cond)


def equal(x, y, cond=None):
    return _compare_layer("equal", x, y, cond)


def logical_and(x, y, cond=None):
    return _compare_layer("logical_and", x, y, cond)


def logical_not(x: Variable, cond=None) -> Variable:
    b = _block()
    out = cond if cond is not None else b.create_var(shape=x.shape, dtype="bool")
    b.append_op("logical_not", {"X": [x.name]}, {"Out": [out.name]})
    return out


def assign(x: Variable, out: Variable) -> Variable:
    b = _block()
    b.append_op("assign", {"X": [x.name]}, {"Out": [out.name]})
    return out


def array_write(x: Variable, i: Variable, array: Optional[Variable] = None,
                capacity: Optional[int] = None) -> Variable:
    """Write x at index i. Without ``array``, allocates a fixed-capacity
    buffer (XLA needs static sizes; capacity stands in for the reference's
    growable TensorArray, tensor_array_read_write_op.cc)."""
    b = _block()
    inputs = {"X": [x.name], "I": [i.name]}
    attrs = {}
    if array is None:
        if capacity is None:
            raise ValueError("array_write needs `capacity` when creating a new array")
        array = b.create_var(shape=(capacity,) + tuple(x.shape), dtype=x.dtype)
        attrs["capacity"] = capacity
    else:
        inputs["Array"] = [array.name]
    b.append_op("array_write", inputs, {"Out": [array.name]}, attrs)
    return array


def array_read(array: Variable, i: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=tuple(array.shape[1:]), dtype=array.dtype)
    b.append_op("array_read", {"Array": [array.name], "I": [i.name]},
                {"Out": [out.name]})
    return out


def lod_tensor_to_array(x: Variable) -> Variable:
    """[B, T, ...] -> time-major array for per-step array_read."""
    b = _block()
    shape = (x.shape[1], x.shape[0]) + tuple(x.shape[2:])
    out = b.create_var(shape=shape, dtype=x.dtype)
    b.append_op("lod_tensor_to_array", {"X": [x.name]}, {"Out": [out.name]})
    return out


def array_to_lod_tensor(arr: Variable) -> Variable:
    b = _block()
    shape = (arr.shape[1], arr.shape[0]) + tuple(arr.shape[2:])
    out = b.create_var(shape=shape, dtype=arr.dtype)
    b.append_op("array_to_lod_tensor", {"X": [arr.name]}, {"Out": [out.name]})
    return out


class While:
    """``with While(cond).block(): ...`` — body ops re-run until cond is
    false; the body must update cond (e.g. ``less_than(i, n, cond=cond)``).
    Any outer var the body writes is loop state (while_op.cc semantics via
    lax.while_loop)."""

    def __init__(self, cond: Variable):
        self.cond = cond
        self.main = default_main_program()

    @_contextlib.contextmanager
    def block(self):
        parent = self.main.current_block()
        sub = self.main.create_block()
        with self.main.block_guard(sub):
            yield
        parent.append_op("while", {"Condition": [self.cond.name]}, {},
                         {"sub_block_idx": sub.idx})


class Cond:
    """Scalar-predicate conditional (conditional_block_op.cc lowered to
    lax.cond). Vars written inside must already exist outside, giving the
    untaken branch a pass-through value::

        c = Cond(pred)
        with c.true_block():  assign(a, out)
        with c.false_block(): assign(b, out)
    """

    def __init__(self, pred: Variable):
        self.pred = pred
        self.main = default_main_program()
        self._op = None

    @_contextlib.contextmanager
    def true_block(self):
        parent = self.main.current_block()
        sub = self.main.create_block()
        with self.main.block_guard(sub):
            yield
        self._op = parent.append_op(
            "conditional_block", {"Condition": [self.pred.name]}, {},
            {"true_block_idx": sub.idx, "false_block_idx": None})

    @_contextlib.contextmanager
    def false_block(self):
        if self._op is None:
            raise ValueError("false_block() requires a prior true_block()")
        sub = self.main.create_block()
        with self.main.block_guard(sub):
            yield
        self._op.attrs["false_block_idx"] = sub.idx
        self.main.version += 1   # attrs edited post-append: invalidate cache


class StaticRNN:
    """Step-network builder compiled to ONE lax.scan (fluid StaticRNN /
    recurrent_op.cc; the TPU-native form of RecurrentGradientMachine's
    per-step frames)::

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)            # x: [B, T, D]
            h_prev = rnn.memory(init=h0)       # or shape=(H,), value=0
            h = layers.fc(x_t, H, act='tanh')  # any ops
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out, = rnn()                           # [B, T, H]
    """

    def __init__(self):
        self.main = default_main_program()
        self._sub = None
        self._outer_inputs: List[str] = []
        self._step_in_names: List[str] = []
        self._boot_mems: List[str] = []
        self._mem_names: List[str] = []
        self._mem_updates: List[str] = []
        self._step_out_names: List[str] = []
        self._outer_outputs: List[Variable] = []
        self._parent = None

    @_contextlib.contextmanager
    def step(self):
        self._parent = self.main.current_block()
        self._sub = self.main.create_block()
        with self.main.block_guard(self._sub):
            yield
        if (len(self._mem_names) != len(self._mem_updates)
                or None in self._mem_updates):
            raise ValueError("every memory() needs an update_memory()")
        a = {"sub_block_idx": self._sub.idx,
             "outer_inputs": list(self._outer_inputs),
             "step_in_names": list(self._step_in_names),
             "boot_mems": list(self._boot_mems),
             "mem_names": list(self._mem_names),
             "mem_update_names": list(self._mem_updates),
             "step_out_names": list(self._step_out_names),
             "outer_outputs": [v.name for v in self._outer_outputs],
             "last_mem_outputs": []}
        self._parent.append_op(
            "static_rnn", {"X": list(self._outer_inputs)},
            {"Out": [v.name for v in self._outer_outputs]}, a)
        self._attrs = a

    def step_input(self, x: Variable) -> Variable:
        """Slice [B, T, ...] per step -> [B, ...] inside the step block."""
        v = self._sub.create_var(shape=(x.shape[0],) + tuple(x.shape[2:]),
                                 dtype=x.dtype)
        self._outer_inputs.append(x.name)
        self._step_in_names.append(v.name)
        return v

    def memory(self, init: Optional[Variable] = None,
               shape=None, value: float = 0.0,
               batch_ref: Optional[Variable] = None) -> Variable:
        """Recurrent state booted from ``init`` (an outer var — the
        bootLayer of MemoryFrameLine, RecurrentGradientMachine.h:329) or
        zeros/[value] of ``shape`` broadcast over the batch of ``batch_ref``."""
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init= or (shape= and batch_ref=)")
            # the boot op runs in the parent block, so a step-input reference
            # must resolve to its outer [B, T, ...] source (same batch dim 0)
            ref_name = batch_ref.name
            if ref_name in self._step_in_names:
                ref_name = self._outer_inputs[self._step_in_names.index(ref_name)]
            b = self._parent   # boot op lives in the parent block
            boot = b.create_var(shape=(batch_ref.shape[0],) + tuple(shape),
                                dtype=batch_ref.dtype)
            b.append_op("fill_constant_batch_size_like",
                        {"Input": [ref_name]}, {"Out": [boot.name]},
                        {"shape": (1,) + tuple(shape), "value": value,
                         "dtype": batch_ref.dtype})
            init = boot
        v = self._sub.create_var(shape=tuple(init.shape), dtype=init.dtype)
        self._boot_mems.append(init.name)
        self._mem_names.append(v.name)
        return v

    def update_memory(self, mem: Variable, new_val: Variable):
        idx = self._mem_names.index(mem.name)
        while len(self._mem_updates) <= idx:
            self._mem_updates.append(None)
        self._mem_updates[idx] = new_val.name

    def step_output(self, out: Variable):
        self._step_out_names.append(out.name)
        v = self._parent.create_var(
            shape=(out.shape[0], -1) + tuple(out.shape[1:]), dtype=out.dtype)
        self._outer_outputs.append(v)

    def get_last_mem(self, mem: Variable) -> Variable:
        """Final memory value after the scan (sequence_last analogue)."""
        idx = self._mem_names.index(mem.name)
        v = self._parent.create_var(shape=tuple(mem.shape), dtype=mem.dtype)
        while len(self._attrs["last_mem_outputs"]) <= idx:
            self._attrs["last_mem_outputs"].append(None)
        self._attrs["last_mem_outputs"][idx] = v.name
        self.main.version += 1
        return v

    def __call__(self) -> List[Variable]:
        return list(self._outer_outputs)


# =============================================================================
# Layer builders — fluid/layers.py parity (batch_norm:765, dynamic_lstm:131,
# conv2d:638, sequence ops, losses, metrics).
# =============================================================================

def batch_norm(input: Variable, act: Optional[str] = None,
               momentum: float = 0.9, epsilon: float = 1e-5,
               is_test: bool = False) -> Variable:
    """Training-capable batch norm: scale/bias are parameters; running
    mean/variance are persistable stats the op updates in-place each step
    (batch_norm_op.cc; fixes round-1's inference-only registration)."""
    main = default_main_program()
    b = _block()
    C = input.shape[-1]
    scale = _create_parameter("bn_scale", (C,), input.dtype, I.constant(1.0))
    bias = _create_parameter("bn_bias", (C,), input.dtype, I.zeros)
    # running stats are state, not weights: trainable=False keeps them out of
    # all_parameters() so optimizers/regularizers never touch them
    mean = _create_parameter("bn_mean", (C,), input.dtype, I.zeros,
                             trainable=False)
    var = _create_parameter("bn_var", (C,), input.dtype, I.constant(1.0),
                            trainable=False)
    out = b.create_var(shape=input.shape, dtype=input.dtype)
    b.append_op("batch_norm",
                {"X": [input.name], "Scale": [scale.name], "Bias": [bias.name],
                 "Mean": [mean.name], "Variance": [var.name]},
                {"Y": [out.name], "MeanOut": [mean.name],
                 "VarianceOut": [var.name]},
                {"momentum": momentum, "epsilon": epsilon, "is_test": is_test})
    if act:
        out = activation(out, act)
    return out


def dynamic_lstm(input: Variable, lengths: Optional[Variable], size: int,
                 reverse: bool = False) -> Variable:
    """Whole-sequence LSTM as one op (dynamic_lstm analog; the scan is inside
    the 'lstm' registry op). input [B, T, D] -> [B, T, size]."""
    b = _block()
    D = input.shape[-1]
    w = _create_parameter("lstm_w", (D, 4 * size), input.dtype)
    u = _create_parameter("lstm_u", (size, 4 * size), input.dtype)
    bias = _create_parameter("lstm_b", (4 * size,), input.dtype, I.zeros)
    out = b.create_var(shape=input.shape[:-1] + (size,), dtype=input.dtype)
    h = b.create_var(shape=(input.shape[0], size), dtype=input.dtype)
    c = b.create_var(shape=(input.shape[0], size), dtype=input.dtype)
    inputs = {"X": [input.name], "W": [w.name], "U": [u.name], "B": [bias.name]}
    if lengths is not None:
        inputs["Lengths"] = [lengths.name]
    b.append_op("lstm", inputs,
                {"Out": [out.name], "LastH": [h.name], "LastC": [c.name]},
                {"reverse": reverse})
    return out


def dynamic_gru(input: Variable, lengths: Optional[Variable], size: int,
                reverse: bool = False) -> Variable:
    b = _block()
    D = input.shape[-1]
    w = _create_parameter("gru_w", (D, 3 * size), input.dtype)
    u = _create_parameter("gru_u", (size, 3 * size), input.dtype)
    bias = _create_parameter("gru_b", (3 * size,), input.dtype, I.zeros)
    out = b.create_var(shape=input.shape[:-1] + (size,), dtype=input.dtype)
    h = b.create_var(shape=(input.shape[0], size), dtype=input.dtype)
    inputs = {"X": [input.name], "W": [w.name], "U": [u.name], "B": [bias.name]}
    if lengths is not None:
        inputs["Lengths"] = [lengths.name]
    b.append_op("gru", inputs, {"Out": [out.name], "LastH": [h.name]},
                {"reverse": reverse})
    return out


def sequence_pool(input: Variable, lengths: Variable,
                  pool_type: str = "average") -> Variable:
    b = _block()
    out = b.create_var(shape=(input.shape[0],) + tuple(input.shape[2:]),
                       dtype=input.dtype)
    b.append_op("sequence_pool",
                {"X": [input.name], "Lengths": [lengths.name]},
                {"Out": [out.name]}, {"pool_type": pool_type})
    return out


def sequence_last_step(input: Variable, lengths: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=(input.shape[0],) + tuple(input.shape[2:]),
                       dtype=input.dtype)
    b.append_op("sequence_last_step",
                {"X": [input.name], "Lengths": [lengths.name]},
                {"Out": [out.name]})
    return out


def sequence_expand(x: Variable, ref_lengths: Variable, max_len: int) -> Variable:
    b = _block()
    out = b.create_var(shape=(x.shape[0], max_len) + tuple(x.shape[1:]),
                       dtype=x.dtype)
    b.append_op("sequence_expand",
                {"X": [x.name], "RefLengths": [ref_lengths.name]},
                {"Out": [out.name]}, {"max_len": max_len})
    return out


def sequence_softmax(x: Variable, lengths: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape, dtype=x.dtype)
    b.append_op("sequence_softmax",
                {"X": [x.name], "Lengths": [lengths.name]},
                {"Out": [out.name]})
    return out


def sequence_conv(input: Variable, lengths: Variable, num_filters: int,
                  filter_size: int = 3, act: Optional[str] = None) -> Variable:
    b = _block()
    D = input.shape[-1]
    filt = _create_parameter("seqconv_w", (filter_size * D, num_filters),
                             input.dtype)
    out = b.create_var(shape=input.shape[:-1] + (num_filters,),
                       dtype=input.dtype)
    b.append_op("sequence_conv",
                {"X": [input.name], "Lengths": [lengths.name],
                 "Filter": [filt.name]},
                {"Out": [out.name]},
                {"context_start": -(filter_size // 2),
                 "context_length": filter_size})
    if act:
        out = activation(out, act)
    return out


def linear_chain_crf(emission: Variable, label: Variable,
                     lengths: Variable) -> tuple:
    """Returns (nll_per_seq, transition_param). Transition packs
    [start; end; pairwise] rows like LinearChainCRF.cpp."""
    b = _block()
    N = emission.shape[-1]
    trans = _create_parameter("crf_transition", (N + 2, N), emission.dtype,
                              I.normal(0.0, 0.1))
    ll = b.create_var(shape=(emission.shape[0],), dtype=emission.dtype)
    b.append_op("linear_chain_crf",
                {"Emission": [emission.name], "Label": [label.name],
                 "Lengths": [lengths.name], "Transition": [trans.name]},
                {"LogLikelihood": [ll.name]})
    return ll, trans


def crf_decoding(emission: Variable, lengths: Variable,
                 transition: Variable) -> Variable:
    b = _block()
    path = b.create_var(shape=emission.shape[:-1], dtype="int32")
    score = b.create_var(shape=(emission.shape[0],), dtype=emission.dtype)
    b.append_op("crf_decoding",
                {"Emission": [emission.name], "Lengths": [lengths.name],
                 "Transition": [transition.name]},
                {"ViterbiPath": [path.name], "Score": [score.name]})
    return path


def conv2d_transpose(input: Variable, num_filters: int, filter_size: int,
                     stride=1, padding=0) -> Variable:
    b = _block()
    cin = input.shape[-1]
    k = (filter_size, filter_size) if isinstance(filter_size, int) else filter_size
    w = _create_parameter("deconv_w", k + (cin, num_filters), input.dtype,
                          I.msra())
    s = (stride, stride) if isinstance(stride, int) else stride
    p = (padding, padding) if isinstance(padding, int) else padding
    # inverse of _spatial_out: (in-1)*stride - 2*pad + kernel
    oh = ((input.shape[1] - 1) * s[0] - 2 * p[0] + k[0]
          if input.shape[1] > 0 else -1)
    ow = ((input.shape[2] - 1) * s[1] - 2 * p[1] + k[1]
          if input.shape[2] > 0 else -1)
    out = b.create_var(shape=(input.shape[0], oh, ow, num_filters),
                       dtype=input.dtype)
    b.append_op("conv2d_transpose",
                {"Input": [input.name], "Filter": [w.name]},
                {"Out": [out.name]},
                {"strides": stride, "paddings": padding})
    return out


def lrn(input: Variable, n: int = 5, alpha: float = 1e-4, beta: float = 0.75,
        k: float = 1.0) -> Variable:
    b = _block()
    out = b.create_var(shape=input.shape, dtype=input.dtype)
    b.append_op("lrn", {"X": [input.name]}, {"Out": [out.name]},
                {"n": n, "alpha": alpha, "beta": beta, "k": k})
    return out


def topk(input: Variable, k: int) -> tuple:
    b = _block()
    vals = b.create_var(shape=input.shape[:-1] + (k,), dtype=input.dtype)
    idx = b.create_var(shape=input.shape[:-1] + (k,), dtype="int32")
    b.append_op("top_k", {"X": [input.name]},
                {"Out": [vals.name], "Indices": [idx.name]}, {"k": k})
    return vals, idx


def cast(x: Variable, dtype: str) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape, dtype=dtype)
    b.append_op("cast", {"X": [x.name]}, {"Out": [out.name]}, {"dtype": dtype})
    return out


def _reduced_shape(shape, dim, keep_dim):
    if dim is None:
        return (1,) * len(shape) if keep_dim else ()
    dims = (dim,) if isinstance(dim, int) else tuple(dim)
    dims = tuple(d % len(shape) for d in dims)
    if keep_dim:
        return tuple(1 if i in dims else s for i, s in enumerate(shape))
    return tuple(s for i, s in enumerate(shape) if i not in dims)


def reduce_sum(x: Variable, dim=None, keep_dim: bool = False) -> Variable:
    b = _block()
    out = b.create_var(shape=_reduced_shape(x.shape, dim, keep_dim),
                       dtype=x.dtype)
    b.append_op("reduce_sum", {"X": [x.name]}, {"Out": [out.name]},
                {"dim": dim, "keep_dim": keep_dim})
    return out


def auc(input: Variable, label: Variable, num_thresholds: int = 200) -> Variable:
    b = _block()
    out = b.create_var(shape=(), dtype="float32")
    ph = b.create_var(shape=(num_thresholds,), dtype="float32")
    nh = b.create_var(shape=(num_thresholds,), dtype="float32")
    b.append_op("auc", {"Out": [input.name], "Label": [label.name]},
                {"AUC": [out.name], "PosHist": [ph.name], "NegHist": [nh.name]},
                {"num_thresholds": num_thresholds})
    return out


def chunk_eval(inference: Variable, label: Variable, lengths: Variable,
               chunk_scheme: str = "IOB", num_chunk_types: int = 1) -> tuple:
    b = _block()
    c = b.create_var(shape=(), dtype="float32")
    p = b.create_var(shape=(), dtype="float32")
    l = b.create_var(shape=(), dtype="float32")
    b.append_op("chunk_eval",
                {"Inference": [inference.name], "Label": [label.name],
                 "Lengths": [lengths.name]},
                {"Correct": [c.name], "Predicted": [p.name], "Labeled": [l.name]},
                {"chunk_scheme": chunk_scheme,
                 "num_chunk_types": num_chunk_types})
    return c, p, l


def squeeze(x: Variable, axis: int) -> Variable:
    b = _block()
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    out = b.create_var(shape=shape, dtype=x.dtype)
    b.append_op("squeeze", {"X": [x.name]}, {"Out": [out.name]},
                {"axis": axis})
    return out


def unsqueeze(x: Variable, axis: int) -> Variable:
    b = _block()
    shape = list(x.shape)
    shape.insert(axis % (len(x.shape) + 1), 1)
    out = b.create_var(shape=tuple(shape), dtype=x.dtype)
    b.append_op("unsqueeze", {"X": [x.name]}, {"Out": [out.name]},
                {"axis": axis})
    return out
