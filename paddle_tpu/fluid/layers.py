"""Op-emitting layer builders (fluid/layers.py analog).

Each function appends OpDescs+VarDescs to the default main program and returns
the output Variable — the same builder pattern as python/paddle/v2/fluid/
layers.py (fc:18, embedding:90, data:179, conv2d:638). Parameter creation goes
through ``_create_parameter`` which also appends the init op to the startup
program (fluid initializer semantics).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..nn import initializer as I
from .framework import (Program, Variable, default_main_program,
                        default_startup_program)

_seed_counter = [0]


def _next_seed() -> int:
    _seed_counter[0] += 1
    return _seed_counter[0]


def _block():
    return default_main_program().global_block()


def _create_parameter(name_hint: str, shape, dtype="float32",
                      init: Optional[I.Initializer] = None) -> Variable:
    main = default_main_program()
    name = main.unique_name(name_hint)
    v = main.global_block().create_var(name=name, shape=shape, dtype=dtype,
                                       persistable=True)
    sb = default_startup_program().global_block()
    sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
    sb.append_op("fill_init", inputs={}, outputs={"Out": [name]},
                 attrs={"shape": tuple(shape), "dtype": dtype,
                        "init": init or I.gen1_default(), "seed": _next_seed()})
    return v


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0) -> Variable:
    """Feed slot (layers.py data:179); shape excludes the batch dim."""
    return _block().create_var(name=name, shape=(-1,) + tuple(shape),
                               dtype=dtype, is_data=True, lod_level=lod_level)


def fc(input: Variable, size: int, act: Optional[str] = None,
       bias_attr: bool = True, param_init=None) -> Variable:
    # reference fc semantics (num_flatten_dims=1): everything after the batch
    # dim is flattened into the contraction, weight is [prod(rest), size]
    b = _block()
    in_dim = int(np.prod(input.shape[1:]))
    w = _create_parameter("fc_w", (in_dim, size), input.dtype, param_init)
    out = b.create_var(shape=(input.shape[0], size), dtype=input.dtype)
    b.append_op("mul", {"X": [input.name], "Y": [w.name]},
                {"Out": [out.name]}, {"x_num_col_dims": 1})
    if bias_attr:
        bias = _create_parameter("fc_b", (size,), input.dtype, I.zeros)
        out2 = b.create_var(shape=out.shape, dtype=out.dtype)
        b.append_op("elementwise_add", {"X": [out.name], "Y": [bias.name]},
                    {"Out": [out2.name]})
        out = out2
    if act:
        out = activation(out, act)
    return out


def embedding(input: Variable, size: Sequence[int], param_init=None) -> Variable:
    b = _block()
    w = _create_parameter("embedding_w", tuple(size), "float32",
                          param_init or I.normal(0.0, 0.01))
    out = b.create_var(shape=input.shape + (size[1],), dtype="float32")
    b.append_op("lookup_table", {"W": [w.name], "Ids": [input.name]},
                {"Out": [out.name]})
    return out


def activation(input: Variable, act: str) -> Variable:
    b = _block()
    out = b.create_var(shape=input.shape, dtype=input.dtype)
    b.append_op(act, {"X": [input.name]}, {"Out": [out.name]})
    return out


def relu(x):
    return activation(x, "relu")


def sigmoid(x):
    return activation(x, "sigmoid")


def tanh(x):
    return activation(x, "tanh")


def softmax(x):
    return activation(x, "softmax")


def _binary(op_type: str, x: Variable, y: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape, dtype=x.dtype)
    b.append_op(op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]})
    return out


def elementwise_add(x, y):
    return _binary("elementwise_add", x, y)


def elementwise_sub(x, y):
    return _binary("elementwise_sub", x, y)


def elementwise_mul(x, y):
    return _binary("elementwise_mul", x, y)


def elementwise_div(x, y):
    return _binary("elementwise_div", x, y)


def matmul(x: Variable, y: Variable, transpose_x=False, transpose_y=False) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape[:-1] + (y.shape[-1],), dtype=x.dtype)
    b.append_op("matmul", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]},
                {"transpose_X": transpose_x, "transpose_Y": transpose_y})
    return out


def cross_entropy(input: Variable, label: Variable,
                  soft_label: bool = False) -> Variable:
    b = _block()
    out = b.create_var(shape=(input.shape[0], 1), dtype=input.dtype)
    b.append_op("cross_entropy", {"X": [input.name], "Label": [label.name]},
                {"Y": [out.name]}, {"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits: Variable, label: Variable) -> Variable:
    b = _block()
    loss = b.create_var(shape=(logits.shape[0], 1), dtype=logits.dtype)
    soft = b.create_var(shape=logits.shape, dtype=logits.dtype)
    b.append_op("softmax_with_cross_entropy",
                {"Logits": [logits.name], "Label": [label.name]},
                {"Loss": [loss.name], "Softmax": [soft.name]})
    return loss


def mean(x: Variable) -> Variable:
    b = _block()
    out = b.create_var(shape=(), dtype=x.dtype)
    b.append_op("mean", {"X": [x.name]}, {"Out": [out.name]})
    return out


def sums(xs: List[Variable]) -> Variable:
    b = _block()
    out = b.create_var(shape=xs[0].shape, dtype=xs[0].dtype)
    b.append_op("sum", {"X": [v.name for v in xs]}, {"Out": [out.name]})
    return out


def reshape(x: Variable, shape: Sequence[int]) -> Variable:
    b = _block()
    out = b.create_var(shape=tuple(shape), dtype=x.dtype)
    b.append_op("reshape", {"X": [x.name]}, {"Out": [out.name]},
                {"shape": tuple(shape)})
    return out


def concat(xs: List[Variable], axis: int = 0) -> Variable:
    b = _block()
    shape = list(xs[0].shape)
    shape[axis] = sum(v.shape[axis] for v in xs)
    out = b.create_var(shape=tuple(shape), dtype=xs[0].dtype)
    b.append_op("concat", {"X": [v.name for v in xs]}, {"Out": [out.name]},
                {"axis": axis})
    return out


def _ensure_step_var() -> str:
    """Implicit int32 step counter the Executor feeds and increments each run
    — gives stochastic ops a fresh key per batch (the reference reseeds
    per-batch via its global RNG)."""
    b = _block()
    if not b.has_var("__step__"):
        b.create_var(name="__step__", shape=(), dtype="int32", is_data=True)
    return "__step__"


def dropout(x: Variable, dropout_prob: float, is_test: bool = False) -> Variable:
    b = _block()
    out = b.create_var(shape=x.shape, dtype=x.dtype)
    inputs = {"X": [x.name]}
    if not is_test:
        inputs["Step"] = [_ensure_step_var()]
    b.append_op("dropout", inputs, {"Out": [out.name]},
                {"dropout_prob": dropout_prob, "is_test": is_test,
                 "seed": _next_seed()})
    return out


def conv2d(input: Variable, num_filters: int, filter_size: int, stride=1,
           padding=0, groups: int = 1, act: Optional[str] = None,
           bias_attr: bool = True) -> Variable:
    b = _block()
    cin = input.shape[-1]
    k = (filter_size, filter_size) if isinstance(filter_size, int) else filter_size
    w = _create_parameter("conv2d_w", k + (cin // groups, num_filters),
                          input.dtype, I.msra())
    out = b.create_var(shape=(-1, -1, -1, num_filters), dtype=input.dtype)
    b.append_op("conv2d", {"Input": [input.name], "Filter": [w.name]},
                {"Out": [out.name]},
                {"strides": stride, "paddings": padding, "groups": groups})
    if bias_attr:
        bias = _create_parameter("conv2d_b", (num_filters,), input.dtype, I.zeros)
        out2 = b.create_var(shape=out.shape, dtype=out.dtype)
        b.append_op("elementwise_add", {"X": [out.name], "Y": [bias.name]},
                    {"Out": [out2.name]})
        out = out2
    if act:
        out = activation(out, act)
    return out


def pool2d(input: Variable, pool_size: int = 2, pool_type: str = "max",
           pool_stride=None, pool_padding=0,
           global_pooling: bool = False) -> Variable:
    b = _block()
    out_shape = ((-1, input.shape[-1]) if global_pooling
                 else (-1, -1, -1, input.shape[-1]))
    out = b.create_var(shape=out_shape, dtype=input.dtype)
    b.append_op("pool2d", {"X": [input.name]}, {"Out": [out.name]},
                {"ksize": pool_size, "pooling_type": pool_type,
                 "strides": pool_stride, "paddings": pool_padding,
                 "global_pooling": global_pooling})
    return out


def accuracy(input: Variable, label: Variable) -> Variable:
    b = _block()
    acc = b.create_var(shape=(), dtype="float32")
    cor = b.create_var(shape=(), dtype="float32")
    tot = b.create_var(shape=(), dtype="float32")
    b.append_op("accuracy", {"Out": [input.name], "Label": [label.name]},
                {"Accuracy": [acc.name], "Correct": [cor.name],
                 "Total": [tot.name]})
    return acc
