"""Prebuilt net compositions (python/paddle/v2/fluid/nets.py analog:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu-style gates)."""

from __future__ import annotations

from typing import Optional, Sequence, Union

from . import layers
from .framework import Variable


def simple_img_conv_pool(input: Variable, num_filters: int, filter_size: int,
                         pool_size: int, pool_stride: int,
                         act: Optional[str] = None,
                         pool_type: str = "max") -> Variable:
    conv = layers.conv2d(input, num_filters=num_filters,
                         filter_size=filter_size, act=act)
    return layers.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def img_conv_group(input: Variable, conv_num_filter: Sequence[int],
                   pool_size: int, conv_padding: Union[int, Sequence[int]] = 1,
                   conv_filter_size: Union[int, Sequence[int]] = 3,
                   conv_act: Optional[str] = None,
                   conv_with_batchnorm: Union[bool, Sequence[bool]] = False,
                   pool_stride: int = 1,
                   pool_type: str = "max") -> Variable:
    """VGG-style conv stack + one pool (nets.py img_conv_group)."""
    def extend(v):
        return list(v) if hasattr(v, "__len__") else [v] * len(conv_num_filter)

    paddings = extend(conv_padding)
    sizes = extend(conv_filter_size)
    with_bn = extend(conv_with_batchnorm)
    tmp = input
    for nf, pad, fs, bn in zip(conv_num_filter, paddings, sizes, with_bn):
        tmp = layers.conv2d(tmp, num_filters=nf, filter_size=fs, padding=pad,
                            act=None if bn else conv_act)
        if bn:
            tmp = layers.batch_norm(tmp, act=conv_act)
    return layers.pool2d(tmp, pool_size=pool_size, pool_stride=pool_stride,
                         pool_type=pool_type)


def sequence_conv_pool(input: Variable, lengths: Variable, num_filters: int,
                       filter_size: int, act: str = "tanh",
                       pool_type: str = "max") -> Variable:
    conv = layers.sequence_conv(input, lengths, num_filters=num_filters,
                                filter_size=filter_size, act=act)
    return layers.sequence_pool(conv, lengths, pool_type=pool_type)
