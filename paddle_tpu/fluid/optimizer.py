"""Program-level optimizers: minimize() appends backward + update ops.

Reference: fluid/optimizer.py — SGD/Momentum/Adam emit optimizer OpDescs plus
learning-rate and accumulator variables into the program
(operators/{sgd,momentum,adam}_op.cc compute the updates). Same structure here;
the update ops run inside the executor's single compiled computation, so the
whole train step (fwd+bwd+update) is one XLA program — the fusion the reference
could not get from per-op dispatch.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..nn import initializer as I
from .backward import append_backward
from .framework import (Program, Variable, default_main_program,
                        default_startup_program)


class Optimizer:
    def __init__(self, learning_rate: float = 0.01):
        self.learning_rate = learning_rate
        self._lr_var: Optional[Variable] = None

    # -- helpers -----------------------------------------------------------
    def _ensure_lr(self, program: Program) -> Variable:
        if self._lr_var is not None:
            return self._lr_var
        b = program.global_block()
        name = program.unique_name("learning_rate")
        v = b.create_var(name=name, shape=(), dtype="float32",
                         persistable=True, trainable=False)
        sb = default_startup_program().global_block()
        sb.create_var(name=name, shape=(), dtype="float32", persistable=True)
        sb.append_op("fill_init", {}, {"Out": [name]},
                     {"shape": (), "dtype": "float32",
                      "init": I.constant(self.learning_rate), "seed": 0})
        self._lr_var = v
        return v

    def _accumulator(self, program: Program, param: Variable, suffix: str,
                     shape=None, value: float = 0.0) -> Variable:
        b = program.global_block()
        name = f"{param.name}@{suffix}"
        shape = tuple(param.shape if shape is None else shape)
        v = b.create_var(name=name, shape=shape, dtype=param.dtype,
                         persistable=True, trainable=False)
        sb = default_startup_program().global_block()
        sb.create_var(name=name, shape=shape, dtype=param.dtype,
                      persistable=True)
        sb.append_op("fill_init", {}, {"Out": [name]},
                     {"shape": shape, "dtype": param.dtype,
                      "init": I.constant(value), "seed": 0})
        return v

    def _append_update(self, program, param, grad, lr):
        raise NotImplementedError

    # -- public ------------------------------------------------------------
    def minimize(self, loss: Variable, program: Optional[Program] = None,
                 regularization=None) -> List[Tuple]:
        program = program or default_main_program()
        pg = append_backward(loss, program=program)
        if regularization is not None:
            from .regularizer import append_regularization_ops
            # a per-param l2_rate (ParamAttr) REPLACES the global default
            # for that parameter (ParameterAttribute semantics), so exclude
            # those pairs from the global pass
            rest = [(p, g) for p, g in pg
                    if getattr(p, "l2_rate", None) is None]
            decayed = dict(
                (p.name, (p, g))
                for p, g in append_regularization_ops(rest, regularization,
                                                      program))
            pg = [(p, g) if getattr(p, "l2_rate", None) is not None
                  else decayed[p.name] for p, g in pg]
        lr = self._ensure_lr(program)
        blk = program.global_block()
        for param, grad in pg:
            # per-parameter ParamAttr settings (ParameterAttribute
            # l2_rate/learning_rate, parameter/ParameterOptimizer semantics):
            # decay folds into the grad; lr scaling produces a scaled lr
            # variable so the rule is exact for adaptive optimizers too
            l2 = getattr(param, "l2_rate", None)
            if l2:
                decay = blk.create_var(shape=param.shape, dtype=param.dtype)
                blk.append_op("scale", {"X": [param.name]},
                              {"Out": [decay.name]}, {"scale": l2})
                g2 = blk.create_var(shape=grad.shape, dtype=grad.dtype)
                blk.append_op("elementwise_add",
                              {"X": [grad.name], "Y": [decay.name]},
                              {"Out": [g2.name]})
                grad = g2
            scale = getattr(param, "lr_scale", None)
            lr_eff = lr
            if scale is not None and scale != 1.0:
                lr_eff = blk.create_var(shape=lr.shape, dtype=lr.dtype)
                blk.append_op("scale", {"X": [lr.name]},
                              {"Out": [lr_eff.name]}, {"scale": scale})
            self._append_update(program, param, grad, lr_eff)
        return pg


class SGDOptimizer(Optimizer):
    def _append_update(self, program, param, grad, lr):
        program.global_block().append_op(
            "sgd",
            {"Param": [param.name], "Grad": [grad.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, momentum: float = 0.9,
                 use_nesterov: bool = False):
        super().__init__(learning_rate)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _append_update(self, program, param, grad, lr):
        vel = self._accumulator(program, param, "velocity")
        program.global_block().append_op(
            "momentum",
            {"Param": [param.name], "Grad": [grad.name],
             "Velocity": [vel.name], "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "VelocityOut": [vel.name]},
            {"mu": self.momentum, "use_nesterov": self.use_nesterov})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, program, param, grad, lr):
        m1 = self._accumulator(program, param, "moment1")
        m2 = self._accumulator(program, param, "moment2")
        b1p = self._accumulator(program, param, "beta1_pow", shape=(),
                                value=self.beta1)
        b2p = self._accumulator(program, param, "beta2_pow", shape=(),
                                value=self.beta2)
        program.global_block().append_op(
            "adam",
            {"Param": [param.name], "Grad": [grad.name],
             "Moment1": [m1.name], "Moment2": [m2.name],
             "Beta1Pow": [b1p.name], "Beta2Pow": [b2p.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "Moment1Out": [m1.name],
             "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
             "Beta2PowOut": [b2p.name]},
            {"beta1": self.beta1, "beta2": self.beta2,
             "epsilon": self.epsilon})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, epsilon: float = 1e-6):
        super().__init__(learning_rate)
        self.epsilon = epsilon

    def _append_update(self, program, param, grad, lr):
        m = self._accumulator(program, param, "moment")
        program.global_block().append_op(
            "adagrad",
            {"Param": [param.name], "Grad": [grad.name], "Moment": [m.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "MomentOut": [m.name]},
            {"epsilon": self.epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate=1.0, rho: float = 0.95,
                 epsilon: float = 1e-6):
        super().__init__(learning_rate)
        self.rho, self.epsilon = rho, epsilon

    def _append_update(self, program, param, grad, lr):
        ag = self._accumulator(program, param, "avg_squared_grad")
        au = self._accumulator(program, param, "avg_squared_update")
        program.global_block().append_op(
            "adadelta",
            {"Param": [param.name], "Grad": [grad.name],
             "AvgSquaredGrad": [ag.name], "AvgSquaredUpdate": [au.name]},
            {"ParamOut": [param.name], "AvgSquaredGradOut": [ag.name],
             "AvgSquaredUpdateOut": [au.name]},
            {"rho": self.rho, "epsilon": self.epsilon})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, decay: float = 0.9,
                 momentum: float = 0.0, epsilon: float = 1e-6):
        super().__init__(learning_rate)
        self.decay, self.momentum, self.epsilon = decay, momentum, epsilon

    def _append_update(self, program, param, grad, lr):
        ms = self._accumulator(program, param, "mean_square")
        mom = self._accumulator(program, param, "rms_moment")
        program.global_block().append_op(
            "rmsprop",
            {"Param": [param.name], "Grad": [grad.name],
             "MeanSquare": [ms.name], "Moment": [mom.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "MeanSquareOut": [ms.name],
             "MomentOut": [mom.name]},
            {"decay": self.decay, "momentum": self.momentum,
             "epsilon": self.epsilon})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, program, param, grad, lr):
        m = self._accumulator(program, param, "adamax_moment")
        u = self._accumulator(program, param, "inf_norm")
        b1p = self._accumulator(program, param, "beta1_pow_ax", shape=(),
                                value=self.beta1)
        program.global_block().append_op(
            "adamax",
            {"Param": [param.name], "Grad": [grad.name], "Moment": [m.name],
             "InfNorm": [u.name], "Beta1Pow": [b1p.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "MomentOut": [m.name],
             "InfNormOut": [u.name], "Beta1PowOut": [b1p.name]},
            {"beta1": self.beta1, "beta2": self.beta2,
             "epsilon": self.epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, decay: float = 0.95,
                 epsilon: float = 1e-6):
        super().__init__(learning_rate)
        self.decay, self.epsilon = decay, epsilon

    def _append_update(self, program, param, grad, lr):
        m = self._accumulator(program, param, "decayed_moment")
        program.global_block().append_op(
            "decayed_adagrad",
            {"Param": [param.name], "Grad": [grad.name], "Moment": [m.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "MomentOut": [m.name]},
            {"decay": self.decay, "epsilon": self.epsilon})


class FtrlOptimizer(Optimizer):
    """FTRL-proximal (ref: operators/ftrl_op.cc) — the CTR-model staple."""

    def __init__(self, learning_rate=0.01, l1: float = 0.0, l2: float = 0.0):
        super().__init__(learning_rate)
        self.l1, self.l2 = l1, l2

    def _append_update(self, program, param, grad, lr):
        sq = self._accumulator(program, param, "squared_accum")
        lin = self._accumulator(program, param, "linear_accum")
        program.global_block().append_op(
            "ftrl",
            {"Param": [param.name], "Grad": [grad.name],
             "SquaredAccumulator": [sq.name], "LinearAccumulator": [lin.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [param.name], "SquaredAccumOut": [sq.name],
             "LinearAccumOut": [lin.name]},
            {"l1": self.l1, "l2": self.l2})
