"""Operator registry: op type -> jax-traceable compute function.

The analog of OpRegistry + OperatorWithKernel (framework/op_registry.h:129-233,
operator.h:375): each op is a pure function from input arrays + attrs to output
arrays. There is no per-op CPU/GPU kernel pair and no hand-written grad op —
XLA lowers one compute to every backend, and JAX autodiff differentiates
through the whole traced block (replacing the grad-op registry +
backward.cc:343 MakeOpGrad machinery).

Compute signature::

    def compute(inputs: Dict[str, List[Array]], attrs: Dict) -> Dict[str, List[Array]]
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp


class OpRegistry:
    _ops: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_type: str):
        def deco(fn):
            cls._ops[op_type] = fn
            return fn
        return deco

    @classmethod
    def has(cls, op_type: str) -> bool:
        return op_type in cls._ops

    @classmethod
    def get(cls, op_type: str) -> Callable:
        return cls._ops[op_type]

    @classmethod
    def registered(cls) -> List[str]:
        return sorted(cls._ops)


def _x(ins, key="X"):
    return ins[key][0]


# ---------------------------------------------------------------- basic math --

@OpRegistry.register("elementwise_add")
def _add(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    return {"Out": [x + y]}


@OpRegistry.register("elementwise_sub")
def _sub(ins, attrs):
    return {"Out": [_x(ins) - _x(ins, "Y")]}


@OpRegistry.register("elementwise_mul")
def _emul(ins, attrs):
    return {"Out": [_x(ins) * _x(ins, "Y")]}


@OpRegistry.register("elementwise_div")
def _ediv(ins, attrs):
    return {"Out": [_x(ins) / _x(ins, "Y")]}


@OpRegistry.register("mul")
def _mul(ins, attrs):
    """X [b.., M] x Y [M, N] with num_col_dims flattening (operators/mul_op.cc)."""
    from ..ops.math import mul as mul_op
    return {"Out": [mul_op(_x(ins), _x(ins, "Y"),
                           x_num_col_dims=attrs.get("x_num_col_dims", 1),
                           y_num_col_dims=attrs.get("y_num_col_dims", 1))]}


@OpRegistry.register("matmul")
def _matmul(ins, attrs):
    from ..ops.math import matmul
    return {"Out": [matmul(_x(ins), _x(ins, "Y"),
                           transpose_x=attrs.get("transpose_X", False),
                           transpose_y=attrs.get("transpose_Y", False))]}


@OpRegistry.register("scale")
def _scale(ins, attrs):
    return {"Out": [_x(ins) * attrs.get("scale", 1.0) + attrs.get("bias", 0.0)]}


@OpRegistry.register("mean")
def _mean(ins, attrs):
    return {"Out": [jnp.mean(_x(ins))]}


@OpRegistry.register("sum")
def _sum(ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@OpRegistry.register("reduce_sum")
def _rsum(ins, attrs):
    return {"Out": [jnp.sum(_x(ins), axis=attrs.get("dim"),
                            keepdims=attrs.get("keep_dim", False))]}


@OpRegistry.register("reshape")
def _reshape(ins, attrs):
    return {"Out": [jnp.reshape(_x(ins), attrs["shape"])]}


@OpRegistry.register("transpose")
def _transpose(ins, attrs):
    return {"Out": [jnp.transpose(_x(ins), attrs.get("axis"))]}


@OpRegistry.register("concat")
def _concat(ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@OpRegistry.register("split")
def _split(ins, attrs):
    from ..ops.math import split as split_op
    outs = split_op(_x(ins), attrs["num_or_sections"], attrs.get("axis", 0))
    return {"Out": list(outs)}


@OpRegistry.register("cast")
def _cast(ins, attrs):
    return {"Out": [_x(ins).astype(attrs["dtype"])]}


@OpRegistry.register("clip")
def _clip(ins, attrs):
    return {"Out": [jnp.clip(_x(ins), attrs["min"], attrs["max"])]}


# -------------------------------------------------------------- activations ---

for _name in ("sigmoid", "tanh", "relu", "softmax", "log_softmax", "gelu",
              "leaky_relu", "elu", "softsign", "square", "sqrt", "abs_act",
              "exponential", "brelu", "soft_shrink", "hard_shrink",
              "thresholded_relu", "stanh", "softrelu", "hard_sigmoid",
              "swish", "reciprocal", "log"):
    def _make(name=_name):
        from ..ops import activations as A
        fn = getattr(A, name)

        def compute(ins, attrs, _fn=fn):
            return {"Out": [_fn(_x(ins), **attrs)]}
        return compute
    OpRegistry._ops[_name] = _make()
OpRegistry._ops["abs"] = OpRegistry._ops["abs_act"]


# -------------------------------------------------------------------- fills ---

@OpRegistry.register("fill_constant")
def _fill(ins, attrs):
    return {"Out": [jnp.full(attrs["shape"], attrs["value"],
                             dtype=attrs.get("dtype", "float32"))]}


@OpRegistry.register("fill_init")
def _fill_init(ins, attrs):
    """Startup-program parameter init: attr 'init' is a host callable
    (initializer), attr 'seed' the fold-in key — runs host-side once."""
    init = attrs["init"]
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    return {"Out": [init(key, attrs["shape"],
                         jnp.dtype(attrs.get("dtype", "float32")))]}


@OpRegistry.register("gaussian_random")
def _gauss(ins, attrs):
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    return {"Out": [attrs.get("mean", 0.0) + attrs.get("std", 1.0)
                    * jax.random.normal(key, attrs["shape"])]}


@OpRegistry.register("uniform_random")
def _unif(ins, attrs):
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    return {"Out": [jax.random.uniform(key, attrs["shape"],
                                       minval=attrs.get("min", -1.0),
                                       maxval=attrs.get("max", 1.0))]}


@OpRegistry.register("dropout")
def _dropout(ins, attrs):
    from ..ops.random import dropout as drop
    rate = attrs.get("dropout_prob", 0.5)
    if not attrs.get("is_test", True):
        key = jax.random.PRNGKey(attrs.get("seed", 0))
        if "Step" in ins:  # fresh mask per executor run
            key = jax.random.fold_in(key, ins["Step"][0])
        out = drop(_x(ins), rate, key, train=True)
    else:
        out = _x(ins)
    return {"Out": [out]}


# ------------------------------------------------------------------- layers ---

@OpRegistry.register("lookup_table")
def _lookup(ins, attrs):
    w = ins["W"][0]
    ids = ins["Ids"][0]
    return {"Out": [jnp.take(w, ids, axis=0)]}


@OpRegistry.register("conv2d")
def _conv2d(ins, attrs):
    from ..ops.conv import conv2d
    return {"Out": [conv2d(ins["Input"][0], ins["Filter"][0],
                           stride=attrs.get("strides", 1),
                           padding=attrs.get("paddings", 0),
                           dilation=attrs.get("dilations", 1),
                           groups=attrs.get("groups", 1))]}


@OpRegistry.register("pool2d")
def _pool2d(ins, attrs):
    from ..ops import pool as P
    fn = P.max_pool2d if attrs.get("pooling_type", "max") == "max" else P.avg_pool2d
    if attrs.get("global_pooling", False):
        g = (P.global_max_pool2d if attrs.get("pooling_type", "max") == "max"
             else P.global_avg_pool2d)
        return {"Out": [g(_x(ins))]}
    return {"Out": [fn(_x(ins), attrs.get("ksize", 2),
                       attrs.get("strides"), attrs.get("paddings", 0))]}


@OpRegistry.register("batch_norm_infer")
def _bn_infer(ins, attrs):
    from ..ops.norm import batch_norm
    out = batch_norm(_x(ins), ins["Scale"][0], ins["Bias"][0],
                     mean=ins["Mean"][0], var=ins["Variance"][0],
                     eps=attrs.get("epsilon", 1e-5))
    return {"Out": [out if not isinstance(out, tuple) else out[0]]}


@OpRegistry.register("layer_norm")
def _ln(ins, attrs):
    from ..ops.norm import layer_norm
    return {"Out": [layer_norm(_x(ins), ins["Scale"][0], ins["Bias"][0],
                               eps=attrs.get("epsilon", 1e-5))]}


# ------------------------------------------------------------------- losses ---

@OpRegistry.register("cross_entropy")
def _ce(ins, attrs):
    from ..ops.loss import cross_entropy
    return {"Y": [cross_entropy(_x(ins), ins["Label"][0],
                                soft_label=attrs.get("soft_label", False))]}


@OpRegistry.register("softmax_with_cross_entropy")
def _sce(ins, attrs):
    from ..ops.loss import softmax_with_cross_entropy
    logits = ins["Logits"][0]
    return {"Loss": [softmax_with_cross_entropy(logits, ins["Label"][0])],
            "Softmax": [jax.nn.softmax(logits, -1)]}


@OpRegistry.register("sigmoid_cross_entropy_with_logits")
def _sigce(ins, attrs):
    from ..ops.loss import sigmoid_cross_entropy_with_logits
    return {"Out": [sigmoid_cross_entropy_with_logits(_x(ins), ins["Label"][0])]}


@OpRegistry.register("square_error")
def _sqerr(ins, attrs):
    from ..ops.loss import square_error
    return {"Out": [square_error(_x(ins), ins["Label"][0])]}


# ------------------------------------------------------------------ metrics ---

@OpRegistry.register("accuracy")
def _acc(ins, attrs):
    from ..ops.metrics import accuracy
    correct, total = accuracy(_x(ins, "Out"), ins["Label"][0])
    return {"Accuracy": [correct / total], "Correct": [correct],
            "Total": [total]}


@OpRegistry.register("top_k")
def _topk(ins, attrs):
    vals, idx = jax.lax.top_k(_x(ins), attrs["k"])
    return {"Out": [vals], "Indices": [idx]}


# ---------------------------------------------------------------- optimizer ---

@OpRegistry.register("sgd")
def _sgd(ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    return {"ParamOut": [p - lr * g]}


@OpRegistry.register("momentum")
def _momentum(ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = ins["LearningRate"][0]
    mu = attrs.get("mu", 0.9)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@OpRegistry.register("adam")
def _adam(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0]
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    mhat = m_new / (1 - b1p)
    vhat = v_new / (1 - b2p)
    return {"ParamOut": [p - lr * mhat / (jnp.sqrt(vhat) + eps)],
            "Moment1Out": [m_new], "Moment2Out": [v_new],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}


@OpRegistry.register("autodiff_grad")
def _autodiff_stub(ins, attrs):
    raise RuntimeError("autodiff_grad is lowered by the executor, not run directly")


# ------------------------------------------------------ sequence / recurrent --
# TPU-idiomatic coarse ops: a whole masked LSTM/GRU pass is ONE op (the
# lax.scan lives inside), replacing the reference's per-step RecurrentOp
# machinery (operators/recurrent_op.cc) for the common fixed-topology case.

@OpRegistry.register("lstm")
def _lstm(ins, attrs):
    from ..ops.rnn import lstm
    out, state = lstm(ins["X"][0], ins["Lengths"][0] if "Lengths" in ins else None,
                      ins["W"][0], ins["U"][0],
                      ins["B"][0] if "B" in ins else None,
                      reverse=attrs.get("reverse", False),
                      forget_bias=attrs.get("forget_bias", 1.0))
    return {"Out": [out], "LastH": [state.h], "LastC": [state.c]}


@OpRegistry.register("gru")
def _gru(ins, attrs):
    from ..ops.rnn import gru
    out, last = gru(ins["X"][0], ins["Lengths"][0] if "Lengths" in ins else None,
                    ins["W"][0], ins["U"][0],
                    ins["B"][0] if "B" in ins else None,
                    reverse=attrs.get("reverse", False))
    return {"Out": [out], "LastH": [last]}


@OpRegistry.register("sequence_pool")
def _seq_pool(ins, attrs):
    from ..ops.sequence import sequence_pool
    return {"Out": [sequence_pool(ins["X"][0], ins["Lengths"][0],
                                  attrs.get("pool_type", "average"))]}


@OpRegistry.register("sequence_conv")
def _seq_conv(ins, attrs):
    from ..ops.sequence import sequence_conv
    return {"Out": [sequence_conv(ins["X"][0], ins["Lengths"][0],
                                  ins["Filter"][0],
                                  context_start=attrs.get("context_start", -1),
                                  context_length=attrs.get("context_length", 3))]}


@OpRegistry.register("sequence_last_step")
def _seq_last(ins, attrs):
    from ..ops.sequence import sequence_last_step
    return {"Out": [sequence_last_step(ins["X"][0], ins["Lengths"][0])]}


@OpRegistry.register("sequence_first_step")
def _seq_first(ins, attrs):
    from ..ops.sequence import sequence_first_step
    return {"Out": [sequence_first_step(ins["X"][0], ins["Lengths"][0])]}
