"""Operator registry: op type -> jax-traceable compute function.

The analog of OpRegistry + OperatorWithKernel (framework/op_registry.h:129-233,
operator.h:375): each op is a pure function from input arrays + attrs to output
arrays. There is no per-op CPU/GPU kernel pair and no hand-written grad op —
XLA lowers one compute to every backend, and JAX autodiff differentiates
through the whole traced block (replacing the grad-op registry +
backward.cc:343 MakeOpGrad machinery).

Compute signature::

    def compute(inputs: Dict[str, List[Array]], attrs: Dict) -> Dict[str, List[Array]]
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp


class OpRegistry:
    _ops: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_type: str):
        def deco(fn):
            cls._ops[op_type] = fn
            return fn
        return deco

    @classmethod
    def has(cls, op_type: str) -> bool:
        return op_type in cls._ops

    @classmethod
    def get(cls, op_type: str) -> Callable:
        return cls._ops[op_type]

    @classmethod
    def registered(cls) -> List[str]:
        return sorted(cls._ops)


def _x(ins, key="X"):
    return ins[key][0]


# ---------------------------------------------------------------- basic math --

@OpRegistry.register("elementwise_add")
def _add(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    return {"Out": [x + y]}


@OpRegistry.register("elementwise_sub")
def _sub(ins, attrs):
    return {"Out": [_x(ins) - _x(ins, "Y")]}


@OpRegistry.register("elementwise_mul")
def _emul(ins, attrs):
    return {"Out": [_x(ins) * _x(ins, "Y")]}


@OpRegistry.register("elementwise_div")
def _ediv(ins, attrs):
    return {"Out": [_x(ins) / _x(ins, "Y")]}


@OpRegistry.register("mul")
def _mul(ins, attrs):
    """X [b.., M] x Y [M, N] with num_col_dims flattening (operators/mul_op.cc)."""
    from ..ops.math import mul as mul_op
    return {"Out": [mul_op(_x(ins), _x(ins, "Y"),
                           x_num_col_dims=attrs.get("x_num_col_dims", 1),
                           y_num_col_dims=attrs.get("y_num_col_dims", 1))]}


@OpRegistry.register("matmul")
def _matmul(ins, attrs):
    from ..ops.math import matmul
    return {"Out": [matmul(_x(ins), _x(ins, "Y"),
                           transpose_x=attrs.get("transpose_X", False),
                           transpose_y=attrs.get("transpose_Y", False))]}


@OpRegistry.register("scale")
def _scale(ins, attrs):
    return {"Out": [_x(ins) * attrs.get("scale", 1.0) + attrs.get("bias", 0.0)]}


@OpRegistry.register("mean")
def _mean(ins, attrs):
    return {"Out": [jnp.mean(_x(ins))]}


@OpRegistry.register("sum")
def _sum(ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@OpRegistry.register("reduce_sum")
def _rsum(ins, attrs):
    return {"Out": [jnp.sum(_x(ins), axis=attrs.get("dim"),
                            keepdims=attrs.get("keep_dim", False))]}


@OpRegistry.register("reshape")
def _reshape(ins, attrs):
    return {"Out": [jnp.reshape(_x(ins), attrs["shape"])]}


@OpRegistry.register("transpose")
def _transpose(ins, attrs):
    return {"Out": [jnp.transpose(_x(ins), attrs.get("axis"))]}


@OpRegistry.register("concat")
def _concat(ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@OpRegistry.register("split")
def _split(ins, attrs):
    from ..ops.math import split as split_op
    outs = split_op(_x(ins), attrs["num_or_sections"], attrs.get("axis", 0))
    return {"Out": list(outs)}


@OpRegistry.register("cast")
def _cast(ins, attrs):
    return {"Out": [_x(ins).astype(attrs["dtype"])]}


@OpRegistry.register("clip")
def _clip(ins, attrs):
    return {"Out": [jnp.clip(_x(ins), attrs["min"], attrs["max"])]}


# -------------------------------------------------------------- activations ---

for _name in ("sigmoid", "tanh", "relu", "softmax", "log_softmax", "gelu",
              "leaky_relu", "elu", "softsign", "square", "sqrt", "abs_act",
              "exponential", "brelu", "soft_shrink", "hard_shrink",
              "thresholded_relu", "stanh", "softrelu", "hard_sigmoid",
              "swish", "reciprocal", "log"):
    def _make(name=_name):
        from ..ops import activations as A
        fn = getattr(A, name)

        def compute(ins, attrs, _fn=fn):
            return {"Out": [_fn(_x(ins), **attrs)]}
        return compute
    OpRegistry._ops[_name] = _make()
OpRegistry._ops["abs"] = OpRegistry._ops["abs_act"]


# -------------------------------------------------------------------- fills ---

@OpRegistry.register("fill_constant")
def _fill(ins, attrs):
    return {"Out": [jnp.full(attrs["shape"], attrs["value"],
                             dtype=attrs.get("dtype", "float32"))]}


@OpRegistry.register("fill_init")
def _fill_init(ins, attrs):
    """Startup-program parameter init: attr 'init' is a host callable
    (initializer), attr 'seed' the fold-in key — runs host-side once."""
    init = attrs["init"]
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    return {"Out": [init(key, attrs["shape"],
                         jnp.dtype(attrs.get("dtype", "float32")))]}


@OpRegistry.register("gaussian_random")
def _gauss(ins, attrs):
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    return {"Out": [attrs.get("mean", 0.0) + attrs.get("std", 1.0)
                    * jax.random.normal(key, attrs["shape"])]}


@OpRegistry.register("uniform_random")
def _unif(ins, attrs):
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    return {"Out": [jax.random.uniform(key, attrs["shape"],
                                       minval=attrs.get("min", -1.0),
                                       maxval=attrs.get("max", 1.0))]}


@OpRegistry.register("dropout")
def _dropout(ins, attrs):
    from ..ops.random import dropout as drop
    rate = attrs.get("dropout_prob", 0.5)
    if not attrs.get("is_test", True):
        key = jax.random.PRNGKey(attrs.get("seed", 0))
        if "Step" in ins:  # fresh mask per executor run
            key = jax.random.fold_in(key, ins["Step"][0])
        out = drop(_x(ins), rate, key, train=True)
    else:
        out = _x(ins)
    return {"Out": [out]}


# ------------------------------------------------------------------- layers ---

@OpRegistry.register("lookup_table")
def _lookup(ins, attrs):
    w = ins["W"][0]
    ids = ins["Ids"][0]
    return {"Out": [jnp.take(w, ids, axis=0)]}


@OpRegistry.register("conv2d")
def _conv2d(ins, attrs):
    from ..ops.conv import conv2d
    return {"Out": [conv2d(ins["Input"][0], ins["Filter"][0],
                           stride=attrs.get("strides", 1),
                           padding=attrs.get("paddings", 0),
                           dilation=attrs.get("dilations", 1),
                           groups=attrs.get("groups", 1))]}


@OpRegistry.register("pool2d")
def _pool2d(ins, attrs):
    from ..ops import pool as P
    fn = P.max_pool2d if attrs.get("pooling_type", "max") == "max" else P.avg_pool2d
    if attrs.get("global_pooling", False):
        g = (P.global_max_pool2d if attrs.get("pooling_type", "max") == "max"
             else P.global_avg_pool2d)
        return {"Out": [g(_x(ins))]}
    return {"Out": [fn(_x(ins), attrs.get("ksize", 2),
                       attrs.get("strides"), attrs.get("paddings", 0))]}


@OpRegistry.register("batch_norm_infer")
def _bn_infer(ins, attrs):
    from ..ops.norm import batch_norm
    y, _, _ = batch_norm(_x(ins), ins["Scale"][0], ins["Bias"][0],
                         ins["Mean"][0], ins["Variance"][0],
                         train=False, eps=attrs.get("epsilon", 1e-5))
    return {"Out": [y]}


@OpRegistry.register("layer_norm")
def _ln(ins, attrs):
    from ..ops.norm import layer_norm
    return {"Out": [layer_norm(_x(ins), ins["Scale"][0], ins["Bias"][0],
                               eps=attrs.get("epsilon", 1e-5))]}


# ------------------------------------------------------------------- losses ---

@OpRegistry.register("cross_entropy")
def _ce(ins, attrs):
    from ..ops.loss import cross_entropy
    return {"Y": [cross_entropy(_x(ins), ins["Label"][0],
                                soft_label=attrs.get("soft_label", False))]}


@OpRegistry.register("softmax_with_cross_entropy")
def _sce(ins, attrs):
    from ..ops.loss import softmax_with_cross_entropy
    logits = ins["Logits"][0]
    return {"Loss": [softmax_with_cross_entropy(logits, ins["Label"][0])],
            "Softmax": [jax.nn.softmax(logits, -1)]}


@OpRegistry.register("sigmoid_cross_entropy_with_logits")
def _sigce(ins, attrs):
    from ..ops.loss import sigmoid_cross_entropy_with_logits
    return {"Out": [sigmoid_cross_entropy_with_logits(_x(ins), ins["Label"][0])]}


@OpRegistry.register("square_error")
def _sqerr(ins, attrs):
    from ..ops.loss import square_error
    return {"Out": [square_error(_x(ins), ins["Label"][0])]}


# ------------------------------------------------------------------ metrics ---

@OpRegistry.register("accuracy")
def _acc(ins, attrs):
    from ..ops.metrics import accuracy
    correct, total = accuracy(_x(ins, "Out"), ins["Label"][0])
    return {"Accuracy": [correct / total], "Correct": [correct],
            "Total": [total]}


@OpRegistry.register("top_k")
def _topk(ins, attrs):
    vals, idx = jax.lax.top_k(_x(ins), attrs["k"])
    return {"Out": [vals], "Indices": [idx]}


# ---------------------------------------------------------------- optimizer ---

@OpRegistry.register("sgd")
def _sgd(ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    return {"ParamOut": [p - lr * g]}


@OpRegistry.register("momentum")
def _momentum(ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = ins["LearningRate"][0]
    mu = attrs.get("mu", 0.9)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@OpRegistry.register("adam")
def _adam(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0]
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    mhat = m_new / (1 - b1p)
    vhat = v_new / (1 - b2p)
    return {"ParamOut": [p - lr * mhat / (jnp.sqrt(vhat) + eps)],
            "Moment1Out": [m_new], "Moment2Out": [v_new],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}


@OpRegistry.register("autodiff_grad")
def _autodiff_stub(ins, attrs):
    raise RuntimeError("autodiff_grad is lowered by the executor, not run directly")


# ------------------------------------------------------ sequence / recurrent --
# TPU-idiomatic coarse ops: a whole masked LSTM/GRU pass is ONE op (the
# lax.scan lives inside), replacing the reference's per-step RecurrentOp
# machinery (operators/recurrent_op.cc) for the common fixed-topology case.

@OpRegistry.register("lstm")
def _lstm(ins, attrs):
    from ..ops.rnn import lstm
    out, state = lstm(ins["X"][0], ins["Lengths"][0] if "Lengths" in ins else None,
                      ins["W"][0], ins["U"][0],
                      ins["B"][0] if "B" in ins else None,
                      reverse=attrs.get("reverse", False),
                      forget_bias=attrs.get("forget_bias", 1.0),
                      # inference bundles set this at export: forward-only
                      # programs run the fused Pallas sequence kernel
                      fused=attrs.get("fused", None))
    return {"Out": [out], "LastH": [state.h], "LastC": [state.c]}


@OpRegistry.register("gru")
def _gru(ins, attrs):
    from ..ops.rnn import gru
    out, last = gru(ins["X"][0], ins["Lengths"][0] if "Lengths" in ins else None,
                    ins["W"][0], ins["U"][0],
                    ins["B"][0] if "B" in ins else None,
                    reverse=attrs.get("reverse", False),
                    fused=attrs.get("fused", None))
    return {"Out": [out], "LastH": [last]}


@OpRegistry.register("simple_rnn")
def _simple_rnn(ins, attrs):
    """Vanilla (Elman) recurrence — the reference's RecurrentLayer.cpp /
    recurrent_layer: h_t = act(x_t [@W] + h_{t-1}@U + b). W optional: the
    v2 recurrent_layer pre-projects outside, per the reference contract."""
    from ..ops.rnn import simple_rnn
    from ..ops import activations as _acts
    act = _acts.get(attrs.get("act", "tanh"))
    out, last = simple_rnn(
        ins["X"][0], ins["Lengths"][0] if "Lengths" in ins else None,
        ins["W"][0] if "W" in ins else None, ins["U"][0],
        ins["B"][0] if "B" in ins else None,
        act=act, reverse=attrs.get("reverse", False))
    return {"Out": [out], "LastH": [last]}


@OpRegistry.register("sequence_pool")
def _seq_pool(ins, attrs):
    from ..ops.sequence import sequence_pool
    return {"Out": [sequence_pool(ins["X"][0], ins["Lengths"][0],
                                  attrs.get("pool_type", "average"))]}


@OpRegistry.register("sequence_conv")
def _seq_conv(ins, attrs):
    from ..ops.sequence import sequence_conv
    return {"Out": [sequence_conv(ins["X"][0], ins["Lengths"][0],
                                  ins["Filter"][0],
                                  context_start=attrs.get("context_start", -1),
                                  context_length=attrs.get("context_length", 3))]}


@OpRegistry.register("sequence_last_step")
def _seq_last(ins, attrs):
    from ..ops.sequence import sequence_last_step
    return {"Out": [sequence_last_step(ins["X"][0], ins["Lengths"][0])]}


@OpRegistry.register("sequence_first_step")
def _seq_first(ins, attrs):
    from ..ops.sequence import sequence_first_step
    return {"Out": [sequence_first_step(ins["X"][0], ins["Lengths"][0])]}


# =============================================================================
# Registry completion toward the reference's 110 op families
# (paddle/operators/*.cc REGISTER_OP list). Compute bodies live in
# paddle_tpu/ops/*; entries here adapt the named-slot convention.
# =============================================================================

# ------------------------------------------------------- control flow stubs --
# Lowered structurally by the executor (_trace_while/_trace_cond/
# _trace_static_rnn) — ref: while_op.cc, conditional_block_op.cc,
# recurrent_op.cc. Registered so Operator construction validates.

for _cf in ("while", "conditional_block", "static_rnn", "beam_search_gen"):
    def _cf_stub(ins, attrs, _n=_cf):
        raise RuntimeError(f"'{_n}' is lowered by the executor, not run directly")
    OpRegistry._ops[_cf] = _cf_stub


# --------------------------------------------------- tensor arrays & compare --
# TensorArray under XLA: a fixed-capacity [T, ...] buffer; write = dynamic
# update at index, read = dynamic index (tensor_array_read_write_op.cc,
# lod_tensor_to_array_op.cc — per-step dynamic arrays become static buffers).

@OpRegistry.register("array_write")
def _array_write(ins, attrs):
    x, i = _x(ins), ins["I"][0]
    if "Array" in ins:
        arr = ins["Array"][0]
    else:
        arr = jnp.zeros((attrs["capacity"],) + x.shape, x.dtype)
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [jax.lax.dynamic_update_index_in_dim(arr, x, i, 0)]}


@OpRegistry.register("array_read")
def _array_read(ins, attrs):
    arr, i = _x(ins, "Array"), ins["I"][0]
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [jax.lax.dynamic_index_in_dim(arr, i, 0, keepdims=False)]}


@OpRegistry.register("array_length")
def _array_length(ins, attrs):
    return {"Out": [jnp.asarray(ins["Array"][0].shape[0], jnp.int32)]}


@OpRegistry.register("lod_tensor_to_array")
def _lod_to_array(ins, attrs):
    # [B, T, ...] -> time-major [T, B, ...] buffer for per-step array_read
    return {"Out": [jnp.moveaxis(_x(ins), 1, 0)]}


@OpRegistry.register("array_to_lod_tensor")
def _array_to_lod(ins, attrs):
    return {"Out": [jnp.moveaxis(_x(ins), 0, 1)]}


@OpRegistry.register("increment")
def _increment(ins, attrs):
    x = _x(ins)
    return {"Out": [x + jnp.asarray(attrs.get("step", 1), x.dtype)]}


def _compare(fn):
    def compute(ins, attrs):
        return {"Out": [fn(_x(ins), ins["Y"][0])]}
    return compute


OpRegistry._ops["less_than"] = _compare(lambda a, b: a < b)
OpRegistry._ops["less_equal"] = _compare(lambda a, b: a <= b)
OpRegistry._ops["greater_than"] = _compare(lambda a, b: a > b)
OpRegistry._ops["greater_equal"] = _compare(lambda a, b: a >= b)
OpRegistry._ops["equal"] = _compare(lambda a, b: a == b)
OpRegistry._ops["not_equal"] = _compare(lambda a, b: a != b)
OpRegistry._ops["logical_and"] = _compare(jnp.logical_and)
OpRegistry._ops["logical_or"] = _compare(jnp.logical_or)


@OpRegistry.register("logical_not")
def _lnot(ins, attrs):
    return {"Out": [jnp.logical_not(_x(ins))]}


@OpRegistry.register("assign")
def _assign(ins, attrs):
    return {"Out": [_x(ins)]}


@OpRegistry.register("fill_zeros_like")
def _zeros_like(ins, attrs):
    return {"Out": [jnp.zeros_like(_x(ins))]}


@OpRegistry.register("fill_constant_batch_size_like")
def _fill_bsl(ins, attrs):
    ref = _x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[attrs.get("input_dim_idx", 0)]
    return {"Out": [jnp.full(tuple(shape), attrs["value"],
                             dtype=attrs.get("dtype", "float32"))]}


@OpRegistry.register("is_empty")
def _is_empty(ins, attrs):
    return {"Out": [jnp.asarray(_x(ins).size == 0)]}


# ------------------------------------------------------------- simple math ---

@OpRegistry.register("sign")
def _sign(ins, attrs):
    return {"Out": [jnp.sign(_x(ins))]}


@OpRegistry.register("minus")
def _minus(ins, attrs):
    return {"Out": [_x(ins) - _x(ins, "Y")]}


@OpRegistry.register("pow")
def _pow(ins, attrs):
    return {"Out": [jnp.power(_x(ins), attrs.get("factor", 1.0))]}


@OpRegistry.register("reduce_mean")
def _rmean(ins, attrs):
    return {"Out": [jnp.mean(_x(ins), axis=attrs.get("dim"),
                             keepdims=attrs.get("keep_dim", False))]}


@OpRegistry.register("reduce_max")
def _rmax(ins, attrs):
    return {"Out": [jnp.max(_x(ins), axis=attrs.get("dim"),
                            keepdims=attrs.get("keep_dim", False))]}


@OpRegistry.register("reduce_min")
def _rmin(ins, attrs):
    return {"Out": [jnp.min(_x(ins), axis=attrs.get("dim"),
                            keepdims=attrs.get("keep_dim", False))]}


@OpRegistry.register("expand")
def _expand(ins, attrs):
    from ..ops.math import expand
    return {"Out": [expand(_x(ins), attrs["expand_times"])]}


@OpRegistry.register("pad")
def _pad(ins, attrs):
    from ..ops.math import pad
    return {"Out": [pad(_x(ins), attrs["paddings"],
                        attrs.get("pad_value", 0.0))]}


@OpRegistry.register("crop")
def _crop(ins, attrs):
    from ..ops.math import crop
    x = _x(ins)
    # non-positive shape entries mean "to the end" (resolved at trace time —
    # lets builders crop feature dims without knowing the batch size)
    shape = [x.shape[i] - o if s <= 0 else s
             for i, (o, s) in enumerate(zip(attrs["offsets"],
                                            attrs["shape"]))]
    return {"Out": [crop(x, attrs["offsets"], shape)]}


@OpRegistry.register("gather")
def _gather(ins, attrs):
    from ..ops.math import gather
    return {"Out": [gather(_x(ins), ins["Index"][0], attrs.get("axis", 0))]}


@OpRegistry.register("scatter")
def _scatter(ins, attrs):
    from ..ops.math import scatter
    return {"Out": [scatter(_x(ins, "Ref"), ins["Index"][0],
                            ins["Updates"][0],
                            overwrite=attrs.get("overwrite", True))]}


@OpRegistry.register("multiplex")
def _multiplex(ins, attrs):
    # out[b] = X[ids[b]][b] (multiplex_op.cc row selection)
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(ins["X"], axis=0)          # [n, B, ...]
    return {"Out": [stacked[ids, jnp.arange(ids.shape[0])]]}


@OpRegistry.register("clip_by_norm")
def _clip_norm(ins, attrs):
    from ..ops.math import clip_by_norm
    return {"Out": [clip_by_norm(_x(ins), attrs["max_norm"])]}


@OpRegistry.register("l1_norm")
def _l1norm(ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(_x(ins)))]}


@OpRegistry.register("squared_l2_norm")
def _sql2(ins, attrs):
    from ..ops.loss import squared_l2_norm
    return {"Out": [squared_l2_norm(_x(ins))]}


@OpRegistry.register("squared_l2_distance")
def _sql2d(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    d = (x - y).reshape(x.shape[0], -1)
    return {"Out": [jnp.sum(d * d, axis=1, keepdims=True)], "sub_result": [d]}


@OpRegistry.register("cos_sim")
def _cos_sim(ins, attrs):
    from ..ops.math import cos_sim
    return {"Out": [cos_sim(_x(ins), _x(ins, "Y"))]}


@OpRegistry.register("l2_normalize")
def _l2n(ins, attrs):
    from ..ops.math import l2_normalize
    return {"Out": [l2_normalize(_x(ins), attrs.get("axis", -1))]}


@OpRegistry.register("prelu")
def _prelu(ins, attrs):
    x, alpha = _x(ins), ins["Alpha"][0]
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@OpRegistry.register("conv_shift")
def _conv_shift(ins, attrs):
    # circular correlation (conv_shift_op.cc): X [B, M], Y [B, N] (N odd, small)
    x, y = _x(ins), _x(ins, "Y")
    M, N = x.shape[1], y.shape[1]
    half = N // 2
    idx = (jnp.arange(M)[:, None] + jnp.arange(-half, half + 1)[None, :]) % M
    windows = x[:, idx]                             # [B, M, N]
    return {"Out": [jnp.einsum("bmn,bn->bm", windows, y)]}


@OpRegistry.register("bilinear_tensor_product")
def _btp(ins, attrs):
    # out[:, k] = x W_k y^T + b (bilinear_tensor_product_op.cc)
    x, y, w = _x(ins), _x(ins, "Y"), ins["Weight"][0]   # w: [K, Dx, Dy]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if "Bias" in ins:
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@OpRegistry.register("interpolation")
def _interp(ins, attrs):
    from ..ops.math import interpolation
    return {"Out": [interpolation(_x(ins), _x(ins, "Y"), ins["W"][0])]}


# ------------------------------------------------------------ conv / pool ----

@OpRegistry.register("depthwise_conv2d")
def _dwconv(ins, attrs):
    from ..ops.conv import depthwise_conv2d
    return {"Out": [depthwise_conv2d(ins["Input"][0], ins["Filter"][0],
                                     stride=attrs.get("strides", 1),
                                     padding=attrs.get("paddings", 0))]}


@OpRegistry.register("conv2d_transpose")
def _deconv(ins, attrs):
    from ..ops.conv import conv2d_transpose
    return {"Out": [conv2d_transpose(ins["Input"][0], ins["Filter"][0],
                                     stride=attrs.get("strides", 1),
                                     padding=attrs.get("paddings", 0))]}


@OpRegistry.register("conv3d")
def _conv3d(ins, attrs):
    from ..ops.conv import conv3d
    return {"Out": [conv3d(ins["Input"][0], ins["Filter"][0],
                           stride=attrs.get("strides", 1),
                           padding=attrs.get("paddings", 0),
                           dilation=attrs.get("dilations", 1),
                           groups=attrs.get("groups", 1))]}


@OpRegistry.register("pool3d")
def _pool3d(ins, attrs):
    from ..ops import pool as P
    fn = (P.max_pool3d if attrs.get("pooling_type", "max") == "max"
          else P.avg_pool3d)
    return {"Out": [fn(_x(ins), attrs.get("ksize", 2),
                       attrs.get("strides"), attrs.get("paddings", 0))]}


@OpRegistry.register("pool2d_with_index")
def _pool_idx(ins, attrs):
    from ..ops.pool import max_pool2d_with_index
    out, idx = max_pool2d_with_index(_x(ins), attrs.get("ksize", 2),
                                     attrs.get("strides"),
                                     attrs.get("paddings", 0))
    return {"Out": [out], "Mask": [idx]}


@OpRegistry.register("lrn")
def _lrn(ins, attrs):
    from ..ops.norm import lrn
    return {"Out": [lrn(_x(ins), size=attrs.get("n", 5),
                        alpha=attrs.get("alpha", 1e-4),
                        beta=attrs.get("beta", 0.75),
                        k=attrs.get("k", 1.0))]}


@OpRegistry.register("maxout")
def _maxout(ins, attrs):
    from ..ops.conv import maxout
    return {"Out": [maxout(_x(ins), attrs["groups"])]}


@OpRegistry.register("roi_pool")
def _roi(ins, attrs):
    from ..ops.pool import roi_pool
    return {"Out": [roi_pool(_x(ins), ins["ROIs"][0],
                             (attrs["pooled_height"], attrs["pooled_width"]),
                             spatial_scale=attrs.get("spatial_scale", 1.0))]}


@OpRegistry.register("row_conv")
def _row_conv(ins, attrs):
    from ..ops.conv import row_conv
    return {"Out": [row_conv(_x(ins), ins["Filter"][0])]}


@OpRegistry.register("block_expand")
def _block_expand(ins, attrs):
    from ..ops.conv import im2col
    return {"Out": [im2col(_x(ins), attrs["block"], attrs.get("strides", 1),
                           attrs.get("paddings", 0))]}


@OpRegistry.register("bilinear_interp")
def _bilinear(ins, attrs):
    from ..ops.conv import bilinear_interp
    return {"Out": [bilinear_interp(_x(ins), attrs["out_h"], attrs["out_w"])]}


@OpRegistry.register("spp")
def _spp(ins, attrs):
    from ..ops.pool import spatial_pyramid_pool
    return {"Out": [spatial_pyramid_pool(_x(ins), attrs["pyramid_height"],
                                         attrs.get("pooling_type", "max"))]}


# ------------------------------------------------------------- batch norm ----

@OpRegistry.register("batch_norm")
def _batch_norm(ins, attrs):
    """Training-capable batch norm (batch_norm_op.cc): updates running stats;
    MeanOut/VarianceOut alias the persistable stat vars so the executor syncs
    them back to the scope after the step."""
    from ..ops.norm import batch_norm
    y, new_mean, new_var = batch_norm(
        _x(ins), ins["Scale"][0], ins["Bias"][0],
        ins["Mean"][0], ins["Variance"][0],
        train=not attrs.get("is_test", False),
        momentum=attrs.get("momentum", 0.9),
        eps=attrs.get("epsilon", 1e-5))
    return {"Y": [y], "MeanOut": [new_mean], "VarianceOut": [new_var]}


# ------------------------------------------------------------------ losses ---

def _loss_reg(name, fn_name, x_key="X", label_key="Label", out_key="Out",
              **fixed):
    from ..ops import loss as L
    fn = getattr(L, fn_name)

    def compute(ins, attrs, _fn=fn):
        kw = dict(fixed)
        for a in ("sigma", "delta", "margin", "eps"):
            if a in attrs:
                kw[a] = attrs[a]
        return {out_key: [_fn(ins[x_key][0], ins[label_key][0], **kw)]}
    OpRegistry._ops[name] = compute


_loss_reg("smooth_l1_loss", "smooth_l1")
_loss_reg("huber_loss", "huber_regression")
_loss_reg("modified_huber_loss", "modified_huber")
_loss_reg("hinge_loss", "hinge")
_loss_reg("log_loss", "log_loss", x_key="Predicted")
_loss_reg("multi_binary_label_cross_entropy", "multi_binary_label_cross_entropy")
_loss_reg("soft_binary_class_cross_entropy", "soft_binary_class_cross_entropy")
_loss_reg("kldiv_loss", "kldiv_loss", label_key="Target")


@OpRegistry.register("rank_loss")
def _rank_loss(ins, attrs):
    from ..ops.loss import rank_loss
    return {"Out": [rank_loss(ins["Left"][0], ins["Right"][0],
                              ins["Label"][0])]}


@OpRegistry.register("margin_rank_loss")
def _margin_rank(ins, attrs):
    from ..ops.loss import margin_rank_loss
    return {"Out": [margin_rank_loss(ins["X1"][0], ins["X2"][0],
                                     ins["Label"][0],
                                     margin=attrs.get("margin", 0.0))]}


# --------------------------------------------------------------- sequences ---

@OpRegistry.register("sequence_expand")
def _seq_expand(ins, attrs):
    from ..ops.sequence import sequence_expand
    # max_len statically from the reference sequence when provided (the
    # v2 expand_layer path), else from the attr
    if "Ref" in ins:
        max_len = ins["Ref"][0].shape[1]
    else:
        max_len = attrs["max_len"]
    return {"Out": [sequence_expand(_x(ins), ins["RefLengths"][0], max_len)]}


@OpRegistry.register("sequence_softmax")
def _seq_softmax(ins, attrs):
    x, lengths = _x(ins), ins["Lengths"][0]
    T = x.shape[1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])
    logits = jnp.where(mask, x, -1e9)
    sm = jax.nn.softmax(logits, axis=1)
    return {"Out": [jnp.where(mask, sm, 0.0)]}


@OpRegistry.register("sequence_reverse")
def _seq_rev(ins, attrs):
    from ..ops.sequence import sequence_reverse
    return {"Out": [sequence_reverse(_x(ins), ins["Lengths"][0])]}


@OpRegistry.register("sequence_slice")
def _seq_slice(ins, attrs):
    from ..ops.sequence import sequence_slice
    x = _x(ins)
    return {"Out": [sequence_slice(x, ins["Lengths"][0], ins["Offset"][0],
                                   ins["Length"][0],
                                   attrs.get("max_out", x.shape[1]))]}


@OpRegistry.register("sequence_concat")
def _seq_concat(ins, attrs):
    from ..ops.sequence import sequence_concat
    out, lengths = sequence_concat(ins["X"][0], ins["XLengths"][0],
                                   ins["Y"][0], ins["YLengths"][0])
    return {"Out": [out], "OutLengths": [lengths]}


@OpRegistry.register("context_projection")
def _ctx_proj(ins, attrs):
    from ..ops.sequence import context_projection
    return {"Out": [context_projection(_x(ins), ins["Lengths"][0],
                                       attrs.get("context_start", -1),
                                       attrs.get("context_length", 3))]}


@OpRegistry.register("lod_reset")
def _lod_reset(ins, attrs):
    # lengths live beside data in this design; the op passes data through and
    # emits the new lengths (lod_reset_op.cc re-labels offsets)
    return {"Out": [_x(ins)],
            "OutLengths": [ins["Lengths"][0] if "Lengths" in ins
                           else jnp.asarray(attrs["target_lengths"])]}


# ----------------------------------------------------------------- CRF/CTC ---

@OpRegistry.register("linear_chain_crf")
def _crf(ins, attrs):
    from ..ops.crf import crf_loss
    t = ins["Transition"][0]   # [N+2, N] packed like the reference
    ll = crf_loss(ins["Emission"][0], ins["Label"][0], ins["Lengths"][0],
                  t[0], t[1], t[2:])
    return {"LogLikelihood": [ll]}


@OpRegistry.register("crf_decoding")
def _crf_dec(ins, attrs):
    from ..ops.crf import crf_decode
    t = ins["Transition"][0]
    tags, score = crf_decode(ins["Emission"][0], ins["Lengths"][0],
                             t[0], t[1], t[2:])
    return {"ViterbiPath": [tags], "Score": [score]}


@OpRegistry.register("warpctc")
def _ctc(ins, attrs):
    from ..ops.ctc import ctc_loss
    return {"Loss": [ctc_loss(ins["Logits"][0], ins["LogitsLengths"][0],
                              ins["Label"][0], ins["LabelLengths"][0],
                              blank=attrs.get("blank", 0))]}


@OpRegistry.register("ctc_greedy_decode")
def _ctc_dec(ins, attrs):
    from ..ops.ctc import ctc_greedy_decode
    toks, lens = ctc_greedy_decode(ins["Logits"][0], ins["LogitsLengths"][0],
                                   blank=attrs.get("blank", 0))
    return {"Out": [toks], "OutLengths": [lens]}


# -------------------------------------------------------------- nce / hsig ---

@OpRegistry.register("nce")
def _nce(ins, attrs):
    from ..ops.nce import nce_loss
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    if "Step" in ins:       # fresh negatives per executor run
        key = jax.random.fold_in(key, ins["Step"][0])
    return {"Cost": [nce_loss(
        ins["Input"][0], ins["Label"][0], ins["Weight"][0],
        ins["Bias"][0] if "Bias" in ins else None, key,
        num_neg_samples=attrs.get("num_neg_samples", 10))]}


@OpRegistry.register("hierarchical_sigmoid")
def _hsig(ins, attrs):
    from ..ops.nce import build_huffman_codes, hsigmoid_loss
    if "Paths" in ins:
        paths, codes = ins["Paths"][0], ins["Codes"][0]
    else:
        # static tree from the num_classes attr (constant-folded at trace)
        paths, codes = build_huffman_codes(attrs["num_classes"])
    return {"Cost": [hsigmoid_loss(
        ins["Input"][0], ins["Label"][0], ins["InnerW"][0],
        ins["InnerB"][0] if "InnerB" in ins else None,
        paths, codes)]}


# ----------------------------------------------------------------- metrics ---

@OpRegistry.register("auc")
def _auc(ins, attrs):
    from ..ops.metrics import auc_from_histogram, auc_histogram
    pos, neg = auc_histogram(ins["Out"][0], ins["Label"][0],
                             attrs.get("num_thresholds", 200))
    return {"AUC": [auc_from_histogram(pos, neg)],
            "PosHist": [pos], "NegHist": [neg]}


@OpRegistry.register("precision_recall")
def _pr(ins, attrs):
    from ..ops.metrics import precision_recall_counts
    tp, fp, fn_ = precision_recall_counts(ins["Out"][0], ins["Label"][0],
                                          attrs["num_classes"])
    return {"TP": [tp], "FP": [fp], "FN": [fn_]}


@OpRegistry.register("chunk_eval")
def _chunk(ins, attrs):
    from ..ops.metrics import chunk_count
    c, p, l = chunk_count(ins["Inference"][0], ins["Label"][0],
                          ins["Lengths"][0],
                          scheme=attrs.get("chunk_scheme", "IOB"),
                          num_chunk_types=attrs.get("num_chunk_types", 1))
    return {"Correct": [c], "Predicted": [p], "Labeled": [l]}


@OpRegistry.register("positive_negative_pair")
def _pnpair(ins, attrs):
    # pn-pair: over query groups, count concordant/discordant score pairs
    # (positive_negative_pair_op.cc); QueryID groups rows.
    score, label, qid = ins["Score"][0], ins["Label"][0], ins["QueryID"][0]
    s, l, q = score.reshape(-1), label.reshape(-1), qid.reshape(-1)
    same_q = q[:, None] == q[None, :]
    ds = s[:, None] - s[None, :]
    dl = l[:, None] - l[None, :]
    valid = same_q & (dl > 0)                       # i more relevant than j
    pos = jnp.sum(valid & (ds > 0))
    neg = jnp.sum(valid & (ds < 0))
    neu = jnp.sum(valid & (ds == 0))
    return {"PositivePair": [pos.astype(jnp.float32)],
            "NegativePair": [neg.astype(jnp.float32)],
            "NeutralPair": [neu.astype(jnp.float32)]}


# --------------------------------------------------------------- detection ---

@OpRegistry.register("prior_box")
def _prior_box(ins, attrs):
    from ..ops.detection import prior_box
    boxes, variances = prior_box(
        tuple(attrs["feature_hw"]), tuple(attrs["image_hw"]),
        min_size=attrs["min_size"], max_size=attrs.get("max_size"),
        aspect_ratios=attrs.get("aspect_ratios", (2.0,)),
        flip=attrs.get("flip", True), clip=attrs.get("clip", True),
        variance=attrs.get("variance", (0.1, 0.1, 0.2, 0.2)))
    return {"Boxes": [boxes], "Variances": [variances]}


@OpRegistry.register("multibox_loss")
def _mb_loss(ins, attrs):
    from ..ops.detection import multibox_loss
    loss = jax.vmap(
        lambda lp, cl, gb, gl, gm: multibox_loss(
            lp, cl, ins["PriorBox"][0], ins["PriorVar"][0], gb, gl, gm,
            neg_pos_ratio=attrs.get("neg_pos_ratio", 3.0),
            overlap_threshold=attrs.get("overlap_threshold", 0.5),
            background_id=attrs.get("background_id", 0))
    )(ins["Loc"][0], ins["Conf"][0], ins["GTBox"][0], ins["GTLabel"][0],
      ins["GTMask"][0])
    return {"Loss": [loss]}


@OpRegistry.register("detection_output")
def _det_out(ins, attrs):
    from ..ops.detection import detection_output
    boxes, scores, valid = jax.vmap(
        lambda lp, cl: detection_output(
            lp, cl, ins["PriorBox"][0], ins["PriorVar"][0],
            num_classes=attrs["num_classes"],
            background_id=attrs.get("background_id", 0),
            iou_threshold=attrs.get("nms_threshold", 0.45),
            score_threshold=attrs.get("score_threshold", 0.01),
            keep_top_k=attrs.get("keep_top_k", 100))
    )(ins["Loc"][0], ins["Conf"][0])
    return {"Boxes": [boxes], "Scores": [scores], "Valid": [valid]}


# ---------------------------------------------------------------- rnn units --

@OpRegistry.register("lstm_unit")
def _lstm_unit(ins, attrs):
    from ..ops.rnn import LSTMState, lstm_cell
    state = LSTMState(h=ins["HPrev"][0], c=ins["CPrev"][0])
    new = lstm_cell(_x(ins), state, ins["U"][0],
                    ins["B"][0] if "B" in ins else None,
                    forget_bias=attrs.get("forget_bias", 0.0))
    return {"H": [new.h], "C": [new.c]}


@OpRegistry.register("gru_unit")
def _gru_unit(ins, attrs):
    from ..ops.rnn import gru_cell
    h = gru_cell(_x(ins), ins["HPrev"][0], ins["U"][0],
                 ins["B"][0] if "B" in ins else None)
    return {"H": [h]}


# ---------------------------------------------------------- optimizer ops ----
# One op per family like operators/{adagrad,adadelta,rmsprop,adamax,
# decayed_adagrad,proximal_gd,proximal_adagrad}_op.cc.

@OpRegistry.register("adagrad")
def _adagrad(ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    eps = attrs.get("epsilon", 1e-6)
    m_new = m + g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_new) + eps)],
            "MomentOut": [m_new]}


@OpRegistry.register("adadelta")
def _adadelta(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ag, au = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    ag_new = rho * ag + (1 - rho) * g * g
    upd = jnp.sqrt(au + eps) / jnp.sqrt(ag_new + eps) * g
    au_new = rho * au + (1 - rho) * upd * upd
    return {"ParamOut": [p - upd], "AvgSquaredGradOut": [ag_new],
            "AvgSquaredUpdateOut": [au_new]}


@OpRegistry.register("rmsprop")
def _rmsprop(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": [p - mom_new], "MeanSquareOut": [ms_new],
            "MomentOut": [mom_new]}


@OpRegistry.register("adamax")
def _adamax(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, u, b1p = ins["Moment"][0], ins["InfNorm"][0], ins["Beta1Pow"][0]
    lr = ins["LearningRate"][0]
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    p_new = p - (lr / (1 - b1p)) * m_new / (u_new + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new], "InfNormOut": [u_new],
            "Beta1PowOut": [b1p * b1]}


@OpRegistry.register("decayed_adagrad")
def _dec_adagrad(ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_new) + eps)],
            "MomentOut": [m_new]}


@OpRegistry.register("proximal_gd")
def _prox_gd(ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    prox = p - lr * g
    p_new = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
    return {"ParamOut": [p_new]}


@OpRegistry.register("proximal_adagrad")
def _prox_adagrad(ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    eps = 1e-10
    m_new = m + g * g
    eff_lr = lr / (jnp.sqrt(m_new) + eps)
    prox = p - eff_lr * g
    p_new = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0.0)
             / (1.0 + eff_lr * l2))
    return {"ParamOut": [p_new], "MomentOut": [m_new]}


@OpRegistry.register("ftrl")
def _ftrl(ins, attrs):
    """FTRL-proximal (ref: operators/ftrl_op.cc; lr_power fixed at -0.5)."""
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    lr = ins["LearningRate"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    sq_new = sq + g * g
    sigma = (jnp.sqrt(sq_new) - jnp.sqrt(sq)) / lr
    lin_new = lin + g - sigma * p
    quad = jnp.sqrt(sq_new) / lr + 2.0 * l2
    p_new = jnp.where(
        jnp.abs(lin_new) > l1,
        (jnp.sign(lin_new) * l1 - lin_new) / quad,
        jnp.zeros_like(p))
    return {"ParamOut": [p_new], "SquaredAccumOut": [sq_new],
            "LinearAccumOut": [lin_new]}


@OpRegistry.register("squeeze")
def _squeeze(ins, attrs):
    return {"Out": [jnp.squeeze(_x(ins), axis=attrs.get("axis"))]}


@OpRegistry.register("unsqueeze")
def _unsqueeze(ins, attrs):
    return {"Out": [jnp.expand_dims(_x(ins), axis=attrs["axis"])]}


@OpRegistry.register("nested_seq_pool")
def _nested_pool(ins, attrs):
    from ..core.lod import NestedSeqBatch
    from ..ops.sequence import nested_seq_pool
    nb = NestedSeqBatch(_x(ins), ins["SubLengths"][0], ins["SeqLengths"][0])
    return {"Out": [nested_seq_pool(nb, attrs.get("pool_type", "average")).data]}


@OpRegistry.register("nested_last_step")
def _nested_last(ins, attrs):
    from ..core.lod import NestedSeqBatch
    from ..ops.sequence import nested_last_step
    nb = NestedSeqBatch(_x(ins), ins["SubLengths"][0], ins["SeqLengths"][0])
    return {"Out": [nested_last_step(nb).data]}


@OpRegistry.register("nested_lstm")
def _nested_lstm(ins, attrs):
    """Inner LSTM per sub-sequence (state resets at sub-seq boundaries —
    the nested recurrent_group semantics of sequence_nest_rnn*.py)."""
    from ..core.lod import NestedSeqBatch
    from ..ops.rnn import lstm
    from ..ops.sequence import nested_rnn
    nb = NestedSeqBatch(_x(ins), ins["SubLengths"][0], ins["SeqLengths"][0])
    out, last = nested_rnn(lstm, nb, ins["W"][0], ins["U"][0],
                           ins["B"][0] if "B" in ins else None,
                           reverse=attrs.get("reverse", False))
    return {"Out": [out], "LastH": [last.data]}


# ---------------------------------------------------------------------------
# gen-1 layer-zoo completions (small ops backing the v2 *_layer DSL surface;
# each cites the gserver layer it re-provides)
# ---------------------------------------------------------------------------

@OpRegistry.register("argmax")
def _argmax(ins, attrs):
    """MaxIdLayer (gserver/layers/MaxIdLayer.cpp)."""
    return {"Out": [jnp.argmax(_x(ins), axis=attrs.get("axis", -1))
                    .astype(jnp.int32)]}


@OpRegistry.register("power")
def _power(ins, attrs):
    """PowerLayer (gserver/layers/PowerLayer.cpp): y = x^w, w a learned
    scalar; sign-preserving for negative activations."""
    x, w = _x(ins), ins["W"][0]
    return {"Out": [jnp.sign(x) * jnp.power(jnp.abs(x) + 1e-12,
                                            jnp.reshape(w, ()))]}


@OpRegistry.register("slope_intercept")
def _slope_intercept(ins, attrs):
    """SlopeInterceptLayer: y = slope * x + intercept (static attrs)."""
    return {"Out": [attrs.get("slope", 1.0) * _x(ins)
                    + attrs.get("intercept", 0.0)]}


@OpRegistry.register("sum_to_one_norm")
def _sum_to_one_norm(ins, attrs):
    """SumToOneNormLayer: rows normalised to sum 1."""
    x = _x(ins)
    s = jnp.sum(x, axis=-1, keepdims=True)
    return {"Out": [x / jnp.where(jnp.abs(s) < 1e-12, 1.0, s)]}


@OpRegistry.register("linear_comb")
def _linear_comb(ins, attrs):
    """LinearCombinationLayer (convex_comb): weights [B, M] over M vectors
    [B, M*D] -> [B, D]."""
    w, x = ins["W"][0], _x(ins)
    B = x.shape[0]
    M = w.shape[-1]
    D = x.shape[-1] // M
    return {"Out": [jnp.einsum("bm,bmd->bd", w, x.reshape(B, M, D))]}


@OpRegistry.register("repeat")
def _repeat(ins, attrs):
    """FeatureMapExpandLayer / repeat_layer: tile features n times."""
    return {"Out": [jnp.repeat(_x(ins), attrs["times"], axis=attrs.get(
        "axis", -1))]}


@OpRegistry.register("rotate")
def _rotate(ins, attrs):
    """RotateLayer: 90-degree CCW rotation of [B, H, W, C] maps."""
    return {"Out": [jnp.rot90(_x(ins), k=1, axes=(1, 2))]}


@OpRegistry.register("seq_reshape")
def _seq_reshape(ins, attrs):
    """SequenceReshapeLayer: [B, T, D] -> [B, T*D//new_dim, new_dim]."""
    x = _x(ins)
    d = attrs["new_dim"]
    B = x.shape[0]
    return {"Out": [x.reshape(B, -1, d)]}


@OpRegistry.register("sampling_id")
def _sampling_id(ins, attrs):
    """SamplingIdLayer: sample class ids from row distributions via the
    Gumbel trick (on-device, reproducible by seed attr)."""
    x = _x(ins)
    key = jax.random.PRNGKey(attrs.get("seed", 0))
    g = jax.random.gumbel(key, x.shape, x.dtype)
    logp = jnp.log(jnp.clip(x, 1e-20, None)) if attrs.get(
        "input_is_prob", True) else x
    return {"Out": [jnp.argmax(logp + g, axis=-1).astype(jnp.int32)]}


@OpRegistry.register("cross_entropy_over_selfnorm")
def _ce_selfnorm(ins, attrs):
    """CostLayer.cpp CrossEntropyOverSelfNorm: CE on unnormalised logits plus
    alpha * log(Z)^2 pulling the partition toward 1 (self-normalised
    softmax for fast inference)."""
    logits, label = _x(ins), ins["Label"][0]
    alpha = attrs.get("softmax_selfnorm_alpha", 0.1)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    logp = logits - logz[..., None]
    nll = -jnp.take_along_axis(logp, label[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    return {"Out": [nll + alpha * logz * logz]}


@OpRegistry.register("huber_classification")
def _huber_cls(ins, attrs):
    """CostLayer.cpp HuberTwoClassification: robust binary loss on {-1,+1}
    labels."""
    from ..ops import loss as L
    return {"Out": [L.huber_classification(_x(ins), ins["Label"][0])]}


@OpRegistry.register("lambda_cost")
def _lambda_cost(ins, attrs):
    """LambdaCost (gserver/layers/CostLayer.cpp LambdaCost): listwise
    LambdaRank — pairwise logistic losses weighted by |delta NDCG|.

    Score [B, T], Label (relevance) [B, T], Lengths [B].
    """
    s, rel = _x(ins).astype(jnp.float32), ins["Label"][0].astype(jnp.float32)
    lens = ins["Lengths"][0]
    B, T = s.shape
    pos = jnp.arange(T)
    valid = pos[None, :] < lens[:, None]                       # [B, T]
    neg_inf = jnp.float32(-1e30)
    s_m = jnp.where(valid, s, neg_inf)
    # rank of each item under the CURRENT scores (0-based, stable)
    order = jnp.argsort(-s_m, axis=-1)
    ranks = jnp.zeros((B, T), jnp.float32)
    ranks = jax.vmap(lambda r, o: r.at[o].set(jnp.arange(T, dtype=jnp.float32))
                     )(ranks, order)
    gain = (jnp.exp2(rel) - 1.0) * valid                       # [B, T]
    disc = 1.0 / jnp.log2(ranks + 2.0)
    # ideal DCG for normalisation
    rel_sorted = -jnp.sort(-jnp.where(valid, rel, 0.0), axis=-1)
    ideal = jnp.sum((jnp.exp2(rel_sorted) - 1.0)
                    / jnp.log2(jnp.arange(T, dtype=jnp.float32) + 2.0),
                    axis=-1, keepdims=True)
    ideal = jnp.where(ideal <= 0, 1.0, ideal)
    # |delta NDCG| of swapping i and j
    dg = gain[:, :, None] - gain[:, None, :]                   # [B, T, T]
    dd = disc[:, :, None] - disc[:, None, :]
    dndcg = jnp.abs(dg * dd) / ideal[:, :, None]
    higher = (rel[:, :, None] > rel[:, None, :]) \
        & valid[:, :, None] & valid[:, None, :]
    sdiff = s[:, :, None] - s[:, None, :]
    pair_loss = jnp.log1p(jnp.exp(-jnp.clip(sdiff, -30, 30)))
    per_row = jnp.sum(jnp.where(higher, dndcg * pair_loss, 0.0), axis=(1, 2))
    return {"Out": [per_row]}


@OpRegistry.register("binary_f1")
def _binary_f1(ins, attrs):
    """Per-batch F1 for one positive class (evaluators.py:340 per-batch
    role) — built on the shared precision/recall counting."""
    from ..ops.metrics import precision_recall_counts
    logits, label = ins["X"][0], ins["Label"][0]
    pos = attrs.get("positive_label", 1)
    pred = jnp.argmax(logits, -1).astype(jnp.int32)
    counts = precision_recall_counts(pred, label.astype(jnp.int32),
                                     int(logits.shape[-1]))
    tp, fp, fn = counts[pos, 0], counts[pos, 1], counts[pos, 2]
    prec = tp / jnp.maximum(tp + fp, 1)
    rec = tp / jnp.maximum(tp + fn, 1)
    return {"Out": [2 * prec * rec / jnp.maximum(prec + rec, 1e-12)]}


# -------------------------------------------------- gen-1 tail (round 3) ----

@OpRegistry.register("lstm_step")
def _lstm_step(ins, attrs):
    """Pre-projected-gates LSTM step with peephole connections
    (LstmStepLayer.cpp; layers.py:3544 lstm_step_layer)."""
    from ..ops.rnn import lstm_peephole_step
    h, c = lstm_peephole_step(_x(ins), ins["CPrev"][0], ins["WPeep"][0],
                              ins["B"][0] if "B" in ins else None,
                              forget_bias=attrs.get("forget_bias", 0.0))
    return {"H": [h], "C": [c]}


@OpRegistry.register("kmax_seq_score")
def _kmax_seq_score(ins, attrs):
    from ..ops.sequence import kmax_seq_score
    return {"Out": [kmax_seq_score(_x(ins), ins["Lengths"][0],
                                   attrs["beam_size"])]}


@OpRegistry.register("sub_nested_seq")
def _sub_nested_seq(ins, attrs):
    from ..ops.sequence import sub_nested_seq
    out, sub = sub_nested_seq(_x(ins), ins["SubLengths"][0],
                              ins["Indices"][0])
    return {"Out": [out], "SubLengthsOut": [sub]}


@OpRegistry.register("equal_scalar")
def _equal_scalar(ins, attrs):
    """Elementwise id == constant (EosIdCheckLayer role, layers.py:4224);
    distinct from the two-input "equal" compare op."""
    val = attrs["value"]
    return {"Out": [(_x(ins) == val).astype(jnp.int32)]}


@OpRegistry.register("dyn_conv2d")
def _dyn_conv2d(ins, attrs):
    """Per-sample dynamic-filter conv (ConvOperator.cpp: the filter is an
    INPUT, not a parameter — e.g. attention-generated kernels). NHWC."""
    from ..ops.conv import conv2d
    x = _x(ins)                                        # [B, H, W, C]
    k = attrs["filter_size"]
    c, nf = attrs["channels"], attrs["num_filters"]
    # flat layout is the reference's (F, C, k, k) per-sample packing;
    # transpose to HWIO for the NHWC conv
    filt = ins["Filter"][0].reshape((-1, nf, c, k, k)).transpose(
        (0, 3, 4, 2, 1))                               # [B, k, k, C, F]

    def one(img, f):
        return conv2d(img[None], f, stride=attrs.get("stride", 1),
                      padding=attrs.get("padding", 0))[0]

    return {"Out": [jax.vmap(one)(x, filt)]}


@OpRegistry.register("scale_sub_region")
def _scale_sub_region(ins, attrs):
    """Multiply a per-sample (C,H,W) box by a constant
    (ScaleSubRegionLayer.cpp). X: [B, H, W, C] NHWC; Indices [B, 6]
    1-based inclusive (C_s, C_e, H_s, H_e, W_s, W_e)."""
    x = _x(ins)
    idx = ins["Indices"][0].astype(jnp.int32)
    B, H, W, C = x.shape
    hh = jnp.arange(H)[None, :, None, None]
    ww = jnp.arange(W)[None, None, :, None]
    cc = jnp.arange(C)[None, None, None, :]
    e = lambda i: idx[:, i][:, None, None, None]
    inside = ((cc >= e(0) - 1) & (cc <= e(1) - 1) &
              (hh >= e(2) - 1) & (hh <= e(3) - 1) &
              (ww >= e(4) - 1) & (ww <= e(5) - 1))
    return {"Out": [jnp.where(inside, x * attrs["value"], x)]}


@OpRegistry.register("cross_entropy_over_beam")
def _ce_over_beam(ins, attrs):
    """Beam-training CE (CrossEntropyOverBeamLayer role): softmax over each
    sample's beam scores, with the reference's per-sample append-gold
    construction — the gold's own score joins as slot K ONLY for samples
    whose gold fell out of the beam (gold_idx == K); in-beam samples mask
    slot K so their gold is never double-counted in the partition."""
    scores = _x(ins)                              # [B, K]
    gold_idx = ins["GoldIdx"][0].astype(jnp.int32)  # [B]; K == out-of-beam
    K = scores.shape[-1]
    if "GoldScore" in ins:
        gs = ins["GoldScore"][0].reshape(-1, 1)   # [B, 1]
        in_beam = (gold_idx < K).reshape(-1, 1)
        slot_k = jnp.where(in_beam, -1e9, gs)
        logits = jnp.concatenate([scores, slot_k], axis=-1)
    else:
        logits = scores
    logp = jax.nn.log_softmax(logits, axis=-1)
    safe_idx = jnp.clip(gold_idx, 0, logits.shape[-1] - 1)
    picked = jnp.take_along_axis(logp, safe_idx[:, None], axis=-1)[:, 0]
    # a gold index outside the logits — the out-of-beam sentinel K without a
    # GoldScore input to back it, or a negative index (which clip would
    # silently send to beam slot 0) — must not train against an arbitrary
    # slot: surface it as +inf loss, which the trainer's NaN/inf guard
    # reports loudly
    picked = jnp.where((gold_idx > safe_idx) | (gold_idx < 0),
                       -jnp.inf, picked)
    return {"Out": [-picked]}
