"""Weight-decay regularizers as op-emitting decorators.

Analog of python/paddle/v2/fluid/regularizer.py (L2DecayRegularizer /
L1DecayRegularizer append ops transforming each gradient before the optimizer
consumes it) and the gen-1 Regularizer.cpp L1/L2 pair. The decay op lands in
the same block as the optimizer ops, so it fuses into the single compiled
train step.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .framework import Program, Variable, default_main_program


class WeightDecayRegularizer:
    def append_decay(self, block, param: Variable, grad: Variable) -> Variable:
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * param (L2DecayRegularizer semantics)."""

    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_decay(self, block, param, grad):
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("scale", {"X": [param.name]}, {"Out": [decay.name]},
                        {"scale": self.coeff})
        out = block.create_var(shape=grad.shape, dtype=grad.dtype)
        block.append_op("elementwise_add",
                        {"X": [grad.name], "Y": [decay.name]},
                        {"Out": [out.name]})
        return out


class L1Decay(WeightDecayRegularizer):
    """grad += coeff * sign(param) (L1DecayRegularizer; the gen-1
    Regularizer.cpp L1 path the round-1 build lacked)."""

    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_decay(self, block, param, grad):
        sgn = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("sign", {"X": [param.name]}, {"Out": [sgn.name]})
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("scale", {"X": [sgn.name]}, {"Out": [decay.name]},
                        {"scale": self.coeff})
        out = block.create_var(shape=grad.shape, dtype=grad.dtype)
        block.append_op("elementwise_add",
                        {"X": [grad.name], "Y": [decay.name]},
                        {"Out": [out.name]})
        return out


def append_regularization_ops(
        params_grads: List[Tuple[Variable, Variable]],
        regularization: Optional[WeightDecayRegularizer] = None,
        program: Optional[Program] = None
) -> List[Tuple[Variable, Variable]]:
    """Transform each grad with its regularizer (param-level attr wins over
    the global one, mirroring fluid append_regularization_ops)."""
    program = program or default_main_program()
    block = program.global_block()
    out = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        out.append((param, reg.append_decay(block, param, grad) if reg
                    else grad))
    return out
