"""Model zoo — mirrors the reference's demo/benchmark/book model families
(SURVEY.md §2.4 v1_api_demo + benchmark/paddle + fluid/tests/book)."""

from .embeddings import DeepFM, Recommender, Word2Vec
from .generative import GAN, VAE
from .image import (AlexNet, GoogleNet, LeNet, ResNet, SmallNet,
                    VGG, resnet50)
from .mlp import MnistMLP
from .seq2seq import AttentionSeq2Seq
from .transformer import TransformerBlock, TransformerLM
from .transformer_nmt import CrossAttentionBlock, TransformerSeq2Seq
from .tagger import BiLSTMCRFTagger, LinearCRFTagger
from .text_cls import BiLSTMTextCls, ConvTextCls, LSTMTextCls

__all__ = [
    "AlexNet", "GoogleNet", "MnistMLP", "LeNet", "SmallNet", "VGG", "ResNet", "resnet50",
           "LSTMTextCls", "BiLSTMTextCls", "ConvTextCls",
           "AttentionSeq2Seq", "LinearCRFTagger", "BiLSTMCRFTagger",
           "Word2Vec", "Recommender", "DeepFM", "GAN", "VAE",
           "TransformerLM", "TransformerBlock",
           "TransformerSeq2Seq", "CrossAttentionBlock"]
