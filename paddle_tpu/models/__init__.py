from .mlp import MnistMLP

__all__ = ["MnistMLP"]
