"""Word2vec + recommender + DeepFM — the embedding-heavy book models.

Analogs:
* word2vec      — ``fluid/tests/book/test_word2vec.py`` (n-gram context ->
  next-word softmax over shared embeddings) and the imikolov dataset.
* recommender   — ``fluid/tests/book/test_recommender_system.py`` (movielens:
  user/movie feature towers -> cosine/fc -> rating regression).
* DeepFM (CTR)  — the sparse wide&deep capability carried by the reference's
  sparse-row embeddings + pserver path (SURVEY §2.5 sparse/embedding-parallel);
  the standard Criteo CTR model shape.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .. import nn
from ..core.lod import SeqBatch
from ..ops import loss as L


class Word2Vec(nn.Module):
    """N-gram neural LM: concat context embeddings -> hidden -> softmax."""

    def __init__(self, vocab_size: int, embed_dim: int = 32, context: int = 4,
                 hidden: int = 128):
        super().__init__()
        self.context = context
        self.embed = nn.Embedding(vocab_size, embed_dim)
        self.fc1 = nn.Linear(context * embed_dim, hidden, act="relu")
        self.out = nn.Linear(hidden, vocab_size)

    def __call__(self, params, context_ids, **kw):
        """context_ids [B, context] -> logits [B, V]."""
        e = self.embed(params["embed"], context_ids)       # [B, C, E]
        h = e.reshape(e.shape[0], -1)
        return self.out(params["out"], self.fc1(params["fc1"], h))

    def loss(self, params, context_ids, target_ids):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, context_ids),
                                                     target_ids))


class Recommender(nn.Module):
    """Two-tower movielens regressor (book test_recommender_system schema):
    user tower (id/gender/age/job embeddings) x movie tower (id emb + category
    pooled) -> fc -> rating."""

    def __init__(self, n_users: int, n_movies: int, n_categories: int,
                 n_jobs: int, n_ages: int, dim: int = 32):
        super().__init__()
        self.uid = nn.Embedding(n_users, dim)
        self.gender = nn.Embedding(2, dim // 2)
        self.age = nn.Embedding(n_ages, dim // 2)
        self.job = nn.Embedding(n_jobs, dim // 2)
        self.user_fc = nn.Linear(dim + 3 * (dim // 2), dim, act="tanh")
        self.mid = nn.Embedding(n_movies, dim)
        self.cat = nn.Embedding(n_categories, dim // 2)
        self.movie_fc = nn.Linear(dim + dim // 2, dim, act="tanh")
        self.head = nn.Linear(2 * dim, 1)

    def __call__(self, params, uid, gender, age, job, mid, cat_ids, cat_vals,
                 **kw):
        """cat_ids/cat_vals: padded sparse category slot [B, K]."""
        u = jnp.concatenate([
            self.uid(params["uid"], uid),
            self.gender(params["gender"], gender),
            self.age(params["age"], age),
            self.job(params["job"], job)], axis=-1)
        u = self.user_fc(params["user_fc"], u)
        cat_e = self.cat(params["cat"], cat_ids)            # [B, K, D/2]
        denom = jnp.maximum(cat_vals.sum(-1, keepdims=True), 1.0)
        cat_pooled = (cat_e * cat_vals[..., None]).sum(1) / denom
        m = jnp.concatenate([self.mid(params["mid"], mid), cat_pooled], axis=-1)
        m = self.movie_fc(params["movie_fc"], m)
        return self.head(params["head"], jnp.concatenate([u, m], axis=-1))[..., 0]

    def loss(self, params, uid, gender, age, job, mid, cat_ids, cat_vals, rating):
        pred = self(params, uid, gender, age, job, mid, cat_ids, cat_vals)
        return jnp.mean((pred - rating) ** 2)


class DeepFM(nn.Module):
    """Factorization machine + deep tower over hashed sparse fields.

    first-order: sum of per-field weights; second-order: FM pairwise via the
    (sum^2 - sum-of-squares)/2 identity — one embedding gather feeds both FM
    and the MLP, all dense MXU work after the gather.
    """

    def __init__(self, hash_size: int, num_fields: int, dense_dim: int,
                 factor: int = 8, hidden: Sequence[int] = (64, 32)):
        super().__init__()
        self.w1 = nn.Embedding(hash_size, 1)               # first-order weights
        self.v = nn.Embedding(hash_size, factor)           # FM factors
        self.dense_w = nn.Linear(dense_dim, 1, bias=False)
        dims = [num_fields * factor + dense_dim] + list(hidden)
        self.deep = [nn.Linear(dims[i], dims[i + 1], act="relu")
                     for i in range(len(hidden))]
        self.deep_out = nn.Linear(dims[-1], 1)

    def __call__(self, params, dense, field_ids, **kw):
        """dense [B, dense_dim]; field_ids [B, num_fields] hashed ids."""
        lin = self.w1(params["w1"], field_ids)[..., 0].sum(-1, keepdims=True)
        lin = lin + self.dense_w(params["dense_w"], dense)
        vi = self.v(params["v"], field_ids)                # [B, F, k]
        fm = 0.5 * (jnp.square(vi.sum(1)) - jnp.square(vi).sum(1)).sum(
            -1, keepdims=True)
        h = jnp.concatenate([vi.reshape(vi.shape[0], -1), dense], axis=-1)
        for i, layer in enumerate(self.deep):
            h = layer(params[f"deep_{i}"], h)
        deep = self.deep_out(params["deep_out"], h)
        return (lin + fm + deep)[..., 0]                   # logit

    def loss(self, params, dense, field_ids, labels):
        logit = self(params, dense, field_ids)
        return jnp.mean(L.sigmoid_cross_entropy_with_logits(
            logit, labels.astype(jnp.float32)))

    def predict_proba(self, params, dense, field_ids):
        return jax.nn.sigmoid(self(params, dense, field_ids))
