"""Generative demo models — GAN and VAE.

Analogs of the reference demos ``v1_api_demo/gan/`` (gan_conf.py: generator/
discriminator MLPs trained adversarially) and ``v1_api_demo/vae/`` (vae_conf.py:
MLP encoder/decoder, gaussian reparameterization). TPU-first: both are plain
jitted train steps; the GAN alternates two optimizers over disjoint param
subtrees (the reference used two separate GradientMachines).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..ops import loss as L


class GAN(nn.Module):
    """MLP GAN (gan_conf.py shapes): G: z->sample; D: sample->real logit."""

    def __init__(self, data_dim: int = 784, noise_dim: int = 64,
                 hidden: int = 128):
        super().__init__()
        self.noise_dim = noise_dim
        self.g1 = nn.Linear(noise_dim, hidden, act="relu")
        self.g2 = nn.Linear(hidden, hidden, act="relu")
        self.g3 = nn.Linear(hidden, data_dim, act="tanh")
        self.d1 = nn.Linear(data_dim, hidden, act="relu")
        self.d2 = nn.Linear(hidden, hidden, act="relu")
        self.d3 = nn.Linear(hidden, 1)

    def generate(self, params, z):
        h = self.g1(params["g1"], z)
        h = self.g2(params["g2"], h)
        return self.g3(params["g3"], h)

    def discriminate(self, params, x):
        h = self.d1(params["d1"], x)
        h = self.d2(params["d2"], h)
        return self.d3(params["d3"], h)[..., 0]

    # -- losses (non-saturating GAN) ---------------------------------------
    def d_loss(self, params, real, z):
        fake = jax.lax.stop_gradient(self.generate(params, z))
        logit_r = self.discriminate(params, real)
        logit_f = self.discriminate(params, fake)
        return (L.sigmoid_cross_entropy_with_logits(
                    logit_r, jnp.ones_like(logit_r)).mean()
                + L.sigmoid_cross_entropy_with_logits(
                    logit_f, jnp.zeros_like(logit_f)).mean())

    def g_loss(self, params, z):
        fake = self.generate(params, z)
        logit_f = self.discriminate(params, fake)
        return L.sigmoid_cross_entropy_with_logits(
            logit_f, jnp.ones_like(logit_f)).mean()

    @staticmethod
    def split_grads(grads) -> Tuple[Dict, Dict]:
        g = {k: v for k, v in grads.items() if k.startswith("g")}
        d = {k: v for k, v in grads.items() if k.startswith("d")}
        return g, d


class VAE(nn.Module):
    """MLP VAE (vae_conf.py): encoder -> (mu, logvar) -> decoder; ELBO loss."""

    def __init__(self, data_dim: int = 784, latent: int = 32,
                 hidden: int = 128):
        super().__init__()
        self.latent = latent
        self.enc1 = nn.Linear(data_dim, hidden, act="relu")
        self.enc_mu = nn.Linear(hidden, latent)
        self.enc_lv = nn.Linear(hidden, latent)
        self.dec1 = nn.Linear(latent, hidden, act="relu")
        self.dec2 = nn.Linear(hidden, data_dim)

    def encode(self, params, x):
        h = self.enc1(params["enc1"], x)
        return self.enc_mu(params["enc_mu"], h), self.enc_lv(params["enc_lv"], h)

    def decode(self, params, z):
        return self.dec2(params["dec2"], self.dec1(params["dec1"], z))

    def loss(self, params, x, rng):
        mu, logvar = self.encode(params, x)
        eps = jax.random.normal(rng, mu.shape)
        z = mu + jnp.exp(0.5 * logvar) * eps          # reparameterization
        logits = self.decode(params, z)
        # Bernoulli reconstruction on x scaled to [0,1]
        x01 = (x + 1.0) / 2.0
        rec = L.sigmoid_cross_entropy_with_logits(logits, x01).sum(-1).mean()
        kl = -0.5 * jnp.sum(1 + logvar - mu ** 2 - jnp.exp(logvar), -1).mean()
        return rec + kl

    def sample(self, params, rng, n: int):
        z = jax.random.normal(rng, (n, self.latent))
        return jax.nn.sigmoid(self.decode(params, z))
