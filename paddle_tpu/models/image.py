"""Image classification models — the benchmark/image family.

Analogs:
* LeNet       — ``v1_api_demo/mnist/light_mnist.py`` (conv mnist demo)
* VGG-16      — ``benchmark/paddle/image/vgg.py`` + networks.py vgg_16_network:468
* ResNet-N    — ``benchmark/paddle/image/resnet.py`` (layer_num 50/101/152)
* SmallNet    — ``benchmark/paddle/image/smallnet_mnist_cifar.py`` (cifar-quick)
* AlexNet     — ``benchmark/paddle/image/alexnet.py``
* GoogleNet   — ``benchmark/paddle/image/googlenet.py`` (inception v1 with
                the two auxiliary towers, loss-weighted 0.3 as in the config)

TPU-first: NHWC layout (XLA's preferred conv layout on TPU), BatchNorm running
stats via the Module 'stats' convention, bottleneck convs sized to keep the MXU
busy. Channel counts stay multiples of 8/128 where it matters.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from .. import nn
from ..ops import conv as conv_ops
from ..ops import loss as L
from ..ops import pool as P


class LeNet(nn.Module):
    """conv-pool x2 + fc — light_mnist analog. Input [B, 28, 28, 1]."""

    def __init__(self, classes: int = 10):
        super().__init__()
        self.c1 = nn.Conv2D(1, 20, 5, act="relu")
        self.c2 = nn.Conv2D(20, 50, 5, act="relu")
        self.fc1 = nn.Linear(4 * 4 * 50, 500, act="relu")
        self.fc2 = nn.Linear(500, classes)

    def __call__(self, params, x, **kw):
        h = P.max_pool2d(self.c1(params["c1"], x), 2, 2)
        h = P.max_pool2d(self.c2(params["c2"], h), 2, 2)
        h = h.reshape(h.shape[0], -1)
        return self.fc2(params["fc2"], self.fc1(params["fc1"], h))

    def loss(self, params, x, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, x), labels))


class SmallNet(nn.Module):
    """cifar-quick: 3x(conv-pool) + fc (smallnet_mnist_cifar.py). [B,32,32,3]."""

    def __init__(self, classes: int = 10):
        super().__init__()
        self.c1 = nn.Conv2D(3, 32, 5, padding=2, act="relu")
        self.c2 = nn.Conv2D(32, 32, 5, padding=2, act="relu")
        self.c3 = nn.Conv2D(32, 64, 5, padding=2, act="relu")
        self.fc1 = nn.Linear(4 * 4 * 64, 64, act="relu")
        self.fc2 = nn.Linear(64, classes)

    def __call__(self, params, x, **kw):
        h = P.max_pool2d(self.c1(params["c1"], x), 2, 2)
        h = P.max_pool2d(self.c2(params["c2"], h), 2, 2)
        h = P.max_pool2d(self.c3(params["c3"], h), 2, 2)
        h = h.reshape(h.shape[0], -1)
        return self.fc2(params["fc2"], self.fc1(params["fc1"], h))

    def loss(self, params, x, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, x), labels))


class _ConvBN(nn.Module):
    def __init__(self, cin, cout, k, stride=1, padding=0, act=None):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                              bias=False)
        self.bn = nn.BatchNorm(cout)
        self.act = act

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.conv(params["conv"], x)
        h = self.bn(params["bn"], h, train=train, mutable=mutable)
        return self.act(h) if self.act else h


class VGG(nn.Module):
    """VGG-16 (vgg.py cfg [2,2,3,3,3] conv blocks + 2x512 fc)."""

    def __init__(self, classes: int = 10, in_ch: int = 3, width_mult: float = 1.0):
        super().__init__()
        cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
        c = in_ch
        for i, (n, ch) in enumerate(cfg):
            ch = max(8, int(ch * width_mult))
            for j in range(n):
                setattr(self, f"b{i}_{j}", _ConvBN(c, ch, 3, padding=1,
                                                   act=jax.nn.relu))
                c = ch
        self.cfg = [n for n, _ in cfg]
        self.fc1 = nn.Linear(c, 512, act="relu")
        self.fc2 = nn.Linear(512, 512, act="relu")
        self.out = nn.Linear(512, classes)

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = x
        for i, n in enumerate(self.cfg):
            for j in range(n):
                m = getattr(self, f"b{i}_{j}")
                h = m(params[f"b{i}_{j}"], h, train=train, mutable=mutable)
            h = P.max_pool2d(h, 2, 2)
        h = P.global_avg_pool2d(h)
        h = self.fc1(params["fc1"], h)
        h = self.fc2(params["fc2"], h)
        return self.out(params["out"], h)

    def loss(self, params, x, labels, train=False, mutable=None):
        logits = self(params, x, train=train, mutable=mutable)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))


class _Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 with projection shortcut (resnet.py bottleneck)."""

    def __init__(self, cin, planes, stride=1):
        super().__init__()
        cout = planes * 4
        self.a = _ConvBN(cin, planes, 1, act=jax.nn.relu)
        self.b = _ConvBN(planes, planes, 3, stride=stride, padding=1,
                         act=jax.nn.relu)
        self.c = _ConvBN(planes, cout, 1)
        self.proj = (None if (cin == cout and stride == 1)
                     else _ConvBN(cin, cout, 1, stride=stride))

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.a(params["a"], x, train=train, mutable=mutable)
        h = self.b(params["b"], h, train=train, mutable=mutable)
        h = self.c(params["c"], h, train=train, mutable=mutable)
        s = (x if self.proj is None
             else self.proj(params["proj"], x, train=train, mutable=mutable))
        return jax.nn.relu(h + s)


class _BasicBlock(nn.Module):
    def __init__(self, cin, planes, stride=1):
        super().__init__()
        self.a = _ConvBN(cin, planes, 3, stride=stride, padding=1,
                         act=jax.nn.relu)
        self.b = _ConvBN(planes, planes, 3, padding=1)
        self.proj = (None if (cin == planes and stride == 1)
                     else _ConvBN(cin, planes, 1, stride=stride))

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.a(params["a"], x, train=train, mutable=mutable)
        h = self.b(params["b"], h, train=train, mutable=mutable)
        s = (x if self.proj is None
             else self.proj(params["proj"], x, train=train, mutable=mutable))
        return jax.nn.relu(h + s)


_RESNET_CFG = {
    18: (_BasicBlock, [2, 2, 2, 2], 1),
    34: (_BasicBlock, [3, 4, 6, 3], 1),
    50: (_Bottleneck, [3, 4, 6, 3], 4),
    101: (_Bottleneck, [3, 4, 23, 3], 4),
    152: (_Bottleneck, [3, 8, 36, 3], 4),
}


class ResNet(nn.Module):
    """ResNet-N for ImageNet-shaped input (resnet.py layer_num param).

    width_mult shrinks channels for tiny tests; stem `small_input=True` swaps
    the 7x7/s2+pool stem for 3x3/s1 (cifar-style).
    """

    def __init__(self, depth: int = 50, classes: int = 1000, in_ch: int = 3,
                 width_mult: float = 1.0, small_input: bool = False):
        super().__init__()
        block, counts, expansion = _RESNET_CFG[depth]
        w = lambda ch: max(8, int(ch * width_mult))
        self.small_input = small_input
        if small_input:
            self.stem = _ConvBN(in_ch, w(64), 3, stride=1, padding=1,
                                act=jax.nn.relu)
        else:
            # nn.Conv2D executes the 7x7/s2 stem via the exact
            # space-to-depth rewrite (MXU contraction 192 instead of 3 —
            # docs/design/conv_mfu.md, ops/conv.py::conv7s2)
            self.stem = _ConvBN(in_ch, w(64), 7, stride=2, padding=3,
                                act=jax.nn.relu)
        c = w(64)
        self.layer_names: List[str] = []
        for li, (planes, n) in enumerate(zip([64, 128, 256, 512], counts)):
            for bi in range(n):
                stride = 2 if (bi == 0 and li > 0) else 1
                blk = block(c, w(planes), stride)
                name = f"layer{li}_{bi}"
                setattr(self, name, blk)
                self.layer_names.append(name)
                c = w(planes) * expansion
        self.head = nn.Linear(c, classes)

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.stem(params["stem"], x, train=train, mutable=mutable)
        if not self.small_input:
            h = P.max_pool2d(h, 3, 2, padding=1)
        for name in self.layer_names:
            h = getattr(self, name)(params[name], h, train=train, mutable=mutable)
        h = P.global_avg_pool2d(h)
        return self.head(params["head"], h)

    def loss(self, params, x, labels, train=False, mutable=None):
        logits = self(params, x, train=train, mutable=mutable)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))


def resnet50(classes: int = 1000, **kw) -> ResNet:
    return ResNet(50, classes, **kw)


class AlexNet(nn.Module):
    """AlexNet (benchmark/paddle/image/alexnet.py): 5 convs with LRN after
    the first two, 3 pools, two dropout-4096 fcs. Input [B, 224, 224, 3].

    ``rng=None`` skips dropout (deterministic eval); pass a PRNG key and
    train=True for the reference's training configuration.
    """

    def __init__(self, classes: int = 1000, in_ch: int = 3):
        super().__init__()
        self.c1 = nn.Conv2D(in_ch, 96, 11, stride=4, padding=2, act="relu")
        self.c2 = nn.Conv2D(96, 256, 5, padding=2, act="relu")
        self.c3 = nn.Conv2D(256, 384, 3, padding=1, act="relu")
        self.c4 = nn.Conv2D(384, 384, 3, padding=1, act="relu")
        self.c5 = nn.Conv2D(384, 256, 3, padding=1, act="relu")
        self.fc1 = nn.Linear(6 * 6 * 256, 4096, act="relu")
        self.fc2 = nn.Linear(4096, 4096, act="relu")
        self.out = nn.Linear(4096, classes)

    def __call__(self, params, x, train=False, rng=None, **kw):
        from ..ops.norm import lrn
        from ..ops.random import dropout
        h = self.c1(params["c1"], x)
        h = P.max_pool2d(lrn(h), 3, 2)
        h = self.c2(params["c2"], h)
        h = P.max_pool2d(lrn(h), 3, 2)
        h = self.c3(params["c3"], h)
        h = self.c4(params["c4"], h)
        h = P.max_pool2d(self.c5(params["c5"], h), 3, 2)
        h = h.reshape(h.shape[0], -1)
        h = self.fc1(params["fc1"], h)
        if train and rng is not None:
            r1, r2 = jax.random.split(rng)
            h = dropout(h, 0.5, r1)
        h = self.fc2(params["fc2"], h)
        if train and rng is not None:
            h = dropout(h, 0.5, r2)
        return self.out(params["out"], h)

    def loss(self, params, x, labels, train=False, rng=None):
        logits = self(params, x, train=train, rng=rng)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))


class _Inception(nn.Module):
    """One inception-v1 block (googlenet.py inception()): 1x1 / 1x1-3x3 /
    1x1-5x5 / pool-1x1 branches, channel-concatenated (NHWC)."""

    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Conv2D(cin, c1, 1, act="relu")
        self.b3r = nn.Conv2D(cin, c3r, 1, act="relu")
        self.b3 = nn.Conv2D(c3r, c3, 3, padding=1, act="relu")
        self.b5r = nn.Conv2D(cin, c5r, 1, act="relu")
        self.b5 = nn.Conv2D(c5r, c5, 5, padding=2, act="relu")
        self.bp = nn.Conv2D(cin, proj, 1, act="relu")
        self.cout = c1 + c3 + c5 + proj

    def __call__(self, params, x, **kw):
        # the three 1x1 branches reading x directly (b1, b3-reduce,
        # b5-reduce) run as ONE conv with trace-time weight concat — same
        # math per branch, one HBM pass over x instead of three (the 1x1
        # convs at inception's spatial sizes are bandwidth-bound,
        # docs/design/conv_mfu.md)
        w = jnp.concatenate([params["b1"]["w"], params["b3r"]["w"],
                             params["b5r"]["w"]], axis=-1)
        bias = jnp.concatenate([params["b1"]["b"], params["b3r"]["b"],
                                params["b5r"]["b"]])
        fused = jax.nn.relu(conv_ops.conv2d(x, w) + bias)
        c1 = params["b1"]["w"].shape[-1]
        c3r = params["b3r"]["w"].shape[-1]
        a = fused[..., :c1]
        b = self.b3(params["b3"], fused[..., c1:c1 + c3r])
        c = self.b5(params["b5"], fused[..., c1 + c3r:])
        d = self.bp(params["bp"], P.max_pool2d(x, 3, 1, padding=1))
        return jnp.concatenate([a, b, c, d], axis=-1)


class _AuxHead(nn.Module):
    """GoogleNet auxiliary classifier (googlenet.py o1/o2 towers)."""

    def __init__(self, cin, classes):
        super().__init__()
        self.conv = nn.Conv2D(cin, 128, 1, act="relu")
        self.fc = nn.Linear(4 * 4 * 128, 1024, act="relu")
        self.out = nn.Linear(1024, classes)

    def __call__(self, params, x, train=False, rng=None, **kw):
        from ..ops.random import dropout
        h = P.avg_pool2d(x, 5, 3)
        h = self.conv(params["conv"], h)
        h = h.reshape(h.shape[0], -1)
        h = self.fc(params["fc"], h)
        if train and rng is not None:
            h = dropout(h, 0.7, rng)
        return self.out(params["out"], h)


class GoogleNet(nn.Module):
    """GoogLeNet / inception v1 (benchmark/paddle/image/googlenet.py).
    Input [B, 224, 224, 3]; train mode returns (main, aux1, aux2) logits,
    combined in :meth:`loss` with the config's 0.3 aux weights."""

    def __init__(self, classes: int = 1000, in_ch: int = 3):
        super().__init__()
        self.stem1 = nn.Conv2D(in_ch, 64, 7, stride=2, padding=3, act="relu")
        self.stem2 = nn.Conv2D(64, 64, 1, act="relu")
        self.stem3 = nn.Conv2D(64, 192, 3, padding=1, act="relu")
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.aux1 = _AuxHead(512, classes)   # after 4a
        self.aux2 = _AuxHead(528, classes)   # after 4d
        self.head = nn.Linear(1024, classes)

    def __call__(self, params, x, train=False, rng=None, **kw):
        from ..ops.norm import lrn
        from ..ops.random import dropout
        r1 = r2 = r3 = None
        if train and rng is not None:
            r1, r2, r3 = jax.random.split(rng, 3)
        # stem1 (7x7/s2) auto-routes through nn.Conv2D's s2d rewrite
        h = P.max_pool2d(self.stem1(params["stem1"], x), 3, 2, padding=1)
        h = lrn(h)
        h = self.stem3(params["stem3"], self.stem2(params["stem2"], h))
        h = P.max_pool2d(lrn(h), 3, 2, padding=1)
        h = self.i3b(params["i3b"], self.i3a(params["i3a"], h))
        h = P.max_pool2d(h, 3, 2, padding=1)
        h = self.i4a(params["i4a"], h)
        a1 = (self.aux1(params["aux1"], h, train=train, rng=r1)
              if train else None)
        h = self.i4c(params["i4c"], self.i4b(params["i4b"], h))
        h = self.i4d(params["i4d"], h)
        a2 = (self.aux2(params["aux2"], h, train=train, rng=r2)
              if train else None)
        h = self.i4e(params["i4e"], h)
        h = P.max_pool2d(h, 3, 2, padding=1)
        h = self.i5b(params["i5b"], self.i5a(params["i5a"], h))
        h = P.global_avg_pool2d(h)
        if train and rng is not None:
            h = dropout(h, 0.4, r3)
        main = self.head(params["head"], h)
        return (main, a1, a2) if train else main

    def loss(self, params, x, labels, train=False, rng=None):
        out = self(params, x, train=train, rng=rng)
        if train:
            main, a1, a2 = out
            l = jnp.mean(L.softmax_with_cross_entropy(main, labels))
            l = l + 0.3 * jnp.mean(L.softmax_with_cross_entropy(a1, labels))
            return l + 0.3 * jnp.mean(L.softmax_with_cross_entropy(a2, labels))
        return jnp.mean(L.softmax_with_cross_entropy(out, labels))
