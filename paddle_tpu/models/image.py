"""Image classification models — the benchmark/image family.

Analogs:
* LeNet       — ``v1_api_demo/mnist/light_mnist.py`` (conv mnist demo)
* VGG-16      — ``benchmark/paddle/image/vgg.py`` + networks.py vgg_16_network:468
* ResNet-N    — ``benchmark/paddle/image/resnet.py`` (layer_num 50/101/152)
* SmallNet    — ``benchmark/paddle/image/smallnet_mnist_cifar.py`` (cifar-quick)

TPU-first: NHWC layout (XLA's preferred conv layout on TPU), BatchNorm running
stats via the Module 'stats' convention, bottleneck convs sized to keep the MXU
busy. Channel counts stay multiples of 8/128 where it matters.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from .. import nn
from ..ops import loss as L
from ..ops import pool as P


class LeNet(nn.Module):
    """conv-pool x2 + fc — light_mnist analog. Input [B, 28, 28, 1]."""

    def __init__(self, classes: int = 10):
        super().__init__()
        self.c1 = nn.Conv2D(1, 20, 5, act="relu")
        self.c2 = nn.Conv2D(20, 50, 5, act="relu")
        self.fc1 = nn.Linear(4 * 4 * 50, 500, act="relu")
        self.fc2 = nn.Linear(500, classes)

    def __call__(self, params, x, **kw):
        h = P.max_pool2d(self.c1(params["c1"], x), 2, 2)
        h = P.max_pool2d(self.c2(params["c2"], h), 2, 2)
        h = h.reshape(h.shape[0], -1)
        return self.fc2(params["fc2"], self.fc1(params["fc1"], h))

    def loss(self, params, x, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, x), labels))


class SmallNet(nn.Module):
    """cifar-quick: 3x(conv-pool) + fc (smallnet_mnist_cifar.py). [B,32,32,3]."""

    def __init__(self, classes: int = 10):
        super().__init__()
        self.c1 = nn.Conv2D(3, 32, 5, padding=2, act="relu")
        self.c2 = nn.Conv2D(32, 32, 5, padding=2, act="relu")
        self.c3 = nn.Conv2D(32, 64, 5, padding=2, act="relu")
        self.fc1 = nn.Linear(4 * 4 * 64, 64, act="relu")
        self.fc2 = nn.Linear(64, classes)

    def __call__(self, params, x, **kw):
        h = P.max_pool2d(self.c1(params["c1"], x), 2, 2)
        h = P.max_pool2d(self.c2(params["c2"], h), 2, 2)
        h = P.max_pool2d(self.c3(params["c3"], h), 2, 2)
        h = h.reshape(h.shape[0], -1)
        return self.fc2(params["fc2"], self.fc1(params["fc1"], h))

    def loss(self, params, x, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, x), labels))


class _ConvBN(nn.Module):
    def __init__(self, cin, cout, k, stride=1, padding=0, act=None):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                              bias=False)
        self.bn = nn.BatchNorm(cout)
        self.act = act

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.conv(params["conv"], x)
        h = self.bn(params["bn"], h, train=train, mutable=mutable)
        return self.act(h) if self.act else h


class VGG(nn.Module):
    """VGG-16 (vgg.py cfg [2,2,3,3,3] conv blocks + 2x512 fc)."""

    def __init__(self, classes: int = 10, in_ch: int = 3, width_mult: float = 1.0):
        super().__init__()
        cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
        c = in_ch
        for i, (n, ch) in enumerate(cfg):
            ch = max(8, int(ch * width_mult))
            for j in range(n):
                setattr(self, f"b{i}_{j}", _ConvBN(c, ch, 3, padding=1,
                                                   act=jax.nn.relu))
                c = ch
        self.cfg = [n for n, _ in cfg]
        self.fc1 = nn.Linear(c, 512, act="relu")
        self.fc2 = nn.Linear(512, 512, act="relu")
        self.out = nn.Linear(512, classes)

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = x
        for i, n in enumerate(self.cfg):
            for j in range(n):
                m = getattr(self, f"b{i}_{j}")
                h = m(params[f"b{i}_{j}"], h, train=train, mutable=mutable)
            h = P.max_pool2d(h, 2, 2)
        h = P.global_avg_pool2d(h)
        h = self.fc1(params["fc1"], h)
        h = self.fc2(params["fc2"], h)
        return self.out(params["out"], h)

    def loss(self, params, x, labels, train=False, mutable=None):
        logits = self(params, x, train=train, mutable=mutable)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))


class _Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 with projection shortcut (resnet.py bottleneck)."""

    def __init__(self, cin, planes, stride=1):
        super().__init__()
        cout = planes * 4
        self.a = _ConvBN(cin, planes, 1, act=jax.nn.relu)
        self.b = _ConvBN(planes, planes, 3, stride=stride, padding=1,
                         act=jax.nn.relu)
        self.c = _ConvBN(planes, cout, 1)
        self.proj = (None if (cin == cout and stride == 1)
                     else _ConvBN(cin, cout, 1, stride=stride))

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.a(params["a"], x, train=train, mutable=mutable)
        h = self.b(params["b"], h, train=train, mutable=mutable)
        h = self.c(params["c"], h, train=train, mutable=mutable)
        s = (x if self.proj is None
             else self.proj(params["proj"], x, train=train, mutable=mutable))
        return jax.nn.relu(h + s)


class _BasicBlock(nn.Module):
    def __init__(self, cin, planes, stride=1):
        super().__init__()
        self.a = _ConvBN(cin, planes, 3, stride=stride, padding=1,
                         act=jax.nn.relu)
        self.b = _ConvBN(planes, planes, 3, padding=1)
        self.proj = (None if (cin == planes and stride == 1)
                     else _ConvBN(cin, planes, 1, stride=stride))

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.a(params["a"], x, train=train, mutable=mutable)
        h = self.b(params["b"], h, train=train, mutable=mutable)
        s = (x if self.proj is None
             else self.proj(params["proj"], x, train=train, mutable=mutable))
        return jax.nn.relu(h + s)


_RESNET_CFG = {
    18: (_BasicBlock, [2, 2, 2, 2], 1),
    34: (_BasicBlock, [3, 4, 6, 3], 1),
    50: (_Bottleneck, [3, 4, 6, 3], 4),
    101: (_Bottleneck, [3, 4, 23, 3], 4),
    152: (_Bottleneck, [3, 8, 36, 3], 4),
}


class ResNet(nn.Module):
    """ResNet-N for ImageNet-shaped input (resnet.py layer_num param).

    width_mult shrinks channels for tiny tests; stem `small_input=True` swaps
    the 7x7/s2+pool stem for 3x3/s1 (cifar-style).
    """

    def __init__(self, depth: int = 50, classes: int = 1000, in_ch: int = 3,
                 width_mult: float = 1.0, small_input: bool = False):
        super().__init__()
        block, counts, expansion = _RESNET_CFG[depth]
        w = lambda ch: max(8, int(ch * width_mult))
        self.small_input = small_input
        self.stem = (_ConvBN(in_ch, w(64), 3, stride=1, padding=1, act=jax.nn.relu)
                     if small_input else
                     _ConvBN(in_ch, w(64), 7, stride=2, padding=3, act=jax.nn.relu))
        c = w(64)
        self.layer_names: List[str] = []
        for li, (planes, n) in enumerate(zip([64, 128, 256, 512], counts)):
            for bi in range(n):
                stride = 2 if (bi == 0 and li > 0) else 1
                blk = block(c, w(planes), stride)
                name = f"layer{li}_{bi}"
                setattr(self, name, blk)
                self.layer_names.append(name)
                c = w(planes) * expansion
        self.head = nn.Linear(c, classes)

    def __call__(self, params, x, train=False, mutable=None, **kw):
        h = self.stem(params["stem"], x, train=train, mutable=mutable)
        if not self.small_input:
            h = P.max_pool2d(h, 3, 2, padding=1)
        for name in self.layer_names:
            h = getattr(self, name)(params[name], h, train=train, mutable=mutable)
        h = P.global_avg_pool2d(h)
        return self.head(params["head"], h)

    def loss(self, params, x, labels, train=False, mutable=None):
        logits = self(params, x, train=train, mutable=mutable)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))


def resnet50(classes: int = 1000, **kw) -> ResNet:
    return ResNet(50, classes, **kw)
