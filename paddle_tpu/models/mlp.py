"""MNIST MLP — the minimum end-to-end model.

Analog of the reference's acceptance test
``python/paddle/v2/fluid/tests/book/test_recognize_digits_mlp.py`` (two 128-unit relu
hidden layers + softmax-10) and the v1 demo ``v1_api_demo/mnist/``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..ops import loss as L


class MnistMLP(nn.Module):
    def __init__(self, in_dim: int = 784, hidden: int = 128, classes: int = 10):
        super().__init__()
        self.fc1 = nn.Linear(in_dim, hidden, act="relu")
        self.fc2 = nn.Linear(hidden, hidden, act="relu")
        self.out = nn.Linear(hidden, classes)

    def __call__(self, params, x, **kw):
        h = self.fc1(params["fc1"], x)
        h = self.fc2(params["fc2"], h)
        return self.out(params["out"], h)  # logits

    def loss(self, params, x, labels):
        logits = self(params, x)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))

    def accuracy(self, params, x, labels):
        logits = self(params, x)
        return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
