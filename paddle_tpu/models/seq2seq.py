"""Attention seq2seq NMT — the machine-translation flagship.

Analog of the reference's seq2seq stack:
* encoder-decoder with additive attention: ``trainer_config_helpers/networks.py``
  simple_attention:654ff + gru_step as used by the wmt14 demo configs.
* training: per-step cross-entropy over the target sequence.
* generation: beam search — gen-1 RecurrentGradientMachine::beamSearch
  (RecurrentGradientMachine.cpp:1020) / gen-2 beam_search_op.cc — here the
  on-device masked top-k decode of ops/beam_search.py.

TPU-first: the encoder is a bidirectional GRU whose gate projections batch into
single MXU matmuls; the decoder step is a pure function reused by (a) a
lax.scan with teacher forcing for training and (b) the beam-search scan for
inference — one definition, two schedules, no per-step frame cloning.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..core.lod import SeqBatch, sequence_mask
from ..nn.initializer import uniform, zeros
from ..ops import beam_search as BS
from ..ops import rnn as R


class DecoderState(NamedTuple):
    h: jax.Array          # [B, H] GRU hidden
    enc: jax.Array        # [B, S, 2H] encoder states (static per sequence)
    enc_proj: jax.Array   # [B, S, H] att_enc(enc), hoisted out of the decode
    #                       loop (XLA does not LICM large ops across scan
    #                       iterations; the v2 DSL passes the same thing as a
    #                       StaticInput)
    enc_mask: jax.Array   # [B, S]


class AttentionSeq2Seq(nn.Module):
    def __init__(self, src_vocab: int, trg_vocab: int, embed_dim: int = 128,
                 hidden: int = 128):
        super().__init__()
        H = hidden
        self.hidden = H
        self.embed_dim = embed_dim
        self.src_embed = nn.Embedding(src_vocab, embed_dim)
        self.trg_embed = nn.Embedding(trg_vocab, embed_dim)
        # bidirectional GRU encoder
        for d in ("f", "b"):
            self.param(f"enc_w_{d}", (embed_dim, 3 * H), uniform(-0.08, 0.08))
            self.param(f"enc_u_{d}", (H, 3 * H), uniform(-0.08, 0.08))
            self.param(f"enc_b_{d}", (3 * H,), zeros)
        # decoder init from encoder backward state (networks.py decoder boot)
        self.init_fc = nn.Linear(H, H, act="tanh")
        # additive attention (simple_attention): score = v . tanh(We e + Wd d)
        self.att_enc = nn.Linear(2 * H, H, bias=False)
        self.att_dec = nn.Linear(H, H, bias=False)
        self.param("att_v", (H,), uniform(-0.08, 0.08))
        # decoder GRU: input [embed + context 2H]
        self.param("dec_w", (embed_dim + 2 * H, 3 * H), uniform(-0.08, 0.08))
        self.param("dec_u", (H, 3 * H), uniform(-0.08, 0.08))
        self.param("dec_b", (3 * H,), zeros)
        self.out = nn.Linear(H, trg_vocab)

    # -- encoder -----------------------------------------------------------
    def encode(self, params, src: SeqBatch) -> DecoderState:
        x = self.src_embed(params["src_embed"], src.data)
        hf, _ = R.gru(x, src.lengths, params["enc_w_f"], params["enc_u_f"],
                      params["enc_b_f"])
        hb, last_b = R.gru(x, src.lengths, params["enc_w_b"], params["enc_u_b"],
                           params["enc_b_b"], reverse=True)
        enc = jnp.concatenate([hf, hb], axis=-1)                 # [B, S, 2H]
        h0 = self.init_fc(params["init_fc"], last_b)
        mask = sequence_mask(src.lengths, src.max_len)
        enc_proj = self.att_enc(params["att_enc"], enc)          # hoisted
        return DecoderState(h0, enc, enc_proj, mask)

    # -- one decoder step (shared by train & beam search) -------------------
    def attend(self, params, h, enc, enc_proj, enc_mask):
        score = jnp.einsum(
            "bsh,h->bs",
            jnp.tanh(enc_proj + self.att_dec(params["att_dec"], h)[:, None, :]),
            params["att_v"])
        score = jnp.where(enc_mask > 0, score, -1e30)
        alpha = jax.nn.softmax(score, axis=-1)
        return jnp.einsum("bs,bsh->bh", alpha, enc)              # context [B, 2H]

    def cell_step(self, params, state: DecoderState, token_embed,
                  embed_proj=None):
        """Advance the decoder GRU one token; no output projection.

        dec_w splits into its embedding and context halves (identical math
        to concat-then-matmul), so teacher forcing can feed a per-step slice
        of the WHOLE-sequence embedding projection (one MXU pass) and only
        the context half stays in the sequential loop.
        """
        ctx = self.attend(params, state.h, state.enc, state.enc_proj,
                          state.enc_mask)
        e_dim = self.embed_dim
        if embed_proj is None:
            embed_proj = token_embed @ params["dec_w"][:e_dim]
        xw = embed_proj + ctx @ params["dec_w"][e_dim:]
        h = R.gru_cell(xw, state.h, params["dec_u"], params["dec_b"])
        return DecoderState(h, state.enc, state.enc_proj, state.enc_mask)

    def decode_step(self, params, state: DecoderState, token_embed):
        new_state = self.cell_step(params, state, token_embed)
        logits = self.out(params["out"], new_state.h)
        return logits, new_state

    # -- training ----------------------------------------------------------
    def __call__(self, params, src: SeqBatch, trg_in: SeqBatch, **kw):
        """Teacher-forced logits [B, T, V].

        TPU mapping: the scan carries ONLY the [B, H] hidden; the embedding
        input projection for all T steps is one batched matmul before the
        scan and the vocab output projection is one [B*T, H] x [H, V] matmul
        after it — the big-matmul FLOPs never serialize through the
        recurrence.
        """
        state = self.encode(params, src)
        emb = self.trg_embed(params["trg_embed"], trg_in.data)   # [B, T, E]
        E = emb.shape[-1]
        embw = emb @ params["dec_w"][:E]                         # [B, T, 3H]

        def step(h, ew_t):
            s = self.cell_step(
                params,
                DecoderState(h, state.enc, state.enc_proj, state.enc_mask),
                token_embed=None, embed_proj=ew_t)
            return s.h, s.h

        _, hs = jax.lax.scan(step, state.h, jnp.swapaxes(embw, 0, 1))
        hs = jnp.swapaxes(hs, 0, 1)                              # [B, T, H]
        return self.out(params["out"], hs)

    def loss(self, params, src: SeqBatch, trg_in: SeqBatch, trg_out: SeqBatch):
        logits = self(params, src, trg_in)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, trg_out.data[..., None], axis=-1)[..., 0]
        mask = trg_out.mask()
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    # -- inference ---------------------------------------------------------
    def generate(self, params, src: SeqBatch, *, beam_size: int = 4,
                 max_len: int = 32, bos_id: int = 0, eos_id: int = 1,
                 length_penalty: float = 0.0) -> Tuple[jax.Array, jax.Array]:
        """Beam-search decode. Returns (tokens [B, K, max_len], scores [B, K])."""
        state = self.encode(params, src)
        vocab = params["out"]["w"].shape[1]

        def step_fn(cell, tokens):
            emb = self.trg_embed(params["trg_embed"], tokens)
            logits, new_cell = self.decode_step(params, cell, emb)
            return jax.nn.log_softmax(logits), new_cell

        return BS.beam_search(
            state, step_fn, batch_size=src.batch_size, beam_size=beam_size,
            max_len=max_len, vocab_size=vocab, bos_id=bos_id, eos_id=eos_id,
            length_penalty=length_penalty)

    def greedy_generate(self, params, src: SeqBatch, *, max_len: int = 32,
                        bos_id: int = 0, eos_id: int = 1):
        state = self.encode(params, src)
        vocab = params["out"]["w"].shape[1]

        def step_fn(cell, tokens):
            emb = self.trg_embed(params["trg_embed"], tokens)
            logits, new_cell = self.decode_step(params, cell, emb)
            return jax.nn.log_softmax(logits), new_cell

        return BS.greedy_search(state, step_fn, batch_size=src.batch_size,
                                max_len=max_len, bos_id=bos_id, eos_id=eos_id)
