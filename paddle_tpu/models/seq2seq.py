"""Attention seq2seq NMT — the machine-translation flagship.

Analog of the reference's seq2seq stack:
* encoder-decoder with additive attention: ``trainer_config_helpers/networks.py``
  simple_attention:654ff + gru_step as used by the wmt14 demo configs.
* training: per-step cross-entropy over the target sequence.
* generation: beam search — gen-1 RecurrentGradientMachine::beamSearch
  (RecurrentGradientMachine.cpp:1020) / gen-2 beam_search_op.cc — here the
  on-device masked top-k decode of ops/beam_search.py.

TPU-first: the encoder is a bidirectional GRU whose gate projections batch into
single MXU matmuls; the decoder step is a pure function reused by (a) a
lax.scan with teacher forcing for training and (b) the beam-search scan for
inference — one definition, two schedules, no per-step frame cloning.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..core.lod import SeqBatch, sequence_mask
from ..nn.initializer import uniform, zeros
from ..ops import beam_search as BS
from ..ops import rnn as R


class DecoderState(NamedTuple):
    h: jax.Array          # [B, H] GRU hidden
    enc: jax.Array        # [B, S, 2H] encoder states (static per sequence)
    enc_mask: jax.Array   # [B, S]


class AttentionSeq2Seq(nn.Module):
    def __init__(self, src_vocab: int, trg_vocab: int, embed_dim: int = 128,
                 hidden: int = 128):
        super().__init__()
        H = hidden
        self.hidden = H
        self.src_embed = nn.Embedding(src_vocab, embed_dim)
        self.trg_embed = nn.Embedding(trg_vocab, embed_dim)
        # bidirectional GRU encoder
        for d in ("f", "b"):
            self.param(f"enc_w_{d}", (embed_dim, 3 * H), uniform(-0.08, 0.08))
            self.param(f"enc_u_{d}", (H, 3 * H), uniform(-0.08, 0.08))
            self.param(f"enc_b_{d}", (3 * H,), zeros)
        # decoder init from encoder backward state (networks.py decoder boot)
        self.init_fc = nn.Linear(H, H, act="tanh")
        # additive attention (simple_attention): score = v . tanh(We e + Wd d)
        self.att_enc = nn.Linear(2 * H, H, bias=False)
        self.att_dec = nn.Linear(H, H, bias=False)
        self.param("att_v", (H,), uniform(-0.08, 0.08))
        # decoder GRU: input [embed + context 2H]
        self.param("dec_w", (embed_dim + 2 * H, 3 * H), uniform(-0.08, 0.08))
        self.param("dec_u", (H, 3 * H), uniform(-0.08, 0.08))
        self.param("dec_b", (3 * H,), zeros)
        self.out = nn.Linear(H, trg_vocab)

    # -- encoder -----------------------------------------------------------
    def encode(self, params, src: SeqBatch) -> DecoderState:
        x = self.src_embed(params["src_embed"], src.data)
        hf, _ = R.gru(x, src.lengths, params["enc_w_f"], params["enc_u_f"],
                      params["enc_b_f"])
        hb, last_b = R.gru(x, src.lengths, params["enc_w_b"], params["enc_u_b"],
                           params["enc_b_b"], reverse=True)
        enc = jnp.concatenate([hf, hb], axis=-1)                 # [B, S, 2H]
        h0 = self.init_fc(params["init_fc"], last_b)
        mask = sequence_mask(src.lengths, src.max_len)
        return DecoderState(h0, enc, mask)

    # -- one decoder step (shared by train & beam search) -------------------
    def attend(self, params, h, enc, enc_mask):
        score = jnp.einsum(
            "bsh,h->bs",
            jnp.tanh(self.att_enc(params["att_enc"], enc)
                     + self.att_dec(params["att_dec"], h)[:, None, :]),
            params["att_v"])
        score = jnp.where(enc_mask > 0, score, -1e30)
        alpha = jax.nn.softmax(score, axis=-1)
        return jnp.einsum("bs,bsh->bh", alpha, enc)              # context [B, 2H]

    def decode_step(self, params, state: DecoderState, token_embed):
        ctx = self.attend(params, state.h, state.enc, state.enc_mask)
        inp = jnp.concatenate([token_embed, ctx], axis=-1)
        xw = inp @ params["dec_w"]
        h = R.gru_cell(xw, state.h, params["dec_u"], params["dec_b"])
        logits = self.out(params["out"], h)
        return logits, DecoderState(h, state.enc, state.enc_mask)

    # -- training ----------------------------------------------------------
    def __call__(self, params, src: SeqBatch, trg_in: SeqBatch, **kw):
        """Teacher-forced logits [B, T, V]."""
        state = self.encode(params, src)
        emb = self.trg_embed(params["trg_embed"], trg_in.data)   # [B, T, E]

        def step(h, e_t):
            logits, new_state = self.decode_step(
                params, DecoderState(h, state.enc, state.enc_mask), e_t)
            return new_state.h, logits

        _, logits = jax.lax.scan(step, state.h, jnp.swapaxes(emb, 0, 1))
        return jnp.swapaxes(logits, 0, 1)

    def loss(self, params, src: SeqBatch, trg_in: SeqBatch, trg_out: SeqBatch):
        logits = self(params, src, trg_in)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, trg_out.data[..., None], axis=-1)[..., 0]
        mask = trg_out.mask()
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    # -- inference ---------------------------------------------------------
    def generate(self, params, src: SeqBatch, *, beam_size: int = 4,
                 max_len: int = 32, bos_id: int = 0, eos_id: int = 1,
                 length_penalty: float = 0.0) -> Tuple[jax.Array, jax.Array]:
        """Beam-search decode. Returns (tokens [B, K, max_len], scores [B, K])."""
        state = self.encode(params, src)
        vocab = params["out"]["w"].shape[1]

        def step_fn(cell, tokens):
            emb = self.trg_embed(params["trg_embed"], tokens)
            logits, new_cell = self.decode_step(params, cell, emb)
            return jax.nn.log_softmax(logits), new_cell

        return BS.beam_search(
            state, step_fn, batch_size=src.batch_size, beam_size=beam_size,
            max_len=max_len, vocab_size=vocab, bos_id=bos_id, eos_id=eos_id,
            length_penalty=length_penalty)

    def greedy_generate(self, params, src: SeqBatch, *, max_len: int = 32,
                        bos_id: int = 0, eos_id: int = 1):
        state = self.encode(params, src)
        vocab = params["out"]["w"].shape[1]

        def step_fn(cell, tokens):
            emb = self.trg_embed(params["trg_embed"], tokens)
            logits, new_cell = self.decode_step(params, cell, emb)
            return jax.nn.log_softmax(logits), new_cell

        return BS.greedy_search(state, step_fn, batch_size=src.batch_size,
                                max_len=max_len, bos_id=bos_id, eos_id=eos_id)
