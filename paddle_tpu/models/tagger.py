"""Sequence tagging — BiLSTM-CRF and linear-CRF.

Analogs of ``v1_api_demo/sequence_tagging/`` (linear_crf.py, rnn_crf.py) and the
CRF layer pair (gserver/layers/CRFLayer.cpp + LinearChainCRF.cpp; gen-2
operators/linear_chain_crf_op.cc + crf_decoding_op.cc). The conll05 SRL demo
(demo/semantic_role_labeling) uses the same shape.

Forward-backward and Viterbi run as lax.scan over time with masked steps
(ops/crf.py) — the dynamic program stays on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..core.lod import SeqBatch
from ..nn.initializer import uniform, zeros
from ..ops import crf as CRF
from ..ops import rnn as R


class _CRFHead(nn.Module):
    def __init__(self, num_tags: int):
        super().__init__()
        self.num_tags = num_tags
        self.param("start", (num_tags,), uniform(-0.05, 0.05))
        self.param("end", (num_tags,), uniform(-0.05, 0.05))
        self.param("trans", (num_tags, num_tags), uniform(-0.05, 0.05))

    def loss(self, params, emissions, tags, lengths):
        return jnp.mean(CRF.crf_loss(emissions, tags, lengths, params["start"],
                                     params["end"], params["trans"]))

    def decode(self, params, emissions, lengths):
        return CRF.crf_decode(emissions, lengths, params["start"],
                              params["end"], params["trans"])


class LinearCRFTagger(nn.Module):
    """embedding(+context window) -> linear -> CRF (linear_crf.py analog)."""

    def __init__(self, vocab_size: int, num_tags: int, embed_dim: int = 64):
        super().__init__()
        self.embed = nn.Embedding(vocab_size, embed_dim)
        self.fc = nn.Linear(embed_dim, num_tags)
        self.crf = _CRFHead(num_tags)

    def emissions(self, params, batch: SeqBatch):
        x = self.embed(params["embed"], batch.data)
        return self.fc(params["fc"], x)

    def loss(self, params, batch: SeqBatch, tags: SeqBatch):
        e = self.emissions(params, batch)
        return self.crf.loss(params["crf"], e, tags.data, batch.lengths)

    def decode(self, params, batch: SeqBatch):
        e = self.emissions(params, batch)
        return self.crf.decode(params["crf"], e, batch.lengths)


class BiLSTMCRFTagger(nn.Module):
    """embedding -> BiLSTM -> linear -> CRF (rnn_crf.py analog)."""

    def __init__(self, vocab_size: int, num_tags: int, embed_dim: int = 64,
                 hidden: int = 64):
        super().__init__()
        self.embed = nn.Embedding(vocab_size, embed_dim)
        for d in ("f", "b"):
            self.param(f"w_{d}", (embed_dim, 4 * hidden), uniform(-0.08, 0.08))
            self.param(f"u_{d}", (hidden, 4 * hidden), uniform(-0.08, 0.08))
            self.param(f"bias_{d}", (4 * hidden,), zeros)
        self.fc = nn.Linear(2 * hidden, num_tags)
        self.crf = _CRFHead(num_tags)

    def emissions(self, params, batch: SeqBatch):
        x = self.embed(params["embed"], batch.data)
        hf, _ = R.lstm(x, batch.lengths, params["w_f"], params["u_f"],
                       params["bias_f"], forget_bias=1.0)
        hb, _ = R.lstm(x, batch.lengths, params["w_b"], params["u_b"],
                       params["bias_b"], reverse=True, forget_bias=1.0)
        return self.fc(params["fc"], jnp.concatenate([hf, hb], axis=-1))

    def loss(self, params, batch: SeqBatch, tags: SeqBatch):
        e = self.emissions(params, batch)
        return self.crf.loss(params["crf"], e, tags.data, batch.lengths)

    def decode(self, params, batch: SeqBatch):
        e = self.emissions(params, batch)
        return self.crf.decode(params["crf"], e, batch.lengths)
