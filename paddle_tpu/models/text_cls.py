"""Text classification models — the quick_start / rnn-benchmark family.

Analogs of the reference's text-classification configs:
* LSTM net: ``benchmark/paddle/rnn/rnn.py`` (IMDB LSTM — the published
  LSTM baseline, benchmark/README.md:115-134) and
  ``v1_api_demo/quick_start/trainer_config.lstm.py``.
* CNN net:  ``trainer_config_helpers/networks.py`` text_conv_pool +
  ``quick_start/trainer_config.cnn.py``.
* BiLSTM:   ``networks.py`` bidirectional_lstm (:553ff).

TPU-first notes: the input-to-hidden projection for all 4 LSTM gates is one
[B*T, D]x[D, 4H] matmul (MXU-sized), only the recurrence runs in a lax.scan;
padding is masked LoD-style (ops/rnn.py), so ragged batches cost one bucket's
padding, not a recompile.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core.lod import SeqBatch
from ..nn.initializer import uniform, zeros
from ..ops import loss as L
from ..ops import rnn as R
from ..ops import sequence as S


class LSTMTextCls(nn.Module):
    """embedding -> (stacked) LSTM -> max-pool over time -> softmax."""

    def __init__(self, vocab_size: int, embed_dim: int = 128, hidden: int = 128,
                 classes: int = 2, num_layers: int = 1):
        super().__init__()
        self.embed = nn.Embedding(vocab_size, embed_dim)
        self.num_layers = num_layers
        dims = [embed_dim] + [hidden] * num_layers
        for i in range(num_layers):
            self.param(f"w{i}", (dims[i], 4 * hidden), uniform(-0.08, 0.08))
            self.param(f"u{i}", (hidden, 4 * hidden), uniform(-0.08, 0.08))
            self.param(f"b{i}", (4 * hidden,), zeros)
        self.fc = nn.Linear(hidden, classes)

    def __call__(self, params, batch: SeqBatch, **kw):
        x = self.embed(params["embed"], batch.data)         # [B, T, E]
        h = x
        for i in range(self.num_layers):
            h, _ = R.lstm(h, batch.lengths, params[f"w{i}"], params[f"u{i}"],
                          params[f"b{i}"], forget_bias=1.0)
        pooled = S.sequence_pool(h, batch.lengths, "max")
        return self.fc(params["fc"], pooled)                # logits

    def loss(self, params, batch: SeqBatch, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, batch), labels))


class BiLSTMTextCls(nn.Module):
    """networks.py bidirectional_lstm analog: fwd+bwd LSTM, concat last states."""

    def __init__(self, vocab_size: int, embed_dim: int = 128, hidden: int = 128,
                 classes: int = 2):
        super().__init__()
        self.embed = nn.Embedding(vocab_size, embed_dim)
        for d in ("f", "b"):
            self.param(f"w_{d}", (embed_dim, 4 * hidden), uniform(-0.08, 0.08))
            self.param(f"u_{d}", (hidden, 4 * hidden), uniform(-0.08, 0.08))
            self.param(f"bias_{d}", (4 * hidden,), zeros)
        self.fc = nn.Linear(2 * hidden, classes)

    def __call__(self, params, batch: SeqBatch, **kw):
        x = self.embed(params["embed"], batch.data)
        hf, _ = R.lstm(x, batch.lengths, params["w_f"], params["u_f"],
                       params["bias_f"], forget_bias=1.0)
        hb, _ = R.lstm(x, batch.lengths, params["w_b"], params["u_b"],
                       params["bias_b"], reverse=True, forget_bias=1.0)
        h = jnp.concatenate([S.sequence_last_step(hf, batch.lengths),
                             S.sequence_first_step(hb, batch.lengths)], axis=-1)
        return self.fc(params["fc"], h)

    def loss(self, params, batch: SeqBatch, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, batch), labels))


class ConvTextCls(nn.Module):
    """sequence_conv + max pool (networks.py text_conv_pool / CNN quick start)."""

    def __init__(self, vocab_size: int, embed_dim: int = 128, num_filters: int = 128,
                 context_len: int = 3, classes: int = 2):
        super().__init__()
        self.embed = nn.Embedding(vocab_size, embed_dim)
        self.context_len = context_len
        self.param("conv_w", (context_len * embed_dim, num_filters),
                   uniform(-0.08, 0.08))
        self.param("conv_b", (num_filters,), zeros)
        self.fc = nn.Linear(num_filters, classes)

    def __call__(self, params, batch: SeqBatch, **kw):
        x = self.embed(params["embed"], batch.data)
        h = S.sequence_conv(x, batch.lengths, params["conv_w"],
                            context_start=-(self.context_len // 2),
                            context_length=self.context_len)
        h = jax.nn.relu(h + params["conv_b"])
        pooled = S.sequence_pool(h, batch.lengths, "max")
        return self.fc(params["fc"], pooled)

    def loss(self, params, batch: SeqBatch, labels):
        return jnp.mean(L.softmax_with_cross_entropy(self(params, batch), labels))
