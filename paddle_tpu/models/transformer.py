"""Decoder-only Transformer language model — the flash-attention kernels'
model-level consumer.

No 2017 analog in the reference (its deepest sequence model is the
attention seq2seq, SURVEY §3.4); this is the modern-extension model family
the repo's Pallas flash attention (ops/pallas_kernels.py — fwd + dq/dkv
backward, no [T, T] matrix in HBM) and ring attention were built for.
TPU-first choices: pre-LN blocks (stable in bf16), one fused qkv matmul per
block, attention as [B, T, H, Dh] through the flash kernel (causal),
whole-model bf16 compute with f32 master params handled by callers, and a
``seq_mesh`` option that runs the same blocks with ring attention over a
``seq`` axis for long-context sharding.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn.initializer import normal
from ..ops import pallas_kernels as pk


class TransformerBlock(nn.Module):
    def __init__(self, d_model: int, n_heads: int, d_ff: int,
                 init_std: float = 0.02):
        super().__init__()
        assert d_model % n_heads == 0
        self.n_heads = n_heads
        self.d_head = d_model // n_heads
        self.ln1 = nn.LayerNorm(d_model)
        self.qkv = nn.Linear(d_model, 3 * d_model,
                             w_init=normal(0.0, init_std))
        self.proj = nn.Linear(d_model, d_model, w_init=normal(0.0, init_std))
        self.ln2 = nn.LayerNorm(d_model)
        self.mlp_in = nn.Linear(d_model, d_ff, act="gelu",
                                w_init=normal(0.0, init_std))
        self.mlp_out = nn.Linear(d_ff, d_model, w_init=normal(0.0, init_std))

    def attend(self, q, k, v, *, seq_axis: Optional[str] = None):
        if seq_axis is not None:
            from ..parallel.ring_attention import ring_attention
            return ring_attention(q, k, v, seq_axis, True)
        return pk.flash_attention(q, k, v, causal=True)

    def __call__(self, params, x, *, seq_axis: Optional[str] = None, **kw):
        B, T, D = x.shape
        h = self.ln1(params["ln1"], x)
        qkv = self.qkv(params["qkv"], h)                 # [B, T, 3D]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.n_heads, self.d_head)
        o = self.attend(q.reshape(shape), k.reshape(shape), v.reshape(shape),
                        seq_axis=seq_axis)
        x = x + self.proj(params["proj"], o.reshape(B, T, D).astype(x.dtype))
        h = self.ln2(params["ln2"], x)
        return x + self.mlp_out(params["mlp_out"],
                                self.mlp_in(params["mlp_in"], h))


class TransformerLM(nn.Module):
    """GPT-style LM: token + learned position embeddings, N pre-LN blocks,
    final LN, head tied to the token embedding (weight sharing)."""

    def __init__(self, vocab: int, d_model: int = 512, n_heads: int = 8,
                 n_layers: int = 6, d_ff: Optional[int] = None,
                 max_len: int = 1024, tie_head: bool = True,
                 remat: bool = False):
        super().__init__()
        d_ff = d_ff or 4 * d_model
        self.vocab, self.max_len, self.tie_head = vocab, max_len, tie_head
        # jax.checkpoint per block: activations rematerialize in the
        # backward instead of living across the whole depth — the
        # FLOPs-for-HBM trade long-context training needs
        self.remat = remat
        self.embed = nn.Embedding(vocab, d_model, w_init=normal(0.0, 0.02))
        self.param("pos_embed", (max_len, d_model), normal(0.0, 0.01))
        self.blocks = [TransformerBlock(d_model, n_heads, d_ff)
                       for _ in range(n_layers)]
        self.ln_f = nn.LayerNorm(d_model)
        if not tie_head:
            self.head = nn.Linear(d_model, vocab, bias=False,
                                  w_init=normal(0.0, 0.02))

    def __call__(self, params, ids, *, positions=None,
                 seq_axis: Optional[str] = None, **kw):
        """ids [B, T] -> logits [B, T, V].

        ``positions`` ([T] or [B, T]) overrides the default 0..T-1 — needed
        under sequence sharding, where each shard's local block starts at a
        non-zero global position.
        """
        B, T = ids.shape
        x = self.embed(params["embed"], ids)
        pos = (params["pos_embed"][:T] if positions is None
               else params["pos_embed"][positions])
        x = x + pos.astype(x.dtype)
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            if self.remat:
                x = jax.checkpoint(
                    lambda p, x, blk=blk: blk(p, x, seq_axis=seq_axis))(
                        params[f"blocks_{i}"], x)
            else:
                x = blk(params[f"blocks_{i}"], x, seq_axis=seq_axis)
        x = self.ln_f(params["ln_f"], x)
        if self.tie_head:
            return x @ params["embed"]["w"].T.astype(x.dtype)
        return self.head(params["head"], x)

    def shifted_loss(self, params, ids_in, targets, *, positions=None,
                     mask=None, seq_axis: Optional[str] = None):
        """CE over ALREADY-shifted (inputs, targets) pairs.

        This is the sequence-parallel entry point: shift GLOBALLY first
        (ids[:, :-1] / ids[:, 1:]), then shard ids_in/targets/positions/mask
        over the seq axis — per-shard shifting inside shard_map would drop
        each shard's last token and misalign every boundary. ``mask`` (same
        shape as targets) weights positions; the mask SUM is psum'd over
        ``seq_axis`` so the mean is global.
        """
        logits = self(params, ids_in, positions=positions, seq_axis=seq_axis)
        # lse - gold == -log_softmax[gold], without materializing the full
        # [B, T, V] log-prob tensor in f32 (the reductions fuse instead)
        l32 = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(l32, axis=-1)
        gold = jnp.take_along_axis(l32, targets[..., None], -1)[..., 0]
        nll = lse - gold
        if mask is None:
            mask = jnp.ones_like(nll)
        mask = mask.astype(nll.dtype)
        num = jnp.sum(nll * mask)
        den = jnp.sum(mask)
        if seq_axis is not None:
            num = jax.lax.psum(num, seq_axis)
            den = jax.lax.psum(den, seq_axis)
        return num / jnp.maximum(den, 1.0)

    def loss(self, params, ids, lengths=None, *,
             seq_axis: Optional[str] = None):
        """Next-token CE over positions < length-1 (true-token masking)."""
        if seq_axis is not None:
            raise ValueError(
                "loss() shifts ids internally, which is wrong per-shard "
                "under sequence sharding (each shard would drop its last "
                "token and misalign targets at shard boundaries); shift "
                "globally and use shifted_loss(ids[:, :-1], ids[:, 1:], "
                "positions=..., seq_axis=...) instead")
        targets = ids[:, 1:]
        if lengths is None:
            mask = None
        else:
            T = targets.shape[1]
            mask = (jnp.arange(T)[None, :] < (lengths - 1)[:, None])
        return self.shifted_loss(params, ids[:, :-1], targets, mask=mask)

    def generate_greedy(self, params, prompt, steps: int):
        """Greedy continuation: prompt [B, T0] -> [B, T0+steps] (full
        re-forward per step: correctness reference, not the serving path)."""
        ids = prompt
        for _ in range(steps):
            logits = self(params, ids[:, -self.max_len:])
            nxt = jnp.argmax(logits[:, -1], axis=-1)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        return ids
