"""Decoder-only Transformer language model — the flash-attention kernels'
model-level consumer.

No 2017 analog in the reference (its deepest sequence model is the
attention seq2seq, SURVEY §3.4); this is the modern-extension model family
the repo's Pallas flash attention (ops/pallas_kernels.py — fwd + dq/dkv
backward, no [T, T] matrix in HBM) and ring attention were built for.
TPU-first choices: pre-LN blocks (stable in bf16), one fused qkv matmul per
block, attention as [B, T, H, Dh] through the flash kernel (causal),
whole-model bf16 compute with f32 master params handled by callers, and a
``seq_mesh`` option that runs the same blocks with ring attention over a
``seq`` axis for long-context sharding.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from .. import obs
from ..nn.initializer import normal
from ..ops import pallas_kernels as pk


class TransformerBlock(nn.Module):
    def __init__(self, d_model: int, n_heads: int, d_ff: int,
                 init_std: float = 0.02, causal: bool = True):
        super().__init__()
        assert d_model % n_heads == 0
        self.n_heads = n_heads
        self.d_head = d_model // n_heads
        self.causal = causal
        self.ln1 = nn.LayerNorm(d_model)
        self.qkv = nn.Linear(d_model, 3 * d_model,
                             w_init=normal(0.0, init_std))
        self.proj = nn.Linear(d_model, d_model, w_init=normal(0.0, init_std))
        self.ln2 = nn.LayerNorm(d_model)
        self.mlp_in = nn.Linear(d_model, d_ff, act="gelu",
                                w_init=normal(0.0, init_std))
        self.mlp_out = nn.Linear(d_ff, d_model, w_init=normal(0.0, init_std))

    def attend(self, q, k, v, *, seq_axis: Optional[str] = None,
               kv_lens=None):
        if seq_axis is not None:
            if kv_lens is not None:
                raise NotImplementedError(
                    "per-sample kv_lens masking is not plumbed through ring "
                    "attention; pad variable-length batches before sequence "
                    "sharding or run without seq_axis")
            from ..parallel.ring_attention import ring_attention
            return ring_attention(q, k, v, seq_axis, self.causal)
        return pk.flash_attention(q, k, v, causal=self.causal,
                                  kv_lens=kv_lens)

    def heads(self, params, x):
        """q, k, v as [B, T, H, Dh] from one fused qkv matmul."""
        B, T, _ = x.shape
        qkv = self.qkv(params["qkv"], self.ln1(params["ln1"], x))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.n_heads, self.d_head)
        return q.reshape(shape), k.reshape(shape), v.reshape(shape)

    def finish(self, params, x, o):
        """Residual + projection + MLP after attention output ``o``."""
        B, T, D = x.shape
        x = x + self.proj(params["proj"], o.reshape(B, T, D).astype(x.dtype))
        h = self.ln2(params["ln2"], x)
        return x + self.mlp_out(params["mlp_out"],
                                self.mlp_in(params["mlp_in"], h))

    def __call__(self, params, x, *, seq_axis: Optional[str] = None,
                 return_kv: bool = False, kv_lens=None, **kw):
        q, k, v = self.heads(params, x)
        o = self.attend(q, k, v, seq_axis=seq_axis, kv_lens=kv_lens)
        out = self.finish(params, x, o)
        return (out, (k, v)) if return_kv else out


class TransformerLM(nn.Module):
    """GPT-style LM: token + learned position embeddings, N pre-LN blocks,
    final LN, head tied to the token embedding (weight sharing)."""

    def __init__(self, vocab: int, d_model: int = 512, n_heads: int = 8,
                 n_layers: int = 6, d_ff: Optional[int] = None,
                 max_len: int = 1024, tie_head: bool = True,
                 remat: bool = False):
        super().__init__()
        d_ff = d_ff or 4 * d_model
        self.vocab, self.max_len, self.tie_head = vocab, max_len, tie_head
        # jax.checkpoint per block: activations rematerialize in the
        # backward instead of living across the whole depth — the
        # FLOPs-for-HBM trade long-context training needs
        self.remat = remat
        self.embed = nn.Embedding(vocab, d_model, w_init=normal(0.0, 0.02))
        self.param("pos_embed", (max_len, d_model), normal(0.0, 0.01))
        self.blocks = [TransformerBlock(d_model, n_heads, d_ff)
                       for _ in range(n_layers)]
        self.ln_f = nn.LayerNorm(d_model)
        if not tie_head:
            self.head = nn.Linear(d_model, vocab, bias=False,
                                  w_init=normal(0.0, 0.02))

    def __call__(self, params, ids, *, positions=None,
                 seq_axis: Optional[str] = None, **kw):
        """ids [B, T] -> logits [B, T, V].

        ``positions`` ([T] or [B, T]) overrides the default 0..T-1 — needed
        under sequence sharding, where each shard's local block starts at a
        non-zero global position.
        """
        B, T = ids.shape
        x = self.embed(params["embed"], ids)
        pos = (params["pos_embed"][:T] if positions is None
               else params["pos_embed"][positions])
        x = x + pos.astype(x.dtype)
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            if self.remat:
                x = jax.checkpoint(
                    lambda p, x, blk=blk: blk(p, x, seq_axis=seq_axis))(
                        params[f"blocks_{i}"], x)
            else:
                x = blk(params[f"blocks_{i}"], x, seq_axis=seq_axis)
        x = self.ln_f(params["ln_f"], x)
        if self.tie_head:
            return x @ params["embed"]["w"].T.astype(x.dtype)
        return self.head(params["head"], x)

    def shifted_loss(self, params, ids_in, targets, *, positions=None,
                     mask=None, seq_axis: Optional[str] = None):
        """CE over ALREADY-shifted (inputs, targets) pairs.

        This is the sequence-parallel entry point: shift GLOBALLY first
        (ids[:, :-1] / ids[:, 1:]), then shard ids_in/targets/positions/mask
        over the seq axis — per-shard shifting inside shard_map would drop
        each shard's last token and misalign every boundary. ``mask`` (same
        shape as targets) weights positions; the mask SUM is psum'd over
        ``seq_axis`` so the mean is global.
        """
        logits = self(params, ids_in, positions=positions, seq_axis=seq_axis)
        # lse - gold == -log_softmax[gold], without materializing the full
        # [B, T, V] log-prob tensor in f32 (the reductions fuse instead)
        l32 = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(l32, axis=-1)
        gold = jnp.take_along_axis(l32, targets[..., None], -1)[..., 0]
        nll = lse - gold
        if mask is None:
            mask = jnp.ones_like(nll)
        mask = mask.astype(nll.dtype)
        num = jnp.sum(nll * mask)
        den = jnp.sum(mask)
        if seq_axis is not None:
            num = jax.lax.psum(num, seq_axis)
            den = jax.lax.psum(den, seq_axis)
        return num / jnp.maximum(den, 1.0)

    def loss(self, params, ids, lengths=None, *,
             seq_axis: Optional[str] = None):
        """Next-token CE over positions < length-1 (true-token masking)."""
        if seq_axis is not None:
            raise ValueError(
                "loss() shifts ids internally, which is wrong per-shard "
                "under sequence sharding (each shard would drop its last "
                "token and misalign targets at shard boundaries); shift "
                "globally and use shifted_loss(ids[:, :-1], ids[:, 1:], "
                "positions=..., seq_axis=...) instead")
        targets = ids[:, 1:]
        if lengths is None:
            mask = None
        else:
            T = targets.shape[1]
            mask = (jnp.arange(T)[None, :] < (lengths - 1)[:, None])
        return self.shifted_loss(params, ids[:, :-1], targets, mask=mask)

    def generate_greedy(self, params, prompt, steps: int):
        """Greedy continuation: prompt [B, T0] -> [B, T0+steps] (full
        re-forward per step: correctness reference, not the serving path —
        see :meth:`generate_cached`)."""
        ids = prompt
        for _ in range(steps):
            logits = self(params, ids[:, -self.max_len:])
            nxt = jnp.argmax(logits[:, -1], axis=-1)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        return ids

    # -- incremental decoding (the serving path) ---------------------------
    def prefill(self, params, prompt, lengths=None, *,
                kv_dtype: Optional[str] = None,
                pad_to: Optional[int] = None):
        """Run the prompt once, materializing per-layer KV caches padded to
        max_len. Returns (cell, last_logits [B, V]); cell carries the caches
        and the per-sample write position.

        ``lengths`` [B] (optional) makes the prompt batch RAGGED — prompts
        right-padded to a common T0. Each sample's write position starts at
        its true length and its returned logits are the ones at position
        length-1. Padded-tail cache rows briefly hold garbage k/v, but the
        decode mask (j <= pos) never reads a row past ``pos``, and each
        generation step overwrites row ``pos`` before advancing — so the
        garbage is overwritten strictly before it becomes readable. This is
        the slot-refill path of continuous batching (serving/batcher.py).

        ``kv_dtype="int8"`` stores the caches as symmetric int8 rows with
        per-(position, head) f32 scales (``k{i}_scale``/``v{i}_scale`` in
        the cell) — decode's HBM cache read halves; the prompt forward
        itself still runs full precision (the quantization error enters
        only through later cache READS; docs/design/kernels.md states the
        numerics contract).

        ``pad_to`` (default max_len) bounds the returned cache padding —
        the PAGED admission path (serving/paged.py) only scatters the
        first prompt-bucket rows into its page pool, and padding the
        transient cell to max_len would spike peak HBM to the pinned-pool
        size paging exists to avoid. Must be >= the prompt width; the
        dense decode paths keep the max_len default."""
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(None or 'int8')")
        B, T0 = prompt.shape
        limit = self.max_len if pad_to is None else min(pad_to, self.max_len)
        if limit < T0:
            raise ValueError(f"prefill cache limit {limit} (pad_to/max_len) "
                             f"is narrower than the prompt ({T0})")
        x = self.embed(params["embed"], prompt)
        x = x + params["pos_embed"][:T0].astype(x.dtype)
        if lengths is None:
            cell = {"pos": jnp.full((B,), T0, jnp.int32)}
        else:
            cell = {"pos": jnp.asarray(lengths, jnp.int32)}
        pad = limit - T0
        pad4 = ((0, 0), (0, pad), (0, 0), (0, 0))
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            q, k, v = blk.heads(params[f"blocks_{i}"], x)
            o = blk.attend(q, k, v)
            x = blk.finish(params[f"blocks_{i}"], x, o)
            if kv_dtype == "int8":
                k8, ks = pk.quantize_kv(k)
                v8, vs = pk.quantize_kv(v)
                cell[f"k{i}"] = jnp.pad(k8, pad4)
                cell[f"v{i}"] = jnp.pad(v8, pad4)
                # padded scales are 1.0 so dequant of (masked) garbage rows
                # stays finite
                cell[f"k{i}_scale"] = jnp.pad(
                    ks, ((0, 0), (0, pad), (0, 0)), constant_values=1.0)
                cell[f"v{i}_scale"] = jnp.pad(
                    vs, ((0, 0), (0, pad), (0, 0)), constant_values=1.0)
            else:
                cell[f"k{i}"] = jnp.pad(k, pad4)
                cell[f"v{i}"] = jnp.pad(v, pad4)
        x = self.ln_f(params["ln_f"], x)
        logits = (x @ params["embed"]["w"].T.astype(x.dtype)
                  if self.tie_head else self.head(params["head"], x))
        if lengths is None:
            return cell, logits[:, -1]
        return cell, logits[jnp.arange(B), cell["pos"] - 1]

    def _append_rows(self, cell, new_cell, i, k, v, pos):
        """Write this step's k/v rows ([B, S, H, Dh]) at pos..pos+S-1 and
        return the updated (kc, vc, k_scale, v_scale) cache views —
        quantizing the new rows when the cell carries an int8 cache."""
        quant = f"k{i}_scale" in cell
        upd = jax.vmap(lambda c, r, p: jax.lax.dynamic_update_slice(
            c, r, (p,) + (0,) * (c.ndim - 1)))
        if quant:
            k, ks = pk.quantize_kv(k)
            v, vs = pk.quantize_kv(v)
            ksc = upd(cell[f"k{i}_scale"], ks, pos)
            vsc = upd(cell[f"v{i}_scale"], vs, pos)
            new_cell[f"k{i}_scale"], new_cell[f"v{i}_scale"] = ksc, vsc
        else:
            ksc = vsc = None
        kc = upd(cell[f"k{i}"], k, pos)
        vc = upd(cell[f"v{i}"], v, pos)
        new_cell[f"k{i}"], new_cell[f"v{i}"] = kc, vc
        return kc, vc, ksc, vsc

    def decode_step(self, params, cell, tokens, *,
                    cache_len: Optional[int] = None,
                    attn_route: Optional[str] = None):
        """One incremental step: tokens [B] -> (logits [B, V], new cell).
        Attention reads the KV cache (masked to written positions) instead
        of re-running the prefix — O(T) per token instead of O(T^2).

        ``cache_len`` (static) bounds the cache READ to its first that-many
        entries: the cache is stored padded to max_len, but a step whose
        positions are all < cache_len only streams cache_len rows from HBM
        instead of max_len — the bucketed serving path (callers guarantee
        pos < cache_len; generate_cached's bucketing does).

        The cache read goes through the ONE auto-routing entry point
        ``ops.pallas_kernels.decode_attention`` (dense reference math for
        short reads / off-TPU, the per-sample Pallas kernel for long
        on-TPU reads; ``attn_route`` forces a route for tests). int8
        cells (prefill ``kv_dtype="int8"``) quantize the appended row and
        dequantize reads in-kernel."""
        pos = cell["pos"]                                  # [B]
        L = self.max_len if cache_len is None else min(cache_len,
                                                       self.max_len)
        x = self.embed(params["embed"], tokens[:, None])   # [B, 1, D]
        x = x + params["pos_embed"][pos][:, None, :].astype(x.dtype)
        new_cell = {"pos": pos + 1}
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            q, k, v = blk.heads(params[f"blocks_{i}"], x)  # [B, 1, H, Dh]
            kc, vc, ksc, vsc = self._append_rows(cell, new_cell, i,
                                                 k, v, pos)
            o = pk.decode_attention(
                q[:, 0], kc[:, :L], vc[:, :L], pos,
                scale=blk.d_head ** -0.5,
                k_scale=None if ksc is None else ksc[:, :L],
                v_scale=None if vsc is None else vsc[:, :L],
                route=attn_route)
            x = blk.finish(params[f"blocks_{i}"], x, o[:, None])
        x = self.ln_f(params["ln_f"], x)
        logits = (x @ params["embed"]["w"].T.astype(x.dtype)
                  if self.tie_head else self.head(params["head"], x))
        return logits[:, 0], new_cell

    def decode_step_paged(self, params, cell, tokens, tables, *,
                          attn_route: Optional[str] = None):
        """One incremental step against a PAGED cache: tokens [B] ->
        (logits [B, V], new cell). The cell holds per-layer page POOLS
        (``k{i}``/``v{i}`` [P, bs, H, Dh], plus ``k{i}_scale``/``v{i}_scale``
        [P, bs, H] when int8) shared by every request, and ``tables``
        [B, NB] names which pages hold each request's positions
        j*bs..(j+1)*bs-1 — HBM holds live tokens, not max_len padding
        (serving/paged.py owns allocation).

        The step's k/v row is appended at page ``tables[b, pos//bs]``, row
        ``pos % bs`` (callers guarantee the page exists and that live
        requests never share a page; the reserved null page 0 absorbs
        drained-slot writes), then the read goes through
        :func:`ops.pallas_kernels.paged_decode_attention` — the same
        masked-softmax formulation as the dense-row path, so paged and
        pinned greedy tokens agree bit-for-bit on the same cache contents.
        ``tables`` is sliced by the CALLER to the live read bound (NB
        pages), the paged twin of ``decode_step``'s ``cache_len``."""
        pos = cell["pos"]                                  # [B]
        B = tokens.shape[0]
        bs = cell["k0"].shape[1]
        page = jnp.take_along_axis(tables, (pos // bs)[:, None],
                                   axis=1)[:, 0]           # [B]
        row = pos % bs
        x = self.embed(params["embed"], tokens[:, None])   # [B, 1, D]
        x = x + params["pos_embed"][pos][:, None, :].astype(x.dtype)
        new_cell = {"pos": pos + 1}
        quant = "k0_scale" in cell
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            q, k, v = blk.heads(params[f"blocks_{i}"], x)  # [B, 1, H, Dh]
            k1, v1 = k[:, 0], v[:, 0]                      # [B, H, Dh]
            if quant:
                k1, ks = pk.quantize_kv(k1)
                v1, vs = pk.quantize_kv(v1)
                ksp = cell[f"k{i}_scale"].at[page, row].set(ks)
                vsp = cell[f"v{i}_scale"].at[page, row].set(vs)
                new_cell[f"k{i}_scale"], new_cell[f"v{i}_scale"] = ksp, vsp
            else:
                ksp = vsp = None
            kp = cell[f"k{i}"].at[page, row].set(
                k1.astype(cell[f"k{i}"].dtype))
            vp = cell[f"v{i}"].at[page, row].set(
                v1.astype(cell[f"v{i}"].dtype))
            new_cell[f"k{i}"], new_cell[f"v{i}"] = kp, vp
            o = pk.paged_decode_attention(
                q[:, 0], kp, vp, tables, pos,
                scale=blk.d_head ** -0.5,
                k_scale=ksp, v_scale=vsp, route=attn_route)
            x = blk.finish(params[f"blocks_{i}"], x, o[:, None])
        x = self.ln_f(params["ln_f"], x)
        logits = (x @ params["embed"]["w"].T.astype(x.dtype)
                  if self.tie_head else self.head(params["head"], x))
        return logits[:, 0], new_cell

    def prefill_paged(self, params, pools, tokens, offsets, lengths,
                      tables):
        """Prefill FROM AN OFFSET against pre-populated block tables — the
        prefix-cache admission path (serving/paged.py): each sample's
        first ``offsets[b]`` positions already sit in shared pool pages,
        so only the non-shared suffix ``tokens[b, :lengths[b]]`` runs the
        forward.

        tokens [B, S] int32 (right-padded suffixes); offsets/lengths [B]
        int32; tables [B, NB] int32 covering positions
        ``0 .. offsets[b] + lengths[b] - 1`` (entries past a sample's
        live pages point at the null page; callers guarantee suffix
        positions land in SLOT-OWNED pages — shared pages are never
        written). ``pools`` is the page-pool dict (``k{i}``/``v{i}``
        [P, bs, H, Dh], plus ``*_scale`` for int8). Returns
        (new pools, last logits [B, V] — logits at each sample's final
        suffix position, the admission's first-token source).

        Numerics: each layer scatters the suffix k/v rows into the pool
        (quantized for int8 pools), then attends q over the gathered
        per-sample view with the suffix's OWN rows overlaid at full
        precision — exactly the precision mix the dense admission prefill
        has (own-prompt attention full precision, only the cache READ
        quantized). The masked-softmax math mirrors
        ``ops.pallas_kernels._dense_attention``'s op order so a zero-
        offset suffix prefill reproduces the full-prefill formulation;
        attending the shared prefix re-reads the very rows the original
        prefill wrote. Garbage (padded i >= length, table nulls, stale
        CoW rows past the match) sits strictly above the causal mask
        ``j <= offset + i`` or is overlaid, and masked rows contribute
        exactly zero (``exp(-1e30 - m) == 0``)."""
        B, S = tokens.shape
        bs = pools["k0"].shape[1]
        NB = tables.shape[1]
        L = NB * bs
        quant = "k0_scale" in pools
        offsets = jnp.asarray(offsets, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        iota = jnp.arange(S, dtype=jnp.int32)
        positions = offsets[:, None] + iota[None, :]            # [B, S]
        live = iota[None, :] < lengths[:, None]                 # [B, S]
        # scatter targets: padded rows (and any position past the table)
        # land in the reserved null page 0 — the drained-write convention
        gpos = jnp.clip(positions, 0, self.max_len - 1)
        blk_idx = jnp.clip(gpos // bs, 0, NB - 1)
        pages = jnp.where(live,
                          jnp.take_along_axis(tables, blk_idx, axis=1), 0)
        rows = gpos % bs
        # read-side overlay index: global position j maps to suffix row
        # j - offset (clipped; selected only where own_mask holds)
        jpos = jnp.arange(L, dtype=jnp.int32)
        rel = jpos[None, :] - offsets[:, None]                  # [B, L]
        own = (rel >= 0) & (rel < lengths[:, None])
        rel_c = jnp.clip(rel, 0, S - 1)
        # [B, 1, S, L]: query at global position offset+i sees keys j <=
        # offset+i — broadcastable over the heads axis of the score tensor
        causal = (jpos[None, None, None, :]
                  <= positions[:, None, :, None])

        def read(pool_q, scale_pool, own_rows):
            g = pk.gather_pages(pool_q, tables).astype(jnp.float32)
            if scale_pool is not None:
                g = g * pk.gather_pages(scale_pool, tables)[..., None]
            o = jnp.take_along_axis(
                own_rows.astype(jnp.float32),
                jnp.broadcast_to(rel_c[:, :, None, None],
                                 (B, L) + own_rows.shape[2:]), axis=1)
            return jnp.where(own[:, :, None, None], o, g)

        x = self.embed(params["embed"], tokens)                 # [B, S, D]
        x = x + params["pos_embed"][gpos].astype(x.dtype)
        new_pools = dict(pools)
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            q, k, v = blk.heads(params[f"blocks_{i}"], x)       # [B, S, H, Dh]
            if quant:
                k8, ks = pk.quantize_kv(k)
                v8, vs = pk.quantize_kv(v)
                new_pools[f"k{i}_scale"] = \
                    new_pools[f"k{i}_scale"].at[pages, rows].set(ks)
                new_pools[f"v{i}_scale"] = \
                    new_pools[f"v{i}_scale"].at[pages, rows].set(vs)
                kw, vw = k8, v8
            else:
                kw, vw = k, v
            new_pools[f"k{i}"] = new_pools[f"k{i}"].at[pages, rows].set(
                kw.astype(new_pools[f"k{i}"].dtype))
            new_pools[f"v{i}"] = new_pools[f"v{i}"].at[pages, rows].set(
                vw.astype(new_pools[f"v{i}"].dtype))
            kr = read(new_pools[f"k{i}"],
                      new_pools.get(f"k{i}_scale"), k)          # [B, L, H, Dh]
            vr = read(new_pools[f"v{i}"],
                      new_pools.get(f"v{i}_scale"), v)
            # op order mirrors _dense_attention: einsum, * scale, mask,
            # jax.nn.softmax, einsum, astype — zero-offset calls reproduce
            # the full-prefill formulation bit for bit on the CPU route
            s = jnp.einsum("bthd,bjhd->bhtj", q.astype(jnp.float32),
                           kr) * blk.d_head ** -0.5
            s = jnp.where(causal, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhtj,bjhd->bthd", p, vr).astype(q.dtype)
            x = blk.finish(params[f"blocks_{i}"], x, o)
        x = self.ln_f(params["ln_f"], x)
        logits = (x @ params["embed"]["w"].T.astype(x.dtype)
                  if self.tie_head else self.head(params["head"], x))
        last = jnp.clip(lengths - 1, 0, S - 1)
        return new_pools, logits[jnp.arange(B), last]

    def verify_step(self, params, cell, tokens, *,
                    cache_len: Optional[int] = None):
        """Multi-token incremental step — the speculative-decoding verify:
        tokens [B, S] are appended to the cache (rows pos..pos+S-1) and
        scored in ONE batched pass, returning (logits [B, S, V], new cell)
        where logits[:, i] is the next-token distribution after tokens
        [..., :i+1]. Equivalent to S sequential decode_step calls at S-th
        of the dispatches; query i attends cache rows j <= pos+i (causal
        within the span, everything live before it). Works on int8 cells
        (span rows quantize on append; reads dequantize)."""
        B, S = tokens.shape
        pos = cell["pos"]                                  # [B]
        L = self.max_len if cache_len is None else min(cache_len,
                                                       self.max_len)
        offs = jnp.arange(S, dtype=jnp.int32)
        positions = pos[:, None] + offs[None, :]           # [B, S]
        x = self.embed(params["embed"], tokens)            # [B, S, D]
        x = x + params["pos_embed"][positions].astype(x.dtype)
        new_cell = {"pos": pos + S}
        for i in range(len(self.blocks)):
            blk = self.blocks[i]
            q, k, v = blk.heads(params[f"blocks_{i}"], x)  # [B, S, H, Dh]
            kc, vc, ksc, vsc = self._append_rows(cell, new_cell, i,
                                                 k, v, pos)
            kr = kc[:, :L].astype(jnp.float32)
            vr = vc[:, :L].astype(jnp.float32)
            if ksc is not None:
                kr = kr * ksc[:, :L, :, None]
                vr = vr * vsc[:, :L, :, None]
            s = jnp.einsum("bihd,bjhd->bhij",
                           q.astype(jnp.float32) * blk.d_head ** -0.5, kr)
            valid = (jnp.arange(L)[None, None, None, :]
                     <= positions[:, None, :, None])       # [B, 1, S, L]
            s = jnp.where(valid, s, -1e30)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            p = p / jnp.sum(p, axis=-1, keepdims=True)
            o = jnp.einsum("bhij,bjhd->bihd", p, vr)       # [B, S, H, Dh]
            x = blk.finish(params[f"blocks_{i}"], x, o)
        x = self.ln_f(params["ln_f"], x)
        logits = (x @ params["embed"]["w"].T.astype(x.dtype)
                  if self.tie_head else self.head(params["head"], x))
        return logits, new_cell

    def generate_cached(self, params, prompt, steps: int,
                        bucket: Optional[int] = None,
                        kv_dtype: Optional[str] = None):
        """Greedy continuation through the KV cache: jitted scans, no
        prefix re-forward. Matches generate_greedy token-for-token.

        ``bucket``: bucketed cache reads — the decode is split into
        segments whose attention reads only the next bucket-multiple of the
        current position instead of the full max_len-padded cache. A
        200-token decode at max_len 1024 with bucket 256 streams ~256-row
        cache slices, not 1024 — the serving-path HBM saving
        (benchmarks/serving_decode.py prints the bytes). One scan compiles
        per touched bucket; token stream is identical to the unbucketed
        path."""
        if prompt.shape[1] + steps > self.max_len:
            # past max_len JAX's clamped indexing would silently corrupt the
            # pos_embed lookup and cache writes (generate_greedy slides its
            # window instead) — fail loudly rather than diverge silently
            raise ValueError(
                f"prompt_len ({prompt.shape[1]}) + steps ({steps}) exceeds "
                f"max_len ({self.max_len}); use generate_greedy for "
                "sliding-window generation past the trained context")
        cell, last_logits = self.prefill(params, prompt, kv_dtype=kv_dtype)
        first = jnp.argmax(last_logits, axis=-1).astype(prompt.dtype)

        def make_body(cache_len):
            def body(carry, _):
                cell, cur = carry
                logits, cell = self.decode_step(params, cell, cur,
                                                cache_len=cache_len)
                nxt = jnp.argmax(logits, axis=-1).astype(cur.dtype)
                return (cell, nxt), cur
            return body

        # each iteration emits its INPUT token: cur_0 = first (from the
        # prompt's logits), cur_j = argmax of step j-1 — exactly the
        # `steps` generated tokens
        if bucket is None:
            _, toks = jax.lax.scan(make_body(None), (cell, first), None,
                                   length=steps)
            toks = jnp.moveaxis(toks, 0, 1)
        else:
            pos = prompt.shape[1]          # max position before each segment
            done, chunks, carry = 0, [], (cell, first)
            while done < steps:
                # positions this segment reads are < pos+1 .. so the read
                # bound is the next bucket multiple that covers them
                cache_len = min(-(-(pos + 1) // bucket) * bucket,
                                self.max_len)
                seg = min(steps - done, cache_len - pos)
                carry, toks = jax.lax.scan(make_body(cache_len), carry,
                                           None, length=seg)
                chunks.append(jnp.moveaxis(toks, 0, 1))
                done += seg
                pos += seg
            toks = jnp.concatenate(chunks, axis=1)
        return jnp.concatenate([prompt, toks], axis=1)

    # -- the fused decode step (one compiled dispatch per token) -----------
    def _decode_fn(self, kind, **static):
        """Model-instance cache of the jitted decode-step programs: a fresh
        ``jax.jit`` closure per call would recompile per call, so repeated
        generate_fused/speculative runs (bench warm-up + measure) reuse one
        executable per static config."""
        cache = self.__dict__.setdefault("_decode_jit", {})
        key = (kind,) + tuple(sorted(static.items()))
        fn = cache.get(key)
        if fn is not None:
            return fn
        extra_bytes = None
        if kind == "prefill":
            kv_dtype = static["kv_dtype"]
            sample = static.get("sample", "greedy")
            top_k = static.get("top_k")
            temp = static.get("temperature", 1.0)

            def pf(params, prompt, rng):
                cell, last = self.prefill(params, prompt, kv_dtype=kv_dtype)
                first, rng = _sample_token(last, rng, sample, top_k, temp)
                return cell, first.astype(prompt.dtype), rng
            fn = jax.jit(pf)
        elif kind == "step":
            cache_len = static["cache_len"]
            sample = static["sample"]
            top_k, temp = static["top_k"], static["temperature"]
            attn_route = static["attn_route"]

            def step(params, cell, cur, rng):
                logits, cell = self.decode_step(params, cell, cur,
                                                cache_len=cache_len,
                                                attn_route=attn_route)
                nxt, rng = _sample_token(logits, rng, sample, top_k, temp)
                return cell, nxt.astype(cur.dtype), rng
            fn = jax.jit(step)
            extra_bytes = self._step_kernel_bytes(cache_len, attn_route)
        elif kind == "verify":
            cache_len = static["cache_len"]

            def vf(params, cell, span):
                logits, cell = self.verify_step(params, cell, span,
                                                cache_len=cache_len)
                return jnp.argmax(logits, axis=-1).astype(span.dtype), cell
            fn = jax.jit(vf)
        else:
            raise ValueError(kind)
        # cost-instrumented: the first call AOT-compiles and records the
        # executable's FLOPs/bytes in the roofline ledger, so a decode
        # loop under an obs session feeds the derived roofline gauges
        # exactly like a fluid Executor run does; extra_bytes contributes
        # the Pallas cache-read model where XLA's analysis sees zero
        fn = obs.roofline.instrument(fn, f"decode.{kind}",
                                     extra_bytes=extra_bytes)
        cache[key] = fn
        return fn

    def _step_kernel_bytes(self, cache_len, attn_route):
        """Per-call modeled HBM bytes of one fused decode step's cache
        read — non-zero only on the Pallas kernel route, where the bytes
        are invisible to XLA's cost analysis (the dense route's read is
        already in the executable's own 'bytes accessed')."""
        L = self.max_len if cache_len is None else cache_len
        if pk.decode_route(L, attn_route) != "kernel":
            return None
        n_heads = self.blocks[0].n_heads
        d_head = self.blocks[0].d_head

        def extra(params, cell, cur, rng):
            kv_dtype = "int8" if "k0_scale" in cell else None
            itemsize = jnp.dtype(cell["k0"].dtype).itemsize
            return obs.roofline.kernel_cost(
                "decode_attention", batch=cur.shape[0], read=L,
                n_heads=n_heads, d_head=d_head, layers=len(self.blocks),
                kv_dtype=kv_dtype, itemsize=itemsize) or 0.0
        return extra

    def generate_fused(self, params, prompt, steps: int, *,
                       bucket: Optional[int] = None,
                       kv_dtype: Optional[str] = None,
                       sample: str = "greedy", top_k: Optional[int] = None,
                       temperature: float = 1.0, key=None,
                       attn_route: Optional[str] = None):
        """The fused decode loop: ONE compiled dispatch per generated token
        (prefill emits the first; every later token is a single jitted
        step fusing cache append + attention read + MLP + logits +
        greedy/top-k sampling), vs one dispatch PER OP for an eager
        decode. Greedy output is token-for-token identical to
        :meth:`generate_cached` (tests/test_decode_fused.py).

        Evidence rides the obs plane: ``decode.dispatches_total``
        (route=prefill|step) counts real host dispatches — exactly
        ``steps`` for ``steps`` tokens — ``decode.tokens_total`` the
        emitted tokens, and ``kernels.bytes_total{kernel=decode_attention}``
        the modeled cache-read bytes (halved under ``kv_dtype="int8"``).

        ``sample="topk"`` needs ``top_k`` and a PRNG ``key``; greedy
        ignores both."""
        if prompt.shape[1] + steps > self.max_len:
            raise ValueError(
                f"prompt_len ({prompt.shape[1]}) + steps ({steps}) exceeds "
                f"max_len ({self.max_len})")
        if sample not in ("greedy", "topk"):
            raise ValueError(f"unknown sample mode {sample!r}")
        if sample == "topk" and (top_k is None or key is None):
            raise ValueError("sample='topk' needs top_k and key")
        B, T0 = prompt.shape
        rng = key if key is not None else jax.random.PRNGKey(0)
        cell, cur, rng = self._decode_fn(
            "prefill", kv_dtype=kv_dtype, sample=sample, top_k=top_k,
            temperature=temperature)(params, prompt, rng)
        obs.count("decode.dispatches_total", route="prefill")
        toks = [cur]
        itemsize = (1 if kv_dtype == "int8" else
                    jnp.dtype(self._compute_dtype(params)).itemsize)
        n_heads = self.blocks[0].n_heads
        d_head = self.blocks[0].d_head
        for j in range(1, steps):
            pos = T0 + j                       # max live position + 1
            if bucket is None:
                cache_len = None
                L = self.max_len
            else:
                cache_len = min(-(-pos // bucket) * bucket, self.max_len)
                L = cache_len
            step = self._decode_fn("step", cache_len=cache_len,
                                   sample=sample, top_k=top_k,
                                   temperature=temperature,
                                   attn_route=attn_route)
            cell, cur, rng = step(params, cell, cur, rng)
            toks.append(cur)
            obs.count("decode.dispatches_total", route="step")
            # modeled cache-read bytes through the ONE registered model
            # (ops/pallas_kernels._decode_attention_bytes) — the same
            # resolution the bench rows and the roofline ledger use
            obs.count("kernels.bytes_total",
                      obs.roofline.kernel_cost(
                          "decode_attention", batch=B, read=L,
                          n_heads=n_heads, d_head=d_head,
                          layers=len(self.blocks), kv_dtype=kv_dtype,
                          itemsize=itemsize) or 0.0,
                      kernel="decode_attention")
        obs.count("decode.tokens_total", B * steps, route="fused")
        return jnp.concatenate([prompt, jnp.stack(toks, axis=1)], axis=1)

    def _compute_dtype(self, params):
        """dtype of the attention k/v activations (follows the embedding
        table, which the cache rows inherit)."""
        return params["embed"]["w"].dtype


def _sample_token(logits, rng, sample, top_k, temperature):
    """Greedy argmax or top-k/temperature sampling from [B, V] logits."""
    if sample == "greedy":
        return jnp.argmax(logits, axis=-1), rng
    v, idx = jax.lax.top_k(logits.astype(jnp.float32), top_k)
    rng, sub = jax.random.split(rng)
    choice = jax.random.categorical(sub, v / temperature)
    return jnp.take_along_axis(idx, choice[:, None], 1)[:, 0], rng
