"""Transformer encoder-decoder NMT — the flash-attention seq2seq.

The reference's NMT is the additive-attention GRU seq2seq
(trainer_config_helpers/networks.py simple_attention:654ff), kept for
parity in :class:`~paddle_tpu.models.seq2seq.AttentionSeq2Seq`. That
architecture's attention query is the recurrent state, so its FLOPs are
trapped inside a sequential scan and no batched attention kernel can apply
(measured roofline: docs/design/nmt_roofline.md). This model is the
TPU-first NMT configuration: a standard pre-LN transformer encoder-decoder
whose every attention — bidirectional encoder self-attention, causal
decoder self-attention, and decoder->encoder cross-attention — goes
through ``flash_attention`` (ops/pallas_kernels.py) with per-sample
source-length masking (``kv_lens``), so variable-length batches never pay
for padded keys in the softmax. At NMT-short lengths that call auto-routes
to its fused dense path (the kernels' per-program overhead beats their HBM
saving below ~256 — measured 1.56x end-to-end, docs/design/nmt_roofline.md);
long-document NMT gets the Pallas kernels with the same masks.

Teacher-forced training is one fully-parallel pass (no scan at all): every
decoder position attends at once — this is what lifts NMT from the GRU
model's recurrence-bound ~15% MFU toward the transformer LM's regime.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core.lod import SeqBatch
from ..nn.initializer import normal
from ..ops import pallas_kernels as pk
from .transformer import TransformerBlock


class CrossAttentionBlock(nn.Module):
    """Decoder block: causal self-attention, encoder cross-attention, FFN —
    all pre-LN, attention through the flash kernel."""

    def __init__(self, d_model: int, n_heads: int, d_ff: int,
                 init_std: float = 0.02):
        super().__init__()
        assert d_model % n_heads == 0
        self.n_heads, self.d_head = n_heads, d_model // n_heads
        self.ln1 = nn.LayerNorm(d_model)
        self.qkv = nn.Linear(d_model, 3 * d_model,
                             w_init=normal(0.0, init_std))
        self.self_proj = nn.Linear(d_model, d_model,
                                   w_init=normal(0.0, init_std))
        self.ln_x = nn.LayerNorm(d_model)
        self.q_x = nn.Linear(d_model, d_model, w_init=normal(0.0, init_std))
        self.kv_x = nn.Linear(d_model, 2 * d_model,
                              w_init=normal(0.0, init_std))
        self.x_proj = nn.Linear(d_model, d_model,
                                w_init=normal(0.0, init_std))
        self.ln2 = nn.LayerNorm(d_model)
        self.mlp_in = nn.Linear(d_model, d_ff, act="gelu",
                                w_init=normal(0.0, init_std))
        self.mlp_out = nn.Linear(d_ff, d_model, w_init=normal(0.0, init_std))

    def _split(self, t, n):
        B, T, _ = t.shape
        parts = jnp.split(t, n, axis=-1)
        return [p.reshape(B, T, self.n_heads, self.d_head) for p in parts]

    def __call__(self, params, x, memory, src_lens=None, **kw):
        B, T, D = x.shape
        # causal self-attention (keys past a sample's own length only meet
        # queries past it, which the loss masks — no kv_lens needed)
        q, k, v = self._split(self.qkv(params["qkv"],
                                       self.ln1(params["ln1"], x)), 3)
        o = pk.flash_attention(q, k, v, causal=True)
        x = x + self.self_proj(params["self_proj"],
                               o.reshape(B, T, D).astype(x.dtype))
        # cross-attention over the encoder memory, source padding masked
        # inside the kernel
        qx = self._split(self.q_x(params["q_x"],
                                  self.ln_x(params["ln_x"], x)), 1)[0]
        kx, vx = self._split(self.kv_x(params["kv_x"], memory), 2)
        ox = pk.flash_attention(qx, kx, vx, causal=False, kv_lens=src_lens)
        x = x + self.x_proj(params["x_proj"],
                            ox.reshape(B, T, D).astype(x.dtype))
        h = self.ln2(params["ln2"], x)
        return x + self.mlp_out(params["mlp_out"],
                                self.mlp_in(params["mlp_in"], h))


class TransformerSeq2Seq(nn.Module):
    """Encoder-decoder NMT, every attention on the flash kernel."""

    def __init__(self, src_vocab: int, trg_vocab: int, d_model: int = 512,
                 n_heads: int = 8, n_enc: int = 6, n_dec: int = 6,
                 d_ff: Optional[int] = None, max_len: int = 512):
        super().__init__()
        d_ff = d_ff or 4 * d_model
        self.max_len = max_len
        self.src_embed = nn.Embedding(src_vocab, d_model,
                                      w_init=normal(0.0, 0.02))
        self.trg_embed = nn.Embedding(trg_vocab, d_model,
                                      w_init=normal(0.0, 0.02))
        self.param("src_pos", (max_len, d_model), normal(0.0, 0.01))
        self.param("trg_pos", (max_len, d_model), normal(0.0, 0.01))
        self.enc_blocks = [TransformerBlock(d_model, n_heads, d_ff,
                                            causal=False)
                           for _ in range(n_enc)]
        self.dec_blocks = [CrossAttentionBlock(d_model, n_heads, d_ff)
                           for _ in range(n_dec)]
        self.ln_enc = nn.LayerNorm(d_model)
        self.ln_f = nn.LayerNorm(d_model)
        # head tied to the target embedding (weight sharing)

    def encode(self, params, src: SeqBatch):
        B, S = src.data.shape
        x = self.src_embed(params["src_embed"], src.data)
        x = x + params["src_pos"][:S].astype(x.dtype)
        for i in range(len(self.enc_blocks)):
            x = self.enc_blocks[i](params[f"enc_blocks_{i}"], x,
                                   kv_lens=src.lengths)
        return self.ln_enc(params["ln_enc"], x)

    def __call__(self, params, src: SeqBatch, trg_in: SeqBatch, **kw):
        """Teacher-forced logits [B, T, V] — one parallel pass, no scan."""
        memory = self.encode(params, src)
        B, T = trg_in.data.shape
        x = self.trg_embed(params["trg_embed"], trg_in.data)
        x = x + params["trg_pos"][:T].astype(x.dtype)
        for i in range(len(self.dec_blocks)):
            x = self.dec_blocks[i](params[f"dec_blocks_{i}"], x, memory,
                                   src_lens=src.lengths)
        x = self.ln_f(params["ln_f"], x)
        return x @ params["trg_embed"]["w"].T.astype(x.dtype)

    def loss(self, params, src: SeqBatch, trg_in: SeqBatch,
             trg_out: SeqBatch):
        logits = self(params, src, trg_in)
        l32 = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(l32, axis=-1)
        gold = jnp.take_along_axis(l32, trg_out.data[..., None], -1)[..., 0]
        nll = lse - gold
        mask = trg_out.mask().astype(nll.dtype)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def greedy_generate(self, params, src: SeqBatch, *, max_len: int = 32,
                        bos_id: int = 0, eos_id: int = 1):
        """Greedy decode by re-forwarding the growing target prefix (the
        correctness path; serving would add a KV cache as TransformerLM's
        generate_cached does)."""
        memory = self.encode(params, src)
        B = src.batch_size
        ids = jnp.full((B, 1), bos_id, jnp.int32)
        done = jnp.zeros((B,), bool)
        for _ in range(max_len):
            T = ids.shape[1]
            x = self.trg_embed(params["trg_embed"], ids)
            x = x + params["trg_pos"][:T].astype(x.dtype)
            for i in range(len(self.dec_blocks)):
                x = self.dec_blocks[i](params[f"dec_blocks_{i}"], x, memory,
                                       src_lens=src.lengths)
            x = self.ln_f(params["ln_f"], x)
            logits = x[:, -1] @ params["trg_embed"]["w"].T.astype(x.dtype)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        return ids[:, 1:]
