from .initializer import constant, gen1_default, msra, normal, ones, uniform, xavier, zeros
from .layers import (AvgPool2D, BatchNorm, Conv2D, Conv2DTranspose, Dropout,
                     Embedding, Fc, LayerNorm, Linear, MaxPool2D)
from .module import Lambda, Module, Sequential, apply_stat_updates, param_count

__all__ = [
    "Module", "Sequential", "Lambda", "param_count", "apply_stat_updates",
    "Linear", "Fc", "Embedding", "Conv2D", "Conv2DTranspose", "BatchNorm",
    "LayerNorm", "Dropout", "MaxPool2D", "AvgPool2D",
    "constant", "zeros", "ones", "uniform", "normal", "xavier", "msra", "gen1_default",
]
