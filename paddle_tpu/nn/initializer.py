"""Parameter initializers.

Analog of python/paddle/v2/fluid/initializer.py (Constant/Uniform/Normal/Xavier/MSRA)
and the gen-1 ``initial_std``/``initial_mean`` ParameterConfig fields
(proto/ParameterConfig.proto).
"""

from __future__ import annotations

import math
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

Initializer = Callable[[jax.Array, Tuple[int, ...], jnp.dtype], jax.Array]


def constant(value: float = 0.0) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init


zeros = constant(0.0)
ones = constant(1.0)


def uniform(low: float = -1.0, high: float = 1.0) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, low, high)
    return init


def normal(mean: float = 0.0, std: float = 1.0) -> Initializer:
    def init(key, shape, dtype=jnp.float32):
        return mean + std * jax.random.normal(key, shape, dtype)
    return init


def _fans(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [kh, kw, cin, cout]
    receptive = 1
    for s in shape[:-2]:
        receptive *= s
    return shape[-2] * receptive, shape[-1] * receptive


def xavier(uniform_dist: bool = True) -> Initializer:
    """Glorot init (ref: fluid/initializer.py XavierInitializer)."""
    def init(key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        if uniform_dist:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return jax.random.uniform(key, shape, dtype, -limit, limit)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    return init


def msra(uniform_dist: bool = False) -> Initializer:
    """He init (ref: fluid/initializer.py MSRAInitializer)."""
    def init(key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        if uniform_dist:
            limit = math.sqrt(6.0 / fan_in)
            return jax.random.uniform(key, shape, dtype, -limit, limit)
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    return init


def gen1_default(initial_std: float = None) -> Initializer:
    """Gen-1 default: N(0, 1/sqrt(fan_in)) (ref: parameter/Parameter.cpp randomize)."""
    def init(key, shape, dtype=jnp.float32):
        std = initial_std if initial_std is not None else 1.0 / math.sqrt(_fans(shape)[0])
        return std * jax.random.normal(key, shape, dtype)
    return init
